"""Fleet router: N engine replicas behind one submit/poll surface.

`FleetRouter` fronts N in-process `EngineReplica`s (each a full
`ServingEngine` — own KV pool, prefix cache, compile caches — i.e. one
failure domain) and owns everything that must survive a replica death:

  * placement — prefix-cache affinity first (`FLAGS_fleet_affinity`: the
    prompt head hashes to a home replica, so shared-prefix traffic keeps
    hitting the replica that already caches it), degrading gracefully to
    least-loaded whenever the home replica is not HEALTHY;
  * health — a `HeartbeatMonitor` over per-replica beats stamped by the
    pumps; a beat older than FLAGS_fleet_heartbeat_s (widened by
    FLAGS_watchdog_scale for slow CI) declares the replica DEAD. Death is
    *discovered*, never announced — kills, hangs, and engine crashes all
    look identical from here: a heartbeat that stopped;
  * failover — every request in flight on a dead replica is replayed from
    its prompt on a survivor through `resilience.retry.fleet_policy` (the
    shared RetryPolicy; max_attempts IS the per-request budget). The
    router keeps the authoritative per-request token ledger (`delivered`),
    so the replay's regenerated prefix is deduplicated position-by-
    position: clients see each token exactly once, and under greedy
    decoding the replayed suffix is bitwise-identical to what the dead
    replica would have produced (batch-composition invariance — the same
    property PR 13's in-engine recovery replay leans on). Positions that
    DO disagree (possible under temperature sampling, where the replay
    re-draws) are suppressed and counted as fleet.replay_divergence;
  * drain-and-retire — `drain(rid)` moves a replica to DRAINING: it
    admits nothing, hands off engine-WAITING work immediately (replayed
    elsewhere, budget-free — a planned migration is not a failure), lets
    RUNNING decodes finish, then RETIRES and stamps fleet.drain_s. Zero
    requests shed: live scale-down;
  * fleet-wide shed — admission is refused (`AdmissionRejected`, same
    type as the engine's) only when EVERY healthy replica reports PR 13
    overload signals; a single overloaded replica just loses the
    placement. Per-replica rejections bounce back asynchronously and
    re-place on another replica under the same failover budget.

Pump modes: `pump="inline"` (default) steps every replica on the caller's
thread inside `step()` — fully deterministic, what the failover-exactness
tests and chaos drills use; `pump="threads"` gives each replica a worker
thread (the serving topology, and what the fleet bench's scaling arms
measure) — the router thread then only routes and polls.

Replay exactness requires every replica to serve the SAME model: the
`engine_factory` must build identically-seeded engines.

Disaggregation (ISSUE 19): pass `roles=["prefill","prefill","decode",...]`
(or set FLAGS_disagg_prefill_replicas) and an engine factory whose engines
share ONE `PagedKVPool` (`handoff.disagg_fleet_factory`). The router then
places every request on a decode-role home (affinity hashes over the
DECODE universe only; prefill replicas never appear in placement) but
dispatches the prompt to the least-loaded prefill replica first; the
prefill side publishes the finished context under a TTL'd lease
("prepared"), the router forwards the commit to the decode home, and the
adopting side streams every token. Crash recovery composes out of the
pieces above plus three lease rules: a dead replica's `OwnedPoolView`
forfeits its pins (lease pins survive — they belong to the
`HandoffManager`), an orphaned PREPARED lease reaps at TTL and replays
the prompt under the ordinary failover budget, and a request that moved
on abandons its stale lease the moment its event surfaces. Disaggregated
fleets pump inline only: the shared pool is single-writer by design.
"""
from __future__ import annotations

import hashlib
import time
from typing import Callable

from ... import observability as obs
from ...resilience.faults import InjectedFault, fault_point
from ...resilience.retry import fleet_policy
from ...resilience.watchdog import HeartbeatMonitor
from ..engine import AdmissionRejected
from .replica import (DEAD, DRAINING, HEALTHY, RETIRED, STATE_ORDINAL,
                      EngineReplica)

__all__ = ["FleetRouter", "FleetRequest", "NoHealthyReplica",
           "QUEUED", "FINISHED", "FAILED", "FLEET_TERMINAL"]

QUEUED, FINISHED, FAILED = "queued", "finished", "failed"
# aborted / deadline_exceeded / shed arrive verbatim from the engine
FLEET_TERMINAL = frozenset(
    {FINISHED, FAILED, "aborted", "deadline_exceeded", "shed"})


class NoHealthyReplica(ConnectionError):
    """Placement found no HEALTHY replica to target (ConnectionError so the
    fleet RetryPolicy treats it as transient while any budget remains)."""


class FleetRequest:
    """Router-side record of one request: where it lives now and the
    authoritative `delivered` token ledger that makes failover replay
    exactly-once from the client's point of view."""

    __slots__ = ("fid", "prompt", "max_new_tokens", "eos_id", "sampling",
                 "priority", "deadline_s", "state", "replica", "delivered",
                 "failovers", "aborting", "t_submit", "t_first", "t_done",
                 "prefill_replica", "lease_id")

    def __init__(self, fid: int, prompt, max_new_tokens: int, eos_id,
                 sampling, priority, deadline_s):
        self.fid = fid
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.sampling = sampling
        self.priority = priority
        self.deadline_s = deadline_s
        self.state = QUEUED
        self.replica: int | None = None
        self.delivered: list[int] = []
        self.failovers = 0
        self.aborting = False
        self.t_submit = time.perf_counter()
        self.t_first: float | None = None
        self.t_done: float | None = None
        # disaggregated path: where the prompt prefills, and the lease id
        # the router is shepherding toward commit (None once adopted)
        self.prefill_replica: int | None = None
        self.lease_id: str | None = None

    def job(self) -> dict:
        return {"fid": self.fid, "prompt": self.prompt,
                "max_new_tokens": self.max_new_tokens, "eos_id": self.eos_id,
                "sampling": self.sampling, "priority": self.priority,
                "deadline_s": self.deadline_s}


class FleetRouter:
    def __init__(self, engine_factory: Callable[[], object],
                 n_replicas: int | None = None, *,
                 heartbeat_s: float | None = None,
                 affinity: bool | None = None,
                 affinity_tokens: int | None = None,
                 failover_budget: int | None = None,
                 pump: str = "inline",
                 roles: "list[str] | None" = None,
                 lease_ttl_s: float | None = None):
        """engine_factory() -> ServingEngine, called once per replica; it
        MUST seed every engine identically (same weights) or failover
        replay loses bitwise exactness. Knobs default from FLAGS_fleet_*.

        `roles` (or FLAGS_disagg_prefill_replicas > 0) turns on
        disaggregation: one entry per replica from {"prefill", "decode",
        "mixed"}; the factory is then called as factory(role) and every
        engine must sit on ONE shared PagedKVPool (see
        handoff.disagg_fleet_factory). `lease_ttl_s` overrides
        FLAGS_disagg_lease_ttl_s for the fleet's HandoffManager."""
        from ... import flags

        if pump not in ("inline", "threads"):
            raise ValueError(f"pump must be 'inline' or 'threads', got {pump!r}")
        n = int(flags.get_flag("fleet_replicas")
                if n_replicas is None else n_replicas)
        if n < 1:
            raise ValueError("n_replicas must be >= 1")
        self.control_role_info: dict = {"tier": "hand",
                                        "reason": "explicit_roles"}
        if roles is None:
            # the prefill:decode split reads its prior from the control
            # measurement store (ISSUE 20): the best-goodput recorded pd
            # for THIS fleet size, confidence-gated back to the hand flag
            # whenever the store is silent or the hand split ties it
            from .. import control as sv_control

            n_pre, self.control_role_info = \
                sv_control.role_split_prior(n)
            if n_pre:
                if n_pre >= n:
                    raise ValueError(
                        f"FLAGS_disagg_prefill_replicas={n_pre} leaves no "
                        f"decode replica in a fleet of {n}")
                roles = ["prefill"] * n_pre + ["decode"] * (n - n_pre)
        self._roles: list[str] | None = None
        if roles is not None:
            roles = [str(r) for r in roles]
            if len(roles) != n:
                raise ValueError(f"{len(roles)} roles for {n} replicas")
            bad = sorted(set(roles) - {"prefill", "decode", "mixed"})
            if bad:
                raise ValueError(f"unknown replica roles {bad}")
            if "prefill" in roles and all(r == "prefill" for r in roles):
                raise ValueError("a disaggregated fleet needs at least one "
                                 "decode-capable replica")
            self._roles = roles
        self._disagg = bool(roles) and "prefill" in roles
        if self._disagg and pump != "inline":
            raise ValueError(
                "disaggregated fleets pump inline only: the shared "
                "PagedKVPool keeps single-writer discipline")
        self.handoff = None  # built below, after the replicas exist
        self.heartbeat_s = float(flags.get_flag("fleet_heartbeat_s")
                                 if heartbeat_s is None else heartbeat_s)
        self.affinity = bool(flags.get_flag("fleet_affinity")
                             if affinity is None else affinity)
        self.affinity_tokens = int(flags.get_flag("fleet_affinity_tokens")
                                   if affinity_tokens is None
                                   else affinity_tokens)
        self.pump = pump
        self._factory = engine_factory
        # deadline already scaled by watchdog_scale inside HeartbeatMonitor
        self.monitor = HeartbeatMonitor(self.heartbeat_s)
        self._retry = fleet_policy() if failover_budget is None \
            else fleet_policy(max_attempts=max(1, failover_budget))
        self.replicas: list[EngineReplica] = []
        self.requests: dict[int, FleetRequest] = {}
        self._next_fid = 0
        self._retire_seen: set[int] = set()
        self.stats: dict[str, int] = {
            "submits": 0, "finished": 0, "failed": 0, "sheds": 0,
            "rejects": 0, "failovers": 0, "handoffs": 0, "deaths": 0,
            "retires": 0, "replayed_tokens": 0, "dedup_tokens": 0,
            "replay_divergence": 0, "affinity_hits": 0, "affinity_misses": 0,
            "prefill_dispatches": 0, "handoff.dropped": 0,
            "handoff.replays": 0, "handoff.released": 0,
        }
        self._started = False
        for i in range(n):
            self.add_replica(self._roles[i] if self._roles else None)
        if self._disagg:
            from .handoff import HandoffManager

            pools = [getattr(r.engine.pool, "pool", None)
                     for r in self.replicas]
            if any(p is None for p in pools) \
                    or any(p is not pools[0] for p in pools):
                raise ValueError(
                    "disaggregated fleet needs every engine on ONE shared "
                    "PagedKVPool (build engines with "
                    "handoff.disagg_fleet_factory)")
            self._lease_now = 0.0
            self._lease_last = time.monotonic()
            self.handoff = HandoffManager(pools[0], ttl_s=lease_ttl_s,
                                          clock=self._lease_clock)
            for rep in self.replicas:
                rep.handoff = self.handoff
        if pump == "threads":
            self._started = True
            for rep in self.replicas:
                rep.start()

    # -- fleet membership ---------------------------------------------------
    def add_replica(self, role: str | None = None) -> EngineReplica:
        """Scale up by one failure domain (elastic counterpart of drain).
        Role-split fleets default new capacity to "decode" (decode is the
        long-lived, load-bearing stage); the factory receives the role."""
        if role is None:
            role = "decode" if self._roles is not None else "mixed"
        engine = (self._factory(role) if self._roles is not None
                  else self._factory())
        if self.handoff is not None \
                and getattr(engine.pool, "pool", None) is not self.handoff.pool:
            raise ValueError(
                "new replica's engine is not on the fleet's shared pool")
        rep = EngineReplica(len(self.replicas), engine, self.monitor,
                            role=role, handoff=self.handoff)
        if self._roles is not None and len(self._roles) == len(self.replicas):
            self._roles.append(role)
        self.replicas.append(rep)
        obs.event("fleet.replica", {"rid": rep.rid, "state": HEALTHY})
        if self.pump == "threads" and self._started:
            rep.start()
        self._refresh_gauges()
        return rep

    def drain(self, rid: int) -> None:
        """Begin drain-and-retire on one replica: admits nothing from now
        on, hands off its waiting work, finishes its running decodes,
        retires. Completion shows up as fleet.retires / fleet.drain_s."""
        rep = self.replicas[rid]
        rep.begin_drain()
        obs.event("fleet.replica", {"rid": rid, "state": DRAINING})
        self._refresh_gauges()

    def kill(self, rid: int) -> None:
        """Administrative kill (tests/chaos): same path a discovered death
        takes — mark dead and fail over its in-flight requests."""
        self._on_dead(self.replicas[rid], reason="killed")

    # -- client surface ------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *, eos_id=None,
               sampling=None, priority: int | None = None,
               deadline_s: float | None = None) -> int:
        """Place one request; returns the fleet request id. Raises
        AdmissionRejected only on FLEET-WIDE overload (every healthy
        replica tripping PR 13 shed signals); single-replica rejections
        are absorbed by re-placement."""
        sig = self.overload_signals()
        if sig is not None:
            self._count("sheds")
            obs.event("fleet.request",
                      {"fid": -1, "phase": "rejected", "signals": sig},
                      level="warning")
            reasons = sorted({k for s in sig.values() for k in s})
            raise AdmissionRejected("fleet:" + ",".join(reasons), 0.05,
                                    {str(r): s for r, s in sig.items()})
        fid = self._next_fid
        self._next_fid += 1
        freq = FleetRequest(fid, prompt, max_new_tokens, eos_id, sampling,
                            priority, deadline_s)
        self.requests[fid] = freq
        self._count("submits")
        try:
            self._place(freq, exclude=frozenset())
        except NoHealthyReplica:
            self._finish(freq, FAILED, "failed")
            raise
        return fid

    def abort(self, fid: int) -> None:
        freq = self.requests[fid]
        if freq.state in FLEET_TERMINAL:
            return
        freq.aborting = True
        if freq.replica is not None:
            self.replicas[freq.replica].enqueue({"abort": fid})

    def state(self, fid: int) -> str:
        return self.requests[fid].state

    def result(self, fid: int) -> list[int]:
        """The delivered-token ledger — every token exactly once, in
        order, regardless of how many replicas the request lived on."""
        return list(self.requests[fid].delivered)

    def overload_signals(self) -> dict | None:
        """Fleet-wide aggregate of per-replica PR 13 overload signals.
        None = at least one healthy replica can absorb work; a dict (rid ->
        signals) = EVERY healthy replica is shedding, the fleet-wide
        refusal condition."""
        per: dict = {}
        healthy = [r for r in self.replicas if r.state == HEALTHY]
        if not healthy:
            return None  # placement failure, not overload — handled there
        for rep in healthy:
            try:
                sig = rep.engine._overload_signals()
            except Exception:  # racing a death: count it as not-shedding
                return None
            if not sig:
                return None
            per[rep.rid] = sig
        return per

    # -- progress ------------------------------------------------------------
    def step(self) -> bool:
        """One router iteration. Inline pump: pump every live replica then
        poll; threaded pump: just poll (the workers pump themselves)."""
        progressed = False
        if self.pump == "inline":
            for rep in self.replicas:
                if rep.alive:
                    progressed |= rep.pump_once()
        return self.poll() or progressed

    def poll(self) -> bool:
        """Drain replica outboxes, run the health check, account retires."""
        progressed = False
        for rep in self.replicas:
            for ev in rep.drain_events():
                progressed = True
                self._handle(rep, ev)
            if rep.state == RETIRED and rep.rid not in self._retire_seen:
                self._retire_seen.add(rep.rid)
                self._count("retires")
                dt = time.perf_counter() - (rep.t_drain_start or
                                            time.perf_counter())
                obs.histogram_observe("fleet.drain_s", dt)
                obs.event("fleet.replica", {"rid": rep.rid, "state": RETIRED,
                                            "drain_s": round(dt, 4)})
                self._refresh_gauges()
                progressed = True
        if self.handoff is not None:
            progressed |= self._reap_orphans()
        self._check_health()
        self._tick_control()
        return progressed

    def _tick_control(self) -> None:
        """Controller epochs for the fleet (ISSUE 20): tick every healthy
        replica's own controller. An engine also ticks itself inside
        step(), but an idle engine never steps — the router's poll is the
        epoch clock of last resort. Ticks are idempotent per epoch (the
        controller fires once per due time, whoever calls first), and
        threaded fleets skip this entirely: the worker thread owns its
        engine, and it ticks from inside step()."""
        if self.pump != "inline":
            return
        for rep in self.replicas:
            if rep.state != HEALTHY:
                continue
            ctrl = getattr(rep.engine, "_ctrl", None)
            if ctrl is not None:
                ctrl.tick(rep.engine)

    def _lease_clock(self) -> float:
        """Stall-capped clock for lease expiry, the TTL counterpart of the
        t_last_pump death rule: a lease only AGES while the router is
        actually pumping. Wall time accrues normally, but any single gap
        between samples — an XLA compile blocking the inline pump for
        seconds — contributes at most TTL/8, so a healthy handoff is never
        reaped just because a neighbor replica sat in a compile. A genuine
        orphan (commit lost while the fleet keeps polling) still reaps
        after ~TTL of live router time."""
        now = time.monotonic()
        cap = self.handoff.ttl_s / 8 if self.handoff is not None else 0.25
        self._lease_now += min(now - self._lease_last, cap)
        self._lease_last = now
        return self._lease_now

    def _reap_orphans(self) -> bool:
        """Orphan recovery: every PREPARED lease past its TTL (commit lost
        to a drop or a dead inbox) reaps — its pin returns to the pool —
        and, when the lease is still the request's CURRENT one, the prompt
        replays under the normal failover budget. Superseded leases reap
        silently: their request already moved on."""
        progressed = False
        for lease in self.handoff.reap_expired():
            progressed = True
            if not self.handoff.is_current(lease):
                continue
            freq = self.requests.get(lease.fid)
            if freq is None or freq.state in FLEET_TERMINAL:
                continue
            freq.lease_id = None  # already reaped; nothing to abandon
            self._count("handoff.replays")
            self._replace(freq, exclude=frozenset(), reason="failover")
        return progressed

    def run_until_idle(self, max_steps: int = 200_000,
                       idle_sleep_s: float = 0.0005) -> None:
        """Drive step() until every request is terminal. Sleeps a hair on
        no-progress iterations so wall clock advances past heartbeat
        deadlines (that is how a silent death gets discovered)."""
        for _ in range(max_steps):
            if all(r.state in FLEET_TERMINAL for r in self.requests.values()):
                return
            if not self.step():
                time.sleep(idle_sleep_s)
        raise RuntimeError(
            f"fleet did not go idle in {max_steps} steps; live="
            f"{[f.fid for f in self.requests.values() if f.state not in FLEET_TERMINAL]}")

    def shutdown(self) -> None:
        for rep in self.replicas:
            rep.stop(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- placement -----------------------------------------------------------
    def _healthy(self, exclude=frozenset()) -> list[EngineReplica]:
        return [r for r in self.replicas
                if r.state == HEALTHY and r.rid not in exclude]

    def _affinity_rid(self, prompt) -> int:
        head = tuple(prompt[:self.affinity_tokens])
        h = hashlib.sha256(repr(head).encode()).digest()
        # modulo the FIXED replica universe so a death or retire elsewhere
        # never reshuffles every other prompt's home — and the DECODE
        # universe only: a prefill-role replica is never a home, and for
        # role-free fleets this reduces to the old h % len(replicas)
        universe = [r.rid for r in self.replicas if r.role != "prefill"]
        return universe[int.from_bytes(h[:8], "big") % len(universe)]

    def _decode_load(self, rep) -> int:
        """Placement load for a decode home. A role-split fleet cannot use
        the replica's queue depth alone: a freshly placed request parks at
        the PREFILL stage, so its decode home reports zero until the
        commit lands — and every placement would pile onto one replica.
        Count the router's own non-terminal assignments instead (plus any
        jobs already on the replica, for the co-located roles)."""
        assigned = sum(1 for q in self.requests.values()
                       if q.replica == rep.rid
                       and q.state not in FLEET_TERMINAL)
        return max(assigned, rep.load())

    def _placement_costs(self, cands) -> dict[int, float]:
        """Apply-mode placement weighting (ISSUE 20): each candidate's
        predicted seconds per goodput token for its CURRENT config, from
        its controller's last epoch. Placement then minimizes
        (load + 1) * cost — queue depth discounted by how fast the
        replica is predicted to serve it. Every replica weighs 1.0 —
        the plain least-loaded rule — unless the mode is apply AND a
        prediction exists for ALL candidates: mixed scales (one replica
        predicted at milliseconds, the rest defaulted to 1.0) would
        stampede the predicted one."""
        neutral = {r.rid: 1.0 for r in cands}
        from .. import control as sv_control

        if sv_control.mode() != "apply":
            return neutral
        out: dict[int, float] = {}
        for r in cands:
            ctrl = getattr(r.engine, "_ctrl", None)
            c = ctrl.last_cost.get(id(r.engine)) if ctrl is not None else None
            if not isinstance(c, (int, float)) or c <= 0:
                return neutral
            out[r.rid] = float(c)
        return out

    def _place(self, freq: FleetRequest, exclude=frozenset()) -> None:
        cands = [r for r in self._healthy(exclude) if r.role != "prefill"]
        if not cands:
            raise NoHealthyReplica(
                f"no healthy decode-capable replica for fid={freq.fid} "
                f"(excluded {sorted(exclude)})")
        load = self._decode_load if self._disagg else \
            (lambda r: r.load())
        costs = self._placement_costs(cands)
        rank = lambda r: ((load(r) + 1) * costs[r.rid], r.rid)
        if self.affinity:
            home = self._affinity_rid(freq.prompt)
            rep = next((r for r in cands if r.rid == home), None)
            if rep is not None:
                self._count("affinity_hits")
            else:  # graceful degradation: least-loaded healthy survivor
                self._count("affinity_misses")
                rep = min(cands, key=rank)
        else:
            rep = min(cands, key=rank)
        hits, misses = self.stats["affinity_hits"], self.stats["affinity_misses"]
        if hits + misses:
            obs.gauge_set("fleet.affinity_hit_rate", hits / (hits + misses))
        freq.replica = rep.rid
        if self._disagg:
            # the decode home is chosen, but the prompt goes to the
            # prefill stage first; the "prepared" event brings it back
            self._dispatch_prefill(freq, exclude)
        else:
            rep.enqueue(freq.job())
        obs.event("fleet.request",
                  {"fid": freq.fid, "phase": "placed", "rid": rep.rid,
                   "prefill_rid": freq.prefill_replica,
                   "failovers": freq.failovers})

    def _dispatch_prefill(self, freq: FleetRequest, exclude) -> None:
        pres = [r for r in self._healthy(exclude) if r.role == "prefill"]
        if not pres:
            raise NoHealthyReplica(
                f"no healthy prefill replica for fid={freq.fid} "
                f"(excluded {sorted(exclude)})")
        prep = min(pres, key=lambda r: (r.load(), r.rid))
        freq.prefill_replica = prep.rid
        freq.lease_id = None
        # any older lease for this fid is now history: it must still reap
        # (its pin needs reclaiming) but must not trigger a second replay
        self.handoff.supersede(freq.fid)
        prep.enqueue(freq.job())
        self._count("prefill_dispatches")

    def _replace(self, freq: FleetRequest, exclude, reason: str) -> None:
        """Move a live request to another replica. `reason` decides the
        cost: failover/reject consume the per-request budget (the
        fleet_policy max_attempts), a drain handoff is free — planned
        migration is not a failure."""
        freq.replica = None
        if freq.lease_id is not None and self.handoff is not None:
            # the replay supersedes any in-flight lease: reclaim its pin
            # now instead of waiting out the TTL
            self.handoff.abandon(freq.lease_id)
            freq.lease_id = None
        if freq.prefill_replica is not None:
            prep = self.replicas[freq.prefill_replica]
            if prep.alive:  # dead prefills already forfeited their pins
                prep.enqueue({"release": freq.fid})
            freq.prefill_replica = None
        if reason == "handoff":
            self._count("handoffs")
        else:
            if freq.failovers >= self._retry.max_attempts:
                self._finish(freq, FAILED, "failed")
                obs.event("fleet.request",
                          {"fid": freq.fid, "phase": "budget_exhausted",
                           "failovers": freq.failovers}, level="error")
                return
            freq.failovers += 1
            self._count("failovers")
            if reason == "reject":  # pace re-placement onto shedding peers
                time.sleep(self._retry.delay(freq.failovers))
        # the replay starts from the prompt; everything already delivered
        # will be regenerated and suppressed by the ledger
        self._count("replayed_tokens", len(freq.delivered))
        try:
            self._place(freq, exclude=exclude)
        except NoHealthyReplica:
            self._finish(freq, FAILED, "failed")
            obs.event("fleet.request",
                      {"fid": freq.fid, "phase": "unplaceable",
                       "failovers": freq.failovers}, level="error")

    # -- event handling ------------------------------------------------------
    def _handle(self, rep: EngineReplica, ev: tuple) -> None:
        kind, fid = ev[0], ev[1]
        freq = self.requests.get(fid)
        if freq is None or freq.state in FLEET_TERMINAL \
                or rep.rid not in (freq.replica, freq.prefill_replica):
            # stale: the request moved on (failover beat this event) — but
            # a stale "prepared" still owns a pin: abandon its lease so
            # the pages come back now rather than at TTL
            if kind == "prepared" and self.handoff is not None:
                self.handoff.abandon(ev[2])
            return
        if kind == "tokens":
            start, toks = ev[2], ev[3]
            for i, tok in enumerate(toks, start):
                if i < len(freq.delivered):
                    # replayed ground we already delivered: suppress
                    self._count("dedup_tokens")
                    if tok != freq.delivered[i]:
                        # sampling replay re-drew; greedy never gets here
                        self._count("replay_divergence")
                else:
                    if freq.t_first is None:
                        freq.t_first = time.perf_counter()
                        obs.histogram_observe(
                            "fleet.ttft_s", freq.t_first - freq.t_submit)
                    freq.delivered.append(tok)
        elif kind == "done":
            estate = ev[2]
            if estate == "shed" and not freq.aborting:
                # a replica shedding under pressure is that replica's
                # problem — re-place on a survivor under the budget
                self._count("rejects")
                self._replace(freq, exclude={rep.rid}, reason="reject")
            else:
                self._finish(freq, estate,
                             "finished" if estate == FINISHED else None)
        elif kind == "reject":
            self._count("rejects")
            self._replace(freq, exclude={rep.rid}, reason="reject")
        elif kind == "handoff":
            self._replace(freq, exclude={rep.rid}, reason="handoff")
        elif kind == "prepared":
            self._on_prepared(freq, ev[2])
        elif kind == "adopted":
            if freq.lease_id != ev[2]:
                return  # a superseded adopt; the replay owns the request
            if freq.prefill_replica is not None:
                prep = self.replicas[freq.prefill_replica]
                if prep.alive:
                    prep.enqueue({"release": fid})
                    self._count("handoff.released")
                freq.prefill_replica = None
            freq.lease_id = None
            if freq.aborting:
                # the abort raced the handoff: re-issue it to the adopter
                rep.enqueue({"abort": fid})
        elif kind == "commit_failed":
            if freq.lease_id != ev[2]:
                return  # this lease was already reaped/abandoned + replayed
            self._count("handoff.replays")
            self._replace(freq, exclude={rep.rid}, reason="failover")

    def _on_prepared(self, freq: FleetRequest, lid: str) -> None:
        """The prefill stage published `freq` under lease `lid`: forward
        the commit to the decode home (re-picking one if the original
        died while the prompt prefilled). `disagg_handoff_drop` loses this
        message in flight — the lease stays published and the reaper
        recovers it at TTL."""
        try:
            fault_point("disagg_handoff_drop")
        except InjectedFault:
            self._count("handoff.dropped")
            return
        freq.lease_id = lid
        target = None
        if freq.replica is not None \
                and self.replicas[freq.replica].state == HEALTHY:
            target = self.replicas[freq.replica]
        else:
            cands = [r for r in self._healthy() if r.role != "prefill"]
            if cands:
                target = min(cands, key=lambda r: (r.load(), r.rid))
                freq.replica = target.rid
        if target is None:
            self.handoff.abandon(lid)
            freq.lease_id = None
            if freq.prefill_replica is not None:
                prep = self.replicas[freq.prefill_replica]
                if prep.alive:
                    prep.enqueue({"release": freq.fid})
                freq.prefill_replica = None
            self._finish(freq, FAILED, "failed")
            return
        target.enqueue({"commit": lid, "fid": freq.fid})

    def _finish(self, freq: FleetRequest, state: str,
                counter: str | None) -> None:
        freq.state = state
        freq.t_done = time.perf_counter()
        if counter:
            self._count(counter)
        if state == FINISHED:
            obs.histogram_observe("fleet.request_s",
                                  freq.t_done - freq.t_submit)
        obs.event("fleet.request", {"fid": freq.fid, "phase": state})

    # -- health --------------------------------------------------------------
    def _check_health(self) -> None:
        now = time.monotonic()
        for name in self.monitor.overdue(now=now):
            rep = next((r for r in self.replicas if r.name == name), None)
            if rep is None or not rep.alive:
                continue
            # a stale beat alone is not death: on the inline pump a
            # neighbor's multi-second XLA compile blocks the shared thread,
            # starving every OTHER replica's beat. Death = the replica WAS
            # pumped after its last beat and still never beat again — only
            # kills, hangs and crashes look like that.
            last_beat = now - self.monitor.age(name, now=now)
            if rep.t_last_pump > last_beat:
                self._on_dead(rep, reason="heartbeat")

    def _on_dead(self, rep: EngineReplica, reason: str) -> None:
        rep.mark_dead()
        self._count("deaths")
        obs.event("fleet.replica",
                  {"rid": rep.rid, "state": DEAD, "reason": reason,
                   "crash": repr(rep.crash) if rep.crash else None},
                  level="error")
        if self._disagg:
            # a dead engine's pins never release themselves: forfeit its
            # owner ledger back to the SHARED pool. Lease pins belong to
            # the HandoffManager, so in-transit pages survive this.
            forfeit = getattr(rep.engine.pool, "forfeit", None)
            freed = forfeit() if forfeit is not None else 0
            if freed:
                obs.event("fleet.replica",
                          {"rid": rep.rid, "state": DEAD,
                           "forfeited_pages": freed}, level="warning")
        self._refresh_gauges()
        victims = [f for f in self.requests.values()
                   if f.state not in FLEET_TERMINAL
                   and self._victim_of(f, rep.rid)]
        for freq in victims:
            self._replace(freq, exclude={rep.rid}, reason="failover")

    def _victim_of(self, freq: FleetRequest, rid: int) -> bool:
        """Does `rid` dying strand `freq`? Non-disagg: placed there. With
        disaggregation the lease decides: a request whose PREFILL died
        pre-lease lost its prompt work (replay); one whose lease is
        published survives a prefill death (the pin lives in the shared
        pool, the commit proceeds); a DECODE death strands both adopted
        requests (classic failover, dedup'd by the ledger) and leases
        whose commit sat in the dead inbox (replay now beats waiting out
        the TTL); a decode death while the prompt still prefills strands
        nothing — "prepared" re-targets a survivor."""
        if freq.replica == rid:
            return not self._disagg or freq.lease_id is not None \
                or freq.prefill_replica is None
        return freq.prefill_replica == rid and freq.lease_id is None

    # -- accounting ----------------------------------------------------------
    def _count(self, key: str, n: int = 1) -> None:
        self.stats[key] = self.stats.get(key, 0) + n
        obs.counter_inc(f"fleet.{key}", n)

    def _refresh_gauges(self) -> None:
        by_state = {HEALTHY: 0, DRAINING: 0, DEAD: 0, RETIRED: 0}
        for rep in self.replicas:
            by_state[rep.state] += 1
            obs.gauge_set("fleet.replica_state", STATE_ORDINAL[rep.state],
                          labels={"rid": str(rep.rid)})
        obs.gauge_set("fleet.replicas_healthy", by_state[HEALTHY])
        obs.gauge_set("fleet.replicas_draining", by_state[DRAINING])
        obs.gauge_set("fleet.replicas_dead", by_state[DEAD])

    def reset_stats(self) -> None:
        """Measurement boundary (mirrors ServingEngine.reset_stats): zero
        the router counters, the handoff lease counters, and the fleet.*
        registry series; per-engine serving.* counters reset separately
        via each engine."""
        for k in self.stats:
            self.stats[k] = 0
        if self.handoff is not None:
            for k in self.handoff.stats:
                self.handoff.stats[k] = 0
        obs.reset("fleet.")
