"""Fleet router: N engine replicas behind one submit/poll surface.

`FleetRouter` fronts N in-process `EngineReplica`s (each a full
`ServingEngine` — own KV pool, prefix cache, compile caches — i.e. one
failure domain) and owns everything that must survive a replica death:

  * placement — prefix-cache affinity first (`FLAGS_fleet_affinity`: the
    prompt head hashes to a home replica, so shared-prefix traffic keeps
    hitting the replica that already caches it), degrading gracefully to
    least-loaded whenever the home replica is not HEALTHY;
  * health — a `HeartbeatMonitor` over per-replica beats stamped by the
    pumps; a beat older than FLAGS_fleet_heartbeat_s (widened by
    FLAGS_watchdog_scale for slow CI) declares the replica DEAD. Death is
    *discovered*, never announced — kills, hangs, and engine crashes all
    look identical from here: a heartbeat that stopped;
  * failover — every request in flight on a dead replica is replayed from
    its prompt on a survivor through `resilience.retry.fleet_policy` (the
    shared RetryPolicy; max_attempts IS the per-request budget). The
    router keeps the authoritative per-request token ledger (`delivered`),
    so the replay's regenerated prefix is deduplicated position-by-
    position: clients see each token exactly once, and under greedy
    decoding the replayed suffix is bitwise-identical to what the dead
    replica would have produced (batch-composition invariance — the same
    property PR 13's in-engine recovery replay leans on). Positions that
    DO disagree (possible under temperature sampling, where the replay
    re-draws) are suppressed and counted as fleet.replay_divergence;
  * drain-and-retire — `drain(rid)` moves a replica to DRAINING: it
    admits nothing, hands off engine-WAITING work immediately (replayed
    elsewhere, budget-free — a planned migration is not a failure), lets
    RUNNING decodes finish, then RETIRES and stamps fleet.drain_s. Zero
    requests shed: live scale-down;
  * fleet-wide shed — admission is refused (`AdmissionRejected`, same
    type as the engine's) only when EVERY healthy replica reports PR 13
    overload signals; a single overloaded replica just loses the
    placement. Per-replica rejections bounce back asynchronously and
    re-place on another replica under the same failover budget.

Pump modes: `pump="inline"` (default) steps every replica on the caller's
thread inside `step()` — fully deterministic, what the failover-exactness
tests and chaos drills use; `pump="threads"` gives each replica a worker
thread (the serving topology, and what the fleet bench's scaling arms
measure) — the router thread then only routes and polls.

Replay exactness requires every replica to serve the SAME model: the
`engine_factory` must build identically-seeded engines.
"""
from __future__ import annotations

import hashlib
import time
from typing import Callable

from ... import observability as obs
from ...resilience.retry import fleet_policy
from ...resilience.watchdog import HeartbeatMonitor
from ..engine import AdmissionRejected
from .replica import (DEAD, DRAINING, HEALTHY, RETIRED, STATE_ORDINAL,
                      EngineReplica)

__all__ = ["FleetRouter", "FleetRequest", "NoHealthyReplica",
           "QUEUED", "FINISHED", "FAILED", "FLEET_TERMINAL"]

QUEUED, FINISHED, FAILED = "queued", "finished", "failed"
# aborted / deadline_exceeded / shed arrive verbatim from the engine
FLEET_TERMINAL = frozenset(
    {FINISHED, FAILED, "aborted", "deadline_exceeded", "shed"})


class NoHealthyReplica(ConnectionError):
    """Placement found no HEALTHY replica to target (ConnectionError so the
    fleet RetryPolicy treats it as transient while any budget remains)."""


class FleetRequest:
    """Router-side record of one request: where it lives now and the
    authoritative `delivered` token ledger that makes failover replay
    exactly-once from the client's point of view."""

    __slots__ = ("fid", "prompt", "max_new_tokens", "eos_id", "sampling",
                 "priority", "deadline_s", "state", "replica", "delivered",
                 "failovers", "aborting", "t_submit", "t_first", "t_done")

    def __init__(self, fid: int, prompt, max_new_tokens: int, eos_id,
                 sampling, priority, deadline_s):
        self.fid = fid
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.sampling = sampling
        self.priority = priority
        self.deadline_s = deadline_s
        self.state = QUEUED
        self.replica: int | None = None
        self.delivered: list[int] = []
        self.failovers = 0
        self.aborting = False
        self.t_submit = time.perf_counter()
        self.t_first: float | None = None
        self.t_done: float | None = None

    def job(self) -> dict:
        return {"fid": self.fid, "prompt": self.prompt,
                "max_new_tokens": self.max_new_tokens, "eos_id": self.eos_id,
                "sampling": self.sampling, "priority": self.priority,
                "deadline_s": self.deadline_s}


class FleetRouter:
    def __init__(self, engine_factory: Callable[[], object],
                 n_replicas: int | None = None, *,
                 heartbeat_s: float | None = None,
                 affinity: bool | None = None,
                 affinity_tokens: int | None = None,
                 failover_budget: int | None = None,
                 pump: str = "inline"):
        """engine_factory() -> ServingEngine, called once per replica; it
        MUST seed every engine identically (same weights) or failover
        replay loses bitwise exactness. Knobs default from FLAGS_fleet_*."""
        from ... import flags

        if pump not in ("inline", "threads"):
            raise ValueError(f"pump must be 'inline' or 'threads', got {pump!r}")
        n = int(flags.get_flag("fleet_replicas")
                if n_replicas is None else n_replicas)
        if n < 1:
            raise ValueError("n_replicas must be >= 1")
        self.heartbeat_s = float(flags.get_flag("fleet_heartbeat_s")
                                 if heartbeat_s is None else heartbeat_s)
        self.affinity = bool(flags.get_flag("fleet_affinity")
                             if affinity is None else affinity)
        self.affinity_tokens = int(flags.get_flag("fleet_affinity_tokens")
                                   if affinity_tokens is None
                                   else affinity_tokens)
        self.pump = pump
        self._factory = engine_factory
        # deadline already scaled by watchdog_scale inside HeartbeatMonitor
        self.monitor = HeartbeatMonitor(self.heartbeat_s)
        self._retry = fleet_policy() if failover_budget is None \
            else fleet_policy(max_attempts=max(1, failover_budget))
        self.replicas: list[EngineReplica] = []
        self.requests: dict[int, FleetRequest] = {}
        self._next_fid = 0
        self._retire_seen: set[int] = set()
        self.stats: dict[str, int] = {
            "submits": 0, "finished": 0, "failed": 0, "sheds": 0,
            "rejects": 0, "failovers": 0, "handoffs": 0, "deaths": 0,
            "retires": 0, "replayed_tokens": 0, "dedup_tokens": 0,
            "replay_divergence": 0, "affinity_hits": 0, "affinity_misses": 0,
        }
        self._started = False
        for _ in range(n):
            self.add_replica()
        if pump == "threads":
            self._started = True
            for rep in self.replicas:
                rep.start()

    # -- fleet membership ---------------------------------------------------
    def add_replica(self) -> EngineReplica:
        """Scale up by one failure domain (elastic counterpart of drain)."""
        rep = EngineReplica(len(self.replicas), self._factory(), self.monitor)
        self.replicas.append(rep)
        obs.event("fleet.replica", {"rid": rep.rid, "state": HEALTHY})
        if self.pump == "threads" and self._started:
            rep.start()
        self._refresh_gauges()
        return rep

    def drain(self, rid: int) -> None:
        """Begin drain-and-retire on one replica: admits nothing from now
        on, hands off its waiting work, finishes its running decodes,
        retires. Completion shows up as fleet.retires / fleet.drain_s."""
        rep = self.replicas[rid]
        rep.begin_drain()
        obs.event("fleet.replica", {"rid": rid, "state": DRAINING})
        self._refresh_gauges()

    def kill(self, rid: int) -> None:
        """Administrative kill (tests/chaos): same path a discovered death
        takes — mark dead and fail over its in-flight requests."""
        self._on_dead(self.replicas[rid], reason="killed")

    # -- client surface ------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *, eos_id=None,
               sampling=None, priority: int | None = None,
               deadline_s: float | None = None) -> int:
        """Place one request; returns the fleet request id. Raises
        AdmissionRejected only on FLEET-WIDE overload (every healthy
        replica tripping PR 13 shed signals); single-replica rejections
        are absorbed by re-placement."""
        sig = self.overload_signals()
        if sig is not None:
            self._count("sheds")
            obs.event("fleet.request",
                      {"fid": -1, "phase": "rejected", "signals": sig},
                      level="warning")
            reasons = sorted({k for s in sig.values() for k in s})
            raise AdmissionRejected("fleet:" + ",".join(reasons), 0.05,
                                    {str(r): s for r, s in sig.items()})
        fid = self._next_fid
        self._next_fid += 1
        freq = FleetRequest(fid, prompt, max_new_tokens, eos_id, sampling,
                            priority, deadline_s)
        self.requests[fid] = freq
        self._count("submits")
        try:
            self._place(freq, exclude=frozenset())
        except NoHealthyReplica:
            self._finish(freq, FAILED, "failed")
            raise
        return fid

    def abort(self, fid: int) -> None:
        freq = self.requests[fid]
        if freq.state in FLEET_TERMINAL:
            return
        freq.aborting = True
        if freq.replica is not None:
            self.replicas[freq.replica].enqueue({"abort": fid})

    def state(self, fid: int) -> str:
        return self.requests[fid].state

    def result(self, fid: int) -> list[int]:
        """The delivered-token ledger — every token exactly once, in
        order, regardless of how many replicas the request lived on."""
        return list(self.requests[fid].delivered)

    def overload_signals(self) -> dict | None:
        """Fleet-wide aggregate of per-replica PR 13 overload signals.
        None = at least one healthy replica can absorb work; a dict (rid ->
        signals) = EVERY healthy replica is shedding, the fleet-wide
        refusal condition."""
        per: dict = {}
        healthy = [r for r in self.replicas if r.state == HEALTHY]
        if not healthy:
            return None  # placement failure, not overload — handled there
        for rep in healthy:
            try:
                sig = rep.engine._overload_signals()
            except Exception:  # racing a death: count it as not-shedding
                return None
            if not sig:
                return None
            per[rep.rid] = sig
        return per

    # -- progress ------------------------------------------------------------
    def step(self) -> bool:
        """One router iteration. Inline pump: pump every live replica then
        poll; threaded pump: just poll (the workers pump themselves)."""
        progressed = False
        if self.pump == "inline":
            for rep in self.replicas:
                if rep.alive:
                    progressed |= rep.pump_once()
        return self.poll() or progressed

    def poll(self) -> bool:
        """Drain replica outboxes, run the health check, account retires."""
        progressed = False
        for rep in self.replicas:
            for ev in rep.drain_events():
                progressed = True
                self._handle(rep, ev)
            if rep.state == RETIRED and rep.rid not in self._retire_seen:
                self._retire_seen.add(rep.rid)
                self._count("retires")
                dt = time.perf_counter() - (rep.t_drain_start or
                                            time.perf_counter())
                obs.histogram_observe("fleet.drain_s", dt)
                obs.event("fleet.replica", {"rid": rep.rid, "state": RETIRED,
                                            "drain_s": round(dt, 4)})
                self._refresh_gauges()
                progressed = True
        self._check_health()
        return progressed

    def run_until_idle(self, max_steps: int = 200_000,
                       idle_sleep_s: float = 0.0005) -> None:
        """Drive step() until every request is terminal. Sleeps a hair on
        no-progress iterations so wall clock advances past heartbeat
        deadlines (that is how a silent death gets discovered)."""
        for _ in range(max_steps):
            if all(r.state in FLEET_TERMINAL for r in self.requests.values()):
                return
            if not self.step():
                time.sleep(idle_sleep_s)
        raise RuntimeError(
            f"fleet did not go idle in {max_steps} steps; live="
            f"{[f.fid for f in self.requests.values() if f.state not in FLEET_TERMINAL]}")

    def shutdown(self) -> None:
        for rep in self.replicas:
            rep.stop(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- placement -----------------------------------------------------------
    def _healthy(self, exclude=frozenset()) -> list[EngineReplica]:
        return [r for r in self.replicas
                if r.state == HEALTHY and r.rid not in exclude]

    def _affinity_rid(self, prompt) -> int:
        head = tuple(prompt[:self.affinity_tokens])
        h = hashlib.sha256(repr(head).encode()).digest()
        # modulo the FIXED replica universe so a death or retire elsewhere
        # never reshuffles every other prompt's home
        return int.from_bytes(h[:8], "big") % len(self.replicas)

    def _place(self, freq: FleetRequest, exclude=frozenset()) -> None:
        cands = self._healthy(exclude)
        if not cands:
            raise NoHealthyReplica(
                f"no healthy replica for fid={freq.fid} "
                f"(excluded {sorted(exclude)})")
        if self.affinity:
            home = self._affinity_rid(freq.prompt)
            rep = next((r for r in cands if r.rid == home), None)
            if rep is not None:
                self._count("affinity_hits")
            else:  # graceful degradation: least-loaded healthy survivor
                self._count("affinity_misses")
                rep = min(cands, key=lambda r: (r.load(), r.rid))
        else:
            rep = min(cands, key=lambda r: (r.load(), r.rid))
        hits, misses = self.stats["affinity_hits"], self.stats["affinity_misses"]
        if hits + misses:
            obs.gauge_set("fleet.affinity_hit_rate", hits / (hits + misses))
        freq.replica = rep.rid
        rep.enqueue(freq.job())
        obs.event("fleet.request",
                  {"fid": freq.fid, "phase": "placed", "rid": rep.rid,
                   "failovers": freq.failovers})

    def _replace(self, freq: FleetRequest, exclude, reason: str) -> None:
        """Move a live request to another replica. `reason` decides the
        cost: failover/reject consume the per-request budget (the
        fleet_policy max_attempts), a drain handoff is free — planned
        migration is not a failure."""
        freq.replica = None
        if reason == "handoff":
            self._count("handoffs")
        else:
            if freq.failovers >= self._retry.max_attempts:
                self._finish(freq, FAILED, "failed")
                obs.event("fleet.request",
                          {"fid": freq.fid, "phase": "budget_exhausted",
                           "failovers": freq.failovers}, level="error")
                return
            freq.failovers += 1
            self._count("failovers")
            if reason == "reject":  # pace re-placement onto shedding peers
                time.sleep(self._retry.delay(freq.failovers))
        # the replay starts from the prompt; everything already delivered
        # will be regenerated and suppressed by the ledger
        self._count("replayed_tokens", len(freq.delivered))
        try:
            self._place(freq, exclude=exclude)
        except NoHealthyReplica:
            self._finish(freq, FAILED, "failed")
            obs.event("fleet.request",
                      {"fid": freq.fid, "phase": "unplaceable",
                       "failovers": freq.failovers}, level="error")

    # -- event handling ------------------------------------------------------
    def _handle(self, rep: EngineReplica, ev: tuple) -> None:
        kind, fid = ev[0], ev[1]
        freq = self.requests.get(fid)
        if freq is None or freq.replica != rep.rid \
                or freq.state in FLEET_TERMINAL:
            return  # stale: the request moved on (failover beat this event)
        if kind == "tokens":
            start, toks = ev[2], ev[3]
            for i, tok in enumerate(toks, start):
                if i < len(freq.delivered):
                    # replayed ground we already delivered: suppress
                    self._count("dedup_tokens")
                    if tok != freq.delivered[i]:
                        # sampling replay re-drew; greedy never gets here
                        self._count("replay_divergence")
                else:
                    if freq.t_first is None:
                        freq.t_first = time.perf_counter()
                        obs.histogram_observe(
                            "fleet.ttft_s", freq.t_first - freq.t_submit)
                    freq.delivered.append(tok)
        elif kind == "done":
            estate = ev[2]
            if estate == "shed" and not freq.aborting:
                # a replica shedding under pressure is that replica's
                # problem — re-place on a survivor under the budget
                self._count("rejects")
                self._replace(freq, exclude={rep.rid}, reason="reject")
            else:
                self._finish(freq, estate,
                             "finished" if estate == FINISHED else None)
        elif kind == "reject":
            self._count("rejects")
            self._replace(freq, exclude={rep.rid}, reason="reject")
        elif kind == "handoff":
            self._replace(freq, exclude={rep.rid}, reason="handoff")

    def _finish(self, freq: FleetRequest, state: str,
                counter: str | None) -> None:
        freq.state = state
        freq.t_done = time.perf_counter()
        if counter:
            self._count(counter)
        if state == FINISHED:
            obs.histogram_observe("fleet.request_s",
                                  freq.t_done - freq.t_submit)
        obs.event("fleet.request", {"fid": freq.fid, "phase": state})

    # -- health --------------------------------------------------------------
    def _check_health(self) -> None:
        now = time.monotonic()
        for name in self.monitor.overdue(now=now):
            rep = next((r for r in self.replicas if r.name == name), None)
            if rep is None or not rep.alive:
                continue
            # a stale beat alone is not death: on the inline pump a
            # neighbor's multi-second XLA compile blocks the shared thread,
            # starving every OTHER replica's beat. Death = the replica WAS
            # pumped after its last beat and still never beat again — only
            # kills, hangs and crashes look like that.
            last_beat = now - self.monitor.age(name, now=now)
            if rep.t_last_pump > last_beat:
                self._on_dead(rep, reason="heartbeat")

    def _on_dead(self, rep: EngineReplica, reason: str) -> None:
        rep.mark_dead()
        self._count("deaths")
        obs.event("fleet.replica",
                  {"rid": rep.rid, "state": DEAD, "reason": reason,
                   "crash": repr(rep.crash) if rep.crash else None},
                  level="error")
        self._refresh_gauges()
        victims = [f for f in self.requests.values()
                   if f.replica == rep.rid and f.state not in FLEET_TERMINAL]
        for freq in victims:
            self._replace(freq, exclude={rep.rid}, reason="failover")

    # -- accounting ----------------------------------------------------------
    def _count(self, key: str, n: int = 1) -> None:
        self.stats[key] = self.stats.get(key, 0) + n
        obs.counter_inc(f"fleet.{key}", n)

    def _refresh_gauges(self) -> None:
        by_state = {HEALTHY: 0, DRAINING: 0, DEAD: 0, RETIRED: 0}
        for rep in self.replicas:
            by_state[rep.state] += 1
            obs.gauge_set("fleet.replica_state", STATE_ORDINAL[rep.state],
                          labels={"rid": str(rep.rid)})
        obs.gauge_set("fleet.replicas_healthy", by_state[HEALTHY])
        obs.gauge_set("fleet.replicas_draining", by_state[DRAINING])
        obs.gauge_set("fleet.replicas_dead", by_state[DEAD])

    def reset_stats(self) -> None:
        """Measurement boundary (mirrors ServingEngine.reset_stats): zero
        the router counters and the fleet.* registry series; per-engine
        serving.* counters reset separately via each engine."""
        for k in self.stats:
            self.stats[k] = 0
        obs.reset("fleet.")
