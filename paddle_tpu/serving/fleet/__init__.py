"""Replica fleet: failure-domain isolation for the serving engine.

One `FleetRouter` in front of N in-process `EngineReplica`s — each a full
`ServingEngine` with its own KV pool, prefix cache, and compile caches,
so a replica death takes nothing down but itself. The router owns
placement (prefix-cache affinity, least-loaded fallback), heartbeat
health checking, failover replay with exactly-once token delivery, and
drain-and-retire live migration. `handoff.py` adds disaggregated
prefill/decode serving over the same machinery: role-split replicas on
ONE shared `PagedKVPool` exchanging finished prompt KV through TTL'd
two-phase leases (prepare -> commit, orphans reaped and replayed). See
router.py / handoff.py for the contracts, README "Serving fleet" and
"Disaggregated serving" for the operator view, and FLAGS_fleet_* /
FLAGS_disagg_* for the knobs.
"""
from .handoff import (  # noqa: F401
    HandoffError, HandoffManager, KVLease, LeaseExpired,
    disagg_fleet_factory)
from .replica import (  # noqa: F401
    DEAD, DRAINING, HEALTHY, RETIRED, STATE_ORDINAL, EngineReplica)
from .router import (  # noqa: F401
    FLEET_TERMINAL, FleetRequest, FleetRouter, NoHealthyReplica)

__all__ = [
    "EngineReplica", "FleetRouter", "FleetRequest", "NoHealthyReplica",
    "HEALTHY", "DRAINING", "DEAD", "RETIRED", "STATE_ORDINAL",
    "FLEET_TERMINAL",
    "HandoffManager", "KVLease", "HandoffError", "LeaseExpired",
    "disagg_fleet_factory",
]
