"""Serving program builders: a small decoder-only transformer ("bert
decoder" — BERT-base geometry, causal masking) expressed twice over ONE
weight namespace:

  * `build_prefill_program` — whole-prompt forward (dense causal attention:
    with bucket padding on the right, every query position attends only to
    real tokens, so no pad bias is needed) that ALSO scatters each layer's
    K/V into the paged pool in-graph (`kv_cache_prefill_write`) and emits
    the greedy next token of the last real position. One XLA compile per
    prompt-length bucket (the PR 2 shape-bucketing convention).
  * `build_decode_program` — one ragged decode step: single query token per
    request row, `kv_cache_append` writes its K/V into the row's current
    page slot, `paged_decode_attention` attends over the row's page table,
    argmax emits the next token. One compile per (batch-bucket,
    page-count-bucket); padded rows ride the `batch_mask` row-mask
    convention from PR 2.
  * `build_full_forward_program` — the dense oracle (no cache, all-position
    logits) the equivalence tests replay generation against.

Every parameter name is explicit (no unique_name counters), so the three
programs resolve the SAME scope entries — prefill trains nothing, decode
reads what prefill's startup initialized (or what a checkpoint restored).
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import layers as L
from ..framework import default_main_program
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from .kv_cache import declare_pool_vars, pool_var_names

__all__ = ["DecoderConfig", "decoder_tiny", "build_prefill_program",
           "build_decode_program", "build_window_program",
           "build_full_forward_program", "apply_tp_annotations"]

# feed names shared by the engine and the programs
TOK_FEED = "sv_tok"
POS_FEED = "sv_pos"
PAGES_FEED = "sv_pages"
LEN_FEED = "sv_len"
START_FEED = "sv_start"   # first global slot of a prefill/verify window
MASK_FEED = "batch_mask"  # the PR 2 row-mask convention (data_feeder)
COW_SRC_FEED = "sv_cow_src"  # copy-on-write: source page id
COW_DST_FEED = "sv_cow_dst"  # copy-on-write: destination page id


@dataclass
class DecoderConfig:
    """Geometry of the served decoder (BERT-base shaped by default)."""

    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_size: int = 3072
    max_position: int = 512
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def decoder_tiny() -> DecoderConfig:
    return DecoderConfig(vocab_size=97, hidden_size=32, num_layers=2,
                         num_heads=2, ffn_size=64, max_position=64)


def _proj(x, size, name, act=None):
    return L.fc(x, size=size, num_flatten_dims=len(x.shape) - 1,
                param_attr=ParamAttr(name=name + ".w"),
                bias_attr=ParamAttr(name=name + ".b"), act=act)


def _ln(x, name):
    return L.layer_norm(x, begin_norm_axis=2,
                        param_attr=ParamAttr(name=name + ".scale"),
                        bias_attr=ParamAttr(name=name + ".bias"))


def _embed(tok, pos, cfg: DecoderConfig):
    emb = L.embedding(tok, size=[cfg.vocab_size, cfg.hidden_size],
                      param_attr=ParamAttr(name="dec.word_emb"),
                      dtype=cfg.dtype)
    pe = L.embedding(pos, size=[cfg.max_position, cfg.hidden_size],
                     param_attr=ParamAttr(name="dec.pos_emb"),
                     dtype=cfg.dtype)
    return _ln(L.elementwise_add(emb, pe), "dec.emb_ln")


def _ffn_block(x, cfg: DecoderConfig, name):
    h = _proj(x, cfg.ffn_size, name + ".ffn.in", act="gelu")
    f = _proj(h, cfg.hidden_size, name + ".ffn.out")
    return _ln(L.elementwise_add(x, f), name + ".ln2")


def _qkv_heads_seq(x, cfg: DecoderConfig, name):
    """[B, S, H] -> q, k, v each [B, nh, S, dh] (prefill / full forward)."""
    nh, dh = cfg.num_heads, cfg.head_dim
    qkv = _proj(x, 3 * cfg.hidden_size, name + ".qkv")
    qkv = L.reshape(qkv, shape=[0, 0, 3, nh, dh])
    qkv = L.transpose(qkv, perm=[2, 0, 3, 1, 4])       # [3, B, nh, S, dh]
    q = L.squeeze(L.slice(qkv, axes=[0], starts=[0], ends=[1]), axes=[0])
    k = L.squeeze(L.slice(qkv, axes=[0], starts=[1], ends=[2]), axes=[0])
    v = L.squeeze(L.slice(qkv, axes=[0], starts=[2], ends=[3]), axes=[0])
    return q, k, v


def _head(x, cfg: DecoderConfig):
    return _proj(x, cfg.vocab_size, "dec.lm_head")


def _greedy(logits_2d):
    return L.argmax(logits_2d, axis=1)


def _layer_names(i: int) -> str:
    return f"dec.layer{i}"


def _prefill_layer(x, i, cfg: DecoderConfig, pages, lens, write_cache: bool):
    name = _layer_names(i)
    nh, dh = cfg.num_heads, cfg.head_dim
    q, k, v = _qkv_heads_seq(x, cfg, name + ".mha")
    if write_cache:
        kn, vn = pool_var_names(cfg.num_layers)[i]
        helper = LayerHelper("kv_cache_prefill_write")
        helper.append_op(
            "kv_cache_prefill_write",
            {"KPool": [kn], "VPool": [vn], "K": [k], "V": [v],
             "PageTable": [pages], "Lens": [lens]},
            {"KPoolOut": [kn], "VPoolOut": [vn]}, {})
    ctxv = L.fused_attention(q, k, v, causal=True, sm_scale=dh ** -0.5)
    ctxv = L.reshape(L.transpose(ctxv, perm=[0, 2, 1, 3]),
                     shape=[0, 0, cfg.hidden_size])
    a = _proj(ctxv, cfg.hidden_size, name + ".mha.out")
    x = _ln(L.elementwise_add(x, a), name + ".ln1")
    return _ffn_block(x, cfg, name)


def build_prefill_program(cfg: DecoderConfig, num_pages: int, page_size: int):
    """Build (in the current default main program) the bucketed prefill.

    Feeds: sv_tok/sv_pos [B, S_bucket] int32, sv_pages [B, P] int32,
    sv_len [B] int32 (real prompt lengths — bucket padding past them is
    never written to the cache and, thanks to causal masking, never read by
    a real position). Fetch: next token ids [B] (greedy)."""
    tok = L.data(name=TOK_FEED, shape=[cfg.max_position], dtype="int32")
    pos = L.data(name=POS_FEED, shape=[cfg.max_position], dtype="int32")
    pages = L.data(name=PAGES_FEED, shape=[1], dtype="int32")
    lens = L.data(name=LEN_FEED, shape=[], dtype="int32")
    declare_pool_vars(default_main_program().global_block, cfg.num_layers,
                      num_pages, page_size, cfg.num_heads, cfg.head_dim,
                      cfg.dtype)
    x = _embed(tok, pos, cfg)
    for i in range(cfg.num_layers):
        x = _prefill_layer(x, i, cfg, pages, lens, write_cache=True)
    logits = _head(x, cfg)                             # [B, S, V]
    helper = LayerHelper("gather_token_logits")
    last = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op("gather_token_logits",
                     {"X": [logits], "Lens": [lens]}, {"Out": [last]}, {})
    nxt = _greedy(last)
    return {"feeds": [TOK_FEED, POS_FEED, PAGES_FEED, LEN_FEED],
            "next_token": nxt, "last_logits": last}


def _window_layer(x, i, cfg: DecoderConfig, pages, start, lens, tp: int):
    """One decoder layer over a WINDOW of S query tokens whose context lives
    in the paged pool: write the window's K/V at slots start+s (s < lens,
    local), then attend over the pool — cached prefix, fresh window and all.
    Shared by suffix prefill (ISSUE 11 prefix caching) and the speculative
    verify step (S = draft k + 1)."""
    name = _layer_names(i)
    dh = cfg.head_dim
    q, k, v = _qkv_heads_seq(x, cfg, name + ".mha")
    kn, vn = pool_var_names(cfg.num_layers)[i]
    helper = LayerHelper("kv_cache_prefill_write")
    helper.append_op(
        "kv_cache_prefill_write",
        {"KPool": [kn], "VPool": [vn], "K": [k], "V": [v],
         "PageTable": [pages], "Lens": [lens], "Start": [start]},
        {"KPoolOut": [kn], "VPoolOut": [vn]}, {})
    helper = LayerHelper("paged_prefill_attention")
    att = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "paged_prefill_attention",
        {"Q": [q], "KPool": [kn], "VPool": [vn],
         "PageTable": [pages], "Start": [start]},
        {"Out": [att]}, {"sm_scale": dh ** -0.5, "tp_degree": tp})
    ctxv = L.reshape(L.transpose(att, perm=[0, 2, 1, 3]),
                     shape=[0, 0, cfg.hidden_size])
    a = _proj(ctxv, cfg.hidden_size, name + ".mha.out")
    x = _ln(L.elementwise_add(x, a), name + ".ln1")
    return _ffn_block(x, cfg, name)


def build_window_program(cfg: DecoderConfig, num_pages: int, page_size: int,
                         tp: int = 1):
    """Build (in the current default main program) the windowed forward the
    two ISSUE 11 stages share:

      * suffix prefill — a prompt whose first Start slots are already in
        the pool (prefix-cache hit) runs ONLY its uncached suffix through
        the model; the window's K/V is appended at slots Start+s and the
        window attends over the whole pooled context, so the prefill
        compute drops from O(prompt) to O(suffix);
      * speculative verify — S = k+1 query tokens per row ([last_token,
        draft_1..draft_k]) in ONE batched step; `tokens` holds the greedy
        next token at every window position, which the engine compares
        against the drafts for exact greedy acceptance.

    Feeds: sv_tok/sv_pos [B, S] int32, sv_pages [B, P] int32, sv_start [B]
    int32 (global slot of window position 0), sv_len [B] int32 (valid LOCAL
    window positions; 0 = padded row, writes nothing). Fetches:
    `next_token` [B] (greedy token after local position Lens-1 — the suffix
    prefill's output), `tokens` [B, S] (greedy token after every window
    position — the verify output), `logits` [B, S, V] (the sampling
    suite's input)."""
    tok = L.data(name=TOK_FEED, shape=[cfg.max_position], dtype="int32")
    pos = L.data(name=POS_FEED, shape=[cfg.max_position], dtype="int32")
    pages = L.data(name=PAGES_FEED, shape=[1], dtype="int32")
    start = L.data(name=START_FEED, shape=[], dtype="int32")
    lens = L.data(name=LEN_FEED, shape=[], dtype="int32")
    declare_pool_vars(default_main_program().global_block, cfg.num_layers,
                      num_pages, page_size, cfg.num_heads, cfg.head_dim,
                      cfg.dtype)
    x = _embed(tok, pos, cfg)
    for i in range(cfg.num_layers):
        x = _window_layer(x, i, cfg, pages, start, lens, tp)
    logits = _head(x, cfg)                             # [B, S, V]
    helper = LayerHelper("gather_token_logits")
    last = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op("gather_token_logits",
                     {"X": [logits], "Lens": [lens]}, {"Out": [last]}, {})
    return {"feeds": [TOK_FEED, POS_FEED, PAGES_FEED, START_FEED, LEN_FEED],
            "next_token": _greedy(last),
            "last_logits": last,
            "tokens": L.argmax(logits, axis=2),
            "logits": logits}


def build_cow_program(cfg: DecoderConfig, num_pages: int, page_size: int):
    """Build (in the current default main program) the copy-on-write step:
    one `kv_cache_copy_page` per layer — pool[Dst] := pool[Src] for K and V,
    in place. Feeds sv_cow_src/sv_cow_dst [1] int32; fetches nothing (the
    pools are the output, via the donation contract). Compiled exactly once
    per engine — COW cost is one tiny device step, not a recompile."""
    src = L.data(name=COW_SRC_FEED, shape=[], dtype="int32")
    dst = L.data(name=COW_DST_FEED, shape=[], dtype="int32")
    declare_pool_vars(default_main_program().global_block, cfg.num_layers,
                      num_pages, page_size, cfg.num_heads, cfg.head_dim,
                      cfg.dtype)
    for kn, vn in pool_var_names(cfg.num_layers):
        helper = LayerHelper("kv_cache_copy_page")
        helper.append_op(
            "kv_cache_copy_page",
            {"KPool": [kn], "VPool": [vn], "Src": [src], "Dst": [dst]},
            {"KPoolOut": [kn], "VPoolOut": [vn]}, {})
    return {"feeds": [COW_SRC_FEED, COW_DST_FEED]}


def build_decode_program(cfg: DecoderConfig, num_pages: int, page_size: int,
                         tp: int = 1):
    """Build (in the current default main program) the ragged decode step.

    Feeds: sv_tok [B, 1] int32 (each row's latest token), sv_pos [B] int32
    (the slot that token occupies — the row's context length so far),
    sv_pages [B, P] int32, batch_mask [B, 1] float32 (0 rows are scheduler
    padding: their KV write is dropped and their output token ignored).
    Fetch: next token ids [B]."""
    tok = L.data(name=TOK_FEED, shape=[], dtype="int32")
    pos = L.data(name=POS_FEED, shape=[], dtype="int32")
    pages = L.data(name=PAGES_FEED, shape=[1], dtype="int32")
    mask = L.data(name=MASK_FEED, shape=[1], dtype="float32")
    declare_pool_vars(default_main_program().global_block, cfg.num_layers,
                      num_pages, page_size, cfg.num_heads, cfg.head_dim,
                      cfg.dtype)
    nh, dh = cfg.num_heads, cfg.head_dim
    # flat [B] ids (a [B, 1] feed would hit lookup_table's trailing-1 LoD
    # squeeze and come back 2-D); the singleton seq dim reappears after
    emb = L.embedding(tok, size=[cfg.vocab_size, cfg.hidden_size],
                      param_attr=ParamAttr(name="dec.word_emb"),
                      dtype=cfg.dtype)                 # [B, H]
    pe = L.embedding(pos, size=[cfg.max_position, cfg.hidden_size],
                     param_attr=ParamAttr(name="dec.pos_emb"),
                     dtype=cfg.dtype)
    x = L.unsqueeze(L.elementwise_add(emb, pe), axes=[1])   # [B, 1, H]
    x = _ln(x, "dec.emb_ln")
    for i in range(cfg.num_layers):
        name = _layer_names(i)
        qkv = _proj(x, 3 * cfg.hidden_size, name + ".mha.qkv")  # [B, 1, 3H]
        qkv = L.reshape(qkv, shape=[0, 3, nh, dh])
        q = L.squeeze(L.slice(qkv, axes=[1], starts=[0], ends=[1]), axes=[1])
        k = L.squeeze(L.slice(qkv, axes=[1], starts=[1], ends=[2]), axes=[1])
        v = L.squeeze(L.slice(qkv, axes=[1], starts=[2], ends=[3]), axes=[1])
        kn, vn = pool_var_names(cfg.num_layers)[i]
        helper = LayerHelper("kv_cache_append")
        helper.append_op(
            "kv_cache_append",
            {"KPool": [kn], "VPool": [vn], "K": [k], "V": [v],
             "PageTable": [pages], "Positions": [pos], "Mask": [mask]},
            {"KPoolOut": [kn], "VPoolOut": [vn]}, {})
        helper = LayerHelper("paged_decode_attention")
        att = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(
            "paged_decode_attention",
            {"Q": [q], "KPool": [kn], "VPool": [vn],
             "PageTable": [pages], "Positions": [pos]},
            {"Out": [att]}, {"sm_scale": dh ** -0.5, "tp_degree": tp})
        a = _proj(L.reshape(att, shape=[0, 1, cfg.hidden_size]),
                  cfg.hidden_size, name + ".mha.out")
        x = _ln(L.elementwise_add(x, a), name + ".ln1")
        x = _ffn_block(x, cfg, name)
    logits = L.squeeze(_head(x, cfg), axes=[1])        # [B, V]
    nxt = _greedy(logits)
    return {"feeds": [TOK_FEED, POS_FEED, PAGES_FEED, MASK_FEED],
            "next_token": nxt, "logits": logits}


# per-dim mesh-axis layout of the decoder's TP-sharded parameters
# (Megatron-style: qkv/ffn-in split their OUTPUT features, the projections
# back to hidden split their INPUT features so the row-parallel matmul's
# psum is the only collective per block). GSPMD treats these as layout
# hints, never correctness: an unannotated or oddly-divisible tensor simply
# replicates.
_TP_PARAM_LAYOUT = [
    (".mha.qkv.w", (None, "{tp}")), (".mha.qkv.b", ("{tp}",)),
    (".mha.out.w", ("{tp}", None)),
    (".ffn.in.w", (None, "{tp}")), (".ffn.in.b", ("{tp}",)),
    (".ffn.out.w", ("{tp}", None)),
]


def apply_tp_annotations(program, cfg: DecoderConfig, tp: int) -> int:
    """Annotate a built serving program's vars for tensor parallelism over
    the `tp` mesh axis (parallel/mesh.MODEL_AXIS): attention/FFN weights
    per _TP_PARAM_LAYOUT and the KV pool vars on their heads dim — the
    layout "Ragged Paged Attention" (arXiv:2604.15464) head-sharded decode
    assumes. Returns how many vars were annotated. Dims that `tp` does not
    divide are left replicated (GSPMD stays correct either way)."""
    from ..parallel.mesh import MODEL_AXIS
    from ..parallel.sharding import annotate_sharding

    done = 0
    block = program.global_block
    for name, var in block.vars.items():
        for suffix, spec in _TP_PARAM_LAYOUT:
            if not name.endswith(suffix):
                continue
            axes = tuple(MODEL_AXIS if a == "{tp}" else a for a in spec)
            if all(a is None or (var.shape[d] % tp == 0)
                   for d, a in enumerate(axes)):
                annotate_sharding(var, axes)
                done += 1
        if name.startswith("kv_cache.") and cfg.num_heads % tp == 0:
            annotate_sharding(var, (None, None, MODEL_AXIS, None))
            done += 1
    return done


def build_full_forward_program(cfg: DecoderConfig):
    """The dense no-cache oracle: feeds sv_tok/sv_pos [B, S], fetches the
    all-position logits [B, S, V]. Same weight names as the serving
    programs, so running it in the engine's scope replays generation
    exactly (tests, and the debugging path for kernel mismatches)."""
    tok = L.data(name=TOK_FEED, shape=[cfg.max_position], dtype="int32")
    pos = L.data(name=POS_FEED, shape=[cfg.max_position], dtype="int32")
    x = _embed(tok, pos, cfg)
    for i in range(cfg.num_layers):
        x = _prefill_layer(x, i, cfg, None, None, write_cache=False)
    return {"feeds": [TOK_FEED, POS_FEED], "logits": _head(x, cfg)}
