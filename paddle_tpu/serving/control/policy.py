"""Ridge-tier knob policy: regime -> proposed config, confidence-gated.

The trained artifact IS a tuning/learned model (MODEL_SCHEMA 1) — the
`serving.control|<device>` group sits next to `conv2d|cpu` in the same
JSON, trained by the same `tools/costmodel.py train` over the same store.
Rows record seconds-per-goodput-token (`median_s = 1 / goodput_tok_s`),
so `predict_times` + argmin — the exact kernel-tier call — picks the
highest-predicted-goodput knob config.

Tier semantics are PR 14's verbatim: a proposal STANDS only when the
group's holdout rank accuracy clears the floor (the stricter of the
model-wide RANK_ACC_FLOOR and FLAGS_serve_control_conf) and the regime's
features sit inside the trained envelope; everything else falls back to
the hand flags, counted by reason — an unseeded prior serves exactly the
config the operator flagged, never a guess.
"""
from __future__ import annotations

import os
import threading
import warnings

from ... import flags
from ... import observability as obs
from ...tuning import device_kind, learned
from . import knobs as _knobs
from . import regime as _regime

__all__ = ["CONTROL_OP", "mode", "model_path", "store_path", "get_model",
           "invalidate_model_cache", "propose", "record_row",
           "role_split_prior"]

CONTROL_OP = "serving.control"

_lock = threading.Lock()
_model_cache: tuple[str, float, dict | None] | None = None
_warned_paths: set[str] = set()


def mode() -> str:
    """FLAGS_serve_control_mode, normalized: off | shadow | apply."""
    m = str(flags.get_flag("serve_control_mode")).strip().lower()
    return m if m in ("off", "shadow", "apply") else "shadow"


def model_path() -> str | None:
    """FLAGS_serve_control_model, falling back to the tuning model path —
    the control group ships inside the same trained artifact unless the
    operator splits it out."""
    p = str(flags.get_flag("serve_control_model")).strip()
    return p or learned.model_path()


def store_path() -> str | None:
    """FLAGS_serve_control_store, falling back to the tuning measurement
    store — one append-only dataset for kernels and regimes alike."""
    p = str(flags.get_flag("serve_control_store")).strip()
    return p or learned.measurements_path()


def get_model() -> dict | None:
    """(path, mtime)-cached model load with the tuning-DB read discipline:
    missing file = no learned tier (silent), corrupt file = warn once and
    fail open to the hand flags. Own cache rather than learned.get_model()
    because FLAGS_serve_control_model may point somewhere else."""
    global _model_cache
    path = model_path()
    if not path:
        return None
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        mtime = -1.0
    with _lock:
        if _model_cache and _model_cache[0] == path \
                and _model_cache[1] == mtime:
            return _model_cache[2]
    try:
        m = learned.load_model(path)
    except ValueError as e:
        if path not in _warned_paths:
            _warned_paths.add(path)
            warnings.warn(
                f"serving control model {path!r} {e}; the learned "
                f"controller is disabled — serving the hand-flag config",
                stacklevel=3)
        m = None
    with _lock:
        _model_cache = (path, mtime, m)
    return m


def invalidate_model_cache() -> None:
    global _model_cache
    with _lock:
        _model_cache = None
        _warned_paths.clear()


def _fallback(reason: str, hand: dict) -> tuple[dict, dict]:
    obs.counter_inc("serving.control.fallbacks", labels={"reason": reason})
    obs.counter_inc("serving.control.proposals", labels={"tier": "hand"})
    return hand, {"tier": "hand", "reason": reason}


def _conf_floor() -> float:
    return max(learned.RANK_ACC_FLOOR,
               float(flags.get_flag("serve_control_conf")))


def propose(sig: dict, *, model: dict | None = None,
            dev: str | None = None) -> tuple[dict, dict]:
    """Propose a knob config for one regime signal dict. Returns
    (knobs, info): info["tier"] is "learned" when a gated prediction
    stood, else "hand" with the fallback reason — the PR 14 tier
    ordering with the hand flags playing the analytic prior's part."""
    hand = _knobs.hand_knobs()
    if mode() == "off":
        return hand, {"tier": "hand", "reason": "off"}
    m = model if model is not None else get_model()
    key = _regime.regime_key(sig)
    obs.gauge_set("serving.control.regime", _regime.regime_id(key))
    if m is None:
        return _fallback("no_model", hand)
    dev = dev or device_kind()
    group = m.get("groups", {}).get(f"{CONTROL_OP}|{dev}")
    if group is None:
        # regimes do not cross-device transfer: goodput under CPU load
        # says nothing about a TPU fleet, so a missing group is a
        # fallback, not a borrowed ranking
        return _fallback("no_group", hand)
    acc = (group.get("holdout") or {}).get("rank_acc")
    if acc is None or acc < _conf_floor():
        return _fallback("accuracy", hand)
    times, info = learned.predict_times(m, CONTROL_OP, key, "-", dev,
                                        gated=True)
    if times is None:
        return _fallback(info.get("reason", "unknown"), hand)
    arm = min(sorted(times), key=lambda a: times[a])
    proposed = _knobs.parse_knobs(arm)
    if proposed is None:
        return _fallback("arm_spelling", hand)
    obs.counter_inc("serving.control.proposals", labels={"tier": "learned"})
    return proposed, {"tier": "learned", "arm": arm, "regime": key,
                      "predicted_s_per_tok": times[arm], "rank_acc": acc,
                      "times": {a: float(t) for a, t in sorted(times.items())}}


def record_row(sig: dict, knob_cfg: dict, goodput_tok_s: float, *,
               source: str = "serve", extras: dict | None = None,
               tool: bool = False, path: str | None = None) -> bool:
    """Append one (regime, knob-config) -> goodput measurement. Stored as
    seconds per goodput token so smaller is better, like every other
    store row. Fail-open, under the store's recording discipline — but
    resolved against the CONTROL store ('off' stays absolute; 'auto'
    records from tools always, from the live controller only in
    sweep/explore runtime modes; a row needs SOME destination, which
    FLAGS_serve_control_store may provide when the tuning store has
    none)."""
    if goodput_tok_s <= 0:
        return False
    target = path or store_path()
    if not target:
        return False
    r = str(flags.get_flag("tuning_record")).strip().lower()
    if r == "off":
        return False
    if r != "on" and not tool:
        m = str(flags.get_flag("tuning_mode")).strip().lower()
        if m not in ("sweep", "explore"):
            return False
    return learned.record(
        CONTROL_OP, _regime.regime_key(sig), "-",
        device_kind(), _knobs.knob_key(knob_cfg),
        median_s=1.0 / float(goodput_tok_s), source=source,
        extras=extras, path=target)


def role_split_prior(n_replicas: int, *, records=None) -> tuple[int, dict]:
    """The disagg prefill:decode split, read from the store instead of the
    hand flag: among recorded fleet rows (pd > 0) the pd whose median
    goodput is best — accepted only when it beats the hand split's own
    recorded median by the near-tie band (a prior that cannot beat the
    flag it replaces defers to it). Falls back to
    FLAGS_disagg_prefill_replicas whenever the store is silent."""
    hand = int(flags.get_flag("disagg_prefill_replicas"))
    if mode() == "off":
        return hand, {"tier": "hand", "reason": "off"}
    if records is None:
        records = learned.iter_records(store_path())
    by_pd: dict[int, list[float]] = {}
    for rec in records:
        if rec.get("op") != CONTROL_OP:
            continue
        # only fleet rows of THIS fleet size compare: engine-level rows
        # (pd irrelevant) and other topologies measure different work
        if rec.get("fleet_n") != n_replicas:
            continue
        cfg = _knobs.parse_knobs(rec.get("arm", ""))
        t = rec.get("median_s")
        if not cfg or not isinstance(t, (int, float)) or t <= 0:
            continue
        by_pd.setdefault(cfg["pd"], []).append(float(t))
    scored = {pd: sorted(ts)[len(ts) // 2] for pd, ts in by_pd.items()
              if pd <= max(0, n_replicas - 1)}
    if not scored:
        return _role_fallback("no_rows", hand)
    best = min(sorted(scored), key=lambda pd: scored[pd])
    if best != hand and hand in scored \
            and scored[best] > scored[hand] * (1.0 - learned.model.RANK_TIE_BAND):
        return _role_fallback("tie_band", hand)
    if best == hand:
        return _role_fallback("hand_best", hand)
    obs.counter_inc("serving.control.proposals", labels={"tier": "learned"})
    return best, {"tier": "learned", "median_s": scored[best],
                  "candidates": {str(k): round(v, 6)
                                 for k, v in sorted(scored.items())}}


def _role_fallback(reason: str, hand: int) -> tuple[int, dict]:
    obs.counter_inc("serving.control.fallbacks", labels={"reason": reason})
    obs.counter_inc("serving.control.proposals", labels={"tier": "hand"})
    return hand, {"tier": "hand", "reason": reason}
