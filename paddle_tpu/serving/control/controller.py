"""Bounded online actuator: epoch ticks, safe-boundary application.

One Controller watches one or more engines. Per engine, per epoch
(FLAGS_serve_control_epoch_s), it:

  1. observes the live regime (regime.observe) and the REALIZED goodput
     since the previous tick (decode tokens / wall seconds);
  2. appends the realized measurement to the store (source="serve", under
     the store's recording gate) and scores it against the previous
     tick's prediction for the engine's current config — the
     serving.control.goodput_rel_err histogram is the controller grading
     its own prior;
  3. asks the ridge-tier policy for a proposal. Shadow mode stops here
     (propose + log, never apply — the default). Apply mode hands the
     proposal to `engine.propose_config`, which stages it as a PENDING
     config the engine adopts only at a safe boundary (no in-flight
     work), re-running `warmup_decode` when the bucket geometry moved.

The tick itself is one perf_counter read and a compare until an epoch is
due — the shadow-mode 0.0% overhead budget is won here, not claimed.
"""
from __future__ import annotations

import time

from ... import flags
from ... import observability as obs
from . import knobs as _knobs
from . import policy as _policy
from . import regime as _regime

__all__ = ["Controller", "engine_knobs"]


def engine_knobs(engine) -> dict:
    """The engine's CURRENT config as a knob dict (pd is fleet-level and
    spelled 0 — an engine does not know its fleet's role split)."""
    return {
        "mi": int(engine.max_inflight),
        "dk": int(engine.draft_k),
        "pc": int(engine.prefix_cache is not None),
        "sp": int(getattr(engine.scheduler, "policy", "fcfs") == "sjf"),
        "sq": int(engine.shed_queue_depth),
        "so": int(round(100 * float(engine.shed_occupancy))),
        "da": int(engine.degrade_after),
        "pd": 0,
    }


class Controller:
    def __init__(self, epoch_s: float | None = None):
        self.epoch_s = float(
            epoch_s if epoch_s is not None
            else flags.get_flag("serve_control_epoch_s"))
        self._next_t: dict[int, float] = {}
        self._win: dict[int, dict] = {}
        # last predicted sec/goodput-token per engine, keyed by the arm it
        # was predicted FOR — graded only while that arm is still serving
        self._pred: dict[int, tuple[str, float]] = {}
        self.last_cost: dict[int, float] = {}
        self.last_info: dict[int, dict] = {}

    def tick(self, engine, now: float | None = None) -> bool:
        """Cheap per-step hook: fires a controller epoch when one is due
        for this engine. Returns True when an epoch ran."""
        if self.epoch_s <= 0:
            return False
        now = time.perf_counter() if now is None else now
        eid = id(engine)
        due = self._next_t.get(eid)
        if due is None:
            # first sight of this engine: open the measurement window,
            # fire only after one full epoch of traffic exists to observe
            self._next_t[eid] = now + self.epoch_s
            self._win[eid] = {"t": now, "rid": engine._next_rid,
                              "tok": engine.stats["decode_tokens"]}
            return False
        if now < due:
            return False
        if _policy.mode() == "off":
            self._next_t[eid] = now + self.epoch_s
            return False
        self._epoch(engine, eid, now)
        self._next_t[eid] = now + self.epoch_s
        return True

    def _epoch(self, engine, eid: int, now: float) -> None:
        win = self._win.get(eid) or {"t": now, "rid": 0, "tok": 0}
        sig = _regime.observe(engine, window=win)
        current = engine_knobs(engine)
        cur_arm = _knobs.knob_key(current)
        dt = now - win.get("t", now)
        dtok = engine.stats["decode_tokens"] - win.get("tok", 0)
        realized = dtok / dt if dt > 0 and dtok > 0 else 0.0
        if realized > 0:
            _policy.record_row(sig, current, realized, source="serve",
                               extras={"live": True})
            pred = self._pred.get(eid)
            if pred and pred[0] == cur_arm and pred[1] > 0:
                rel = abs(pred[1] - 1.0 / realized) * realized
                obs.histogram_observe("serving.control.goodput_rel_err",
                                      rel)
        proposal, info = _policy.propose(sig)
        self.last_info[eid] = info
        times = info.get("times") or {}
        if cur_arm in times:
            self._pred[eid] = (cur_arm, float(times[cur_arm]))
            self.last_cost[eid] = float(times[cur_arm])
        elif realized > 0:
            self.last_cost[eid] = 1.0 / realized
        if _policy.mode() == "apply" and info.get("tier") == "learned":
            if engine.propose_config(proposal, source="controller"):
                obs.counter_inc("serving.control.staged")
        self._win[eid] = {"t": now, "rid": engine._next_rid,
                          "tok": engine.stats["decode_tokens"]}

    def forget(self, engine) -> None:
        """Drop a retired engine's cursors (fleet replacement churn)."""
        for d in (self._next_t, self._win, self._pred,
                  self.last_cost, self.last_info):
            d.pop(id(engine), None)
