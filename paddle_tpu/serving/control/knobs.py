"""The serving knob space and its canonical arm spelling.

A knob config is a flat int dict; its canonical spelling is the ARM name
under which the measurement store records it (`op="serving.control"`), so
"which config is fastest for this regime" is literally the kernel tier's
"which lowering is fastest for this shape" with different nouns.

Fields (all ints, fixed order):

  mi — max_inflight (decode batch ceiling)   dk — speculative draft k
  pc — prefix cache on/off                   sp — sched policy (0 fcfs, 1 sjf)
  sq — shed queue-depth floor (0 = off)      so — shed occupancy floor, %
  da — degrade_after (ladder patience)       pd — disagg prefill replicas

ONLINE-ACTUATABLE vs construction-only: mi/dk/sq/so/da can change on a
live engine (mi/dk change the decode bucket lattice, which is why the
actuator re-runs `warmup_decode`); pc and sp would rebuild live objects
(the cache trie, the scheduler) and pd re-roles a fleet — those three are
proposed and logged, but only honored at construction time.
"""
from __future__ import annotations

import itertools

import numpy as np

from ... import flags

__all__ = ["KNOB_FIELDS", "ACTUATABLE", "knob_key", "parse_knobs",
           "hand_knobs", "engine_kwargs", "sweep_arms"]

KNOB_FIELDS = ("mi", "dk", "pc", "sp", "sq", "so", "da", "pd")
ACTUATABLE = ("mi", "dk", "sq", "so", "da")

# sweep candidate values per field — a deliberately small lattice around
# the hand defaults (TVM's lesson: a bounded, structured space beats an
# open-ended one at this budget)
_SWEEP_SPACE = {
    "mi": (2, 4, 8),
    "dk": (0, 2),
    "pc": (1,),
    "sp": (0, 1),
    "sq": (4, 8, 16),
    "so": (90, 95),
    "da": (1, 2, 4),
    "pd": (0,),
}


def knob_key(knobs: dict) -> str:
    """Canonical arm spelling (field order fixed, every field spelled —
    two dicts describing one config cannot mint two arms)."""
    return " ".join(f"{f}={int(knobs.get(f, 0))}" for f in KNOB_FIELDS)


def parse_knobs(arm: str) -> dict | None:
    """Inverse of knob_key, fail-soft: None for a spelling that is not a
    knob arm (the store may hold foreign rows)."""
    out = {}
    try:
        for tok in str(arm).split():
            k, v = tok.split("=", 1)
            out[k] = int(v)
    except ValueError:
        return None
    return out if set(out) == set(KNOB_FIELDS) else None


def hand_knobs(**overrides) -> dict:
    """The hand-flag config as a knob dict — the fallback every
    confidence-gated proposal resolves to, and the reference arm every
    sweep measures alongside its candidates."""
    k = {
        "mi": int(flags.get_flag("serving_max_inflight")),
        "dk": int(flags.get_flag("serving_draft_k")),
        "pc": int(bool(flags.get_flag("serving_prefix_cache"))),
        "sp": int(str(flags.get_flag("serving_sched_policy")) == "sjf"),
        "sq": int(flags.get_flag("serving_shed_queue_depth")),
        "so": int(round(100 * float(
            flags.get_flag("serving_shed_occupancy")))),
        "da": int(flags.get_flag("serving_degrade_after")),
        "pd": int(flags.get_flag("disagg_prefill_replicas")),
    }
    k.update({f: int(v) for f, v in overrides.items()})
    return k


def engine_kwargs(knobs: dict) -> dict:
    """ServingEngine ctor kwargs for one knob config (pd is fleet-level
    and does not appear — the router consumes it)."""
    return {
        "max_inflight": int(knobs["mi"]),
        "draft_k": int(knobs["dk"]),
        "prefix_cache": bool(knobs["pc"]),
        "policy": "sjf" if knobs["sp"] else "fcfs",
        "shed_queue_depth": int(knobs["sq"]),
        "shed_occupancy": knobs["so"] / 100.0,
        "degrade_after": int(knobs["da"]),
    }


def sweep_arms(n: int, seed: int = 0, include: dict | None = None) -> list:
    """`n` knob configs to sweep: a seeded latin-hypercube-style draw from
    the candidate lattice (deterministic for a given (n, seed)), with
    `include` (the hand config, typically) always first so every regime
    measures the reference arm. Returns knob dicts, no duplicates."""
    grid = [dict(zip(_SWEEP_SPACE, combo))
            for combo in itertools.product(*_SWEEP_SPACE.values())]
    grid.sort(key=knob_key)
    rng = np.random.default_rng(seed)
    picked: list[dict] = []
    seen: set[str] = set()
    if include is not None:
        picked.append(dict(include))
        seen.add(knob_key(include))
    # stratify the draw over mi (the dominant axis) so a small n still
    # spans the batch-geometry range instead of clustering by chance
    by_mi: dict[int, list] = {}
    for g in grid:
        by_mi.setdefault(g["mi"], []).append(g)
    lanes = [by_mi[m] for m in sorted(by_mi)]
    all_keys = {knob_key(g) for g in grid}
    li = 0
    while len(picked) < n and not all_keys <= seen:
        lane = lanes[li % len(lanes)]
        li += 1
        order = rng.permutation(len(lane))
        for i in order:
            k = knob_key(lane[int(i)])
            if k not in seen:
                seen.add(k)
                picked.append(dict(lane[int(i)]))
                break
    return picked[:max(1, n)]
