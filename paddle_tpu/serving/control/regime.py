"""Traffic-regime featurizer: one canonical spelling per serving regime.

The learned controller keys the measurement store the way the kernel tier
keys it: `op="serving.control"`, a canonical `shape_key` naming the TRAFFIC
REGIME, and the knob-config spelling as the arm. This module owns that
spelling. Two producers write it:

  * the offline sweep (`tools/_serve_ab.py --sweep-knobs`) spells the
    regime from the WORKLOAD INTENT (arrival rate, prompt-length
    percentiles, output budget) plus the runtime signals observed under
    the hand-flag reference pass — every knob arm of one regime then
    shares one key, which is what lets the ridge fit rank arms at all;
  * the live controller (`controller.py`) spells it from a running
    engine's registry-backed stats between two epoch ticks.

Signals are BUCKETED before spelling (pow2 lengths/queues, 5-point
percent ratios): a regime key is a coarse address, and the ridge
generalizes across the gaps — exact key reuse is a bonus, not a
requirement (the arXiv:2008.01040 framing, unchanged).

Every ratio is spelled as a percent int so the key round-trips through
`tuning/learned/features.parse_shape_key` like every other canonical
shape spelling.
"""
from __future__ import annotations

import math
import zlib

__all__ = ["REGIME_FIELDS", "regime_key", "regime_id", "bucket_signals",
           "parse_regime", "workload_signals", "observe"]

# canonical field order of the regime spelling (all integer-valued):
#   rate — offered arrivals/s            p50/p95 — prompt-length percentiles
#   out  — median output budget          hit     — prefix-cache hit %
#   occ  — pool occupancy %              q       — waiting-queue depth
#   hr   — TTFT/SLO headroom % (100 = no SLO pressure / no floor armed)
REGIME_FIELDS = ("rate", "p50", "p95", "out", "hit", "occ", "q", "hr")


def _pow2(x: float) -> int:
    x = max(0, int(round(x)))
    return 0 if x == 0 else 1 << max(0, math.ceil(math.log2(max(1, x))))


def _pct5(x: float) -> int:
    """Ratios quantize to 5-point percent buckets — coarse enough that one
    noisy pass does not mint a fresh regime, fine enough to separate an
    idle pool from a saturated one."""
    return int(5 * round(20.0 * min(max(float(x), 0.0), 1.0)))


def bucket_signals(sig: dict) -> dict:
    """Raw signal dict -> bucketed integer dict in REGIME_FIELDS order."""
    return {
        "rate": max(1, int(round(float(sig.get("rate", 1.0))))),
        "p50": _pow2(sig.get("p50", 1)),
        "p95": _pow2(sig.get("p95", 1)),
        "out": _pow2(sig.get("out", 1)),
        "hit": _pct5(sig.get("hit", 0.0)),
        "occ": _pct5(sig.get("occ", 0.0)),
        "q": _pow2(sig.get("q", 0)),
        "hr": _pct5(sig.get("hr", 1.0)),
    }


def regime_key(sig: dict) -> str:
    """The canonical shape_key spelling for one (raw or bucketed) signal
    dict — the store/featurizer address of this traffic regime."""
    b = bucket_signals(sig)
    return " ".join(f"{f}={b[f]}" for f in REGIME_FIELDS)


def parse_regime(key: str) -> dict | None:
    """Inverse of regime_key, fail-soft: the bucketed spelling back to a
    raw signal dict (percent fields back to fractions), such that
    regime_key(parse_regime(k)) == k — the CLI and the gate re-enter the
    policy through the same spelling the store recorded."""
    out: dict = {}
    try:
        for tok in str(key).split():
            f, v = tok.split("=", 1)
            out[f] = int(v)
    except ValueError:
        return None
    if set(out) != set(REGIME_FIELDS):
        return None
    for f in ("hit", "occ", "hr"):
        out[f] = out[f] / 100.0
    return out


def regime_id(key: str) -> int:
    """Stable small int for the serving.control.regime gauge (crc32 bucket
    — the dashboards need 'did the regime change', not the spelling)."""
    return zlib.crc32(key.encode()) % 10_000


def _percentile(xs, frac: float) -> float:
    if not xs:
        return 1.0
    xs = sorted(xs)
    return float(xs[min(len(xs) - 1, int(frac * len(xs)))])


def workload_signals(reqs, rate: float, *, hit: float = 0.0,
                     occ: float = 0.0, q: int = 0, hr: float = 1.0) -> dict:
    """Regime signals from a workload INTENT: `reqs` is the seeded arrival
    list ((t, prompt, max_new) tuples) a `_serve_ab` sweep is about to
    offer. Runtime signals default to the quiet values unless the caller
    measured them (the sweep passes the hand-flag reference pass's)."""
    plens = [len(p) for _, p, _ in reqs]
    outs = [int(mn) for _, _, mn in reqs]
    return {"rate": rate, "p50": _percentile(plens, 0.50),
            "p95": _percentile(plens, 0.95),
            "out": _percentile(outs, 0.50),
            "hit": hit, "occ": occ, "q": q, "hr": hr}


def observe(engine, *, window: dict | None = None) -> dict:
    """Regime signals from a LIVE engine. `window` is the controller's
    previous-tick cursor ({"t": perf_counter, "rid": next_rid}) so the
    arrival rate is the rate over the last epoch, not over the engine's
    lifetime; without one the rate falls back to 1/s (boot regime).

    Reads only what the engine already tracks — stats counters, the
    request table, the pool — so an observation is a handful of dict
    reads, cheap enough for the shadow-mode 0% overhead budget."""
    import time

    now = time.perf_counter()
    st = engine.stats
    denom = st["prefix_hit_tokens"] + st["prefill_tokens_computed"]
    hit = st["prefix_hit_tokens"] / denom if denom else 0.0
    occ = engine.pool.pages_in_use / engine.pool.num_pages
    q = len(engine._waiting)
    rate = 1.0
    if window and now > window.get("t", now):
        rate = max(0.0, (engine._next_rid - window.get("rid", 0))
                   / (now - window["t"]))
    reqs = list(engine.requests.values())[-64:]
    plens = [r.prompt_len for r in reqs]
    outs = [r.max_new_tokens for r in reqs]
    hr = 1.0
    floor_ms = getattr(engine, "shed_ttft_p99_ms", 0.0)
    if floor_ms and floor_ms > 0:
        # headroom under an armed TTFT floor: tripped floor = 0 headroom
        hr = 0.0 if engine._overload_signals().get("ttft_p99_s") else 0.5
    return {"rate": rate, "p50": _percentile(plens, 0.50),
            "p95": _percentile(plens, 0.95),
            "out": _percentile(outs, 0.50),
            "hit": hit, "occ": occ, "q": q, "hr": hr}
