"""Learned serving control (ISSUE 20, ROADMAP item 5).

The kernel cost-model loop (store -> ridge prior -> confidence-gated
decision, PRs 14-17) generalized from (op-shape, kernel-arm) -> runtime
to (traffic-regime, knob-config) -> goodput:

  regime.py     — the regime featurizer: arrival rate, prompt/output
                  percentiles, prefix-hit rate, occupancy, queue depth,
                  SLO headroom, folded into one canonical spelling;
  knobs.py      — the knob space (batch geometry, draft k, shed floors,
                  sched policy, prefill:decode split) and its canonical
                  arm spelling;
  policy.py     — the ridge-tier proposal over the shared measurement
                  store/model, hand flags as the gated fallback;
  controller.py — the bounded online actuator: epoch ticks, shadow vs
                  apply, safe-boundary staging via engine.propose_config.

Modes (FLAGS_serve_control_mode): `off` — hand flags, no observation;
`shadow` (default) — observe regimes, propose, log and count, never
touch a knob; `apply` — stage confident proposals for adoption at the
next idle gap / epoch boundary.
"""
from __future__ import annotations

from . import controller, knobs, policy, regime
from .controller import Controller, engine_knobs
from .knobs import (ACTUATABLE, KNOB_FIELDS, engine_kwargs, hand_knobs,
                    knob_key, parse_knobs, sweep_arms)
from .policy import (CONTROL_OP, get_model, invalidate_model_cache, mode,
                     model_path, propose, record_row, role_split_prior,
                     store_path)
from .regime import (REGIME_FIELDS, bucket_signals, observe, parse_regime,
                     regime_id, regime_key, workload_signals)

__all__ = [
    "controller", "knobs", "policy", "regime",
    "Controller", "engine_knobs",
    "ACTUATABLE", "KNOB_FIELDS", "engine_kwargs", "hand_knobs", "knob_key",
    "parse_knobs", "sweep_arms",
    "CONTROL_OP", "get_model", "invalidate_model_cache", "mode",
    "model_path", "propose", "record_row", "role_split_prior", "store_path",
    "REGIME_FIELDS", "bucket_signals", "observe", "parse_regime",
    "regime_id", "regime_key",
    "workload_signals",
]
