"""Sampling suite for the serving engine: temperature / top-k / top-p.

Sampling runs on the HOST over the decode step's fetched logits row, not
inside the compiled graph, for one load-bearing reason: determinism across
batch-bucket recompiles. An in-graph PRNG would key off the padded batch
shape, so the same request would draw different tokens depending on who it
happened to be batched with. Here every (engine seed, request id, token
index) triple owns its own numpy Generator stream, so a request's token
sequence is a pure function of its own identity — replayable across runs,
engine restarts, and whatever bucket the scheduler packed it into.

Greedy (temperature <= 0) stays the engine's compiled argmax path; sampling
requests read the same step's `logits` fetch.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["SamplingParams", "sample_token", "request_rng"]


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode distribution controls.

    temperature <= 0 means greedy (argmax; the speculative-decode fast
    path). top_k <= 0 disables the top-k filter; top_p >= 1 disables the
    nucleus filter. Filters compose in the standard order:
    logits/temperature -> top-k -> top-p -> renormalize -> sample.
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError(f"temperature must be >= 0, got "
                             f"{self.temperature}")
        if not (0.0 < self.top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")

    @property
    def is_greedy(self) -> bool:
        return self.temperature <= 0.0


def request_rng(seed: int, rid: int, token_index: int) -> np.random.Generator:
    """The deterministic per-token stream: distinct (seed, rid, index)
    triples give independent streams, identical triples identical draws —
    the whole determinism contract in one constructor."""
    return np.random.default_rng(
        np.random.SeedSequence((int(seed), int(rid), int(token_index))))


def sample_token(logits, params: SamplingParams,
                 rng: np.random.Generator) -> int:
    """Draw one token id from a [V] logits row under `params`."""
    logits = np.asarray(logits, np.float64).reshape(-1)
    if params.is_greedy:
        return int(np.argmax(logits))
    z = logits / max(params.temperature, 1e-6)
    if params.top_k and params.top_k < z.size:
        kth = np.partition(z, -params.top_k)[-params.top_k]
        z = np.where(z >= kth, z, -np.inf)
    # softmax in float64 (host-side; V rows are small next to the model)
    z = z - z.max()
    probs = np.exp(z)
    probs /= probs.sum()
    if params.top_p < 1.0:
        order = np.argsort(-probs, kind="stable")
        csum = np.cumsum(probs[order])
        # smallest prefix whose mass reaches top_p (always >= 1 token)
        cut = int(np.searchsorted(csum, params.top_p)) + 1
        keep = order[:cut]
        mask = np.zeros_like(probs)
        mask[keep] = probs[keep]
        probs = mask / mask.sum()
    return int(rng.choice(probs.size, p=probs))
