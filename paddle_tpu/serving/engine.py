"""Continuous-batching serving engine over the paged KV cache.

The scheduling loop the "millions of users" scenario needs (ROADMAP item 1):
requests arrive at any time, and the engine admits/evicts them BETWEEN
decode steps instead of running fixed generation batches:

    step():  (maybe) inject a chaos abort -> admit waiting requests while
             pages + inflight slots allow (prefill each, bucketed) ->
             grow/allocate pages for the next token slot (preempting the
             youngest request on pool exhaustion) -> one ragged decode step
             over ALL running requests -> retire finished rows.

Compile discipline (the PR 2 machinery doing serving duty):
  * prefill compiles once per prompt-length bucket (pow2 rounding, the
    shape-bucketing convention);
  * decode compiles once per (batch-bucket, page-count-bucket) — rows are
    padded up to the batch bucket and masked with the `batch_mask` row-mask
    convention, page tables padded to the page bucket (masked by length);
  * `stats["prefill_signatures"]/["decode_signatures"]` record exactly which
    buckets compiled, so tests can assert the open-loop run compiled decode
    at most once per bucket (via pipeline.jit_compile_counter).

Failure/backpressure semantics:
  * admission backpressure: a request whose context needs more pages than
    the free list holds (or when max_inflight is reached) WAITS — the pool
    can never be oversubscribed;
  * mid-decode growth: when a running request crosses a page boundary and
    the pool is dry, the YOUNGEST running request is preempted back to the
    waiting queue (pages freed; on re-admission its prompt+generated prefix
    is re-prefilled — recompute-style preemption, exact under greedy
    decoding);
  * abort (client gone, or the `serving_abort` chaos fault site): the
    request's pages return to the free list immediately — the
    zero-leak invariant the chaos test pins down.
"""
from __future__ import annotations

import time

import numpy as np

from .. import flags, unique_name
from ..data_feeder import _round_up_pow2
from ..executor import Executor, Scope
from ..framework import Program, program_guard
from ..resilience.faults import InjectedFault, fault_point
from . import model as sv_model
from .kv_cache import PagedKVPool, create_device_pools

__all__ = ["GenRequest", "ContinuousBatchingScheduler", "ServingEngine"]

WAITING, RUNNING, FINISHED, ABORTED = "waiting", "running", "finished", "aborted"


class GenRequest:
    """One generate request's lifetime.

    `all_tokens` is the full sequence so far (prompt + generated); the KV
    cache always holds exactly len(all_tokens) - 1 slots while RUNNING (the
    newest token's KV is written by the decode step that consumes it). On
    preemption the pages are dropped and the whole prefix re-prefills — no
    separate bookkeeping for "how much cache survived".
    """

    def __init__(self, rid: int, prompt, max_new_tokens: int, eos_id=None):
        if not len(prompt):
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.rid = rid
        self.prompt_len = len(prompt)
        self.all_tokens: list[int] = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.state = WAITING
        self.pages: list[int] = []
        self.admit_seq = -1  # admission order; preemption evicts the newest
        self.preemptions = 0
        self.arrival_t = time.perf_counter()
        self.t_first_token: float | None = None
        self.t_done: float | None = None

    @property
    def n_generated(self) -> int:
        return len(self.all_tokens) - self.prompt_len

    @property
    def out_tokens(self) -> list[int]:
        return self.all_tokens[self.prompt_len:]

    @property
    def cache_len(self) -> int:
        """Valid KV slots while RUNNING (last token not yet appended)."""
        return len(self.all_tokens) - 1

    def is_done(self) -> bool:
        return (self.n_generated >= self.max_new_tokens
                or (self.eos_id is not None and self.n_generated > 0
                    and self.all_tokens[-1] == self.eos_id))


class ContinuousBatchingScheduler:
    """Admission ordering policy over the waiting queue."""

    def __init__(self, policy: str):
        if policy not in ("fcfs", "sjf"):
            raise ValueError(f"unknown FLAGS_serving_sched_policy "
                             f"'{policy}' (fcfs | sjf)")
        self.policy = policy

    def order(self, waiting: list[GenRequest]) -> list[GenRequest]:
        if self.policy == "sjf":
            # stable sort: equal lengths keep arrival order
            return sorted(waiting, key=lambda r: len(r.all_tokens))
        return list(waiting)


class ServingEngine:
    """Paged-KV continuous-batching runtime for one decoder model.

    Single-threaded by design (one scheduler loop owns the pool and the
    scope); the parallelism is inside the compiled steps.
    """

    def __init__(self, cfg: "sv_model.DecoderConfig | None" = None,
                 page_size: int | None = None,
                 pool_pages: int | None = None,
                 max_inflight: int | None = None,
                 policy: str | None = None,
                 seed: int = 0):
        self.cfg = cfg or sv_model.decoder_tiny()
        self.page_size = int(page_size
                             or flags.get_flag("serving_page_size"))
        self.pool_pages = int(pool_pages
                              or flags.get_flag("serving_pool_pages"))
        self.max_inflight = int(max_inflight
                                or flags.get_flag("serving_max_inflight"))
        self.scheduler = ContinuousBatchingScheduler(
            policy or str(flags.get_flag("serving_sched_policy")))
        self.pool = PagedKVPool(self.pool_pages, self.page_size)
        self._exe = Executor()
        self._scope = Scope()

        self._prefill_prog = Program()
        self._decode_prog = Program()
        startup = Program()
        decoy_startup = Program()  # decode re-declares params; inits unused
        self._prefill_prog.random_seed = startup.random_seed = int(seed)
        with program_guard(self._prefill_prog, startup), \
                unique_name.guard():
            self._prefill_io = sv_model.build_prefill_program(
                self.cfg, self.pool_pages, self.page_size)
        with program_guard(self._decode_prog, decoy_startup), \
                unique_name.guard():
            self._decode_io = sv_model.build_decode_program(
                self.cfg, self.pool_pages, self.page_size)
        self._exe.run(startup, scope=self._scope)
        create_device_pools(self._scope, self.cfg.num_layers,
                            self.pool_pages, self.page_size,
                            self.cfg.num_heads, self.cfg.head_dim,
                            self.cfg.dtype)

        self.requests: dict[int, GenRequest] = {}
        self._waiting: list[GenRequest] = []
        self._running: list[GenRequest] = []
        self._next_rid = 0
        self._admit_seq = 0
        self.stats = {
            "prefills": 0, "decode_steps": 0, "decode_tokens": 0,
            "preemptions": 0, "aborts": 0,
            "prefill_signatures": set(), "decode_signatures": set(),
            "peak_pages_in_use": 0, "occupancy_sum": 0.0, "occupancy_n": 0,
        }

    # -- client API ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, eos_id=None) -> int:
        if len(prompt) + max_new_tokens > self.cfg.max_position:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_position {self.cfg.max_position}")
        rid = self._next_rid
        self._next_rid += 1
        req = GenRequest(rid, prompt, max_new_tokens, eos_id)
        self.requests[rid] = req
        self._waiting.append(req)
        return rid

    def abort(self, rid: int) -> None:
        """Drop a request wherever it is; its pages return to the free list
        immediately (the zero-leak contract the chaos test asserts)."""
        req = self.requests.get(rid)
        if req is None or req.state in (FINISHED, ABORTED):
            return
        if req in self._waiting:
            self._waiting.remove(req)
        if req in self._running:
            self._running.remove(req)
        self._release(req)
        req.state = ABORTED
        req.t_done = time.perf_counter()
        self.stats["aborts"] += 1

    def has_work(self) -> bool:
        return bool(self._waiting or self._running)

    def result(self, rid: int) -> list[int]:
        return list(self.requests[rid].out_tokens)

    def run_until_drained(self, max_steps: int = 100_000) -> None:
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"serving loop made no exit after {max_steps} steps "
                    f"(waiting={len(self._waiting)} "
                    f"running={len(self._running)})")

    # -- the scheduler iteration --------------------------------------------
    def step(self) -> bool:
        """One continuous-batching iteration; returns True if any request
        made progress (admitted or decoded a token)."""
        try:
            fault_point("serving_abort")
        except InjectedFault:
            # chaos: the oldest running request's client vanished mid-decode
            victim = self._running[0] if self._running else (
                self._waiting[0] if self._waiting else None)
            if victim is not None:
                self.abort(victim.rid)
        admitted = self._admit()
        decoded = self._decode_once() if self._running else False
        if not decoded and not admitted and self._waiting:
            need = min(self.pool.pages_for(len(r.all_tokens) + 1)
                       for r in self._waiting)
            if need > self.pool.num_pages:
                raise RuntimeError(
                    f"request needs {need} pages but the pool only has "
                    f"{self.pool.num_pages} (FLAGS_serving_pool_pages / "
                    f"FLAGS_serving_page_size)")
            if not self._running:
                raise RuntimeError(
                    "admission stuck: no running requests to free pages, "
                    f"yet {len(self._waiting)} waiting (free "
                    f"{self.pool.free_count}/{self.pool.num_pages} pages)")
        self._note_occupancy()
        return bool(admitted or decoded)

    # -- internals ----------------------------------------------------------
    def _release(self, req: GenRequest) -> None:
        if req.pages:
            self.pool.free(req.pages)
            req.pages = []

    def _note_occupancy(self) -> None:
        used = self.pool.pages_in_use
        self.stats["peak_pages_in_use"] = max(
            self.stats["peak_pages_in_use"], used)
        self.stats["occupancy_sum"] += used / self.pool.num_pages
        self.stats["occupancy_n"] += 1

    def _admit(self) -> int:
        """Admit waiting requests in policy order until pages or inflight
        slots run out. Head-of-line backpressure: the first request that
        does not fit stops admission (no starvation of big requests by
        later small ones under fcfs)."""
        admitted = 0
        for req in self.scheduler.order(self._waiting):
            if len(self._running) >= self.max_inflight:
                break
            # +1: the decode step after prefill writes one more slot
            need = self.pool.pages_for(len(req.all_tokens) + 1)
            pages = self.pool.allocate(need)
            if pages is None:
                break
            req.pages = pages
            self._waiting.remove(req)
            req.admit_seq = self._admit_seq
            self._admit_seq += 1
            self._prefill(req)
            admitted += 1
        return admitted

    def _seq_bucket(self, n: int) -> int:
        return min(self.cfg.max_position, max(8, _round_up_pow2(n)))

    def _prefill(self, req: GenRequest) -> None:
        """Run the bucketed prefill for one request: writes its context's
        K/V into its pages and produces its first new token."""
        n = len(req.all_tokens)
        sb = self._seq_bucket(n)
        pb = max(len(req.pages), self.pool.pages_for(sb))
        tok = np.zeros((1, sb), np.int32)
        tok[0, :n] = req.all_tokens
        pos = np.arange(sb, dtype=np.int32)[None, :]
        pos = np.minimum(pos, self.cfg.max_position - 1)
        pages = np.zeros((1, pb), np.int32)
        pages[0, :len(req.pages)] = req.pages
        feed = {sv_model.TOK_FEED: tok, sv_model.POS_FEED: pos,
                sv_model.PAGES_FEED: pages,
                sv_model.LEN_FEED: np.asarray([n], np.int32)}
        (nxt,) = self._exe.run(self._prefill_prog, feed=feed,
                               fetch_list=[self._prefill_io["next_token"]],
                               scope=self._scope)
        req.state = RUNNING
        self._running.append(req)
        self.stats["prefills"] += 1
        self.stats["prefill_signatures"].add((sb, pb))
        self._accept_token(req, int(np.asarray(nxt).reshape(-1)[0]))

    def _accept_token(self, req: GenRequest, tok: int) -> None:
        req.all_tokens.append(tok)
        now = time.perf_counter()
        if req.t_first_token is None:
            req.t_first_token = now
        if req.is_done() or len(req.all_tokens) >= self.cfg.max_position:
            if req in self._running:
                self._running.remove(req)
            self._release(req)
            req.state = FINISHED
            req.t_done = now

    def _ensure_pages(self) -> None:
        """Every running request must own the page its next slot lands in;
        on pool exhaustion preempt the youngest (recompute-style)."""
        for req in list(self._running):
            if req.state != RUNNING:
                continue
            while req.cache_len // self.page_size >= len(req.pages):
                got = self.pool.allocate(1)
                if got is not None:
                    req.pages.extend(got)
                    continue
                victim = max(self._running, key=lambda r: r.admit_seq)
                if victim is req and len(self._running) == 1:
                    raise RuntimeError(
                        f"request {req.rid} needs page "
                        f"{len(req.pages) + 1} but the pool "
                        f"({self.pool.num_pages} pages) is exhausted with "
                        f"nothing left to preempt")
                self._preempt(victim)
                if victim is req:
                    break

    def _preempt(self, req: GenRequest) -> None:
        self._running.remove(req)
        self._release(req)
        req.state = WAITING
        req.preemptions += 1
        self.stats["preemptions"] += 1
        # head of the waiting queue: a preempted request lost work, so it
        # outranks new arrivals under fcfs
        self._waiting.insert(0, req)

    def _decode_once(self) -> bool:
        self._ensure_pages()
        rows = [r for r in self._running if r.state == RUNNING]
        if not rows:
            return False
        bb = min(_round_up_pow2(len(rows)), _round_up_pow2(self.max_inflight))
        pb = _round_up_pow2(max(len(r.pages) for r in rows))
        tok = np.zeros((bb, 1), np.int32)
        pos = np.zeros((bb,), np.int32)
        pages = np.zeros((bb, pb), np.int32)
        mask = np.zeros((bb, 1), np.float32)
        for i, r in enumerate(rows):
            tok[i, 0] = r.all_tokens[-1]
            pos[i] = r.cache_len
            pages[i, :len(r.pages)] = r.pages
            mask[i, 0] = 1.0
        feed = {sv_model.TOK_FEED: tok, sv_model.POS_FEED: pos,
                sv_model.PAGES_FEED: pages, sv_model.MASK_FEED: mask}
        (nxt,) = self._exe.run(self._decode_prog, feed=feed,
                               fetch_list=[self._decode_io["next_token"]],
                               scope=self._scope)
        nxt = np.asarray(nxt).reshape(-1)
        self.stats["decode_steps"] += 1
        self.stats["decode_signatures"].add((bb, pb))
        for i, r in enumerate(rows):
            self.stats["decode_tokens"] += 1
            self._accept_token(r, int(nxt[i]))
        return True
