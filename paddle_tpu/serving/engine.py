"""Continuous-batching serving engine over the paged KV cache.

The scheduling loop the "millions of users" scenario needs (ROADMAP item 2):
requests arrive at any time, and the engine admits/evicts them BETWEEN
decode steps instead of running fixed generation batches:

    step():  (maybe) inject a chaos abort -> admit waiting requests while
             pages + inflight slots allow (prefix-cache hits map shared
             pages, then prefill ONLY the uncached suffix, bucketed) ->
             grow/allocate/copy-on-write pages for the next write window
             (preempting the youngest request on pool exhaustion) -> one
             ragged decode step over ALL running requests (a k-token
             draft-verify window when speculative decoding is on) ->
             retire finished rows.

Multi-tenant machinery (ISSUE 11), three composable stages:
  * PREFIX CACHING — prompts are indexed at page granularity
    (kv_cache.PrefixCache); a new request maps every cached full page of
    its prompt with a refcount bump (`PagedKVPool.share`) and prefills only
    the suffix through the windowed program (model.build_window_program).
    Shared pages are immutable: the first write past the shared boundary
    (e.g. a fully-cached prompt's first generated token re-writing the last
    prompt slot) triggers COPY-ON-WRITE — a fresh page, one in-place
    `kv_cache_copy_page` step, and the writer's table repointed, everyone
    else untouched.
  * SPECULATIVE DECODING — with FLAGS_serving_draft_k > 0 each decode step
    self-drafts k tokens per row (n-gram continuation of the request's own
    history) and verifies all k+1 positions in ONE batched window step;
    the greedy tokens the verify emits are accepted up to the first draft
    mismatch, so the result is EXACTLY the plain greedy sequence — only
    (potentially) several tokens per step instead of one. Rejected drafts
    cost nothing to roll back: their KV slots sit past the new context
    length and are overwritten before they can ever be attended.
  * TENSOR PARALLELISM — with tp > 1 the engine builds its programs over a
    `tp` mesh (parallel/mesh.make_tp_mesh): attention heads and the KV pool
    shard across the axis (model.apply_tp_annotations), and
    `paged_decode_attention` keys the tuning DB on the PER-SHARD shape
    (nh/tp) so TP decode resolves through the same swept verdicts as every
    other lever.

Compile discipline (the PR 2 machinery doing serving duty):
  * prefill compiles once per prompt-length bucket (pow2 rounding); suffix
    prefill once per (suffix-bucket, page-bucket);
  * decode compiles once per (batch-bucket, page-count-bucket) — rows are
    padded up to the batch bucket and masked with the `batch_mask` row-mask
    convention (the verify window masks via zero valid-lengths instead);
  * `stats["prefill_signatures"]/["decode_signatures"]` record exactly which
    buckets compiled, so tests can assert the open-loop run compiled decode
    at most once per bucket (via pipeline.jit_compile_counter).

Failure/backpressure semantics:
  * admission backpressure: a request whose context needs more private
    pages than the free list holds (after evicting unshared prefix-cache
    pages, LRU-first) WAITS — the pool can never be oversubscribed;
  * mid-decode growth: when a running request crosses a page boundary and
    the pool is dry, the YOUNGEST running request is preempted back to the
    waiting queue (its refcounts released; on re-admission its
    prompt+generated prefix re-prefills past whatever the prefix cache
    still holds — recompute-style preemption, exact under greedy decoding);
  * abort (client gone, or the `serving_abort` chaos fault site): the
    request's refcounts release immediately; pages nobody else maps return
    to the free list — the zero-leak invariant the chaos test pins down.

Resilience layer (ISSUE 14 — see README "Serving resilience"):
  * DEADLINES — a per-request TTL checked at admission and between decode
    steps; an expired request keeps its partial tokens, returns every page,
    and finishes in the distinct `deadline_exceeded` terminal state;
  * ADMISSION CONTROL — when pool occupancy / queue depth / p99 TTFT (read
    through the SloMonitor) cross the FLAGS_serving_shed_* floors, submit()
    sheds lower-priority WAITING requests first and then rejects with
    `AdmissionRejected` (retry-after hint) instead of queueing unboundedly;
  * a graceful-DEGRADATION ladder under sustained pressure, the StepGuard
    ladder's serving twin: speculative decode off -> no decode-lookahead
    reservation at admission -> prefix-cache LRU eviction -> shed, one rung
    per FLAGS_serving_degrade_after pressured steps, descending when calm;
  * SUPERVISION — every compiled dispatch runs under a RetryPolicy (the
    `serving_step_fail` site injects there); retry exhaustion or a dirty
    `PagedKVPool.check_consistency` audit (`serving_pool_corrupt` injects
    the damage) triggers the recovery pass: quarantine poisoned requests,
    rebuild the pool pristine, replay survivors from their prompts —
    bitwise-equal to a fault-free greedy run.
"""
from __future__ import annotations

import time

import dataclasses

import numpy as np

from .. import flags, unique_name
from .. import observability as obs
from ..data_feeder import _round_up_pow2
from ..executor import Executor, Scope
from ..framework import Program, program_guard
from ..observability.slo import hist_p99_above
from ..resilience.faults import InjectedFault, fault_point
from ..resilience.retry import serving_policy
from . import model as sv_model
from .kv_cache import (OwnedPoolView, PagedKVPool, PrefixCache,
                       create_device_pools, pool_var_names)
from .sampling import SamplingParams, request_rng, sample_token

__all__ = ["GenRequest", "ContinuousBatchingScheduler", "ServingEngine",
           "EngineConfig", "AdmissionRejected", "ngram_draft"]

WAITING, RUNNING, FINISHED, ABORTED = "waiting", "running", "finished", "aborted"
DEADLINE_EXCEEDED, SHED = "deadline_exceeded", "shed"
# disaggregated serving (ISSUE 19): the request left this engine through a
# KV handoff — NOT terminal; its pages stay pinned (the prefill pin) until
# the adopting side commits and the router sends release_handoff
HANDED_OFF = "handed_off"
# the states a request never leaves; pop_result/prune accept any of them
_TERMINAL = frozenset({FINISHED, ABORTED, DEADLINE_EXCEEDED, SHED})
# graceful-degradation ladder rungs, mildest first (see _update_ladder)
_LADDER_RUNGS = {1: "spec_off", 2: "lookahead_shrink",
                 3: "cache_evict", 4: "shed"}


class AdmissionRejected(RuntimeError):
    """submit() refused the request under overload: an explicit shed with a
    retry-after hint instead of unbounded queueing. `signals` carries the
    tripped triggers (occupancy / queue_depth / ttft_p99_s)."""

    def __init__(self, reason: str, retry_after_s: float, signals: dict):
        super().__init__(f"admission rejected ({reason}); retry after "
                         f"~{retry_after_s}s")
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.signals = dict(signals)


class _StepFailure(RuntimeError):
    """A compiled dispatch failed past its retry budget; step() converts it
    into a recovery pass instead of letting it poison the batch."""

    def __init__(self, kind: str, cause: BaseException):
        super().__init__(f"{kind} dispatch failed after retries: {cause}")
        self.kind = kind
        self.cause = cause


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """One immutable snapshot of every runtime knob the engine consults
    while scheduling (ISSUE 20). The ctor resolves flags into ONE of these;
    every hot-path read goes through it, so a mid-request flag flip — or a
    controller actuation — can never tear a request's config: the only way
    a knob changes is `propose_config` staging a replacement snapshot that
    `maybe_adopt_config` swaps in whole at a safe boundary (no in-flight
    work). Construction-only knobs (page/pool geometry, tp, scheduler
    policy, prefix-cache presence) stay plain attributes — no actuation
    path exists for them."""

    max_inflight: int
    draft_k: int
    deadline_s: float
    priority_default: int
    shed_occupancy: float
    shed_queue_depth: int
    shed_ttft_p99_ms: float
    degrade_after: int
    audit_every: int

    def bucket_geometry(self) -> tuple:
        """What the decode-signature lattice depends on: the batch-bucket
        ceiling and the window program's draft width. A pending config
        with a different geometry re-runs warmup_decode on adoption."""
        return (_round_up_pow2(max(1, self.max_inflight)), self.draft_k)


def ngram_draft(tokens, k: int, window: int = 128) -> list[int]:
    """Self-drafting proposer: continue `tokens` with the k tokens that
    followed the most recent earlier occurrence of its tail n-gram (longest
    of 3/2/1), falling back to repeating the last token. No draft model,
    no extra weights — the request's own history is the draft distribution,
    which is exactly where decode traffic is redundant (templated outputs,
    code, quoted context). Wrong drafts only cost their share of the
    verify window; acceptance is checked exactly."""
    if k <= 0:
        return []
    toks = [int(t) for t in tokens]
    lo = max(0, len(toks) - window)
    for glen in (3, 2, 1):
        if len(toks) < glen + 1:
            continue
        tail = toks[-glen:]
        for i in range(len(toks) - glen - 1, lo - 1, -1):
            if toks[i:i + glen] == tail:
                cont = toks[i + glen:i + glen + k]
                if cont:
                    return (cont + [toks[-1]] * (k - len(cont)))[:k]
    return [toks[-1]] * k


class GenRequest:
    """One generate request's lifetime.

    `all_tokens` is the full sequence so far (prompt + generated); the KV
    cache always holds exactly len(all_tokens) - 1 slots while RUNNING (the
    newest token's KV is written by the decode step that consumes it). On
    preemption the pages are dropped and the whole prefix re-prefills — no
    separate bookkeeping for "how much cache survived".
    """

    def __init__(self, rid: int, prompt, max_new_tokens: int, eos_id=None,
                 sampling: "SamplingParams | None" = None,
                 deadline_s: float | None = None, priority: int = 1):
        if not len(prompt):
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.rid = rid
        self.prompt_len = len(prompt)
        self.all_tokens: list[int] = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.sampling = sampling or SamplingParams()
        self.state = WAITING
        self.pages: list[int] = []
        self.cached_len = 0      # slots mapped from the prefix cache
        self.admit_seq = -1      # admission order; preemption evicts the newest
        self.preemptions = 0
        self.arrival_t = time.perf_counter()
        self.priority = int(priority)  # higher = more important to keep
        # wall-clock TTL: an expired request keeps its partial tokens but
        # releases every page (the deadline_exceeded terminal state)
        self.deadline_t = (self.arrival_t + float(deadline_s)
                           if deadline_s and deadline_s > 0 else None)
        self.t_first_token: float | None = None
        self.t_done: float | None = None

    @property
    def n_generated(self) -> int:
        return len(self.all_tokens) - self.prompt_len

    @property
    def out_tokens(self) -> list[int]:
        return self.all_tokens[self.prompt_len:]

    @property
    def cache_len(self) -> int:
        """Valid KV slots while RUNNING (last token not yet appended)."""
        return len(self.all_tokens) - 1

    def is_done(self) -> bool:
        return (self.n_generated >= self.max_new_tokens
                or (self.eos_id is not None and self.n_generated > 0
                    and self.all_tokens[-1] == self.eos_id))


class ContinuousBatchingScheduler:
    """Admission ordering policy over the waiting queue."""

    def __init__(self, policy: str):
        if policy not in ("fcfs", "sjf"):
            raise ValueError(f"unknown FLAGS_serving_sched_policy "
                             f"'{policy}' (fcfs | sjf)")
        self.policy = policy

    def order(self, waiting: list[GenRequest]) -> list[GenRequest]:
        if self.policy == "sjf":
            # stable sort: equal lengths keep arrival order
            return sorted(waiting, key=lambda r: len(r.all_tokens))
        return list(waiting)


class ServingEngine:
    """Paged-KV continuous-batching runtime for one decoder model.

    Single-threaded by design (one scheduler loop owns the pool and the
    scope); the parallelism is inside the compiled steps.
    """

    def __init__(self, cfg: "sv_model.DecoderConfig | None" = None,
                 page_size: int | None = None,
                 pool_pages: int | None = None,
                 max_inflight: int | None = None,
                 policy: str | None = None,
                 seed: int = 0,
                 prefix_cache: bool | None = None,
                 draft_k: int | None = None,
                 tp: int | None = None,
                 deadline_s: float | None = None,
                 priority_default: int | None = None,
                 shed_occupancy: float | None = None,
                 shed_queue_depth: int | None = None,
                 shed_ttft_p99_ms: float | None = None,
                 degrade_after: int | None = None,
                 step_retries: int | None = None,
                 audit_every: int | None = None,
                 shared_pool: "PagedKVPool | None" = None,
                 shared_scope: "Scope | None" = None,
                 pool_owner: str | None = None,
                 prefill_only: bool = False):
        """Disaggregated serving (ISSUE 19): pass `shared_pool` (ONE
        `PagedKVPool` spanning the fleet — this engine sees it through an
        `OwnedPoolView` tagged `pool_owner`) plus `shared_scope` (the
        device pools and weights every role reads/writes) to build a
        role-split engine. `prefill_only=True` skips the decode stage of
        every step: requests prefill, then sit RUNNING until
        `extract_for_handoff` publishes them to a decode engine."""
        self.cfg = cfg or sv_model.decoder_tiny()
        self.page_size = int(page_size
                             or flags.get_flag("serving_page_size"))
        self.pool_pages = int(pool_pages
                              or flags.get_flag("serving_pool_pages"))
        self.scheduler = ContinuousBatchingScheduler(
            policy or str(flags.get_flag("serving_sched_policy")))
        if prefix_cache is None:
            prefix_cache = bool(flags.get_flag("serving_prefix_cache"))
        self.tp = int(tp if tp is not None else flags.get_flag("serving_tp"))
        self.seed = int(seed)
        # runtime knobs resolve ONCE into an immutable EngineConfig
        # snapshot (ISSUE 20): the scheduling loop reads self._ecfg, never
        # the flags — a flag flipped mid-request changes nothing until an
        # explicit propose/adopt cycle swaps the whole snapshot at a safe
        # boundary. Resilience defaults keep the machinery off/cheap.
        self._ecfg = EngineConfig(
            max_inflight=int(max_inflight
                             or flags.get_flag("serving_max_inflight")),
            draft_k=int(draft_k if draft_k is not None
                        else flags.get_flag("serving_draft_k")),
            deadline_s=float(
                deadline_s if deadline_s is not None
                else flags.get_flag("serving_deadline_s")),
            priority_default=int(
                priority_default if priority_default is not None
                else flags.get_flag("serving_priority_default")),
            shed_occupancy=float(
                shed_occupancy if shed_occupancy is not None
                else flags.get_flag("serving_shed_occupancy")),
            shed_queue_depth=int(
                shed_queue_depth if shed_queue_depth is not None
                else flags.get_flag("serving_shed_queue_depth")),
            shed_ttft_p99_ms=float(
                shed_ttft_p99_ms if shed_ttft_p99_ms is not None
                else flags.get_flag("serving_shed_ttft_p99_ms")),
            degrade_after=max(1, int(
                degrade_after if degrade_after is not None
                else flags.get_flag("serving_degrade_after"))),
            audit_every=int(
                audit_every if audit_every is not None
                else flags.get_flag("serving_audit_every")),
        )
        self._pending_ecfg: EngineConfig | None = None
        self._warm_ctx: int | None = None
        if self.draft_k < 0:
            raise ValueError(f"draft_k must be >= 0, got {self.draft_k}")
        retries = int(step_retries if step_retries is not None
                      else flags.get_flag("serving_step_retries"))
        self._retry = serving_policy(max_attempts=max(1, retries),
                                     seed=self.seed)
        self._slo = None
        if self.shed_ttft_p99_ms > 0:
            # a private monitor over the default registry with muted
            # callbacks: the breach verdicts still land on the slo.* series,
            # the engine just reads them as one more overload signal
            self._slo = obs.SloMonitor(
                window_s=30.0, alert_after=1,
                on_warn=lambda b: None, on_alert=lambda b: None)
            self._slo.add_rule(
                "serving_ttft_p99",
                hist_p99_above("serving.ttft_s",
                               self.shed_ttft_p99_ms / 1e3),
                self.shed_ttft_p99_ms / 1e3,
                "p99 TTFT above the shed floor")
        self._ladder_rung = 0
        self._pressure_steps = 0
        self._calm_steps = 0
        self._step_i = 0
        self.prefill_only = bool(prefill_only)
        self._shared_pool = shared_pool is not None
        if shared_pool is not None:
            if (shared_pool.num_pages != self.pool_pages
                    or shared_pool.page_size != self.page_size):
                raise ValueError(
                    f"shared pool is {shared_pool.num_pages}x"
                    f"{shared_pool.page_size} but this engine asked for "
                    f"{self.pool_pages}x{self.page_size}")
            self.pool = OwnedPoolView(shared_pool,
                                      pool_owner or f"engine@{id(self)}")
        else:
            self.pool = PagedKVPool(self.pool_pages, self.page_size)
        self.prefix_cache = PrefixCache(self.pool) if prefix_cache else None
        self._exe = Executor()
        self._scope = shared_scope if shared_scope is not None else Scope()

        self._mesh = None
        if self.tp > 1:
            from ..parallel.mesh import make_tp_mesh

            if self.cfg.num_heads % self.tp:
                raise ValueError(
                    f"serving tp degree {self.tp} must divide num_heads "
                    f"{self.cfg.num_heads} (head-sharded decode)")
            self._mesh = make_tp_mesh(self.tp)

        self._prefill_prog = Program()
        self._decode_prog = Program()
        self._window_prog = Program()
        self._cow_prog = Program()
        startup = Program()
        decoy_startup = Program()  # non-prefill progs re-declare; inits unused
        self._prefill_prog.random_seed = startup.random_seed = self.seed
        with program_guard(self._prefill_prog, startup), \
                unique_name.guard():
            self._prefill_io = sv_model.build_prefill_program(
                self.cfg, self.pool_pages, self.page_size)
        with program_guard(self._decode_prog, decoy_startup), \
                unique_name.guard():
            self._decode_io = sv_model.build_decode_program(
                self.cfg, self.pool_pages, self.page_size, tp=self.tp)
        with program_guard(self._window_prog, decoy_startup), \
                unique_name.guard():
            self._window_io = sv_model.build_window_program(
                self.cfg, self.pool_pages, self.page_size, tp=self.tp)
        with program_guard(self._cow_prog, decoy_startup), \
                unique_name.guard():
            self._cow_io = sv_model.build_cow_program(
                self.cfg, self.pool_pages, self.page_size)
        # rng_counter pinned to what a FRESH scope's first run folds in:
        # on a shared scope the run counter has already advanced, and
        # letting it leak into the init keys would give every engine after
        # the first different weights — silently breaking replay exactness
        self._exe.run(startup, scope=self._scope, rng_counter=1)
        # a shared scope may already carry live KV (an engine added to a
        # running disaggregated fleet): re-zeroing the pools would clobber
        # every peer's context, so only the FIRST engine materializes them.
        # Identically-seeded startup runs make the weight re-init above a
        # bitwise no-op on a shared scope.
        if not self._scope.has_var(pool_var_names(self.cfg.num_layers)[0][0]):
            create_device_pools(self._scope, self.cfg.num_layers,
                                self.pool_pages, self.page_size,
                                self.cfg.num_heads, self.cfg.head_dim,
                                self.cfg.dtype)
        self._prefill_run = self._exec_target(self._prefill_prog)
        self._decode_run = self._exec_target(self._decode_prog)
        self._window_run = self._exec_target(self._window_prog)
        self._cow_run = self._exec_target(self._cow_prog)

        self.requests: dict[int, GenRequest] = {}
        self._waiting: list[GenRequest] = []
        self._running: list[GenRequest] = []
        self._next_rid = 0
        self._admit_seq = 0
        self.stats = {
            "prefills": 0, "decode_steps": 0, "decode_tokens": 0,
            "preemptions": 0, "aborts": 0,
            "prefill_signatures": set(), "decode_signatures": set(),
            "peak_pages_in_use": 0, "occupancy_sum": 0.0, "occupancy_n": 0,
            # prefix caching (ISSUE 11)
            "prefill_tokens_computed": 0, "prefix_hit_tokens": 0,
            "prefix_lookups": 0, "prefix_full_hits": 0, "cow_copies": 0,
            # speculative decoding (ISSUE 11)
            "spec_steps": 0, "spec_proposed": 0, "spec_accepted": 0,
            # resilience (ISSUE 14) — dotted keys mirror to the registry
            # verbatim through _count ("serving." + key)
            "deadline_exceeded": 0, "shed": 0, "rejects": 0,
            # disaggregated handoff (ISSUE 19)
            "adopts": 0, "handoff_extracts": 0,
            "step_retries": 0, "recovery.passes": 0,
            "recovery.replayed": 0, "recovery.quarantined": 0,
            "ladder.spec_off": 0, "ladder.lookahead_shrink": 0,
            "ladder.cache_evict": 0, "ladder.shed": 0,
            # learned serving control (ISSUE 20)
            "control.applies": 0, "control.rewarmups": 0,
        }
        # the learned controller's per-engine epoch hook (ISSUE 20):
        # shadow by default — one perf_counter read per step until an
        # epoch is due, then observe/propose/log (apply mode additionally
        # stages a pending EngineConfig for the next safe boundary)
        from . import control as sv_control

        self._ctrl = sv_control.Controller()

    # -- runtime knobs: the EngineConfig snapshot (ISSUE 20) ----------------
    # Compatibility properties: every pre-existing `engine.<knob>` read —
    # internal hot paths and external harnesses alike — resolves through
    # the one immutable snapshot.
    @property
    def engine_config(self) -> EngineConfig:
        return self._ecfg

    @property
    def max_inflight(self) -> int:
        return self._ecfg.max_inflight

    @property
    def draft_k(self) -> int:
        return self._ecfg.draft_k

    @property
    def deadline_s(self) -> float:
        return self._ecfg.deadline_s

    @property
    def priority_default(self) -> int:
        return self._ecfg.priority_default

    @property
    def shed_occupancy(self) -> float:
        return self._ecfg.shed_occupancy

    @property
    def shed_queue_depth(self) -> int:
        return self._ecfg.shed_queue_depth

    @property
    def shed_ttft_p99_ms(self) -> float:
        return self._ecfg.shed_ttft_p99_ms

    @property
    def degrade_after(self) -> int:
        return self._ecfg.degrade_after

    @property
    def audit_every(self) -> int:
        return self._ecfg.audit_every

    def propose_config(self, knobs: dict, source: str = "controller") -> bool:
        """Stage a knob change (controller proposal or operator nudge) as
        a PENDING EngineConfig. Nothing changes here: the pending snapshot
        waits for `maybe_adopt_config` at a safe boundary. Only the
        online-actuatable knobs are honored (mi/dk/sq/so/da — see
        control/knobs.py); construction-only fields keep their values.
        Returns True when a pending config was staged (i.e. the proposal
        differs from the current snapshot)."""
        cur = self._ecfg
        cand = dataclasses.replace(
            cur,
            max_inflight=max(1, int(knobs.get("mi", cur.max_inflight))),
            draft_k=max(0, int(knobs.get("dk", cur.draft_k))),
            shed_queue_depth=max(0, int(knobs.get("sq",
                                                  cur.shed_queue_depth))),
            shed_occupancy=min(1.0, max(0.0, float(
                knobs.get("so", cur.shed_occupancy * 100)) / 100.0)),
            degrade_after=max(1, int(knobs.get("da", cur.degrade_after))),
        )
        if cand == cur:
            self._pending_ecfg = None
            return False
        self._pending_ecfg = cand
        obs.event("serving.control.actuation",
                  {"phase": "staged", "source": source,
                   "geometry_change": cand.bucket_geometry()
                   != cur.bucket_geometry()})
        return True

    def maybe_adopt_config(self) -> bool:
        """Adopt the pending EngineConfig — but ONLY at a safe boundary:
        no waiting and no running requests (the engine idle gap; the
        fleet replica pump and submit()/step() all call this, so the gap
        is found wherever it opens). When the decode bucket geometry
        changed, re-runs `warmup_decode` over the previously warmed
        context range so the next measured pass still triggers zero fresh
        XLA compiles — no stray compile ever lands on the serving path."""
        pend = self._pending_ecfg
        if pend is None or self.has_work():
            return False
        old = self._ecfg
        self._ecfg = pend
        self._pending_ecfg = None
        self._count("control.applies")
        rewarmed = False
        if (pend.bucket_geometry() != old.bucket_geometry()
                and self._warm_ctx is not None):
            self.warmup_decode(self._warm_ctx)
            self._count("control.rewarmups")
            rewarmed = True
        obs.event("serving.control.actuation",
                  {"phase": "adopted", "rewarmed": rewarmed,
                   "max_inflight": pend.max_inflight,
                   "draft_k": pend.draft_k})
        return True

    def warmup_decode(self, max_context: int | None = None) -> int:
        """Precompile the decode-step signature lattice for contexts up to
        `max_context` (default max_position): which (batch-bucket,
        page-bucket) a step hits depends on how many requests HAPPEN to be
        running — pure load timing — so organic warmup can leave signatures
        uncompiled and a mid-measurement XLA compile (~1s on CPU) then
        decides an open-loop verdict instead of the engines. Drives every
        signature with fully-masked rows (zero valid lengths): writes drop,
        outputs are ignored, no engine state moves. Returns the signature
        count."""
        max_context = min(int(max_context or self.cfg.max_position),
                          self.cfg.max_position)
        # remembered so a controller actuation that changes the bucket
        # geometry can re-warm the SAME context range before serving
        self._warm_ctx = max_context
        pbs = sorted({_round_up_pow2(self.pool.pages_for(c))
                      for c in range(1, max_context + 2)})
        bbs = sorted({_round_up_pow2(b)
                      for b in range(1, self.max_inflight + 1)})
        n = 0
        for bb in bbs:
            for pb in pbs:
                pages = np.zeros((bb, pb), np.int32)
                if self.draft_k > 0:
                    S = self.draft_k + 1
                    feed = {sv_model.TOK_FEED: np.zeros((bb, S), np.int32),
                            sv_model.POS_FEED: np.zeros((bb, S), np.int32),
                            sv_model.PAGES_FEED: pages,
                            sv_model.START_FEED: np.zeros((bb,), np.int32),
                            sv_model.LEN_FEED: np.zeros((bb,), np.int32)}
                    self._exe.run(self._window_run, feed=feed,
                                  fetch_list=[self._window_io["tokens"],
                                              self._window_io["logits"]],
                                  scope=self._scope)
                else:
                    feed = {sv_model.TOK_FEED: np.zeros((bb, 1), np.int32),
                            sv_model.POS_FEED: np.zeros((bb,), np.int32),
                            sv_model.PAGES_FEED: pages,
                            sv_model.MASK_FEED: np.zeros((bb, 1),
                                                         np.float32)}
                    self._exe.run(self._decode_run, feed=feed,
                                  fetch_list=[self._decode_io["next_token"],
                                              self._decode_io["logits"]],
                                  scope=self._scope)
                n += 1
        return n

    def reset_stats(self) -> None:
        """Zero the counters (and the compile-signature sets) without
        touching the executor compile cache, the pool, or the prefix
        cache — the steady-state measurement boundary: warm the engine on
        one pass of a workload, reset, measure the second pass. The
        registry's `serving.` series reset with it so both views stay
        scoped to the same measurement window."""
        for k, v in self.stats.items():
            if isinstance(v, set):
                v.clear()
            elif isinstance(v, float):
                self.stats[k] = 0.0
            else:
                self.stats[k] = 0
        obs.reset("serving.")

    def _count(self, key: str, n: int = 1) -> None:
        """Bump a stats counter AND its registry mirror (`serving.<key>`):
        the dict stays the cheap in-process view, the registry carries the
        same number out through snapshot/exporters."""
        self.stats[key] += n
        obs.counter_inc("serving." + key, n)

    def stats_snapshot(self) -> dict:
        """The stats dict plus derived rates, every divide guarded: a
        snapshot taken before any decode/prefill/spec step reports 0.0
        rather than raising ZeroDivisionError or emitting NaN (notably
        spec_accept_rate with speculation enabled but no spec step yet).
        Signature sets become bucket counts so the result is JSON-clean."""
        st = self.stats
        out = {k: (len(v) if isinstance(v, set) else v)
               for k, v in st.items()}
        out["spec_accept_rate"] = (
            st["spec_accepted"] / st["spec_proposed"]
            if st["spec_proposed"] else 0.0)
        out["tokens_per_decode_step"] = (
            st["decode_tokens"] / st["decode_steps"]
            if st["decode_steps"] else 0.0)
        denom = st["prefix_hit_tokens"] + st["prefill_tokens_computed"]
        out["prefix_cache_hit_rate"] = (
            st["prefix_hit_tokens"] / denom if denom else 0.0)
        out["occupancy_mean"] = (
            st["occupancy_sum"] / st["occupancy_n"]
            if st["occupancy_n"] else 0.0)
        out["leaked_pages"] = self.leaked_pages()
        return out

    def _exec_target(self, prog: Program):
        """The executor target for `prog`: the bare program single-chip, a
        GSPMD CompiledProgram over the tp mesh when sharded (built ONCE so
        the executor compile cache keys stay stable)."""
        if self._mesh is None:
            return prog
        from ..compiler import CompiledProgram

        sv_model.apply_tp_annotations(prog, self.cfg, self.tp)
        return CompiledProgram(prog).with_data_parallel(mesh=self._mesh)

    # -- client API ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, eos_id=None,
               sampling: "SamplingParams | dict | None" = None,
               deadline_s: float | None = None,
               priority: int | None = None) -> int:
        """Queue one request. `deadline_s`/`priority` default to the
        engine-wide knobs (FLAGS_serving_deadline_s /
        FLAGS_serving_priority_default). Under overload (any
        FLAGS_serving_shed_* floor tripped) this sheds WAITING requests of
        strictly lower priority to make room, and raises AdmissionRejected
        with a retry-after hint when that is not enough — explicit refusal
        instead of an unbounded queue."""
        # the admit boundary is a safe boundary: nothing in flight means a
        # staged controller config can swap in before this request's
        # admission reads any knob
        self.maybe_adopt_config()
        if len(prompt) + max_new_tokens > self.cfg.max_position:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_position {self.cfg.max_position}")
        if isinstance(sampling, dict):
            sampling = SamplingParams(**sampling)
        if priority is None:
            priority = self.priority_default
        if deadline_s is None:
            deadline_s = self.deadline_s
        sig = self._overload_signals()
        if "occupancy" in sig and self.prefix_cache is not None:
            # evictable prefix-cache pages are reclaimable memory, not
            # pressure: free enough of the LRU tail to get back under the
            # floor before refusing admission. Without this, a cache that
            # grew to the pool size while the engine drained would shed
            # every future submit with no step ever running — the rung-3
            # eviction only fires on pressured STEPS, so an idle engine
            # could never climb out (admission starvation the ISSUE 20
            # knob sweep's engine reuse exposed).
            floor_pages = int(self.shed_occupancy * self.pool.num_pages)
            need = self.pool.pages_in_use - floor_pages + 1
            if need > 0:
                self.prefix_cache.evict(need)
            sig = self._overload_signals()
        while sig and self._shed_one(max_priority=int(priority)):
            sig = self._overload_signals()
        if sig:
            retry_after = round(
                0.05 * max(1, len(self._waiting) + len(self._running)), 3)
            self._count("rejects")
            obs.event("serving.request",
                      {"rid": -1, "phase": "rejected", "signals": sig,
                       "retry_after_s": retry_after}, level="warning")
            raise AdmissionRejected(",".join(sorted(sig)), retry_after, sig)
        rid = self._next_rid
        self._next_rid += 1
        req = GenRequest(rid, prompt, max_new_tokens, eos_id, sampling,
                         deadline_s=deadline_s, priority=int(priority))
        self.requests[rid] = req
        self._waiting.append(req)
        obs.event("serving.request", {"rid": rid, "phase": "queued",
                                      "prompt_len": req.prompt_len,
                                      "priority": req.priority,
                                      "max_new_tokens": req.max_new_tokens})
        return rid

    def abort(self, rid: int) -> None:
        """Drop a request wherever it is; its page refcounts release
        immediately and pages nobody else maps return to the free list
        (the zero-leak contract the chaos test asserts). A WAITING request
        leaves the admission queue AND releases any prefix-cache pages a
        failed admission attempt left pinned on it."""
        req = self.requests.get(rid)
        if req is None or req.state in _TERMINAL:
            return
        self._terminate(req, ABORTED, "aborts")

    def has_work(self) -> bool:
        return bool(self._waiting or self._running)

    @property
    def decode_slots_free(self) -> int:
        """RUNNING capacity left under max_inflight — what an adopting
        replica checks before committing a lease (an adopted request
        enters RUNNING directly, so it must fit the decode batch NOW)."""
        return max(0, self.max_inflight - len(self._running))

    # -- disaggregated KV handoff (ISSUE 19) --------------------------------
    def extract_for_handoff(self, rid: int) -> dict:
        """PREPARE half of the prefill->decode handoff: pull a freshly
        prefilled RUNNING request out of the scheduler and publish its full
        transfer state (token history + page table). The request record
        stays, HANDED_OFF, with its pages still held — the PREFILL PIN the
        two-phase protocol keeps until the adopting side commits — so the
        audit and leak accounting see the pin as a live holder throughout.
        The caller (the prefill replica) grants the lease over the
        returned page table before anything else moves."""
        req = self.requests[rid]
        if req.state != RUNNING:
            raise ValueError(
                f"request {rid} is {req.state}; only RUNNING (prefilled) "
                f"requests can hand off")
        self._running.remove(req)
        req.state = HANDED_OFF
        self._count("handoff_extracts")
        obs.event("serving.request",
                  {"rid": rid, "phase": HANDED_OFF,
                   "n_generated": req.n_generated, "pages": len(req.pages)})
        return {"prompt_len": req.prompt_len,
                "all_tokens": list(req.all_tokens),
                "pages": list(req.pages),
                "max_new_tokens": req.max_new_tokens,
                "eos_id": req.eos_id, "sampling": req.sampling,
                "priority": req.priority, "deadline_t": req.deadline_t}

    def release_handoff(self, rid: int) -> None:
        """Drop the prefill pin of a HANDED_OFF request (the adopting side
        committed — its view now carries the transferred lease refcount —
        or the handoff failed terminally and the router is cleaning up).
        Idempotent: a second release, or one after this engine already
        recovered, is a no-op."""
        req = self.requests.pop(rid, None)
        if req is None or req.state != HANDED_OFF:
            return
        if req.pages:
            self.pool.release(req.pages)
            req.pages = []

    def adopt_request(self, handoff: dict) -> int:
        """COMMIT half of the handoff: admit a request whose context KV
        some OTHER engine already materialized into the shared pool — the
        page table transfers, prefill is skipped entirely. The pages'
        refcount arrives by lease transfer (the caller committed the lease
        first), so this only records the pins in the owner ledger and
        resumes decoding from wherever the prefill side stopped: with a
        first token (the next decode step continues it) or at a full
        prefix hit (the next decode step derives token one under COW —
        the same regime a local full hit takes). The only admission rule
        that re-runs is the RUNNING cap: an adopted request joins the
        decode batch immediately, so it must fit max_inflight — the
        adopting replica checks `decode_slots_free` and defers the commit
        when full, and this guard backstops it (the caller returns the
        transferred refcount to the pool on rejection, so nothing
        leaks)."""
        adopt = getattr(self.pool, "adopt_transferred", None)
        if adopt is None:
            raise RuntimeError(
                "adopt_request needs a shared pool (OwnedPoolView): a "
                "private pool cannot receive a lease-transferred refcount")
        if len(self._running) >= self.max_inflight:
            raise AdmissionRejected(
                "adopt_no_decode_slot", 0.05,
                {"running": len(self._running),
                 "max_inflight": self.max_inflight})
        toks = [int(t) for t in handoff["all_tokens"]]
        pages = list(handoff["pages"])
        prompt_len = int(handoff["prompt_len"])
        if self.pool.pages_for(max(1, len(toks) - 1)) > len(pages):
            raise ValueError(
                f"adopted table has {len(pages)} pages for "
                f"{len(toks) - 1} KV slots")
        rid = self._next_rid
        self._next_rid += 1
        req = GenRequest(rid, toks[:prompt_len], handoff["max_new_tokens"],
                         handoff.get("eos_id"), handoff.get("sampling"),
                         priority=int(handoff.get("priority", 1)))
        req.all_tokens = toks
        req.pages = pages
        req.deadline_t = handoff.get("deadline_t")
        req.state = RUNNING
        req.admit_seq = self._admit_seq
        self._admit_seq += 1
        adopt(pages)
        self.requests[rid] = req
        self._running.append(req)
        self._count("adopts")
        obs.event("serving.request",
                  {"rid": rid, "phase": "adopted",
                   "n_generated": req.n_generated, "pages": len(pages)})
        return rid

    def result(self, rid: int) -> list[int]:
        return list(self.requests[rid].out_tokens)

    def pop_result(self, rid: int) -> list[int]:
        """Return a terminal request's generated tokens and drop its
        record. `requests` otherwise retains every completed request (full
        token list included) for the engine's lifetime — unbounded growth
        and ever-slower leak accounting under continuous serving."""
        req = self.requests[rid]
        if req.state not in _TERMINAL:
            raise ValueError(
                f"request {rid} is {req.state}; only terminal "
                f"(finished/aborted/deadline_exceeded/shed) results can "
                f"be popped")
        del self.requests[rid]
        return list(req.out_tokens)

    def prune_finished(self) -> int:
        """Drop every terminal request record (results the caller has
        already read or will never read). Returns records dropped."""
        done = [rid for rid, r in self.requests.items()
                if r.state in _TERMINAL]
        for rid in done:
            del self.requests[rid]
        return len(done)

    def leaked_pages(self) -> int:
        """Pages in use that NO live request and NO prefix-cache entry can
        account for — must be zero at every quiescent point. Over a shared
        pool the base is this OWNER's pages (the OwnedPoolView ledger), not
        the global pool: peers' pages are theirs to account for."""
        mapped: set[int] = set()
        for r in self.requests.values():
            mapped.update(r.pages)
        if self.prefix_cache is not None:
            mapped.update(n.page for n in self.prefix_cache._nodes.values())
        in_use = getattr(self.pool, "owned_pages_in_use",
                         self.pool.pages_in_use)
        return in_use - len(mapped)

    def flush_prefix_cache(self) -> int:
        """Evict every prefix-cache entry no live request still maps (frees
        their pages). Returns pages freed."""
        if self.prefix_cache is None:
            return 0
        return self.prefix_cache.flush()

    def run_until_drained(self, max_steps: int = 100_000) -> None:
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"serving loop made no exit after {max_steps} steps "
                    f"(waiting={len(self._waiting)} "
                    f"running={len(self._running)})")

    # -- the scheduler iteration --------------------------------------------
    def step(self) -> bool:
        """One continuous-batching iteration; returns True if any request
        made progress (admitted or decoded a token). Supervised: a compiled
        dispatch that exhausts its retry budget becomes a recovery pass
        (quarantine + pool rebuild + prompt replay) instead of a poisoned
        batch."""
        self._step_i += 1
        self.maybe_adopt_config()
        try:
            progressed = self._step_inner()
        except _StepFailure as e:
            self._recover(f"step_fail:{e.kind}")
            progressed = True
        # controller epoch hook: one perf_counter read + compare per step
        # until an epoch is due (the shadow-mode 0% overhead budget)
        self._ctrl.tick(self)
        return progressed

    def _step_inner(self) -> bool:
        try:
            fault_point("serving_deadline")
        except InjectedFault:
            # chaos: the oldest live request's deadline collapses to the past
            victim = self._running[0] if self._running else (
                self._waiting[0] if self._waiting else None)
            if victim is not None:
                victim.deadline_t = time.perf_counter() - 1e-9
        try:
            fault_point("serving_pool_corrupt")
        except InjectedFault as e:
            self._corrupt_pool(e.hit)
        try:
            fault_point("serving_abort")
        except InjectedFault:
            # chaos: the oldest running request's client vanished mid-decode
            victim = self._running[0] if self._running else (
                self._waiting[0] if self._waiting else None)
            if victim is not None:
                self.abort(victim.rid)
        self._expire_deadlines(time.perf_counter())
        if self.audit_every > 0 and self._step_i % self.audit_every == 0:
            problems, poisoned = self.audit_pool()
            if problems:
                self._recover("pool_corrupt", poisoned=poisoned,
                              problems=problems)
                return True
        self._update_ladder()
        admitted = self._admit()
        if self._running and not self.prefill_only:
            with obs.span("serving.decode"):
                decoded = self._decode_once()
        else:
            # prefill-only engines stop at the prompt boundary: freshly
            # prefilled rows sit RUNNING until extract_for_handoff moves
            # them to a decode engine
            decoded = False
        # a request that crossed its TTL inside the prefill/decode above is
        # caught here — "mid-step" expiry still releases pages this step
        self._expire_deadlines(time.perf_counter())
        if not decoded and not admitted and self._waiting:
            need = min(self.pool.pages_for(len(r.all_tokens) + 1)
                       for r in self._waiting)
            if need > self.pool.num_pages:
                raise RuntimeError(
                    f"request needs {need} pages but the pool only has "
                    f"{self.pool.num_pages} (FLAGS_serving_pool_pages / "
                    f"FLAGS_serving_page_size)")
            if not self._running and not self._shared_pool:
                # over a SHARED pool this engine being starved is not
                # fatal: peers (or the lease reaper) free pages it never
                # could — keep waiting instead of declaring deadlock
                raise RuntimeError(
                    "admission stuck: no running requests to free pages, "
                    f"yet {len(self._waiting)} waiting (free "
                    f"{self.pool.free_count}/{self.pool.num_pages} pages)")
        self._note_occupancy()
        return bool(admitted or decoded)

    # -- internals ----------------------------------------------------------
    def _release(self, req: GenRequest) -> None:
        if req.pages:
            self.pool.release(req.pages)
            req.pages = []
        req.cached_len = 0

    def _allocate(self, n: int) -> list[int] | None:
        """allocate() with prefix-cache pressure relief: when the free list
        runs dry, evict unshared cache entries (LRU-first) before giving
        up — cached prompts are a performance bet, never a reason to queue
        live work."""
        if n <= 0:
            return []
        got = self.pool.allocate(n)
        if got is None and self.prefix_cache is not None:
            self.prefix_cache.evict(n - self.pool.free_count)
            got = self.pool.allocate(n)
        return got

    def _note_occupancy(self) -> None:
        used = self.pool.pages_in_use
        self.stats["peak_pages_in_use"] = max(
            self.stats["peak_pages_in_use"], used)
        self.stats["occupancy_sum"] += used / self.pool.num_pages
        self.stats["occupancy_n"] += 1
        obs.gauge_set("serving.pages_in_use", used)
        obs.gauge_set("serving.pool_occupancy", used / self.pool.num_pages)

    # -- resilience: deadlines, shedding, the degradation ladder ------------
    def _terminate(self, req: GenRequest, state: str, counter: str,
                   extra: dict | None = None,
                   level: str = "warning") -> None:
        """Shared terminal transition: drop the request from whichever
        queue holds it, release every page it maps (including a WAITING
        request's pinned prefix-cache pages), stamp the state, count and
        event it."""
        if req in self._waiting:
            self._waiting.remove(req)
        if req in self._running:
            self._running.remove(req)
        self._release(req)
        req.state = state
        req.t_done = time.perf_counter()
        self._count(counter)
        payload = {"rid": req.rid, "phase": state,
                   "n_generated": req.n_generated}
        if extra:
            payload.update(extra)
        obs.event("serving.request", payload, level=level)

    def _expire_deadlines(self, now: float) -> int:
        """Expire every live request past its TTL (checked between decode
        steps and at admission, never inside a compiled step): partial
        tokens are kept, every page returns, and the terminal state is
        distinct from abort so clients can tell 'too slow' from
        'cancelled'. Returns requests expired."""
        expired = [r for r in self._running + self._waiting
                   if r.deadline_t is not None and now > r.deadline_t]
        for req in expired:
            self._terminate(req, DEADLINE_EXCEEDED, "deadline_exceeded",
                            extra={"overrun_s":
                                   round(now - req.deadline_t, 6)})
        return len(expired)

    def _overload_signals(self) -> dict:
        """The overload triggers currently tripped ({} = healthy): pool
        occupancy and waiting-queue depth read directly, p99 TTFT through
        the SloMonitor so the breach is also counted/evented on the slo.*
        series. Disabled floors (<= 0) never trip."""
        sig: dict = {}
        if self.shed_occupancy > 0:
            occ = self.pool.pages_in_use / self.pool.num_pages
            if occ >= self.shed_occupancy:
                sig["occupancy"] = round(occ, 4)
        if (self.shed_queue_depth > 0
                and len(self._waiting) >= self.shed_queue_depth):
            sig["queue_depth"] = len(self._waiting)
        if self._slo is not None:
            for b in self._slo.observe():
                if b["rule"] == "serving_ttft_p99":
                    sig["ttft_p99_s"] = round(float(b["value"]), 6)
        return sig

    def _shed_one(self, max_priority: int | None = None) -> bool:
        """Shed ONE waiting request: the lowest priority class, youngest
        arrival within it (it has lost the least). `max_priority`
        restricts victims to classes strictly below it — a submit never
        sheds its own class to make room for itself."""
        cands = (self._waiting if max_priority is None
                 else [r for r in self._waiting
                       if r.priority < max_priority])
        if not cands:
            return False
        victim = min(cands, key=lambda r: (r.priority, -r.arrival_t))
        self._terminate(victim, SHED, "shed",
                        extra={"priority": victim.priority})
        return True

    def _update_ladder(self) -> None:
        """Graceful degradation under sustained pressure (the StepGuard
        ladder's serving twin): one rung up per `degrade_after`
        consecutive overloaded steps, one rung down per equally long calm
        streak. Rungs: 1 speculative decode off, 2 admission stops
        reserving the decode-lookahead page, 3 the prefix-cache LRU tail
        is evicted each pressured step, 4 lowest-priority waiters shed."""
        sig = self._overload_signals()
        if sig:
            self._pressure_steps += 1
            self._calm_steps = 0
            if (self._ladder_rung < 4
                    and self._pressure_steps >= self.degrade_after):
                self._pressure_steps = 0
                self._ladder_rung += 1
                name = _LADDER_RUNGS[self._ladder_rung]
                self._count("ladder." + name)
                obs.gauge_set("serving.ladder_rung", self._ladder_rung)
                obs.event("serving.degrade",
                          {"rung": self._ladder_rung, "name": name,
                           "direction": "up", "signals": sig},
                          level="warning")
        else:
            self._calm_steps += 1
            self._pressure_steps = 0
            if (self._ladder_rung > 0
                    and self._calm_steps >= self.degrade_after):
                self._calm_steps = 0
                self._ladder_rung -= 1
                obs.gauge_set("serving.ladder_rung", self._ladder_rung)
                obs.event("serving.degrade",
                          {"rung": self._ladder_rung, "direction": "down"})
        if sig and self._ladder_rung >= 3 and self.prefix_cache is not None:
            self.prefix_cache.evict(1)
        if sig and self._ladder_rung >= 4:
            self._shed_one()

    # -- supervision: retried dispatch, invariant audit, recovery -----------
    def _dispatch(self, kind: str, target, feed, fetch_list):
        """Every compiled prefill/decode/window/COW step dispatches here:
        the serving_step_fail fault site, then the executor, under the
        serving RetryPolicy. Retrying a step is safe — the compiled
        programs write fixed KV slots derived from the feed, so attempt
        N+1 overwrites attempt N's partial effects exactly. Retry
        exhaustion raises _StepFailure; step() turns it into the recovery
        pass."""
        def attempt():
            fault_point("serving_step_fail")
            return self._exe.run(target, feed=feed, fetch_list=fetch_list,
                                 scope=self._scope)

        def on_retry(n, exc):
            self._count("step_retries")
            obs.event("serving.step_retry",
                      {"kind": kind, "attempt": n, "error": repr(exc)},
                      level="warning")

        try:
            return self._retry.call(attempt, on_retry=on_retry)
        except self._retry.retryable as e:
            raise _StepFailure(kind, e) from e

    def _corrupt_pool(self, hit: int) -> None:
        """The serving_pool_corrupt payload: vandalize ONE piece of
        host-side bookkeeping so the audit has something real to catch —
        a phantom refcount holder, a live page pushed back on the free
        list, or a duplicate ordinal in the newest running request's
        table (that request is poisoned and must be quarantined). The
        kind cycles with the fault's hit index; no-op when nothing is
        live."""
        in_use = [p for p in range(self.pool.num_pages)
                  if self.pool.refcount(p) > 0]
        kind = hit % 3
        if kind == 0 and in_use:
            self.pool._refs[in_use[0]] += 1
        elif kind == 1 and in_use:
            self.pool._free.append(in_use[0])
        elif kind == 2:
            live = [r for r in self._running if r.pages]
            if live:
                victim = max(live, key=lambda r: r.admit_seq)
                victim.pages.append(victim.pages[0])

    def audit_pool(self) -> tuple[list[str], list[int]]:
        """Cross-check every live page table and the prefix cache against
        the pool invariants (free list and mapped ordinals partition the
        pool; refcounts equal live holder counts). Returns (problems,
        poisoned_rids): a request whose OWN table is malformed —
        out-of-range or duplicate ordinals — is poisoned, and recovery
        quarantines it instead of replaying it."""
        problems: list[str] = []
        poisoned: list[int] = []
        holders: dict[int, int] = {}
        for r in self.requests.values():
            if not r.pages or r.state in _TERMINAL:
                continue
            bad = False
            seen: set[int] = set()
            for p in r.pages:
                if not (0 <= p < self.pool.num_pages):
                    problems.append(f"request {r.rid} maps page {p} "
                                    f"outside the pool")
                    bad = True
                    continue
                if p in seen:
                    problems.append(f"request {r.rid} maps page {p} twice")
                    bad = True
                seen.add(p)
                holders[p] = holders.get(p, 0) + 1
            if bad:
                poisoned.append(r.rid)
        if self.prefix_cache is not None:
            for node in self.prefix_cache._nodes.values():
                holders[node.page] = holders.get(node.page, 0) + 1
        problems.extend(self.pool.check_consistency(holders))
        return problems, poisoned

    def _recover(self, reason: str, poisoned=(), problems=()) -> None:
        """The recovery pass: quarantine poisoned requests (their tables
        are garbage), drop every page table and the whole prefix-cache
        index, rebuild the pool pristine, and replay every survivor from
        its PROMPT. Greedy decoding is deterministic, so the replayed
        outputs are bitwise-equal to a fault-free run (the oracle test's
        contract); sampled requests re-derive the same tokens through the
        per-(seed, rid, position) rng."""
        self._count("recovery.passes")
        obs.event("serving.recovery",
                  {"reason": reason, "problems": list(problems)[:8],
                   "quarantined": list(poisoned),
                   "running": len(self._running),
                   "waiting": len(self._waiting)}, level="error")
        for rid in poisoned:
            req = self.requests.get(rid)
            if req is None or req.state in _TERMINAL:
                continue
            if req in self._waiting:
                self._waiting.remove(req)
            if req in self._running:
                self._running.remove(req)
            req.pages = []  # garbage table; the pool rebuild reclaims it
            req.cached_len = 0
            req.state = ABORTED
            req.t_done = time.perf_counter()
            self._count("recovery.quarantined")
            obs.event("serving.request",
                      {"rid": req.rid, "phase": "quarantined",
                       "n_generated": req.n_generated}, level="error")
        survivors = sorted(self._running, key=lambda r: r.admit_seq)
        self._running = []
        for req in survivors:
            del req.all_tokens[req.prompt_len:]  # replay from the prompt
            req.pages = []
            req.cached_len = 0
            req.state = WAITING
            req.admit_seq = -1
            self._count("recovery.replayed")
        for req in self._waiting:
            req.pages = []  # admission pins die with the pool rebuild
            req.cached_len = 0
        for req in self.requests.values():
            if req.state == HANDED_OFF:
                # the rebuild forfeits the prefill pin with everything
                # else; clear the table so a late release_handoff cannot
                # double-release (the LEASE still keeps the pages alive
                # for the adopting side)
                req.pages = []
        self._waiting[:0] = survivors
        if self.prefix_cache is not None:
            self.prefix_cache.clear()
        self.pool.reset()
        post, _ = self.audit_pool()
        if post:
            raise RuntimeError(
                f"recovery left the pool inconsistent: {post[:4]}")

    def _admit(self) -> int:
        """Admit waiting requests in policy order until pages or inflight
        slots run out. Head-of-line backpressure: the first request that
        does not fit stops admission (no starvation of big requests by
        later small ones under fcfs). Prefix-cache hits cut the PRIVATE
        page bill: cached full pages of the prompt map with a refcount
        bump instead of an allocation."""
        admitted = 0
        for req in self.scheduler.order(self._waiting):
            if len(self._running) >= self.max_inflight:
                break
            if req.deadline_t is not None \
                    and time.perf_counter() > req.deadline_t:
                # expired while WAITING: never admit, return any pin
                self._terminate(req, DEADLINE_EXCEEDED, "deadline_exceeded")
                continue
            if req.pages:
                # a previous attempt already pinned this prefix hit; the
                # pin persisted across the failed admission so eviction
                # relief could not free the match out from under the waiter
                matched = req.pages
            else:
                matched = []
                if self.prefix_cache is not None:
                    self._count("prefix_lookups")
                    matched = self.prefix_cache.match(
                        req.all_tokens[:req.prompt_len])
                    # pin the hit BEFORE allocating: the cache's own ref
                    # may be these pages' only holder, and _allocate's
                    # eviction relief under pool pressure could otherwise
                    # free the matched pages and hand them right back as
                    # this request's PRIVATE pages (one physical page
                    # mapped at two ordinals)
                    if matched:
                        self.pool.share(matched)
            # +1: the decode step after prefill writes one more slot (the
            # ladder's lookahead-shrink rung drops the reservation to the
            # bare context; _ensure_writable then allocates on demand)
            lookahead = 0 if self._ladder_rung >= 2 else 1
            need = self.pool.pages_for(len(req.all_tokens) + lookahead)
            private = self._allocate(max(0, need - len(matched)))
            if private is None:
                # keep the pin on the request: abort/shed/deadline release
                # it through _terminate, and the next attempt starts with
                # the shared pages already held
                req.pages = matched
                req.cached_len = len(matched) * self.page_size
                break
            req.pages = matched + private
            req.cached_len = len(matched) * self.page_size
            self._count("prefix_hit_tokens", req.cached_len)
            self._waiting.remove(req)
            req.admit_seq = self._admit_seq
            self._admit_seq += 1
            obs.histogram_observe("serving.queue_s",
                                  time.perf_counter() - req.arrival_t)
            obs.event("serving.request", {"rid": req.rid, "phase": "admitted",
                                          "cached_len": req.cached_len,
                                          "pages": len(req.pages)})
            # no rid label: span labels flow to the histogram series key,
            # and a per-request label would mint unbounded series
            with obs.span("serving.prefill"):
                self._prefill(req)
            admitted += 1
        return admitted

    def _seq_bucket(self, n: int) -> int:
        return min(self.cfg.max_position, max(8, _round_up_pow2(n)))

    def _first_token(self, req: GenRequest, nxt, last_logits) -> int:
        """The prompt's first generated token: compiled argmax for greedy
        requests, the host-side seeded sampler otherwise."""
        if req.sampling.is_greedy:
            return int(np.asarray(nxt).reshape(-1)[0])
        rng = request_rng(self.seed, req.rid, req.n_generated)
        return sample_token(np.asarray(last_logits)[0], req.sampling, rng)

    def _prefill(self, req: GenRequest) -> None:
        """Materialize one request's context KV and (unless the whole
        prompt was cached) its first new token.

        Three regimes by prefix-cache depth: cold (classic whole-prompt
        prefill), suffix (cached_len slots mapped shared — only the suffix
        runs, through the windowed program), full hit (every prompt page
        mapped — NO prefill compute at all; the next decode step re-derives
        the last prompt slot under copy-on-write and emits token one)."""
        n = len(req.all_tokens)
        req.state = RUNNING
        self._running.append(req)
        if req.cached_len >= n:
            self._count("prefix_full_hits")
            self._register_prefix(req)
            return
        if req.cached_len > 0:
            suf = n - req.cached_len
            sb = self._seq_bucket(suf)
            pb = _round_up_pow2(max(len(req.pages),
                                    self.pool.pages_for(req.cached_len + sb)))
            tok = np.zeros((1, sb), np.int32)
            tok[0, :suf] = req.all_tokens[req.cached_len:]
            pos = req.cached_len + np.arange(sb, dtype=np.int32)[None, :]
            pos = np.minimum(pos, self.cfg.max_position - 1)
            pages = np.zeros((1, pb), np.int32)
            pages[0, :len(req.pages)] = req.pages
            feed = {sv_model.TOK_FEED: tok, sv_model.POS_FEED: pos,
                    sv_model.PAGES_FEED: pages,
                    sv_model.START_FEED: np.asarray([req.cached_len],
                                                    np.int32),
                    sv_model.LEN_FEED: np.asarray([suf], np.int32)}
            nxt, lg = self._dispatch(
                "suffix_prefill", self._window_run, feed,
                [self._window_io["next_token"],
                 self._window_io["last_logits"]])
            self.stats["prefill_signatures"].add(("suffix", sb, pb))
            self._count("prefill_tokens_computed", suf)
        else:
            sb = self._seq_bucket(n)
            pb = max(len(req.pages), self.pool.pages_for(sb))
            tok = np.zeros((1, sb), np.int32)
            tok[0, :n] = req.all_tokens
            pos = np.arange(sb, dtype=np.int32)[None, :]
            pos = np.minimum(pos, self.cfg.max_position - 1)
            pages = np.zeros((1, pb), np.int32)
            pages[0, :len(req.pages)] = req.pages
            feed = {sv_model.TOK_FEED: tok, sv_model.POS_FEED: pos,
                    sv_model.PAGES_FEED: pages,
                    sv_model.LEN_FEED: np.asarray([n], np.int32)}
            nxt, lg = self._dispatch(
                "prefill", self._prefill_run, feed,
                [self._prefill_io["next_token"],
                 self._prefill_io["last_logits"]])
            self.stats["prefill_signatures"].add((sb, pb))
            self._count("prefill_tokens_computed", n)
        self._count("prefills")
        self._register_prefix(req)
        self._accept_token(req, self._first_token(req, nxt, lg))

    def _register_prefix(self, req: GenRequest) -> None:
        """Index the request's full PROMPT pages so later arrivals sharing
        the prompt map them instead of recomputing. The cache takes its own
        refcount per page, so the entries outlive the request."""
        if self.prefix_cache is not None:
            self.prefix_cache.insert(req.all_tokens[:req.prompt_len],
                                     req.pages)

    def _accept_token(self, req: GenRequest, tok: int) -> None:
        req.all_tokens.append(tok)
        now = time.perf_counter()
        if req.t_first_token is None:
            req.t_first_token = now
            obs.histogram_observe("serving.ttft_s", now - req.arrival_t)
            obs.event("serving.request",
                      {"rid": req.rid, "phase": "first_token",
                       "ttft_s": round(now - req.arrival_t, 9)})
        if req.is_done() or len(req.all_tokens) >= self.cfg.max_position:
            if req in self._running:
                self._running.remove(req)
            self._release(req)
            req.state = FINISHED
            req.t_done = now
            obs.histogram_observe("serving.request_s", now - req.arrival_t)
            obs.event("serving.request",
                      {"rid": req.rid, "phase": "finished",
                       "n_generated": req.n_generated,
                       "preemptions": req.preemptions,
                       "request_s": round(now - req.arrival_t, 9)})

    def _cow(self, req: GenRequest, ordinal: int) -> bool:
        """Copy-on-write req's page `ordinal`: fresh page, one in-place
        device copy across every layer's K/V pools, table repointed, old
        refcount released (other holders untouched). Returns False when the
        pool pressure this created preempted `req` itself."""
        new = self._allocate(1)
        while new is None:
            victim = max(self._running, key=lambda r: r.admit_seq)
            if victim is req and len(self._running) == 1:
                raise RuntimeError(
                    f"request {req.rid} needs a copy-on-write page but the "
                    f"pool ({self.pool.num_pages} pages) is exhausted with "
                    f"nothing left to preempt")
            self._preempt(victim)
            if victim is req:
                return False
            new = self._allocate(1)
        old = req.pages[ordinal]
        self._dispatch("cow", self._cow_run, {
            sv_model.COW_SRC_FEED: np.asarray([old], np.int32),
            sv_model.COW_DST_FEED: np.asarray([new[0]], np.int32)}, [])
        self.pool.release([old])
        req.pages[ordinal] = new[0]
        self._count("cow_copies")
        return True

    def _ensure_writable(self, lookahead: int = 0) -> dict[int, int]:
        """Every running request must OWN every page its next write window
        [cache_len, cache_len + lookahead] touches, and own it EXCLUSIVELY
        (refcount 1) — shared pages copy-on-write first. On pool exhaustion
        the lookahead shrinks before anyone is preempted (speculative slots
        are optional; the required slot is cache_len's). Returns per-rid
        granted lookahead."""
        ps = self.page_size
        granted: dict[int, int] = {}
        for req in list(self._running):
            if req.state != RUNNING:
                continue
            extra = lookahead
            while (req.cache_len + extra) // ps >= len(req.pages):
                got = self._allocate(1)
                if got is not None:
                    req.pages.extend(got)
                    continue
                if extra > 0:
                    extra -= 1
                    continue
                victim = max(self._running, key=lambda r: r.admit_seq)
                if victim is req and len(self._running) == 1:
                    raise RuntimeError(
                        f"request {req.rid} needs page "
                        f"{len(req.pages) + 1} but the pool "
                        f"({self.pool.num_pages} pages) is exhausted with "
                        f"nothing left to preempt")
                self._preempt(victim)
                if victim is req:
                    break
            if req.state != RUNNING:
                continue
            top = min(req.cache_len + extra, len(req.pages) * ps - 1)
            ok = True
            for o in range(req.cache_len // ps, top // ps + 1):
                if self.pool.refcount(req.pages[o]) > 1:
                    if not self._cow(req, o):
                        ok = False
                        break
            if ok and req.state == RUNNING:
                granted[req.rid] = extra
        return granted

    def _preempt(self, req: GenRequest) -> None:
        self._running.remove(req)
        self._release(req)
        req.state = WAITING
        req.preemptions += 1
        self._count("preemptions")
        # head of the waiting queue: a preempted request lost work, so it
        # outranks new arrivals under fcfs
        self._waiting.insert(0, req)

    def _decode_once(self) -> bool:
        # ladder rung 1+ falls back to plain one-token decode: the verify
        # window is the most speculative compute in the engine, so it is
        # the first thing sustained overload switches off
        if self.draft_k > 0 and self._ladder_rung < 1:
            return self._decode_spec()
        self._ensure_writable(0)
        rows = [r for r in self._running if r.state == RUNNING]
        if not rows:
            return False
        bb = min(_round_up_pow2(len(rows)), _round_up_pow2(self.max_inflight))
        pb = _round_up_pow2(max(len(r.pages) for r in rows))
        tok = np.zeros((bb, 1), np.int32)
        pos = np.zeros((bb,), np.int32)
        pages = np.zeros((bb, pb), np.int32)
        mask = np.zeros((bb, 1), np.float32)
        for i, r in enumerate(rows):
            tok[i, 0] = r.all_tokens[-1]
            pos[i] = r.cache_len
            pages[i, :len(r.pages)] = r.pages
            mask[i, 0] = 1.0
        feed = {sv_model.TOK_FEED: tok, sv_model.POS_FEED: pos,
                sv_model.PAGES_FEED: pages, sv_model.MASK_FEED: mask}
        nxt, lg = self._dispatch(
            "decode", self._decode_run, feed,
            [self._decode_io["next_token"], self._decode_io["logits"]])
        nxt = np.asarray(nxt).reshape(-1)
        self._count("decode_steps")
        self.stats["decode_signatures"].add((bb, pb))
        lg = None if all(r.sampling.is_greedy for r in rows) \
            else np.asarray(lg)
        for i, r in enumerate(rows):
            if r.sampling.is_greedy:
                t = int(nxt[i])
            else:
                rng = request_rng(self.seed, r.rid, r.n_generated)
                t = sample_token(lg[i], r.sampling, rng)
            self._count("decode_tokens")
            self._accept_token(r, t)
        return True

    def _decode_spec(self) -> bool:
        """One draft-verify window step: propose k tokens per row
        (ngram_draft over the row's own history), run all k+1 positions
        through the windowed program in ONE compiled step, and accept the
        verify's greedy tokens up to the first draft mismatch — bitwise the
        plain greedy sequence, 1..k+1 tokens per step."""
        k = self.draft_k
        S = k + 1
        granted = self._ensure_writable(k)
        rows = [r for r in self._running if r.state == RUNNING
                and r.rid in granted]
        if not rows:
            return False
        plans = []
        for r in rows:
            n_valid = min(S,
                          self.cfg.max_position - len(r.all_tokens),
                          r.max_new_tokens - r.n_generated,
                          granted.get(r.rid, 0) + 1)
            plans.append((r, max(1, n_valid),
                          ngram_draft(r.all_tokens, k)))
        bb = min(_round_up_pow2(len(rows)), _round_up_pow2(self.max_inflight))
        pb = _round_up_pow2(max(len(r.pages) for r in rows))
        tok = np.zeros((bb, S), np.int32)
        pos = np.zeros((bb, S), np.int32)
        pages = np.zeros((bb, pb), np.int32)
        start = np.zeros((bb,), np.int32)
        lens = np.zeros((bb,), np.int32)
        for i, (r, n_valid, drafts) in enumerate(plans):
            tok[i, 0] = r.all_tokens[-1]
            tok[i, 1:] = drafts
            pos[i] = np.minimum(r.cache_len + np.arange(S),
                                self.cfg.max_position - 1)
            pages[i, :len(r.pages)] = r.pages
            start[i] = r.cache_len
            lens[i] = n_valid
        feed = {sv_model.TOK_FEED: tok, sv_model.POS_FEED: pos,
                sv_model.PAGES_FEED: pages, sv_model.START_FEED: start,
                sv_model.LEN_FEED: lens}
        toks, lg = self._dispatch(
            "verify_window", self._window_run, feed,
            [self._window_io["tokens"], self._window_io["logits"]])
        toks = np.asarray(toks)
        self._count("decode_steps")
        self._count("spec_steps")
        self.stats["decode_signatures"].add((bb, pb))
        lg = None if all(r.sampling.is_greedy for r, _, _ in plans) \
            else np.asarray(lg)
        for i, (r, n_valid, drafts) in enumerate(plans):
            if not r.sampling.is_greedy:
                # sampling rows take exactly one (seeded) token per step;
                # draft acceptance is a greedy-only contract
                rng = request_rng(self.seed, r.rid, r.n_generated)
                t = sample_token(lg[i, 0], r.sampling, rng)
                self._count("decode_tokens")
                self._accept_token(r, t)
                continue
            m = 0
            while m < n_valid - 1 and int(drafts[m]) == int(toks[i, m]):
                m += 1
            self._count("spec_proposed", n_valid - 1)
            self._count("spec_accepted", m)
            for j in range(m + 1):
                if r.state != RUNNING:
                    break
                self._count("decode_tokens")
                self._accept_token(r, int(toks[i, j]))
        return True
