"""LLM serving runtime: paged KV cache + continuous batching + ragged
paged decode attention (ROADMAP item 1; "Ragged Paged Attention",
arXiv:2604.15464 for the kernel, "Tensor Processing Primitives",
arXiv:2104.05755 for the reusable-primitive framing).

Four pieces, one runtime:
  * `kv_cache`   — fixed-size pages over a preallocated HBM pool (device
                   side: persistable pool vars the compiled steps update in
                   place; host side: refcounted free-list + per-request page
                   tables + the page-granular PrefixCache that lets requests
                   sharing a system prompt map the SAME physical pages);
  * `model`      — the served decoder expressed as bucketed prefill /
                   windowed suffix-prefill+verify / ragged decode programs
                   over one explicit weight namespace (plus the dense
                   oracle for equivalence tests, the COW page-copy step,
                   and the GSPMD tp annotations);
  * `engine`     — the continuous-batching scheduler: admit/evict between
                   decode steps, copy-on-write prefix reuse, speculative
                   draft-verify decode (exact under greedy), backpressure
                   on pool exhaustion, recompute-style preemption,
                   chaos-abort page reclamation with refcount accounting;
  * `sampling`   — per-request temperature/top-k/top-p with per-(seed,
                   request, token) determinism across batch-bucket
                   recompiles;
  * `fleet`      — N engine replicas behind one router: heartbeat health
                   checking, prefix-affinity placement, failover replay
                   with exactly-once token delivery, drain-and-retire
                   (FLAGS_fleet_*, README "Serving fleet").

Knobs: FLAGS_serving_page_size, FLAGS_serving_pool_pages,
FLAGS_serving_max_inflight, FLAGS_serving_sched_policy,
FLAGS_serving_prefix_cache, FLAGS_serving_draft_k, FLAGS_serving_tp (see
README "Serving"). Load: tools/_serve_ab.py (open-loop arrival sweep incl.
the --shared-prefix zipf mix + --ab baseline arm) and the bench.py
`serving` block (served tokens/s, p50/p99 latency, pool occupancy, the
three-arm shared_prefix A/B) gated by tools/gate.py.
"""
from .engine import (AdmissionRejected, ContinuousBatchingScheduler,
                     GenRequest, ServingEngine, ngram_draft)
from .kv_cache import (OwnedPoolView, PagedKVPool, PrefixCache,
                       create_device_pools, pool_var_names)
from .model import (DecoderConfig, build_decode_program,
                    build_full_forward_program, build_prefill_program,
                    build_window_program, decoder_tiny)
from .sampling import SamplingParams, sample_token
from .fleet import (EngineReplica, FleetRequest, FleetRouter,
                    HandoffManager, KVLease, NoHealthyReplica,
                    disagg_fleet_factory)

__all__ = [
    "EngineReplica", "FleetRouter", "FleetRequest", "NoHealthyReplica",
    "HandoffManager", "KVLease", "disagg_fleet_factory",
    "ServingEngine", "GenRequest", "ContinuousBatchingScheduler",
    "AdmissionRejected", "OwnedPoolView",
    "PagedKVPool", "PrefixCache", "pool_var_names", "create_device_pools",
    "DecoderConfig", "decoder_tiny", "build_prefill_program",
    "build_decode_program", "build_window_program",
    "build_full_forward_program", "SamplingParams", "sample_token",
    "ngram_draft",
]
