"""LLM serving runtime: paged KV cache + continuous batching + ragged
paged decode attention (ROADMAP item 1; "Ragged Paged Attention",
arXiv:2604.15464 for the kernel, "Tensor Processing Primitives",
arXiv:2104.05755 for the reusable-primitive framing).

Three pieces, one runtime:
  * `kv_cache`   — fixed-size pages over a preallocated HBM pool (device
                   side: persistable pool vars the compiled steps update in
                   place; host side: free-list + per-request page tables);
  * `model`      — the served decoder expressed as bucketed prefill /
                   ragged decode programs over one explicit weight
                   namespace (plus the dense oracle for equivalence tests);
  * `engine`     — the continuous-batching scheduler: admit/evict between
                   decode steps, backpressure on pool exhaustion,
                   recompute-style preemption, chaos-abort page reclamation.

Knobs: FLAGS_serving_page_size, FLAGS_serving_pool_pages,
FLAGS_serving_max_inflight, FLAGS_serving_sched_policy (see README
"Serving"). Load: tools/_serve_ab.py (open-loop arrival sweep) and the
bench.py `serving` block (served tokens/s, p50/p99 latency, pool occupancy)
gated by tools/gate.py.
"""
from .engine import ContinuousBatchingScheduler, GenRequest, ServingEngine
from .kv_cache import PagedKVPool, create_device_pools, pool_var_names
from .model import (DecoderConfig, build_decode_program,
                    build_full_forward_program, build_prefill_program,
                    decoder_tiny)

__all__ = [
    "ServingEngine", "GenRequest", "ContinuousBatchingScheduler",
    "PagedKVPool", "pool_var_names", "create_device_pools",
    "DecoderConfig", "decoder_tiny", "build_prefill_program",
    "build_decode_program", "build_full_forward_program",
]
