"""Program visualization: emit Graphviz DOT for a Program's op/var graph.

Parity with /root/reference/python/paddle/fluid/net_drawer.py (draw_graph:89,
parse_graph:63): same entry points, rendered through the repo's own
`debugger.program_to_dot` (which already styles ops/vars/quant nodes) rather
than a second DOT writer. The optional `graphviz` python package is only
needed for rasterizing; DOT text generation has no dependency.

CLI parity:  python -m paddle_tpu.net_drawer --graph out.dot  (plus
--startup_graph) after pointing it at a saved program JSON.
"""
from __future__ import annotations

import argparse
import json
import logging

from .debugger import program_to_dot
from .framework import Program

__all__ = ["draw_graph", "parse_graph"]

logger = logging.getLogger(__name__)


def parse_graph(program: Program, block_idx: int = 0) -> str:
    """DOT text for one block of `program` (reference parse_graph builds the
    graphviz object; the DOT string is the portable equivalent)."""
    return program_to_dot(program, block_idx=block_idx)


def draw_graph(startup_program: Program, main_program: Program,
               graph_path: str | None = None,
               startup_graph_path: str | None = None) -> str:
    """Write DOT for the main (and optionally startup) program; returns the
    main program's DOT text (reference net_drawer.py:89 draw_graph)."""
    dot = parse_graph(main_program)
    if graph_path:
        with open(graph_path, "w") as f:
            f.write(dot)
        logger.info("wrote %s", graph_path)
    if startup_graph_path:
        with open(startup_graph_path, "w") as f:
            f.write(parse_graph(startup_program))
        logger.info("wrote %s", startup_graph_path)
    return dot


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("program_json",
                        help="program serialized by Program.to_dict (JSON)")
    parser.add_argument("--graph", default=None, help="main graph DOT path")
    parser.add_argument("--startup_graph", default=None,
                        help="also treat the input as the startup program "
                             "and write its DOT here")
    args = parser.parse_args()
    with open(args.program_json) as f:
        prog = Program.from_dict(json.load(f))
    dot = draw_graph(prog, prog, graph_path=args.graph,
                     startup_graph_path=args.startup_graph)
    if not args.graph:
        print(dot)


if __name__ == "__main__":
    main()
