"""Profiler front-end over jax.profiler (XPlane/Xprof traces).

TPU-native replacement for the reference's profiler stack:
  * python context manager `profiler` — reference fluid/profiler.py:225
  * RecordEvent host spans — reference platform/profiler.h:81
  * CUPTI device tracer -> here the XLA runtime's own trace collection
    (/root/reference/paddle/fluid/platform/device_tracer.cc:272); the output
    is an XPlane protobuf directory loadable in TensorBoard/Xprof instead of
    the reference's chrome://tracing JSON (tools/timeline.py).

The stage counters below are thin shims over the unified telemetry
registry (observability/): record_stage/bump/stage_counters keep their PR 2
API exactly (every legacy call site lands unchanged), but the accumulators
now live in the one registry snapshot() reads back, and timed stages gain
streaming-percentile histograms when FLAGS_obs_enable is on.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time

import jax

from . import flags
from . import observability as _obs

__all__ = ["profiler", "start_profiler", "stop_profiler", "RecordEvent",
           "record_event", "record_stage", "stage_timer", "stage_counters",
           "bump"]


def _resolve_dir(path: str | None) -> str:
    return path or flags.get_flag("profiler_dir")


# trace lifecycle state: start/stop must pair, and a failed start (e.g.
# os.makedirs on a read-only path) must not leave a half-open trace that
# makes every later start_profiler fail with a raw jax error
_trace_lock = threading.Lock()
_trace_active = False


def _begin_trace(path: str) -> None:
    global _trace_active
    with _trace_lock:
        if _trace_active:
            raise RuntimeError(
                "a profiler trace is already active; call stop_profiler() "
                "(or leave the profiler() context) before starting another")
        # makedirs BEFORE start_trace: if the directory cannot be created
        # nothing has started and the profiler stays cleanly stoppable/
        # restartable (no half-open trace)
        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
        _trace_active = True


def _end_trace() -> None:
    global _trace_active
    with _trace_lock:
        if not _trace_active:
            raise RuntimeError(
                "no active profiler trace — call start_profiler() (or use "
                "the profiler() context manager) before stop_profiler()")
        try:
            jax.profiler.stop_trace()
        finally:
            _trace_active = False


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str | None = None,
             profile_path: str | None = None):
    """`with profiler.profiler(...):` traces everything inside to an XPlane
    directory. `state`/`sorted_key` are accepted for reference API parity
    (fluid/profiler.py:225); on TPU the trace always covers host + device and
    sorting happens in the viewer."""
    _begin_trace(_resolve_dir(profile_path))
    try:
        yield
    finally:
        _end_trace()


def start_profiler(state: str = "All", profile_path: str | None = None):
    """Imperative start (reference fluid/profiler.py start_profiler)."""
    _begin_trace(_resolve_dir(profile_path))


def stop_profiler(sorted_key: str | None = None, profile_path: str | None = None):
    """Stop the active trace. Both args are reference-API-parity no-ops: the
    trace lands in the directory given to start_profiler, and sorting happens
    in the viewer. Raises RuntimeError (naming start_profiler) when no trace
    is active instead of surfacing the raw jax error."""
    _end_trace()


class RecordEvent(contextlib.ContextDecorator):
    """Named host span visible in the trace (reference platform/profiler.h:81
    RAII RecordEvent). Usable as a context manager or decorator."""

    def __init__(self, name: str):
        self._name = name
        self._anns: list = []  # stack: one instance may nest/recurse

    def __enter__(self):
        ann = jax.profiler.TraceAnnotation(self._name)
        ann.__enter__()
        self._anns.append(ann)
        return self

    def __exit__(self, *a):
        return self._anns.pop().__exit__(*a)


record_event = RecordEvent


# -- pipeline stage counters --------------------------------------------------
# Cheap always-on accumulators for the async feed/dispatch pipeline (host
# ingest / device transfer / dispatch / window drain). Unlike the XPlane
# trace these need no viewer: tools/_pipeline_ab.py and ad-hoc debugging read
# them directly to see which stage the end-to-end path is losing time to.
# Since ISSUE 13 the storage is the observability registry — same API, same
# cost, but the counters ride the unified snapshot/export path too.


def record_stage(stage: str, seconds: float, events: int = 1):
    """Accumulate `seconds` of wall time against a named pipeline stage."""
    _obs.stage_record(stage, seconds, events)


def bump(stage: str, events: int = 1):
    """Count an event with no wall time against a named counter — the
    robustness paths (corrupt-record skips, non-finite send drops, guard
    skips) use these so post-mortems can see how much was dropped."""
    _obs.stage_record(stage, 0.0, events)


@contextlib.contextmanager
def stage_timer(stage: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_stage(stage, time.perf_counter() - t0)


def stage_counters(reset: bool = False) -> dict:
    """Snapshot {stage: {"events": n, "seconds": s}}; reset=True zeroes the
    accumulators after reading (epoch-scoped measurements)."""
    return _obs.stage_counters(reset)
