"""Profiler front-end over jax.profiler (XPlane/Xprof traces).

TPU-native replacement for the reference's profiler stack:
  * python context manager `profiler` — reference fluid/profiler.py:225
  * RecordEvent host spans — reference platform/profiler.h:81
  * CUPTI device tracer -> here the XLA runtime's own trace collection
    (/root/reference/paddle/fluid/platform/device_tracer.cc:272); the output
    is an XPlane protobuf directory loadable in TensorBoard/Xprof instead of
    the reference's chrome://tracing JSON (tools/timeline.py).
"""
from __future__ import annotations

import contextlib
import os
import threading
import time

import jax

from . import flags

__all__ = ["profiler", "start_profiler", "stop_profiler", "RecordEvent",
           "record_event", "record_stage", "stage_timer", "stage_counters",
           "bump"]


def _resolve_dir(path: str | None) -> str:
    return path or flags.get_flag("profiler_dir")


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str | None = None,
             profile_path: str | None = None):
    """`with profiler.profiler(...):` traces everything inside to an XPlane
    directory. `state`/`sorted_key` are accepted for reference API parity
    (fluid/profiler.py:225); on TPU the trace always covers host + device and
    sorting happens in the viewer."""
    path = _resolve_dir(profile_path)
    os.makedirs(path, exist_ok=True)
    with jax.profiler.trace(path):
        yield


def start_profiler(state: str = "All", profile_path: str | None = None):
    """Imperative start (reference fluid/profiler.py start_profiler)."""
    path = _resolve_dir(profile_path)
    os.makedirs(path, exist_ok=True)
    jax.profiler.start_trace(path)


def stop_profiler(sorted_key: str | None = None, profile_path: str | None = None):
    """Stop the active trace. Both args are reference-API-parity no-ops: the
    trace lands in the directory given to start_profiler, and sorting happens
    in the viewer."""
    jax.profiler.stop_trace()


class RecordEvent(contextlib.ContextDecorator):
    """Named host span visible in the trace (reference platform/profiler.h:81
    RAII RecordEvent). Usable as a context manager or decorator."""

    def __init__(self, name: str):
        self._name = name
        self._anns: list = []  # stack: one instance may nest/recurse

    def __enter__(self):
        ann = jax.profiler.TraceAnnotation(self._name)
        ann.__enter__()
        self._anns.append(ann)
        return self

    def __exit__(self, *a):
        return self._anns.pop().__exit__(*a)


record_event = RecordEvent


# -- pipeline stage counters --------------------------------------------------
# Cheap always-on accumulators for the async feed/dispatch pipeline (host
# ingest / device transfer / dispatch / window drain). Unlike the XPlane
# trace these need no viewer: tools/_pipeline_ab.py and ad-hoc debugging read
# them directly to see which stage the end-to-end path is losing time to.
_stage_lock = threading.Lock()
_stage_counters: dict[str, list] = {}  # stage -> [events, seconds]


def record_stage(stage: str, seconds: float, events: int = 1):
    """Accumulate `seconds` of wall time against a named pipeline stage."""
    with _stage_lock:
        c = _stage_counters.setdefault(stage, [0, 0.0])
        c[0] += events
        c[1] += seconds


def bump(stage: str, events: int = 1):
    """Count an event with no wall time against a named counter — the
    robustness paths (corrupt-record skips, non-finite send drops, guard
    skips) use these so post-mortems can see how much was dropped."""
    record_stage(stage, 0.0, events)


@contextlib.contextmanager
def stage_timer(stage: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_stage(stage, time.perf_counter() - t0)


def stage_counters(reset: bool = False) -> dict:
    """Snapshot {stage: {"events": n, "seconds": s}}; reset=True zeroes the
    accumulators after reading (epoch-scoped measurements)."""
    with _stage_lock:
        snap = {k: {"events": v[0], "seconds": v[1]}
                for k, v in _stage_counters.items()}
        if reset:
            _stage_counters.clear()
    return snap
