"""Rolling-window SLO monitor: registry thresholds -> warn/alert callbacks.

The watchdog (resilience/watchdog.py) turns a *hang* into a structured
exception; this turns a *degradation* into a structured callback. Rules
read the registry snapshot (p99 latency histograms, leak gauges, hit-rate
counters); one breach within the window is a WARN, a rule breached
`alert_after` times inside `window_s` escalates to ALERT — a single slow
scrape never pages, a sustained one always does.

Every breach is also recorded on the registry itself (`slo.breaches`
counter labeled (rule, severity) + an `slo.breach` event), so the export
path carries the verdicts along with the measurements that produced them.

Default rules come from the FLAGS_obs_slo_* knobs (serving p99 request
latency, KV-page leaks, prefix-cache hit-rate floor); `add_rule` takes
arbitrary snapshot predicates for everything else.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque

from .. import flags
from .registry import registry as _default_registry

__all__ = ["SloRule", "SloMonitor", "default_serving_monitor"]

logger = logging.getLogger("paddle_tpu.observability.slo")


class SloRule:
    """One threshold: `check(snapshot)` returns the measured value when
    breached, None when healthy."""

    def __init__(self, name: str, check, threshold, describe: str = ""):
        self.name = name
        self.check = check
        self.threshold = threshold
        self.describe = describe or name


def hist_p99_above(hist_name: str, ceiling_s: float):
    def check(snap):
        h = snap.get("histograms", {}).get(hist_name)
        if not h or not h.get("count"):
            return None
        p99 = h.get("p99")
        return p99 if p99 is not None and p99 > ceiling_s else None
    return check


def gauge_above(gauge_name: str, ceiling: float):
    def check(snap):
        v = snap.get("gauges", {}).get(gauge_name)
        return v if v is not None and v > ceiling else None
    return check


def counter_ratio_below(num_name: str, den_names, floor: float,
                        min_den: float = 1.0):
    """Breach when num / sum(dens) < floor (hit-rate style). Quiet until
    the denominator has seen at least `min_den` events."""
    def check(snap):
        c = snap.get("counters", {})
        den = sum(c.get(n, 0.0) for n in den_names)
        if den < min_den:
            return None
        rate = c.get(num_name, 0.0) / den
        return rate if rate < floor else None
    return check


class SloMonitor:
    """Evaluate rules against registry snapshots on demand (`observe()`)
    or on a background cadence (`start(period_s)`)."""

    def __init__(self, registry=None, window_s: float = 60.0,
                 alert_after: int = 3, on_warn=None, on_alert=None):
        self.registry = registry
        self.window_s = float(window_s)
        self.alert_after = max(1, int(alert_after))
        self.on_warn = on_warn or self._log_warn
        self.on_alert = on_alert or self._log_alert
        self.rules: list[SloRule] = []
        self._breach_times: dict[str, deque] = {}
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    @staticmethod
    def _log_warn(breach: dict) -> None:
        logger.warning("SLO warn: %s = %s (threshold %s)",
                       breach["rule"], breach["value"], breach["threshold"],
                       extra={"slo_breach": breach})

    @staticmethod
    def _log_alert(breach: dict) -> None:
        logger.error("SLO ALERT: %s = %s (threshold %s, %d breaches in "
                     "%.3gs)", breach["rule"], breach["value"],
                     breach["threshold"], breach["count_in_window"],
                     breach["window_s"], extra={"slo_breach": breach})

    def add_rule(self, name: str, check, threshold,
                 describe: str = "") -> "SloMonitor":
        self.rules.append(SloRule(name, check, threshold, describe))
        return self

    def observe(self, snapshot: dict | None = None,
                now: float | None = None) -> list[dict]:
        """One evaluation pass; returns the breaches it saw (each already
        counted, evented and dispatched to its callback)."""
        reg = self.registry or _default_registry()
        snap = snapshot if snapshot is not None else reg.snapshot()
        now = time.monotonic() if now is None else now
        breaches = []
        for rule in self.rules:
            value = rule.check(snap)
            if value is None:
                continue
            times = self._breach_times.setdefault(rule.name, deque())
            times.append(now)
            while times and now - times[0] > self.window_s:
                times.popleft()
            severity = ("alert" if len(times) >= self.alert_after
                        else "warn")
            breach = {"rule": rule.name, "value": value,
                      "threshold": rule.threshold, "severity": severity,
                      "describe": rule.describe,
                      "count_in_window": len(times),
                      "window_s": self.window_s}
            reg.counter_inc("slo.breaches",
                            labels={"rule": rule.name, "severity": severity})
            reg.event("slo.breach", breach,
                      level="error" if severity == "alert" else "warning")
            (self.on_alert if severity == "alert" else self.on_warn)(breach)
            breaches.append(breach)
        return breaches

    def start(self, period_s: float = 5.0) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(period_s):
                try:
                    self.observe()
                except Exception:  # noqa: BLE001 — monitor never kills work
                    logger.exception("SLO monitor pass failed")

        self._thread = threading.Thread(target=loop, name="obs-slo-monitor",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def default_serving_monitor(registry=None, **kw) -> SloMonitor:
    """The flag-configured serving monitor: FLAGS_obs_slo_p99_ms caps
    serving.request_s p99, FLAGS_obs_slo_max_leaked_pages caps the
    serving.leaked_pages gauge, FLAGS_obs_slo_min_hit_rate floors the
    prefix-cache hit rate. Disabled thresholds (0/negative where 0 means
    off) add no rule."""
    mon = SloMonitor(registry=registry, **kw)
    p99_ms = float(flags.get_flag("obs_slo_p99_ms"))
    if p99_ms > 0:
        mon.add_rule("serving_p99_latency",
                     hist_p99_above("serving.request_s", p99_ms / 1e3),
                     p99_ms / 1e3,
                     f"serving.request_s p99 above {p99_ms} ms")
    max_leak = int(flags.get_flag("obs_slo_max_leaked_pages"))
    mon.add_rule("kv_pages_leaked",
                 gauge_above("serving.leaked_pages", float(max_leak)),
                 max_leak, "KV pool pages leaked past the allowance")
    hit_floor = float(flags.get_flag("obs_slo_min_hit_rate"))
    if hit_floor > 0:
        mon.add_rule(
            "prefix_hit_rate",
            counter_ratio_below(
                "serving.prefix_hit_tokens",
                ("serving.prefix_hit_tokens",
                 "serving.prefill_tokens_computed"),
                hit_floor),
            hit_floor, "prefix-cache hit rate below floor")
    return mon
