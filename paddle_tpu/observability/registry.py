"""Typed, thread-safe metrics registry — the one telemetry spine.

Five previously-incompatible instrumentation vocabularies (profiler stage
counters, the serving engine's stats dict, watchdog dumps, guardrail
events, tuner provenance) all land here, so one `snapshot()` answers what
used to take five bespoke readers. Design points:

  * one lock, plain dicts: the hot path (a counter bump) costs one lock
    acquisition and two dict operations — the same as the PR 2 stage
    counters it replaces, so always-on instrumentation stays ~free;
  * histograms are streaming log-bucketed (8 buckets/decade, 1e-9..1e9):
    p50/p95/p99 in O(buckets) with bounded memory, no reservoir, no sort;
  * labeled series: a (name, labels) pair is one series — the tuner's
    per-(op, tier) provenance and the embedding engine's per-table
    counters stop being ad-hoc nested dicts;
  * declared schema: names are registered up front (schema.DECLARED);
    free-form names still record but surface in `snapshot()["undeclared"]`
    and tools/gate.py --obs fails on them;
  * `snapshot(reset=True)` is atomic — read-and-zero under the lock, so
    concurrent writers can never be double-counted or lost across the
    reset boundary (the 8-thread test pins this);
  * FLAGS_obs_enable gates the *extra* machinery (histograms, events,
    spans, exporter sinks). Counters/gauges/stages stay on either way so
    `profiler.stage_counters()` semantics never depend on the flag — off
    reduces the layer to exactly the legacy accumulator cost (the bench
    telemetry A/B measures the difference; gate ceiling 2%).
"""
from __future__ import annotations

import bisect
import contextlib
import math
import threading
import time
from collections import deque

from .. import flags
from . import schema as _schema

__all__ = ["MetricsRegistry", "registry", "enabled", "counter_inc",
           "gauge_set", "histogram_observe", "event", "span", "snapshot",
           "stage_record", "stage_counters", "reset", "attach_sink",
           "detach_sink"]


def enabled() -> bool:
    """FLAGS_obs_enable (histograms/events/spans/sinks). Counters, gauges
    and stage accumulators are always on."""
    try:
        return bool(flags.get_flag("obs_enable"))
    except KeyError:  # flags module mid-import
        return True


# log-spaced histogram bounds: 8 per decade over 1e-9 .. 1e9 (145 bounds,
# 146 buckets). Bucket ratio 10^(1/8) ~= 1.33, so a reported percentile is
# within ~15% of the true one — plenty for latency SLOs.
_BOUNDS = tuple(10.0 ** (k / 8.0) for k in range(-72, 73))


class _Histogram:
    __slots__ = ("count", "total", "vmin", "vmax", "buckets")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.buckets = [0] * (len(_BOUNDS) + 1)

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        self.buckets[bisect.bisect_right(_BOUNDS, v)] += 1

    def quantile(self, q: float) -> float | None:
        if not self.count:
            return None
        target = q * self.count
        cum = 0
        for i, n in enumerate(self.buckets):
            cum += n
            if n and cum >= target:
                lo = _BOUNDS[i - 1] if i > 0 else self.vmin
                hi = _BOUNDS[i] if i < len(_BOUNDS) else self.vmax
                mid = math.sqrt(lo * hi) if lo > 0 and hi > 0 else hi
                return min(max(mid, self.vmin), self.vmax)
        return self.vmax

    def summary(self) -> dict:
        if not self.count:
            return {"count": 0, "sum": 0.0, "min": None, "max": None,
                    "p50": None, "p95": None, "p99": None}
        return {"count": self.count, "sum": self.total,
                "min": self.vmin, "max": self.vmax,
                "p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}


def _lkey(labels: dict | None) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items())) \
        if labels else ()


def _fmt(key: tuple) -> str:
    """Series display key: `name` or `name{k="v",...}` (Prometheus style)."""
    name, labels = key
    if not labels:
        return name
    body = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{body}}}"


def base_name(series_key: str) -> str:
    """Strip the label body from a formatted series key."""
    return series_key.split("{", 1)[0]


class MetricsRegistry:
    """Thread-safe typed metric store; see module docstring."""

    def __init__(self, schema=None, max_events: int = 1024):
        self._lock = threading.Lock()
        self._schema: dict[str, dict] = {}
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        self._hists: dict[tuple, _Histogram] = {}
        self._stages: dict[str, list] = {}  # name -> [events, seconds]
        self._undeclared: set[str] = set()
        self._events: deque = deque(maxlen=max(1, int(max_events)))
        self._sinks: list = []
        for spec in (schema or ()):
            name, kind = spec[0], spec[1]
            help_ = spec[2] if len(spec) > 2 else ""
            labels = spec[3] if len(spec) > 3 else ()
            self.declare(name, kind, help_, labels)

    # -- schema --------------------------------------------------------------
    def declare(self, name: str, kind: str, help: str = "",
                labels=()) -> None:
        with self._lock:
            self._schema[name] = {"kind": kind, "help": help,
                                  "labels": tuple(labels)}
            self._undeclared.discard(name)

    def declared_names(self) -> frozenset:
        with self._lock:
            return frozenset(self._schema)

    def _note(self, name: str) -> None:
        # caller holds self._lock
        if name not in self._schema:
            self._undeclared.add(name)

    # -- mutators ------------------------------------------------------------
    def counter_inc(self, name: str, value: float = 1,
                    labels: dict | None = None) -> None:
        key = (name, _lkey(labels))
        with self._lock:
            self._note(name)
            self._counters[key] = self._counters.get(key, 0) + value

    def gauge_set(self, name: str, value: float,
                  labels: dict | None = None) -> None:
        key = (name, _lkey(labels))
        with self._lock:
            self._note(name)
            self._gauges[key] = float(value)

    def histogram_observe(self, name: str, value: float,
                          labels: dict | None = None) -> None:
        if not enabled():
            return
        key = (name, _lkey(labels))
        with self._lock:
            self._note(name)
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _Histogram()
            h.observe(value)

    def stage_record(self, stage: str, seconds: float,
                     events: int = 1) -> None:
        """The profiler.record_stage/bump accumulator: exact legacy
        semantics ([events, seconds] per stage) plus, when the layer is
        enabled, a latency histogram per timed stage."""
        hist = seconds > 0.0 and enabled()
        with self._lock:
            self._note(stage)
            c = self._stages.get(stage)
            if c is None:
                c = self._stages[stage] = [0, 0.0]
            c[0] += events
            c[1] += seconds
            if hist:
                h = self._hists.get((stage, ()))
                if h is None:
                    h = self._hists[(stage, ())] = _Histogram()
                h.observe(seconds)

    def event(self, name: str, payload: dict | None = None,
              level: str = "info") -> dict | None:
        if not enabled():
            return None
        rec = {"ts": time.time(), "type": "event", "name": name,
               "level": level}
        if payload:
            rec["payload"] = payload
        with self._lock:
            self._note(name)
            self._events.append(rec)
            sinks = list(self._sinks)
        for s in sinks:
            try:
                s(rec)
            except Exception:  # noqa: BLE001 — a broken sink never kills work
                pass
        return rec

    @contextlib.contextmanager
    def span(self, name: str, labels: dict | None = None):
        """Named span: a `jax.profiler.TraceAnnotation` (visible in XPlane
        traces) + a `<name>.seconds` histogram sample + a JSONL span record
        through the sinks. No-op when the layer is disabled."""
        if not enabled():
            yield
            return
        import jax

        t0 = time.perf_counter()
        with jax.profiler.TraceAnnotation(name):
            try:
                yield
            finally:
                dt = time.perf_counter() - t0
                self.histogram_observe(name + ".seconds", dt, labels)
                rec = {"ts": time.time(), "type": "span", "name": name,
                       "dur_s": round(dt, 9)}
                if labels:
                    rec["labels"] = dict(labels)
                with self._lock:
                    sinks = list(self._sinks)
                for s in sinks:
                    try:
                        s(rec)
                    except Exception:  # noqa: BLE001
                        pass

    # -- readers -------------------------------------------------------------
    def stage_counters(self, reset: bool = False) -> dict:
        with self._lock:
            snap = {k: {"events": v[0], "seconds": v[1]}
                    for k, v in self._stages.items()}
            if reset:
                self._stages.clear()
        return snap

    def snapshot(self, reset: bool = False) -> dict:
        """One atomic read of everything; reset=True zeroes the store under
        the same lock (no event can land between the read and the clear)."""
        with self._lock:
            out = {
                "counters": {_fmt(k): v for k, v in self._counters.items()},
                "gauges": {_fmt(k): v for k, v in self._gauges.items()},
                "histograms": {_fmt(k): h.summary()
                               for k, h in self._hists.items()},
                "stages": {k: {"events": v[0], "seconds": v[1]}
                           for k, v in self._stages.items()},
                "events": list(self._events),
                "undeclared": sorted(self._undeclared),
            }
            if reset:
                self._counters.clear()
                self._gauges.clear()
                self._hists.clear()
                self._stages.clear()
                self._events.clear()
                self._undeclared.clear()
        return out

    def reset(self, prefix: str | None = None) -> None:
        """Zero series (optionally only those whose name starts with
        `prefix`) without touching the event ring or the schema — the
        measurement boundary for scoped runs (bench arms, warmup passes)."""
        with self._lock:
            if prefix is None:
                self._counters.clear()
                self._gauges.clear()
                self._hists.clear()
                self._stages.clear()
                return
            for store in (self._counters, self._gauges, self._hists):
                for key in [k for k in store if k[0].startswith(prefix)]:
                    del store[key]
            for key in [k for k in self._stages if k.startswith(prefix)]:
                del self._stages[key]

    # -- sinks ---------------------------------------------------------------
    def attach_sink(self, sink) -> None:
        """`sink(record: dict)` receives every event/span record."""
        with self._lock:
            self._sinks.append(sink)

    def detach_sink(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)


# -- the process-wide default registry ----------------------------------------
_default: MetricsRegistry | None = None
_default_lock = threading.Lock()


def registry() -> MetricsRegistry:
    """The default registry, created on first use with the declared schema
    and the flag-configured exporters (FLAGS_obs_jsonl_dir JSONL stream,
    FLAGS_obs_http_port /metrics endpoint) attached."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                try:
                    max_ev = int(flags.get_flag("obs_max_events"))
                except KeyError:
                    max_ev = 1024
                reg = MetricsRegistry(_schema.DECLARED, max_events=max_ev)
                from . import exporters

                exporters.install_flag_exporters(reg)
                _default = reg
    return _default


def counter_inc(name, value=1, labels=None):
    registry().counter_inc(name, value, labels)


def gauge_set(name, value, labels=None):
    registry().gauge_set(name, value, labels)


def histogram_observe(name, value, labels=None):
    registry().histogram_observe(name, value, labels)


def event(name, payload=None, level="info"):
    return registry().event(name, payload, level)


def span(name, labels=None):
    return registry().span(name, labels)


def snapshot(reset=False):
    return registry().snapshot(reset)


def stage_record(stage, seconds, events=1):
    registry().stage_record(stage, seconds, events)


def stage_counters(reset=False):
    return registry().stage_counters(reset)


def reset(prefix=None):
    registry().reset(prefix)


def attach_sink(sink):
    registry().attach_sink(sink)


def detach_sink(sink):
    registry().detach_sink(sink)
