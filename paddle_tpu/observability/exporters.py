"""Exporters: rotating atomic JSONL stream, Prometheus text snapshots,
and an optional /metrics HTTP endpoint.

Three consumers, three formats:
  * JSONL — the durable stream (events, spans, snapshots) tools/obs.py
    tails/summarizes/diffs. One record per line, written with a single
    O_APPEND write so concurrent writers never interleave mid-line;
    rotation is size-triggered and atomic (os.replace to `<path>.1`).
  * Prometheus text exposition — the scrape format ops tooling already
    speaks. `prometheus_text()` renders a registry snapshot; counters and
    gauges verbatim, histograms as summaries (quantile-labeled series +
    _sum/_count), stage accumulators as `<stage>_events`/`_seconds_total`
    counter pairs. `write_prometheus()` is temp+rename atomic (the same
    discipline as tuning/db.py).
  * HTTP — `start_http_exporter(port)` serves the live snapshot at
    /metrics from a stdlib daemon thread (FLAGS_obs_http_port).

`parse_prometheus()` is the round-trip half: it parses the exposition
text back to {series: value}, and tools/gate.py-adjacent tests use it to
prove a live run's export is byte-for-byte parseable.
"""
from __future__ import annotations

import json
import os
import re
import threading

__all__ = ["JsonlWriter", "jsonl_line", "prometheus_text",
           "write_prometheus", "parse_prometheus", "start_http_exporter",
           "install_flag_exporters"]


def jsonl_line(record: dict) -> bytes:
    """The canonical encoding of one stream record (compact separators,
    sorted keys): the byte-for-byte round-trip contract is
    `jsonl_line(json.loads(line)) == line`."""
    return (json.dumps(record, default=str, sort_keys=True,
                       separators=(",", ":")) + "\n").encode()


class JsonlWriter:
    """Append-only JSONL stream with atomic line writes and size-based
    rotation. Callable, so it plugs straight in as a registry sink."""

    def __init__(self, path: str, rotate_bytes: int = 8 << 20):
        self.path = path
        self.rotate_bytes = max(4096, int(rotate_bytes))
        self._lock = threading.Lock()
        self._fd: int | None = None
        self._size = 0

    def _open(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._fd = os.open(self.path,
                           os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        self._size = os.fstat(self._fd).st_size

    def write(self, record: dict) -> None:
        line = jsonl_line(record)
        with self._lock:
            if self._fd is None:
                self._open()
            if self._size + len(line) > self.rotate_bytes and self._size:
                os.close(self._fd)
                # atomic rotation: the live path always holds a complete
                # stream; readers of `<path>.1` see the previous one
                os.replace(self.path, self.path + ".1")
                self._open()
            os.write(self._fd, line)
            self._size += len(line)

    __call__ = write

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None


# -- Prometheus text exposition ----------------------------------------------
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LINE_RE = re.compile(  # value: float incl. negative exponents / nan / inf
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+([-+0-9.eEnaif]+)$')


def _prom_name(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _split_series(series: str) -> tuple[str, str]:
    """'name{k="v"}' -> ('name', '{k="v"}'); bare name -> (name, '')."""
    if "{" in series:
        name, rest = series.split("{", 1)
        return name, "{" + rest
    return series, ""


def _num(v) -> str:
    if v is None:
        return "nan"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(snapshot: dict) -> str:
    """Render a registry snapshot in the Prometheus text exposition format
    (deterministic ordering, so identical snapshots render identical
    bytes)."""
    out: list[str] = []
    for series, value in sorted(snapshot.get("counters", {}).items()):
        name, labels = _split_series(series)
        pname = _prom_name(name)
        out.append(f"# TYPE {pname} counter")
        out.append(f"{pname}{labels} {_num(value)}")
    for series, value in sorted(snapshot.get("gauges", {}).items()):
        name, labels = _split_series(series)
        pname = _prom_name(name)
        out.append(f"# TYPE {pname} gauge")
        out.append(f"{pname}{labels} {_num(value)}")
    for stage, row in sorted(snapshot.get("stages", {}).items()):
        pname = _prom_name(stage)
        out.append(f"# TYPE {pname}_events counter")
        out.append(f"{pname}_events {_num(row['events'])}")
        out.append(f"# TYPE {pname}_seconds_total counter")
        out.append(f"{pname}_seconds_total {_num(row['seconds'])}")
    for series, h in sorted(snapshot.get("histograms", {}).items()):
        name, labels = _split_series(series)
        pname = _prom_name(name)
        body = labels[1:-1] if labels else ""
        out.append(f"# TYPE {pname} summary")
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            lab = f'quantile="{q}"' + (f",{body}" if body else "")
            out.append(f"{pname}{{{lab}}} {_num(h.get(key))}")
        out.append(f"{pname}_sum{labels} {_num(h.get('sum', 0.0))}")
        out.append(f"{pname}_count{labels} {_num(h.get('count', 0))}")
    return "\n".join(out) + ("\n" if out else "")


def write_prometheus(path: str, snapshot: dict) -> str:
    """Atomic (temp+rename) Prometheus snapshot file; returns the text."""
    text = prometheus_text(snapshot)
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    return text


def parse_prometheus(text: str) -> dict[str, float]:
    """Parse exposition text back to {series_line_key: value}. Raises
    ValueError on any unparseable non-comment line — the strictness IS the
    round-trip check."""
    out: dict[str, float] = {}
    for i, ln in enumerate(text.splitlines()):
        ln = ln.strip()
        if not ln or ln.startswith("#"):
            continue
        m = _LINE_RE.match(ln)
        if not m:
            raise ValueError(f"unparseable exposition line {i + 1}: {ln!r}")
        name, labels, value = m.groups()
        out[name + (labels or "")] = float(value)
    return out


def start_http_exporter(registry, port: int, host: str = "127.0.0.1"):
    """Serve the live registry snapshot at /metrics (Prometheus text) from
    a stdlib daemon thread. Returns the HTTPServer (its .server_address[1]
    is the bound port — pass port=0 for an ephemeral one)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_error(404)
                return
            body = prometheus_text(registry.snapshot()).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # silence per-request stderr noise
            pass

    server = ThreadingHTTPServer((host, int(port)), _Handler)
    t = threading.Thread(target=server.serve_forever,
                         name="obs-metrics-http", daemon=True)
    t.start()
    return server


def install_flag_exporters(registry) -> None:
    """Attach the flag-configured exporters to a registry at creation:
    FLAGS_obs_jsonl_dir (event/span JSONL stream) and FLAGS_obs_http_port
    (/metrics endpoint). Failures are non-fatal — telemetry must never be
    the reason a job dies."""
    from .. import flags

    try:
        d = str(flags.get_flag("obs_jsonl_dir")).strip()
        if d:
            rotate = float(flags.get_flag("obs_jsonl_rotate_mb")) * 1e6
            registry.attach_sink(
                JsonlWriter(os.path.join(d, "obs.jsonl"), int(rotate)))
    except Exception:  # noqa: BLE001
        pass
    try:
        port = int(flags.get_flag("obs_http_port"))
        if port > 0:
            start_http_exporter(registry, port)
    except Exception:  # noqa: BLE001
        pass
