"""Unified telemetry layer (ISSUE 13): one registry, three exports.

The stack's five ad-hoc instrumentation vocabularies — profiler stage
counters, the serving engine's stats dict, watchdog stdout dumps,
guardrail events, tuner provenance — all migrate onto the typed
thread-safe registry here. `registry.py` is the spine (counters, gauges,
streaming-percentile histograms, labeled series, events, spans,
atomic `snapshot(reset=True)`), `schema.py` declares every permitted
metric name (tools/gate.py --obs lints drift), `exporters.py` ships it
(rotating atomic JSONL, Prometheus text, /metrics endpoint) and `slo.py`
watches it (rolling-window thresholds -> warn/alert callbacks).

Usage is module-level against the process-wide default registry:

    from paddle_tpu import observability as obs
    obs.counter_inc("serving.prefills")
    obs.histogram_observe("serving.ttft_s", 0.042)
    with obs.span("serving.decode"):
        ...                         # TraceAnnotation + histogram + JSONL
    snap = obs.snapshot()           # everything, atomically
"""
from __future__ import annotations

from . import schema  # noqa: F401
from .exporters import (  # noqa: F401
    JsonlWriter, jsonl_line, parse_prometheus, prometheus_text,
    start_http_exporter, write_prometheus)
from .registry import (  # noqa: F401
    MetricsRegistry, attach_sink, base_name, counter_inc, detach_sink,
    enabled, event, gauge_set, histogram_observe, registry, reset,
    snapshot, span, stage_counters, stage_record)
from .slo import SloMonitor, SloRule, default_serving_monitor  # noqa: F401


def export_prometheus(path: str | None = None) -> str | None:
    """Write the default registry's snapshot as a Prometheus text file to
    `path` (default FLAGS_obs_prometheus_path; no-op when unset). Returns
    the rendered text."""
    from .. import flags as _flags

    p = path or str(_flags.get_flag("obs_prometheus_path")).strip()
    if not p:
        return None
    return write_prometheus(p, snapshot())

__all__ = [
    "MetricsRegistry", "registry", "enabled", "counter_inc", "gauge_set",
    "histogram_observe", "event", "span", "snapshot", "stage_record",
    "stage_counters", "reset", "attach_sink", "detach_sink", "base_name",
    "schema", "JsonlWriter", "jsonl_line", "prometheus_text",
    "write_prometheus", "parse_prometheus", "start_http_exporter",
    "SloMonitor", "SloRule", "default_serving_monitor",
    "export_prometheus",
]
