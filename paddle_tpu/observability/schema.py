"""The declared metric schema: every name the runtime is allowed to emit.

One flat list, imported by the registry at construction and by
tools/gate.py --obs at lint time. A metric recorded under a name that is
not declared here still lands (post-mortems beat purity), but the registry
tracks it in `snapshot()["undeclared"]` and the gate turns that into a
hard failure — adding a counter is a schema act, not just a call site.

Kinds:
  stage     — the profiler.record_stage/bump accumulators ([events, seconds]
              pairs; the PR 2 pipeline vocabulary, kept verbatim so every
              legacy call site lands unchanged)
  counter   — monotonically increasing value, optionally labeled
  gauge     — last-set value (occupancy, rates)
  histogram — streaming distribution with p50/p95/p99 (log-spaced buckets)
  event     — structured record on the event ring / JSONL stream
"""
from __future__ import annotations

STAGE, COUNTER, GAUGE, HISTOGRAM, EVENT = (
    "stage", "counter", "gauge", "histogram", "event")

# (name, kind, help, label keys)
DECLARED: list[tuple] = [
    # -- pipeline stage counters (profiler.record_stage / profiler.bump) ----
    ("pipeline.host_ingest", STAGE,
     "DeviceLoader producer: host batch materialization", ()),
    ("pipeline.device_put", STAGE,
     "host->device staging transfers (DeviceLoader / feed_placer)", ()),
    ("pipeline.dispatch", STAGE,
     "Executor compiled-step dispatch (host side of one async step)", ()),
    ("pipeline.window_drain", STAGE,
     "run_async window-boundary waits on the oldest completion token", ()),
    ("feed.skip_corrupt", STAGE,
     "corrupt records skipped under FLAGS_feed_skip_corrupt", ()),
    ("emb.resolved_batches", STAGE,
     "tiered-embedding batches resolved through the hot-ID cache", ()),
    ("ps.nonfinite_drop", STAGE,
     "non-finite gradient sends dropped by the pserver", ()),
    ("comm.nonfinite_drop", STAGE,
     "non-finite gradient sends dropped by the async communicator", ()),
    # -- serving runtime (serving/engine.py) --------------------------------
    ("serving.prefills", COUNTER, "prompt prefills executed", ()),
    ("serving.decode_steps", COUNTER, "batched decode steps", ()),
    ("serving.decode_tokens", COUNTER, "tokens accepted by decode", ()),
    ("serving.preemptions", COUNTER,
     "requests preempted back to the waiting queue", ()),
    ("serving.aborts", COUNTER, "requests aborted", ()),
    ("serving.prefill_tokens_computed", COUNTER,
     "prompt tokens that actually ran through prefill compute", ()),
    ("serving.prefix_hit_tokens", COUNTER,
     "prompt tokens served from the prefix cache", ()),
    ("serving.prefix_lookups", COUNTER, "prefix-cache lookups", ()),
    ("serving.prefix_full_hits", COUNTER,
     "prompts fully covered by cached pages (zero-prefill admits)", ()),
    ("serving.cow_copies", COUNTER, "copy-on-write page copies", ()),
    ("serving.spec_steps", COUNTER, "speculative draft-verify steps", ()),
    ("serving.spec_proposed", COUNTER, "draft tokens proposed", ()),
    ("serving.spec_accepted", COUNTER, "draft tokens accepted", ()),
    ("serving.pages_in_use", GAUGE, "KV pool pages currently mapped", ()),
    ("serving.pool_occupancy", GAUGE,
     "KV pool occupancy fraction (pages_in_use / num_pages)", ()),
    ("serving.leaked_pages", GAUGE,
     "pages no live request or cache entry accounts for (must be 0)", ()),
    ("serving.queue_s", HISTOGRAM,
     "request queue time: submit -> admission", ()),
    ("serving.ttft_s", HISTOGRAM,
     "time to first token: submit -> first generated token", ()),
    ("serving.request_s", HISTOGRAM,
     "request latency: submit -> finished", ()),
    ("serving.prefill.seconds", HISTOGRAM,
     "prefill span durations (also a TraceAnnotation in XPlane)", ()),
    ("serving.decode.seconds", HISTOGRAM,
     "decode-step span durations (also a TraceAnnotation in XPlane)", ()),
    ("serving.request", EVENT,
     "per-request lifecycle record: queued/admitted/first_token/finished/"
     "aborted/deadline_exceeded/shed/rejected/quarantined",
     ("rid", "phase")),
    # -- serving resilience (ISSUE 14: deadlines/shedding/supervision) ------
    ("serving.deadline_exceeded", COUNTER,
     "requests expired past their TTL (at admission or between steps)", ()),
    ("serving.shed", COUNTER,
     "WAITING requests shed by admission control or the ladder", ()),
    ("serving.rejects", COUNTER,
     "submits rejected with AdmissionRejected (retry-after surfaced)", ()),
    ("serving.step_retries", COUNTER,
     "compiled-step dispatch retries absorbed by the supervisor", ()),
    ("serving.recovery.passes", COUNTER,
     "engine recovery passes (quarantine + pool rebuild + replay)", ()),
    ("serving.recovery.replayed", COUNTER,
     "surviving requests replayed from their prompts by recovery", ()),
    ("serving.recovery.quarantined", COUNTER,
     "poisoned requests quarantined (aborted, pages forfeited) by "
     "recovery", ()),
    ("serving.handoff_extracts", COUNTER,
     "prefilled requests extracted HANDED_OFF for disaggregated "
     "prefill->decode transfer (ISSUE 19)", ()),
    ("serving.adopts", COUNTER,
     "lease-transferred requests adopted mid-decode from a prefill "
     "engine (prefill skipped entirely)", ()),
    ("serving.ladder.spec_off", COUNTER,
     "degradation-ladder climbs to rung 1: speculative decode off", ()),
    ("serving.ladder.lookahead_shrink", COUNTER,
     "degradation-ladder climbs to rung 2: admission reserves no decode "
     "lookahead page", ()),
    ("serving.ladder.cache_evict", COUNTER,
     "degradation-ladder climbs to rung 3: prefix-cache LRU tail "
     "evicted under pressure", ()),
    ("serving.ladder.shed", COUNTER,
     "degradation-ladder climbs to rung 4: lowest-priority waiters "
     "shed", ()),
    ("serving.ladder_rung", GAUGE,
     "current degradation-ladder rung (0 = nominal .. 4 = shedding)", ()),
    ("serving.degrade", EVENT,
     "ladder transition record (rung, direction, pressure signals)", ()),
    ("serving.recovery", EVENT,
     "recovery-pass record (reason, quarantined, replayed, problems)", ()),
    ("serving.step_retry", EVENT,
     "one absorbed dispatch retry (kind, attempt, error)", ()),
    # -- serving fleet (serving/fleet/: router + replicas, ISSUE 16) --------
    ("fleet.submits", COUNTER, "requests accepted by the fleet router", ()),
    ("fleet.finished", COUNTER, "fleet requests finished", ()),
    ("fleet.failed", COUNTER,
     "fleet requests failed: failover budget exhausted or no healthy "
     "replica left to place on", ()),
    ("fleet.sheds", COUNTER,
     "submits refused fleet-wide (EVERY healthy replica shedding)", ()),
    ("fleet.rejects", COUNTER,
     "per-replica admission rejections absorbed by re-placement", ()),
    ("fleet.failovers", COUNTER,
     "budget-consuming re-placements (replica death or rejection)", ()),
    ("fleet.handoffs", COUNTER,
     "budget-free drain handoffs of waiting work off a DRAINING replica",
     ()),
    ("fleet.deaths", COUNTER,
     "replicas declared DEAD (missed heartbeats or administrative kill)",
     ()),
    ("fleet.retires", COUNTER,
     "replicas that completed drain-and-retire", ()),
    ("fleet.replayed_tokens", COUNTER,
     "already-delivered tokens a re-placement must regenerate", ()),
    ("fleet.dedup_tokens", COUNTER,
     "regenerated tokens suppressed by the router's delivered ledger "
     "(each client token delivered exactly once)", ()),
    ("fleet.replay_divergence", COUNTER,
     "replayed positions that disagreed with the ledger (possible under "
     "temperature sampling; must be 0 under greedy)", ()),
    ("fleet.affinity_hits", COUNTER,
     "placements landing on the prompt's affinity home replica", ()),
    ("fleet.affinity_misses", COUNTER,
     "placements degraded to least-loaded (home not HEALTHY)", ()),
    ("fleet.affinity_hit_rate", GAUGE,
     "affinity_hits / (hits + misses) over the router's lifetime", ()),
    ("fleet.replicas_healthy", GAUGE, "replicas currently HEALTHY", ()),
    ("fleet.replicas_draining", GAUGE, "replicas currently DRAINING", ()),
    ("fleet.replicas_dead", GAUGE, "replicas currently DEAD", ()),
    ("fleet.replica_state", GAUGE,
     "per-replica lifecycle state (0=healthy 1=draining 2=retired 3=dead)",
     ("rid",)),
    ("fleet.drain_s", HISTOGRAM,
     "drain-and-retire duration: begin_drain -> RETIRED", ()),
    ("fleet.ttft_s", HISTOGRAM,
     "fleet-level time to first DELIVERED token (failover included)", ()),
    ("fleet.request_s", HISTOGRAM,
     "fleet-level request latency: submit -> finished", ()),
    ("fleet.replica", EVENT,
     "replica lifecycle record (healthy/draining/dead/retired/crashed)",
     ()),
    ("fleet.request", EVENT,
     "fleet request lifecycle record (placed/finished/failed/rejected/"
     "budget_exhausted/unplaceable)", ()),
    # -- disaggregated prefill/decode handoff (serving/fleet/handoff.py,
    #    ISSUE 19) -----------------------------------------------------------
    ("fleet.prefill_dispatches", COUNTER,
     "prompts dispatched to a prefill-role replica (disaggregated "
     "placement: decode home chosen, prefill stage runs first)", ()),
    ("fleet.handoff.prepared", COUNTER,
     "prefill->decode handoffs published under a lease (PREPARE)", ()),
    ("fleet.handoff.committed", COUNTER,
     "handoffs adopted by a decode engine (COMMIT: lease refcount "
     "transferred, decode resumes mid-request)", ()),
    ("fleet.handoff.commit_failed", COUNTER,
     "commits rejected: unknown lease, double commit, expiry race, or a "
     "draining/bouncing adopter", ()),
    ("fleet.handoff.released", COUNTER,
     "post-commit prefill-pin releases confirmed to the prefill side", ()),
    ("fleet.handoff.dropped", COUNTER,
     "prepared messages lost in flight (disagg_handoff_drop site): the "
     "lease stays published and the reaper recovers it at TTL", ()),
    ("fleet.handoff.replays", COUNTER,
     "handed-off requests replayed from the prompt (reaped lease, failed "
     "commit, or a death mid-handoff)", ()),
    ("fleet.handoff.s", HISTOGRAM,
     "handoff latency: lease PREPARE -> decode COMMIT", ()),
    ("fleet.handoff", EVENT,
     "handoff lifecycle record (prepared/committed/reaped/abandoned)", ()),
    ("fleet.lease.granted", COUNTER,
     "KV leases granted (page tables pinned in the shared pool)", ()),
    ("fleet.lease.reaped", COUNTER,
     "leases reclaimed: TTL expiry, abandonment, or expiry at commit", ()),
    ("fleet.lease.expired_at_commit", COUNTER,
     "commits that lost the expiry race (rejected atomically; the "
     "request replays)", ()),
    ("fleet.lease.active", GAUGE, "leases currently PREPARED", ()),
    ("fleet.lease.pinned_pages", GAUGE,
     "shared-pool pages currently pinned by leases (in transit)", ()),
    # -- learned serving control (serving/control/, ISSUE 20) ---------------
    ("serving.control.proposals", COUNTER,
     "knob-config proposals resolved, by tier (learned = a gated ridge "
     "prediction stood; hand = the flag config served)", ("tier",)),
    ("serving.control.fallbacks", COUNTER,
     "proposals that fell back to the hand flags, by reason (no_model/"
     "no_group/accuracy/envelope/features/off/...)", ("reason",)),
    ("serving.control.staged", COUNTER,
     "apply-mode proposals staged as a pending EngineConfig", ()),
    ("serving.control.applies", COUNTER,
     "pending EngineConfigs adopted at a safe boundary (engine idle gap "
     "/ router epoch tick)", ()),
    ("serving.control.rewarmups", COUNTER,
     "warmup_decode re-runs forced by an adopted bucket-geometry change "
     "(keeps XLA compiles off the serving path)", ()),
    ("serving.control.regime", GAUGE,
     "current traffic-regime id (stable hash bucket of the regime key)",
     ()),
    ("serving.control.goodput_rel_err", HISTOGRAM,
     "realized-vs-predicted goodput relative error per controller epoch "
     "(the controller grading its own prior)", ()),
    ("serving.control.actuation", EVENT,
     "actuation lifecycle record (staged/adopted, geometry change, "
     "rewarm)", ()),
    # -- training step telemetry (executor.py async window) -----------------
    ("train.steps", COUNTER, "async steps drained to completion", ()),
    ("train.step_latency_s", HISTOGRAM,
     "dispatch -> completion-token latency per drained step", ()),
    ("train.batches_per_sec", GAUGE,
     "train_from_dataset steady-state batch rate", ()),
    ("train.jit_compiles", COUNTER,
     "whole-block XLA compiles observed by jit_compile_counter", ()),
    # -- numeric guardrails (resilience/guardrails.py) ----------------------
    ("guard.events", COUNTER,
     "StepGuard verdicts by action (skip/rewind/...)", ("action",)),
    ("guard.step", EVENT,
     "structured StepGuard event (the PR 4 health-vector verdicts)", ()),
    # -- hang watchdog (resilience/watchdog.py) -----------------------------
    ("watchdog.stalls", COUNTER, "StallError raises", ()),
    ("watchdog.stall", EVENT,
     "watchdog stall dump (what/window/in-flight state)", ()),
    # -- autotuner provenance (tuning/policy.py) ----------------------------
    ("tuning.decisions", COUNTER,
     "decide() resolutions by (op, tier) — tier in "
     "db/learned/analytic/default",
     ("op", "tier")),
    # -- learned cost model (tuning/learned/) -------------------------------
    ("tuning.learned.predictions", COUNTER,
     "learned-tier decisions that stood (confidence gates passed, "
     "validate accepted)", ("op",)),
    ("tuning.learned.fallbacks", COUNTER,
     "learned-tier attempts that fell back to the analytic prior, by "
     "reason (accuracy/envelope/features/feature_drift/validate)",
     ("op", "reason")),
    ("tuning.learned.explore_promotions", COUNTER,
     "explore-mode candidates promoted to swept DB entries by an "
     "out-of-band online verdict", ("op",)),
    # -- tiered embeddings (embedding/engine.py) ----------------------------
    ("emb.hit_ids", COUNTER,
     "id occurrences served from the hot-ID cache", ("table",)),
    ("emb.miss_ids", COUNTER,
     "id occurrences that missed the cache (host-tier prefetch)",
     ("table",)),
    ("emb.evictions", COUNTER, "cache rows evicted (written back)",
     ("table",)),
    ("emb.writebacks", COUNTER, "dirty rows written back to the host tier",
     ("table",)),
    # -- pserver liveness (distributed/ps_rpc.py) ---------------------------
    ("ps.evictions", COUNTER,
     "trainers evicted from the sync barrier by the liveness monitor", ()),
    ("ps.rejoins", COUNTER, "evicted trainers re-admitted", ()),
    ("ps.liveness", EVENT, "evict/rejoin/grace-shutdown liveness record",
     ()),
    # -- SLO monitor (observability/slo.py) ---------------------------------
    ("slo.breaches", COUNTER, "SLO rule breaches", ("rule", "severity")),
    ("slo.breach", EVENT, "SLO breach record (rule, value, threshold)", ()),
]

DECLARED_NAMES = frozenset(spec[0] for spec in DECLARED)

# the stage names every legacy profiler.bump/record_stage call site uses —
# tests/test_observability.py greps the source tree against this set, so a
# new bump("...") literal must be declared here to stay green
STAGE_NAMES = frozenset(s[0] for s in DECLARED if s[1] == STAGE)
