"""Gradient clipping (reference /root/reference/python/paddle/fluid/clip.py:
GradientClipByValue:132, GradientClipByNorm:196, GradientClipByGlobalNorm:261,
set_gradient_clip:332, append_gradient_clip_ops:367).

The global-norm clip builds the reduction as ops in the program, so under
XLA+GSPMD the norm is computed once per step, fused, and — in data-parallel
runs — on already-allreduced grads.
"""
from __future__ import annotations

from .layer_helper import LayerHelper

__all__ = [
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "set_gradient_clip",
    "append_gradient_clip_ops",
    "error_clip_callback",
]


class BaseGradientClipAttr:
    def _create_operators(self, param, grad, helper):
        raise NotImplementedError


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _create_operators(self, param, grad, helper):
        out = helper.create_variable_for_type_inference(grad.dtype)
        helper.append_op(
            "clip",
            inputs={"X": [grad]},
            outputs={"Out": [out]},
            attrs={"min": self.min, "max": self.max},
        )
        return param, out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _create_operators(self, param, grad, helper):
        out = helper.create_variable_for_type_inference(grad.dtype)
        helper.append_op(
            "clip_by_norm",
            inputs={"X": [grad]},
            outputs={"Out": [out]},
            attrs={"max_norm": self.clip_norm},
        )
        return param, out


class GradientClipByGlobalNorm:
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_all(self, params_grads, helper):
        from .layers import nn as L
        from .layers import tensor as T

        sq_norms = []
        for _, g in params_grads:
            if g is None:
                continue
            sq = helper.create_variable_for_type_inference(g.dtype)
            helper.append_op("squared_l2_norm", inputs={"X": [g]}, outputs={"Out": [sq]})
            sq_norms.append(sq)
        global_sq = helper.create_variable_for_type_inference(sq_norms[0].dtype)
        helper.append_op("sum", inputs={"X": sq_norms}, outputs={"Out": [global_sq]})
        global_norm = L.sqrt(global_sq)
        clip_var = T.fill_constant([1], "float32", self.clip_norm)
        # scale = clip / max(clip, global_norm)
        denom = L.elementwise_max(global_norm, clip_var)
        factor = L.elementwise_div(clip_var, denom)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            ng = L.elementwise_mul(g, factor)
            out.append((p, ng))
        return out


_global_clip = None


def set_gradient_clip(clip, param_list=None, program=None):
    global _global_clip
    _global_clip = clip


def append_gradient_clip_ops(params_grads):
    helper = LayerHelper("gradient_clip")
    if isinstance(_global_clip, GradientClipByGlobalNorm):
        return _global_clip._clip_all(params_grads, helper)
    out = []
    for p, g in params_grads:
        clip_attr = getattr(p, "gradient_clip_attr", None) or _global_clip
        if g is None or clip_attr is None:
            out.append((p, g))
            continue
        out.append(clip_attr._create_operators(p, g, helper))
    return out


def error_clip_callback(block, context):
    pass
