"""MNIST (reference python/paddle/dataset/mnist.py): samples are
(image: float32[784] in [-1,1], label: int64 scalar)."""
from __future__ import annotations

import gzip
import struct

import numpy as np

from .common import locate

__all__ = ["train", "test", "is_synthetic"]

_TRAIN_N, _TEST_N = 8192, 1024  # synthetic sizes (real: 60000/10000)


def is_synthetic() -> bool:
    return locate("mnist", "train-images-idx3-ubyte.gz") is None


def _parse_idx(images_path: str, labels_path: str):
    with gzip.open(images_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad idx3 magic {magic}"
        imgs = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
    with gzip.open(labels_path, "rb") as f:
        magic, n2 = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx1 magic {magic}"
        labels = np.frombuffer(f.read(), np.uint8)
    imgs = imgs.astype(np.float32) / 127.5 - 1.0
    return imgs, labels.astype(np.int64)


def _synthetic(n, seed):
    rng = np.random.default_rng(seed)
    # 10 fixed class prototypes + noise: learnable, deterministic
    protos = rng.standard_normal((10, 784)).astype(np.float32)
    labels = rng.integers(0, 10, n).astype(np.int64)
    imgs = np.clip(protos[labels] * 0.5 +
                   rng.standard_normal((n, 784)).astype(np.float32) * 0.3,
                   -1.0, 1.0).astype(np.float32)
    return imgs, labels


def _reader(split: str):
    def reader():
        img_f = locate("mnist", f"{split}-images-idx3-ubyte.gz")
        lbl_f = locate("mnist", f"{split}-labels-idx1-ubyte.gz")
        if img_f and lbl_f:
            imgs, labels = _parse_idx(img_f, lbl_f)
        else:
            n = _TRAIN_N if split == "train" else _TEST_N
            imgs, labels = _synthetic(n, seed=0 if split == "train" else 1)
        for i in range(len(labels)):
            yield imgs[i], int(labels[i])

    return reader


def train():
    return _reader("train")


def test():
    return _reader("t10k") if locate("mnist", "t10k-images-idx3-ubyte.gz") \
        else _reader("test")
