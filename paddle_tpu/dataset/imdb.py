"""IMDB sentiment (reference python/paddle/dataset/imdb.py): samples are
(token_ids: int64 list, label: int64 0/1). word_dict() gives the vocab."""
from __future__ import annotations

import re
import tarfile

import numpy as np

from .common import locate

__all__ = ["train", "test", "word_dict", "is_synthetic"]

_VOCAB = 5147  # synthetic vocab size (reference build_dict cutoff ~5147)
_SYN_TRAIN, _SYN_TEST = 2048, 256
_TOKEN = re.compile(r"[a-z]+")


def is_synthetic() -> bool:
    return locate("imdb", "aclImdb_v1.tar.gz") is None


_word_dict_cache: dict = {}


def word_dict() -> dict:
    path = locate("imdb", "aclImdb_v1.tar.gz")
    key = path or "<synthetic>"
    if key in _word_dict_cache:
        return _word_dict_cache[key]
    if path:
        freq: dict = {}
        with tarfile.open(path, "r:gz") as tf:
            for m in tf.getmembers():
                if re.match(r"aclImdb/train/(pos|neg)/.*\.txt$", m.name):
                    text = tf.extractfile(m).read().decode("utf-8", "ignore").lower()
                    for w in _TOKEN.findall(text):
                        freq[w] = freq.get(w, 0) + 1
        words = sorted(freq, key=lambda w: (-freq[w], w))
        d = {w: i for i, w in enumerate(words)}
    else:
        d = {f"w{i}": i for i in range(_VOCAB - 1)}
    d["<unk>"] = len(d)
    _word_dict_cache[key] = d
    return d


def _parse(path, split, wd):
    unk = wd["<unk>"]
    with tarfile.open(path, "r:gz") as tf:
        for m in tf.getmembers():
            mm = re.match(rf"aclImdb/{split}/(pos|neg)/.*\.txt$", m.name)
            if mm:
                text = tf.extractfile(m).read().decode("utf-8", "ignore").lower()
                ids = [wd.get(w, unk) for w in _TOKEN.findall(text)]
                yield ids, int(mm.group(1) == "pos")


def _synthetic(n, seed, vocab=_VOCAB):
    rng = np.random.default_rng(seed)
    # class-dependent token distributions so the task is learnable
    for _ in range(n):
        label = int(rng.integers(0, 2))
        length = int(rng.integers(16, 128))
        lo, hi = (0, vocab // 2) if label == 0 else (vocab // 2, vocab)
        ids = rng.integers(lo, hi, length).tolist()
        yield ids, label


def _reader(split, seed, word_idx=None):
    def reader():
        path = locate("imdb", "aclImdb_v1.tar.gz")
        if path:
            yield from _parse(path, split, word_idx or word_dict())
        else:
            vocab = (max(word_idx.values()) + 1) if word_idx else _VOCAB
            yield from _synthetic(_SYN_TRAIN if split == "train" else _SYN_TEST,
                                  seed, vocab)

    return reader


def train(word_idx=None):
    """word_idx: optional custom vocabulary dict {word: id} (reference
    imdb.py train(word_idx)); ids are emitted from it so they never exceed
    the caller's embedding table."""
    return _reader("train", 0, word_idx)


def test(word_idx=None):
    return _reader("test", 1, word_idx)
