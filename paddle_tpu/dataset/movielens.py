"""MovieLens-1M recommendation (reference python/paddle/dataset/movielens.py):
each sample is user features + movie features + [[rating]] —
[user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
[rating]] matching the reference's `usr.value() + mov.value() + [[rating]]`
(movielens.py:166).

Real data: place ml-1m.zip under DATA_HOME/movielens (reference layout:
users.dat/movies.dat/ratings.dat '::'-separated). Zero-egress fallback:
deterministic synthetic interactions with the same id spaces.
"""
from __future__ import annotations

import re
import zipfile

import numpy as np

from .common import locate

__all__ = [
    "train", "test", "get_movie_title_dict", "max_movie_id", "max_user_id",
    "max_job_id", "age_table", "movie_categories", "is_synthetic",
]

age_table = [1, 18, 25, 35, 45, 50, 56]

_SYN_USERS, _SYN_MOVIES = 500, 400
_SYN_CATS, _SYN_TITLE_WORDS = 18, 1500
_SYN_TRAIN, _SYN_TEST = 4096, 512
_SYN_JOBS = 21


def is_synthetic() -> bool:
    return locate("movielens", "ml-1m.zip") is None


def max_user_id() -> int:
    return _SYN_USERS if is_synthetic() else _real()["max_user"]


def max_movie_id() -> int:
    return _SYN_MOVIES if is_synthetic() else _real()["max_movie"]


def max_job_id() -> int:
    return _SYN_JOBS - 1


def movie_categories() -> list[str]:
    if is_synthetic():
        return [f"cat{i}" for i in range(_SYN_CATS)]
    return sorted(_real()["categories"])


def get_movie_title_dict() -> dict:
    if is_synthetic():
        return {f"t{i}": i for i in range(_SYN_TITLE_WORDS)}
    return _real()["title_dict"]


_cache: dict = {}


def _real():
    if _cache:
        return _cache
    path = locate("movielens", "ml-1m.zip")
    users, movies, ratings = {}, {}, []
    categories, title_words = set(), {}
    with zipfile.ZipFile(path) as zf:
        def _lines(name):
            for n in zf.namelist():
                if n.endswith(name):
                    return zf.read(n).decode("latin1").splitlines()
            return []

        for line in _lines("users.dat"):
            uid, gender, age, job, _ = line.split("::")
            # reference UserInfo.value(): 0 if male else 1
            users[int(uid)] = [int(uid), int(gender != "M"),
                              age_table.index(int(age)), int(job)]
        for line in _lines("movies.dat"):
            mid, title, cats = line.split("::")
            words = re.findall(r"[a-z0-9]+", title.lower())
            for w in words:
                title_words.setdefault(w, len(title_words))
            cat_list = cats.strip().split("|")
            categories.update(cat_list)
            movies[int(mid)] = (words, cat_list)
        cat_idx = {c: i for i, c in enumerate(sorted(categories))}
        for line in _lines("ratings.dat"):
            uid, mid, r, _ = line.split("::")
            uid, mid = int(uid), int(mid)
            if uid in users and mid in movies:
                words, cat_list = movies[mid]
                ratings.append(
                    users[uid]
                    + [mid, [cat_idx[c] for c in cat_list],
                       [title_words[w] for w in words], [float(r)]])
    _cache.update(
        max_user=max(users), max_movie=max(movies),
        categories=categories, title_dict=title_words, samples=ratings)
    return _cache


def _synthetic(n, seed):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        uid = int(rng.integers(1, _SYN_USERS + 1))
        mid = int(rng.integers(1, _SYN_MOVIES + 1))
        cats = rng.integers(0, _SYN_CATS, int(rng.integers(1, 4))).tolist()
        title = rng.integers(0, _SYN_TITLE_WORDS,
                             int(rng.integers(1, 6))).tolist()
        # deterministic preference structure so models can actually learn
        rating = 1.0 + ((uid * 7 + mid * 13) % 9) / 2.0
        yield [uid, int(uid % 2), int(uid % len(age_table)),
               int(uid % _SYN_JOBS), mid, cats, title, [rating]]


def _reader(split, n, seed):
    def reader():
        if is_synthetic():
            yield from _synthetic(n, seed)
            return
        samples = _real()["samples"]
        # reference __initialize_meta_info__ shuffles then splits 9:1
        rng = np.random.default_rng(0)
        idx = rng.permutation(len(samples))
        cut = int(len(samples) * 0.9)
        chosen = idx[:cut] if split == "train" else idx[cut:]
        for i in chosen:
            yield samples[i]

    return reader


def train():
    return _reader("train", _SYN_TRAIN, 0)


def test():
    return _reader("test", _SYN_TEST, 1)
