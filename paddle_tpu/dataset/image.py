"""Image preprocessing utilities (reference python/paddle/dataset/image.py:
197-327 resize_short, to_chw, center_crop, random_crop, left_right_flip,
simple_transform, load_and_transform).

NumPy-native: the reference hard-requires cv2 for resizing; here
`resize_short` is a pure-numpy bilinear resample (no cv2/PIL dependency),
with cv2 used opportunistically when present (identical contract, cubic
interpolation). Decoding compressed files (`load_image`) still needs
PIL or cv2 and raises a clear error when neither is importable.
"""
from __future__ import annotations

import numpy as np

try:  # probe once: a failed import inside the per-image hot path would
    import cv2 as _cv2  # re-run a full finder scan per call
except ImportError:
    _cv2 = None

__all__ = [
    "resize_short", "to_chw", "center_crop", "random_crop",
    "left_right_flip", "simple_transform", "load_and_transform",
    "load_image",
]


def _resize_bilinear(im: np.ndarray, h_new: int, w_new: int) -> np.ndarray:
    """Pure-numpy bilinear resize, HWC or HW layout, dtype-preserving."""
    h, w = im.shape[:2]
    if (h, w) == (h_new, w_new):
        return im
    squeeze = im.ndim == 2
    if squeeze:
        im = im[:, :, None]
    # sample positions with half-pixel centers (align_corners=False)
    ys = (np.arange(h_new) + 0.5) * h / h_new - 0.5
    xs = (np.arange(w_new) + 0.5) * w / w_new - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
    imf = im.astype(np.float32)
    top = imf[y0][:, x0] * (1 - wx) + imf[y0][:, x1] * wx
    bot = imf[y1][:, x0] * (1 - wx) + imf[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if np.issubdtype(im.dtype, np.integer):
        out = np.clip(np.rint(out), np.iinfo(im.dtype).min,
                      np.iinfo(im.dtype).max)
    out = out.astype(im.dtype)
    return out[:, :, 0] if squeeze else out


def resize_short(im, size):
    """Resize so the SHORTER edge equals `size` (reference image.py:197).
    im: HWC (or HW) ndarray."""
    h, w = im.shape[:2]
    h_new, w_new = size, size
    if h > w:
        h_new = size * h // w
    else:
        w_new = size * w // h
    if _cv2 is not None:  # optional fast path, reference interpolation
        return _cv2.resize(im, (w_new, h_new),
                           interpolation=_cv2.INTER_CUBIC)
    return _resize_bilinear(im, h_new, w_new)


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW transpose (reference image.py:225)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """Crop the center `size` x `size` patch (reference image.py:249)."""
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    if is_color:
        return im[h_start:h_start + size, w_start:w_start + size, :]
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True, rng=None):
    """Crop a random `size` x `size` patch (reference image.py:277). The
    extra `rng` lets callers make the crop deterministic."""
    rng = rng or np.random
    # accept both the legacy RandomState API (randint) and the modern
    # Generator API (integers)
    draw = getattr(rng, "integers", None) or rng.randint
    h, w = im.shape[:2]
    h_start = int(draw(0, h - size + 1)) if h > size else 0
    w_start = int(draw(0, w - size + 1)) if w > size else 0
    if is_color:
        return im[h_start:h_start + size, w_start:w_start + size, :]
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    """Mirror horizontally (reference image.py:305)."""
    if len(im.shape) == 3 and is_color:
        return im[:, ::-1, :]
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short -> crop (random+flip when training, center otherwise)
    -> CHW -> optional mean subtraction (reference image.py:327)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and len(im.shape) == 3:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_image(file, is_color=True):
    """Decode an image file to an HWC uint8 ndarray. Needs PIL or cv2
    (reference image.py:167 uses cv2)."""
    if _cv2 is not None:
        flag = _cv2.IMREAD_COLOR if is_color else _cv2.IMREAD_GRAYSCALE
        return _cv2.imread(file, flag)
    try:
        from PIL import Image

        img = Image.open(file)
        img = img.convert("RGB" if is_color else "L")
        return np.asarray(img)
    except ImportError:
        raise ImportError(
            "decoding image files needs cv2 or PIL; neither is importable "
            "(the numpy transforms resize_short/center_crop/random_crop/"
            "to_chw work on already-decoded arrays)")


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """load_image + simple_transform (reference image.py:383)."""
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
