"""CIFAR-10/100 (reference python/paddle/dataset/cifar.py): samples are
(image: float32[3072] in [0,1], label: int64)."""
from __future__ import annotations

import pickle
import tarfile

import numpy as np

from .common import locate

__all__ = ["train10", "test10", "train100", "test100", "is_synthetic"]

_SYN_TRAIN, _SYN_TEST = 4096, 512


def is_synthetic() -> bool:
    return locate("cifar", "cifar-10-python.tar.gz") is None


def _read_batches(tar_path: str, want_train: bool, label_key: str):
    with tarfile.open(tar_path, "r:gz") as tf:
        for member in tf.getmembers():
            name = member.name
            is_train = "data_batch" in name or "train" in name.split("/")[-1]
            is_test = "test" in name.split("/")[-1]
            if (want_train and is_train) or (not want_train and is_test):
                d = pickle.load(tf.extractfile(member), encoding="bytes")
                data = d[b"data"].astype(np.float32) / 255.0
                labels = next(
                    (v for k in (label_key.encode(), b"labels", b"fine_labels")
                     if (v := d.get(k)) is not None),
                    None,
                )
                if labels is None:
                    raise KeyError(
                        f"no label key in cifar batch: {sorted(d.keys())}")
                for row, lab in zip(data, labels):
                    yield row, int(lab)


def _synthetic(n, classes, seed):
    rng = np.random.default_rng(seed)
    protos = rng.random((classes, 3072)).astype(np.float32)
    labels = rng.integers(0, classes, n).astype(np.int64)
    imgs = np.clip(protos[labels] * 0.6 + rng.random((n, 3072)).astype(np.float32) * 0.4,
                   0.0, 1.0).astype(np.float32)
    for i in range(n):
        yield imgs[i], int(labels[i])


def _reader(archive, want_train, classes, label_key, seed):
    def reader():
        path = locate("cifar", archive)
        if path:
            yield from _read_batches(path, want_train, label_key)
        else:
            yield from _synthetic(_SYN_TRAIN if want_train else _SYN_TEST,
                                  classes, seed)

    return reader


def train10():
    return _reader("cifar-10-python.tar.gz", True, 10, "labels", 0)


def test10():
    return _reader("cifar-10-python.tar.gz", False, 10, "labels", 1)


def train100():
    return _reader("cifar-100-python.tar.gz", True, 100, "fine_labels", 2)


def test100():
    return _reader("cifar-100-python.tar.gz", False, 100, "fine_labels", 3)
