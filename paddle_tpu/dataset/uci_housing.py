"""UCI housing regression (reference python/paddle/dataset/uci_housing.py):
samples are (features: float32[13] normalized, price: float32[1])."""
from __future__ import annotations

import numpy as np

from .common import locate

__all__ = ["train", "test", "is_synthetic"]

_N = 506  # real dataset size; synthetic matches


def is_synthetic() -> bool:
    return locate("uci_housing", "housing.data") is None


def _load():
    path = locate("uci_housing", "housing.data")
    if path:
        data = np.loadtxt(path).astype(np.float32)
        feats, prices = data[:, :-1], data[:, -1:]
    else:
        rng = np.random.default_rng(42)
        feats = rng.standard_normal((_N, 13)).astype(np.float32)
        w = rng.standard_normal((13, 1)).astype(np.float32)
        prices = (feats @ w + rng.standard_normal((_N, 1)).astype(np.float32) * 0.1
                  + 22.0).astype(np.float32)
    mu, sigma = feats.mean(0), feats.std(0) + 1e-8
    feats = (feats - mu) / sigma
    return feats, prices


def _reader(lo, hi):
    def reader():
        feats, prices = _load()
        n = len(feats)
        for i in range(int(lo * n), int(hi * n)):
            yield feats[i], prices[i]

    return reader


def train():
    return _reader(0.0, 0.8)


def test():
    return _reader(0.8, 1.0)
