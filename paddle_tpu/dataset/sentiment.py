"""NLTK movie-review sentiment (reference
python/paddle/dataset/sentiment.py:116): samples are
(word_ids tuple, label 0/1); get_word_dict() gives the frequency-ranked
vocabulary.

Real data: movie_reviews.zip (NLTK corpus layout, pos/neg folders of .txt)
under DATA_HOME/sentiment. Zero-egress fallback: deterministic synthetic
reviews whose word distribution differs by class.
"""
from __future__ import annotations

import re
import zipfile

import numpy as np

from .common import locate

__all__ = ["train", "test", "get_word_dict", "is_synthetic"]

_VOCAB = 2000
_SYN_TRAIN, _SYN_TEST = 1600, 400
_TOKEN = re.compile(r"[a-z]+")


def is_synthetic() -> bool:
    return locate("sentiment", "movie_reviews.zip") is None


_cache: dict = {}


def get_word_dict() -> dict:
    if "wd" in _cache:
        return _cache["wd"]
    path = locate("sentiment", "movie_reviews.zip")
    if path:
        freq: dict = {}
        with zipfile.ZipFile(path) as zf:
            for n in zf.namelist():
                if n.endswith(".txt"):
                    for w in _TOKEN.findall(
                            zf.read(n).decode("latin1").lower()):
                        freq[w] = freq.get(w, 0) + 1
        wd = {w: i for i, w in enumerate(
            sorted(freq, key=lambda w: (-freq[w], w)))}
    else:
        wd = {f"w{i}": i for i in range(_VOCAB)}
    _cache["wd"] = wd
    return wd


def _real_samples():
    if "samples" in _cache:
        return _cache["samples"]
    wd = get_word_dict()
    path = locate("sentiment", "movie_reviews.zip")
    samples = []
    with zipfile.ZipFile(path) as zf:
        for n in sorted(zf.namelist()):
            m = re.search(r"(pos|neg)/[^/]+\.txt$", n)
            if m:
                ids = [wd[w] for w in _TOKEN.findall(
                    zf.read(n).decode("latin1").lower()) if w in wd]
                samples.append((tuple(ids), int(m.group(1) == "pos")))
    rng = np.random.default_rng(0)
    rng.shuffle(samples)
    _cache["samples"] = samples
    return samples


def _synthetic(n, seed):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        label = int(rng.integers(0, 2))
        length = int(rng.integers(10, 120))
        # class-conditional token distribution: pos reviews skew low ids
        base = 0 if label else _VOCAB // 2
        ids = (base + rng.integers(0, _VOCAB // 2, length)).tolist()
        yield tuple(ids), label


def _reader(split, n, seed):
    def reader():
        if is_synthetic():
            yield from _synthetic(n, seed)
            return
        samples = _real_samples()
        cut = int(len(samples) * 0.8)
        chosen = samples[:cut] if split == "train" else samples[cut:]
        yield from chosen

    return reader


def train():
    return _reader("train", _SYN_TRAIN, 0)


def test():
    return _reader("test", _SYN_TEST, 1)
