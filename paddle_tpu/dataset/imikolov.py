"""PTB language modeling / imikolov (reference
python/paddle/dataset/imikolov.py:99): NGRAM mode yields n-gram tuples of
word ids (the word2vec training data); SEQ mode yields (src_seq, trg_seq)
shifted pairs.

Real data: simple-examples.tgz under DATA_HOME/imikolov (PTB layout).
Zero-egress fallback: deterministic synthetic corpus with Zipf-ish unigram
statistics.
"""
from __future__ import annotations

import tarfile

import numpy as np

from .common import locate

__all__ = ["train", "test", "build_dict", "DataType", "is_synthetic"]

_VOCAB = 2000
_SYN_SENTS_TRAIN, _SYN_SENTS_TEST = 2048, 256


class DataType:
    NGRAM = 1
    SEQ = 2


def is_synthetic() -> bool:
    return locate("imikolov", "simple-examples.tgz") is None


def build_dict(min_word_freq: int = 50) -> dict:
    path = locate("imikolov", "simple-examples.tgz")
    if path:
        # reference contract: count ptb.train.txt AND ptb.valid.txt, add one
        # '<s>'/'<e>' per line, keep words with freq strictly > threshold,
        # assign ids by (-frequency, word), then append only '<unk>'
        freq: dict = {}
        with tarfile.open(path, "r:gz") as tf:
            for m in tf.getmembers():
                if m.name.endswith(("ptb.train.txt", "ptb.valid.txt")):
                    for line in tf.extractfile(m).read().decode(
                            "utf-8").splitlines():
                        for w in line.split() + ["<s>", "<e>"]:
                            freq[w] = freq.get(w, 0) + 1
        words = sorted(
            ((w, c) for w, c in freq.items() if c > min_word_freq),
            key=lambda wc: (-wc[1], wc[0]))
        d = {w: i for i, (w, _) in enumerate(words)}
        d["<unk>"] = len(d)
    else:
        d = {f"w{i}": i for i in range(_VOCAB - 3)}
        d["<s>"] = len(d)
        d["<unk>"] = len(d)
        d["<e>"] = len(d)
    return d


def _sentences(split, n, seed, word_idx):
    path = locate("imikolov", "simple-examples.tgz")
    if path:
        unk = word_idx["<unk>"]
        fname = f"ptb.{split}.txt"
        with tarfile.open(path, "r:gz") as tf:
            for m in tf.getmembers():
                if m.name.endswith(fname):
                    for line in tf.extractfile(m).read().decode(
                            "utf-8").splitlines():
                        yield [word_idx.get(w, unk) for w in line.split()]
        return
    rng = np.random.default_rng(seed)
    v = len(word_idx)
    for _ in range(n):
        length = int(rng.integers(5, 30))
        # Zipf-ish draw: squared uniform concentrates mass on low ids
        yield (np.minimum((rng.random(length) ** 2) * v, v - 1)
               .astype(np.int64).tolist())


def _reader(split, n, seed, word_idx, ngram_n, data_type):
    def reader():
        s_, e = word_idx["<s>"], word_idx["<e>"]
        for sent in _sentences(split, n, seed, word_idx):
            if data_type == DataType.NGRAM:
                # reference wraps sentences ['<s>'] + l + ['<e>']
                l = [s_] + sent + [e]
                if len(l) >= ngram_n:
                    for i in range(ngram_n, len(l) + 1):
                        yield tuple(l[i - ngram_n:i])
            else:
                # reference SEQ: src = [<s>] + l, trg = l + [<e>],
                # skipping sentences longer than n (when n > 0)
                src, trg = [s_] + sent, sent + [e]
                if ngram_n > 0 and len(src) > ngram_n:
                    continue
                yield src, trg

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _reader("train", _SYN_SENTS_TRAIN, 0, word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _reader("test", _SYN_SENTS_TEST, 1, word_idx, n, data_type)
