"""LETOR MQ2007 learning-to-rank dataset (reference
python/paddle/dataset/mq2007.py): LETOR-format lines
``rel qid:N 1:v1 2:v2 ... 46:v46 #docid ...`` grouped by query.

Readers mirror the reference's three formats:
  * pointwise — (feature [46], score)
  * pairwise  — (d_high [46], d_low [46]) for every rel_a > rel_b pair
  * listwise  — (label_list, feature_list) per query

Real data: Fold1/train.txt & Fold1/vali.txt & Fold1/test.txt under
DATA_HOME/MQ2007 (the reference's unzipped layout) — served by `train()`,
`vali()` and `test()` respectively. Zero-egress fallback: synthetic queries
whose relevance is a noisy linear function of the features, so rankers have
learnable signal.
"""
from __future__ import annotations

import numpy as np

from .common import locate

__all__ = ["train", "vali", "test", "Query", "QueryList", "is_synthetic"]

_N_FEATS = 46
_SYN_QUERIES = {"train": 120, "vali": 30, "test": 30}


class Query:
    """One judged document: relevance score, query id, feature vector
    (reference mq2007.py:50)."""

    def __init__(self, query_id=-1, relevance_score=-1, feature_vector=None,
                 description=""):
        self.query_id = query_id
        self.relevance_score = relevance_score
        self.feature_vector = feature_vector or []
        self.description = description

    def __str__(self):
        feats = " ".join(f"{i + 1}:{v}" for i, v in
                         enumerate(self.feature_vector))
        return f"{self.relevance_score} qid:{self.query_id} {feats}"

    @classmethod
    def parse(cls, line: str) -> "Query":
        body, _, desc = line.partition("#")
        parts = body.split()
        rel = int(parts[0])
        qid = int(parts[1].split(":")[1])
        feats = [float(p.split(":")[1]) for p in parts[2:]]
        return cls(qid, rel, feats, desc.strip())


class QueryList:
    """All docs of one query id (reference mq2007.py:106)."""

    def __init__(self, querylist=None):
        self.querylist = querylist or []
        self.query_id = self.querylist[0].query_id if self.querylist else -1

    def __iter__(self):
        return iter(self.querylist)

    def __len__(self):
        return len(self.querylist)

    def __getitem__(self, i):
        return self.querylist[i]

    def _correct_ranking_(self):
        self.querylist.sort(key=lambda q: q.relevance_score, reverse=True)

    def append(self, query: Query):
        self.querylist.append(query)
        self.query_id = query.query_id


def _synthetic_queries(tag: str):
    rng = np.random.default_rng({"train": 7, "vali": 9, "test": 8}[tag])
    w = np.random.default_rng(99).standard_normal(_N_FEATS)
    for qid in range(_SYN_QUERIES[tag]):
        ql = QueryList()
        for _ in range(int(rng.integers(5, 15))):
            f = rng.random(_N_FEATS)
            score = float(f @ w + rng.standard_normal() * 0.5)
            rel = int(np.clip(np.digitize(score, [-0.5, 1.0]), 0, 2))
            ql.append(Query(qid, rel, list(f.astype(float))))
        yield ql


def _file_queries(path: str):
    cur: QueryList | None = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            q = Query.parse(line)
            if cur is None or q.query_id != cur.query_id:
                if cur is not None and len(cur):
                    yield cur
                cur = QueryList()
            cur.append(q)
    if cur is not None and len(cur):
        yield cur


def _queries(tag: str):
    fname = {"train": "Fold1/train.txt", "vali": "Fold1/vali.txt",
             "test": "Fold1/test.txt"}[tag]
    path = locate("MQ2007", fname)
    return _file_queries(path) if path else _synthetic_queries(tag)


def is_synthetic() -> bool:
    return locate("MQ2007", "Fold1/train.txt") is None


def _reader(tag: str, format: str):
    def pointwise():
        for ql in _queries(tag):
            for q in ql:
                yield (np.array(q.feature_vector, np.float32),
                       np.array([q.relevance_score], np.float32))

    def pairwise():
        for ql in _queries(tag):
            docs = list(ql)
            for i, a in enumerate(docs):
                for b in docs[i + 1:]:
                    if a.relevance_score == b.relevance_score:
                        continue
                    hi, lo = ((a, b) if a.relevance_score >
                              b.relevance_score else (b, a))
                    yield (np.array(hi.feature_vector, np.float32),
                           np.array(lo.feature_vector, np.float32))

    def listwise():
        for ql in _queries(tag):
            labels = [float(q.relevance_score) for q in ql]
            feats = [np.array(q.feature_vector, np.float32) for q in ql]
            yield labels, feats

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def train(format="pairwise"):
    return _reader("train", format)


def vali(format="pairwise"):
    """The Fold1/vali.txt validation split (reference LETOR layout)."""
    return _reader("vali", format)


def test(format="pairwise"):
    return _reader("test", format)
