"""Oxford-102 flowers (reference python/paddle/dataset/flowers.py:136):
samples are (image [3*224*224] float32 flattened CHW, label 0..101).

Real data: 102flowers.tgz + imagelabels.mat + setid.mat under
DATA_HOME/flowers (the reference's triple) — parsed only when scipy/PIL are
available. Zero-egress fallback: deterministic synthetic images whose class
determines the color statistics, so classifiers have learnable signal.
"""
from __future__ import annotations

import numpy as np

from .common import locate

__all__ = ["train", "test", "valid", "is_synthetic"]

_CLASSES = 102
_SYN_TRAIN, _SYN_TEST = 1024, 128
_SHAPE = (3, 224, 224)


def is_synthetic() -> bool:
    return locate("flowers", "102flowers.tgz") is None


def _synthetic(n, seed):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        label = int(rng.integers(0, _CLASSES))
        # per-class channel means + noise
        means = np.array([(label * 37 % 97) / 97.0,
                          (label * 53 % 89) / 89.0,
                          (label * 71 % 83) / 83.0], np.float32)
        img = (means[:, None, None]
               + 0.1 * rng.standard_normal(_SHAPE).astype(np.float32))
        yield img.reshape(-1), label


def _real(split):
    import tarfile

    try:
        from scipy.io import loadmat
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "parsing real flowers data needs scipy (imagelabels.mat)") from e
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("parsing real flowers data needs PIL") from e
    import io

    labels = loadmat(locate("flowers", "imagelabels.mat"))["labels"][0]
    setid = loadmat(locate("flowers", "setid.mat"))
    # The reference deliberately swaps the official splits (flowers.py
    # TRAIN_FLAG='tstid', TEST_FLAG='trnid'): the official test set is the
    # large one, so training uses it.
    key = {"train": "tstid", "test": "trnid", "valid": "valid"}[split]
    wanted = set(int(i) for i in setid[key][0])
    with tarfile.open(locate("flowers", "102flowers.tgz"), "r:gz") as tf:
        for m in tf.getmembers():
            name = m.name.split("/")[-1]
            if not name.startswith("image_"):
                continue
            idx = int(name[6:11])
            if idx not in wanted:
                continue
            img = Image.open(io.BytesIO(tf.extractfile(m).read()))
            # the reference pipeline: resize_short(256) -> center_crop(224)
            # -> CHW (paddle.dataset.image.simple_transform)
            from . import image as img_utils

            arr = img_utils.simple_transform(
                np.asarray(img.convert("RGB")), 256, 224,
                is_train=False) / 255.0
            yield arr.reshape(-1), int(labels[idx - 1]) - 1


def _reader(split, n, seed, mapper=None, cycle=False):
    def reader():
        while True:
            it = _synthetic(n, seed) if is_synthetic() else _real(split)
            for sample in it:
                yield mapper(sample) if mapper is not None else sample
            if not cycle:
                return

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader("train", _SYN_TRAIN, 0, mapper, cycle)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader("test", _SYN_TEST, 1, mapper, cycle)


def valid(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader("valid", _SYN_TEST, 2, mapper, cycle)
