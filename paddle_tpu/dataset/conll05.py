"""CoNLL-2005 semantic role labeling (reference
python/paddle/dataset/conll05.py:199): each sample is the 9-tuple
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_ids, mark, label_ids)
— five predicate-context windows broadcast over the sentence, a 0/1
predicate mark, and per-token SRL labels.

Real data: conll05st-tests.tar.gz under DATA_HOME/conll05st with the
reference's props/words test files. Zero-egress fallback: deterministic
synthetic sentences with a consistent predicate/label structure.
"""
from __future__ import annotations

import numpy as np

from .common import locate

__all__ = ["test", "get_dict", "get_embedding", "is_synthetic"]

_WORDS, _VERBS, _LABELS = 4000, 300, 59
_SYN_TEST = 512


def is_synthetic() -> bool:
    return locate("conll05st", "conll05st-tests.tar.gz") is None


def get_dict():
    """(word_dict, verb_dict, label_dict) (reference conll05.get_dict)."""
    word_dict = {f"w{i}": i for i in range(_WORDS)}
    verb_dict = {f"v{i}": i for i in range(_VERBS)}
    label_dict = {f"L{i}": i for i in range(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Deterministic word embedding table (reference ships emb download)."""
    rng = np.random.default_rng(42)
    return rng.standard_normal((_WORDS, 32)).astype(np.float32)


def _synthetic(n, seed):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        sen_len = int(rng.integers(5, 40))
        words = rng.integers(0, _WORDS, sen_len).tolist()
        pred_pos = int(rng.integers(0, sen_len))
        verb = int(rng.integers(0, _VERBS))

        def ctx(off):
            i = min(max(pred_pos + off, 0), sen_len - 1)
            return [words[i]] * sen_len

        mark = [int(i == pred_pos) for i in range(sen_len)]
        # labels correlated with distance to the predicate so SRL models
        # have signal to learn
        labels = [min(abs(i - pred_pos), _LABELS - 1) for i in range(sen_len)]
        yield (words, ctx(-2), ctx(-1), ctx(0), ctx(1), ctx(2),
               [verb] * sen_len, mark, labels)


def test():
    def reader():
        yield from _synthetic(_SYN_TEST, 1)

    return reader
