"""Pascal VOC2012 segmentation dataset (reference
python/paddle/dataset/voc2012.py): yields (image HWC uint8,
label HW uint8 class mask) pairs.

Real data: VOCtrainval_11-May-2012.tar under DATA_HOME/voc2012 — same
tar layout the reference streams (ImageSets/Segmentation split files,
JPEGImages, SegmentationClass); decoding needs PIL. Zero-egress fallback:
synthetic scenes of colored rectangles whose mask marks the rectangle
class, so segmentation models have learnable signal.
"""
from __future__ import annotations

import io
import tarfile

import numpy as np

from .common import locate

__all__ = ["train", "test", "val", "is_synthetic"]

_TAR = "VOCtrainval_11-May-2012.tar"
SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
_N_CLASSES = 21
_SYN = {"trainval": 64, "train": 48, "val": 16}
_SYN_HW = (96, 128)


def is_synthetic() -> bool:
    return locate("voc2012", _TAR) is None


_SPLIT_SEED = {"trainval": 11, "train": 12, "val": 13}


def _synthetic(sub_name: str):
    # fixed per-split seed: hash() is randomized per process and would
    # break the dataset package's deterministic-fallback contract
    rng = np.random.default_rng(_SPLIT_SEED[sub_name])
    h, w = _SYN_HW
    for _ in range(_SYN[sub_name]):
        img = rng.integers(0, 64, (h, w, 3), dtype=np.uint8)
        label = np.zeros((h, w), np.uint8)
        for _ in range(int(rng.integers(1, 4))):
            cls = int(rng.integers(1, _N_CLASSES))
            y0, x0 = int(rng.integers(0, h // 2)), int(rng.integers(0, w // 2))
            y1 = y0 + int(rng.integers(h // 4, h // 2))
            x1 = x0 + int(rng.integers(w // 4, w // 2))
            color = np.array([cls * 11 % 256, cls * 37 % 256,
                              cls * 73 % 256], np.uint8)
            img[y0:y1, x0:x1] = color
            label[y0:y1, x0:x1] = cls
        yield img, label


def _tar_reader(path: str, sub_name: str):
    from PIL import Image

    tarobject = tarfile.open(path)
    name2mem = {ele.name: ele for ele in tarobject.getmembers()}

    def reader():
        sets = tarobject.extractfile(name2mem[SET_FILE.format(sub_name)])
        for line in sets:
            line = line.strip().decode()
            data = tarobject.extractfile(
                name2mem[DATA_FILE.format(line)]).read()
            label = tarobject.extractfile(
                name2mem[LABEL_FILE.format(line)]).read()
            yield (np.array(Image.open(io.BytesIO(data))),
                   np.array(Image.open(io.BytesIO(label))))

    return reader


def _reader(sub_name: str):
    path = locate("voc2012", _TAR)
    if path:
        return _tar_reader(path, sub_name)
    return lambda: _synthetic(sub_name)


def train():
    """2913 trainval images HWC (reference voc2012.py:68)."""
    return _reader("trainval")


def test():
    return _reader("train")


def val():
    return _reader("val")
