"""Built-in dataset loaders (reference python/paddle/dataset/: mnist.py,
cifar.py, uci_housing.py, imdb.py, wmt14/16.py, movielens.py, flowers.py).

Each module exposes `train()` / `test()` reader creators yielding the same
sample tuples as the reference. Loaders read the standard archive formats
from DATA_HOME (`PADDLE_TPU_DATA_HOME`, default ~/.cache/paddle_tpu/dataset)
when present; this build has zero network egress, so when the files are
absent the loaders yield a deterministic synthetic dataset with identical
shapes/dtypes/ranges (flagged via `is_synthetic()`), keeping every
train/eval pipeline runnable end to end.
"""
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
from . import movielens  # noqa: F401
from . import flowers  # noqa: F401
from . import conll05  # noqa: F401
from . import sentiment  # noqa: F401
from . import imikolov  # noqa: F401
from . import image  # noqa: F401
from . import mq2007  # noqa: F401
from . import voc2012  # noqa: F401

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "wmt14", "wmt16",
           "movielens", "flowers", "conll05", "sentiment", "imikolov",
           "image", "mq2007", "voc2012"]
