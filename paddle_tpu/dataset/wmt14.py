"""WMT14 fr-en translation (reference python/paddle/dataset/wmt14.py:112):
samples are (src_ids, trg_ids, trg_ids_next) with trg_ids = [<s>] + trg and
trg_ids_next = trg + [<e>] — same contract as wmt16, different corpus.

Real data: wmt14.tgz under DATA_HOME/wmt14 with members containing the split
name, lines "src\ttrg". Zero-egress fallback: deterministic synthetic
parallel corpus.
"""
from __future__ import annotations

import tarfile

import numpy as np

from .common import locate

__all__ = ["train", "test", "get_dict", "is_synthetic"]

_DICT_SIZE = 30000
_SYN_TRAIN, _SYN_TEST = 2048, 256
BOS, EOS, UNK = 0, 1, 2


def is_synthetic() -> bool:
    return locate("wmt14", "wmt14.tgz") is None


def get_dict(dict_size: int = _DICT_SIZE, reverse=False):
    """Returns (src_dict, trg_dict) (reference wmt14.get_dict)."""
    def mk(lang):
        d = {"<s>": BOS, "<e>": EOS, "<unk>": UNK}
        for i in range(3, dict_size):
            d[f"{lang}{i}"] = i
        return {v: k for k, v in d.items()} if reverse else d

    return mk("fr"), mk("en")


def _parse_real(path, split, dict_size):
    src_dict, trg_dict = get_dict(dict_size)
    with tarfile.open(path, "r:gz") as tf:
        for m in tf.getmembers():
            if split not in m.name.split("/")[-1] or not m.isfile():
                continue
            for raw in tf.extractfile(m).read().decode(
                    "utf-8", "ignore").splitlines():
                if "\t" not in raw:
                    continue
                s, t = raw.split("\t", 1)
                src = [src_dict.get(w, UNK) for w in s.split()]
                trg = [trg_dict.get(w, UNK) for w in t.split()]
                if src and trg:
                    yield src, [BOS] + trg, trg + [EOS]


def _synthetic(n, dict_size, seed):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        length = int(rng.integers(4, 40))
        src = rng.integers(3, dict_size, length).tolist()
        trg = [3 + ((t - 3 + 11) % (dict_size - 3)) for t in src]
        yield src, [BOS] + trg, trg + [EOS]


def _reader(split, n, seed, dict_size):
    def reader():
        path = locate("wmt14", "wmt14.tgz")
        if path:
            yield from _parse_real(path, split, dict_size)
        else:
            yield from _synthetic(n, dict_size, seed)

    return reader


def train(dict_size=_DICT_SIZE):
    return _reader("train", _SYN_TRAIN, 0, dict_size)


def test(dict_size=_DICT_SIZE):
    return _reader("test", _SYN_TEST, 1, dict_size)
