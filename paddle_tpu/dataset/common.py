"""Shared dataset plumbing (reference python/paddle/dataset/common.py:
DATA_HOME, download, md5file). No downloads here (zero-egress build):
`locate` finds a pre-placed file under DATA_HOME or returns None."""
from __future__ import annotations

import os

__all__ = ["DATA_HOME", "locate"]

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu", "dataset"),
)


def locate(module: str, filename: str) -> str | None:
    for base in (os.path.join(DATA_HOME, module), DATA_HOME):
        p = os.path.join(base, filename)
        if os.path.exists(p):
            return p
    return None
