"""WMT16 en-de translation (reference python/paddle/dataset/wmt16.py:142):
samples are (src_ids, trg_ids, trg_ids_next) int64 lists where
trg_ids = [<s>] + trg and trg_ids_next = trg + [<e>] — decoder input second,
next-token labels third, matching the reference tuple order.

Real data: place wmt16.tar.gz under DATA_HOME/wmt16; members whose names
contain the split ("train"/"val"/"test") are parsed as UTF-8 lines
"src sentence\ttrg sentence". Zero-egress fallback: deterministic synthetic
parallel corpus with the same tuple contract."""
from __future__ import annotations

import tarfile

import numpy as np

from .common import locate

__all__ = ["train", "test", "validation", "get_dict", "is_synthetic"]

_SRC_VOCAB = 2000
_TRG_VOCAB = 2000
_SYN_TRAIN, _SYN_TEST = 2048, 256
BOS, EOS, UNK = 0, 1, 2


def is_synthetic() -> bool:
    return locate("wmt16", "wmt16.tar.gz") is None


def get_dict(lang: str, dict_size: int | None = None, reverse=False):
    size = dict_size or (_SRC_VOCAB if lang == "en" else _TRG_VOCAB)
    d = {"<s>": BOS, "<e>": EOS, "<unk>": UNK}
    path = locate("wmt16", f"{lang}.dict")
    if path:
        with open(path, encoding="utf-8") as f:
            for line in f:
                w = line.strip()
                if w and w not in d and len(d) < size:
                    d[w] = len(d)
    else:
        for i in range(3, size):
            d[f"{lang}{i}"] = i
    return {v: k for k, v in d.items()} if reverse else d


def _parse_real(path, split, src_dict, trg_dict):
    with tarfile.open(path, "r:gz") as tf:
        for m in tf.getmembers():
            base = m.name.split("/")[-1]
            if split not in base or not m.isfile():
                continue
            for raw in tf.extractfile(m).read().decode("utf-8", "ignore").splitlines():
                if "\t" not in raw:
                    continue
                src_s, trg_s = raw.split("\t", 1)
                src = [src_dict.get(w, UNK) for w in src_s.split()]
                trg = [trg_dict.get(w, UNK) for w in trg_s.split()]
                if src and trg:
                    yield src, [BOS] + trg, trg + [EOS]


def _synthetic(n, src_vocab, trg_vocab, seed):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        length = int(rng.integers(4, 50))
        src = rng.integers(3, src_vocab, length).tolist()
        # deterministic "translation": shifted token ids, same length
        trg = [3 + ((t - 3 + 7) % (trg_vocab - 3)) for t in src]
        yield src, [BOS] + trg, trg + [EOS]


def _reader(split, n, seed, src_vocab, trg_vocab):
    def reader():
        path = locate("wmt16", "wmt16.tar.gz")
        if path:
            yield from _parse_real(path, split, get_dict("en", src_vocab),
                                   get_dict("de", trg_vocab))
        else:
            yield from _synthetic(n, src_vocab, trg_vocab, seed)

    return reader


def train(src_dict_size=_SRC_VOCAB, trg_dict_size=_TRG_VOCAB, src_lang="en"):
    return _reader("train", _SYN_TRAIN, 0, src_dict_size, trg_dict_size)


def test(src_dict_size=_SRC_VOCAB, trg_dict_size=_TRG_VOCAB, src_lang="en"):
    return _reader("test", _SYN_TEST, 1, src_dict_size, trg_dict_size)


def validation(src_dict_size=_SRC_VOCAB, trg_dict_size=_TRG_VOCAB, src_lang="en"):
    return _reader("val", _SYN_TEST, 2, src_dict_size, trg_dict_size)
