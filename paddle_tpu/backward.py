"""Autodiff by program transformation: `append_backward`.

TPU-native re-design of /root/reference/python/paddle/fluid/backward.py
(append_backward:558, _addup_repetitive_outputs_:135, _find_op_path_:780).
The contract is identical — walk the forward op list in reverse, emit one grad
op per forward op (via each op's grad maker), sum repeated gradients, and
return (param, grad_var) pairs for the optimizer — but grad *kernels* are
derived from the forward JAX computes via vjp (see ops/registry.py), so this
file only orchestrates naming and topology, never math.
"""
from __future__ import annotations

from .framework import Program, Variable, grad_var_name
from .ops.registry import default_grad_maker, get_op_def

__all__ = ["append_backward", "gradients", "grad_ready_index"]


def grad_ready_index(block, grad_name: str, before: int) -> int:
    """Index of the LAST op writing `grad_name` strictly below op `before`.

    This is the earliest program point where a gradient is final and may be
    bucketed onto a collective (parallel/collective.py): "last writer"
    rather than "grad-op producer" because AMP's unscale/check ops, clip,
    regularizers and the guardrail sentinel all rewrite gradients in place
    AFTER the raw grad op — a reduce inserted above any of them would ship
    a stale value. Returns -1 when nothing below `before` writes the name
    (the caller falls back to inserting at `before`)."""
    last = -1
    for i in range(min(before, len(block.ops))):
        if grad_name in block.ops[i].output_names:
            last = i
    return last


def _find_op_path(block, target_names) -> list[int]:
    """Indices of ops that (transitively) produce any target from data/params.

    Mirrors the reference's _find_op_path_ (backward.py:780): a backward sweep
    collecting ops whose outputs are needed.
    """
    needed = set(target_names) if not isinstance(target_names, str) else {target_names}
    path = []
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        if any(n in needed for n in op.output_names):
            path.append(i)
            needed.update(n for n in op.input_names if n)
    path.reverse()
    return path


def append_backward(
    loss: Variable,
    parameter_list: list[str] | None = None,
    no_grad_set: set[str] | None = None,
    callbacks=None,
):
    """Append grad ops for `loss` to its program; return [(param, grad)] pairs.

    Reference: backward.py:558. Only single-block programs are differentiated
    in-line; control-flow sub-blocks differentiate through their op's vjp
    (the while/cond op kernels are themselves JAX-traceable).
    """
    program: Program = loss.block.program
    block = program.global_block
    no_grad = set(no_grad_set or ())
    for v in block.vars.values():
        if v.stop_gradient and not v.persistable:
            no_grad.add(v.name)

    op_path = _find_op_path(block, loss.name)

    # 1. seed: d loss / d loss = 1
    loss_grad = grad_var_name(loss.name)
    block.create_var(name=loss_grad, shape=loss.shape, dtype=loss.dtype)
    block.append_op(
        "fill_constant",
        outputs={"Out": [loss_grad]},
        attrs={"shape": list(loss.shape), "value": 1.0, "dtype": loss.dtype.value},
    )

    # 2. reverse sweep, with repeated-grad accumulation
    available_grads = _backward_sweep(block, op_path, {loss_grad}, no_grad)

    # 3. collect (param, grad) pairs
    if parameter_list is not None:
        params = [block.var(p) if isinstance(p, str) else p for p in parameter_list]
    else:
        params = [p for p in program.all_parameters() if getattr(p, "trainable", True)]
    result = []
    for p in params:
        g = grad_var_name(p.name)
        if g in available_grads:
            result.append((p, block.var(g)))
    return result


def _backward_sweep(block, op_path, seed_grads: set, no_grad: set) -> set:
    """Reverse sweep over `op_path` emitting grad ops; returns all grad var
    names made available. `seed_grads` are pre-seeded cotangent var names."""
    available_grads = set(seed_grads)
    pending_sum: dict[str, list[str]] = {}  # fwd var -> partial grad var names

    ops_snapshot = [block.ops[i] for i in op_path]
    for op in reversed(ops_snapshot):
        opdef = get_op_def(op.type) if _has(op.type) else None
        if not any(grad_var_name(n) in available_grads for n in op.output_names):
            # no grad flows into this op's outputs
            continue
        if opdef is None or opdef.no_grad:
            # forward-only op ON the gradient path: silently skipping would
            # freeze every upstream parameter with no diagnostic. Raise unless
            # the op has no differentiable inputs (pure sources like
            # fill_constant are harmless).
            if _has_differentiable_inputs(op, block, no_grad):
                raise RuntimeError(
                    f"op '{op.type}' lies on the gradient path"
                    f" but has no gradient (forward-only). Parameters upstream "
                    f"of it would silently stop training. Use a differentiable "
                    f"alternative (e.g. static_rnn instead of while), or mark "
                    f"its inputs stop_gradient=True if this is intended.")
            continue
        maker = opdef.grad_maker or default_grad_maker
        specs = maker(op, block, frozenset(no_grad))
        for spec in specs:
            # rename repeated-grad outputs: if a grad var was already produced
            # by another consumer — or appears twice within THIS spec (e.g.
            # elementwise_mul(x, x) emits X@GRAD and Y@GRAD for the same var) —
            # emit into a temp and sum (reference _addup_repetitive_outputs_
            # backward.py:135)
            outputs = {}
            renames = []
            local_seen: set[str] = set()
            for slot, names in spec["outputs"].items():
                new_names = []
                for n in names:
                    if n and (n in available_grads or n in local_seen):
                        tmp = n + "@RENAME@" + str(len(pending_sum.get(n, [])))
                        pending_sum.setdefault(n, [n]).append(tmp)
                        renames.append((n, tmp))
                        new_names.append(tmp)
                    else:
                        if n:
                            local_seen.add(n)
                        new_names.append(n)
                outputs[slot] = new_names
            block.append_op(spec["type"], spec["inputs"], outputs, spec.get("attrs", {}))
            for slot, names in outputs.items():
                for n in names:
                    if n:
                        available_grads.add(n)
            # fold pending sums immediately when a rename happened
            for orig, tmp in renames:
                parts = pending_sum[orig]
                if len(parts) >= 2:
                    block.append_op(
                        "sum",
                        inputs={"X": list(parts)},
                        outputs={"Out": [orig]},
                    )
                    pending_sum[orig] = [orig]
    return available_grads


def _has(t):
    try:
        get_op_def(t)
        return True
    except KeyError:
        return False


def _has_differentiable_inputs(op, block, no_grad: set) -> bool:
    from .core.types import is_floating

    for n in op.input_names:
        if not n or n in no_grad:
            continue
        try:
            v = block.var(n)
        except KeyError:
            continue
        if is_floating(v.dtype) and not v.stop_gradient:
            return True
    return False


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Compute grads of targets w.r.t. inputs (reference backward.py:938
    calc_gradient): supports multiple targets and per-target seed cotangents.
    A missing/None target_gradient seeds with ones (matching the reference)."""
    tgts = list(targets) if isinstance(targets, (list, tuple)) else [targets]
    ins = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    tgs = (list(target_gradients)
           if isinstance(target_gradients, (list, tuple))
           else [target_gradients] * len(tgts))
    if len(tgs) != len(tgts):
        raise ValueError(
            f"target_gradients has {len(tgs)} entries for {len(tgts)} targets")

    program: Program = tgts[0].block.program
    block = program.global_block
    # asking for d(target)/d(input) implies the input is differentiable, even
    # for data vars (which default to stop_gradient=True); restored after the
    # sweep so later append_backward calls on this program are unaffected
    saved_sg = [(v, v.stop_gradient) for v in ins]
    for v in ins:
        v.stop_gradient = False
    try:
        return _calc_gradients(block, tgts, ins, tgs, no_grad_set)
    finally:
        for v, sg in saved_sg:
            v.stop_gradient = sg


def _calc_gradients(block, tgts, ins, tgs, no_grad_set):
    no_grad = set(no_grad_set or ())
    for v in block.vars.values():
        if v.stop_gradient and not v.persistable:
            no_grad.add(v.name)

    op_path = _find_op_path(block, {t.name for t in tgts})

    seeds = set()
    for t, tg in zip(tgts, tgs):
        g = grad_var_name(t.name)
        block.create_var(name=g, shape=t.shape, dtype=t.dtype)
        if tg is None:
            # fill_any_like handles batch-polymorphic (-1) target shapes
            block.append_op(
                "fill_any_like",
                inputs={"X": [t.name]},
                outputs={"Out": [g]},
                attrs={"value": 1.0},
            )
        else:
            if len(tg.shape) != len(t.shape) or any(
                td not in (-1, gd) and gd != -1
                for td, gd in zip(t.shape, tg.shape)
            ):
                raise ValueError(
                    f"target_gradient for '{t.name}' has shape "
                    f"{tuple(tg.shape)}, expected {tuple(t.shape)}")
            block.append_op("assign", {"X": [tg.name]}, {"Out": [g]}, {})
        seeds.add(g)

    available = _backward_sweep(block, op_path, seeds, no_grad)
    out = []
    for v in ins:
        g = grad_var_name(v.name)
        out.append(block.var(g) if g in available and block.has_var(g) else None)
    return out
