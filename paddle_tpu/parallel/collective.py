"""Collective program transpilers: GradAllReduce / LocalSGD.

TPU-native re-design of /root/reference/python/paddle/fluid/transpiler/
collective.py (Collective:36, GradAllReduce:178, LocalSGD:269): same program
rewrite — find the grad vars produced by the backward pass, insert
`c_allreduce_sum` (+ scale by 1/nranks) between backward and optimizer ops —
but the inserted ops lower to mesh-axis psum under shard_map execution (or to
identity under GSPMD, where the partitioner already reduces).
"""
from __future__ import annotations

from ..framework import Program

__all__ = ["Collective", "GradAllReduce", "LocalSGD"]

OPTIMIZER_OP_TYPES = {
    "sgd",
    "momentum",
    "lars_momentum",
    "adagrad",
    "adam",
    "adamax",
    "decayed_adagrad",
    "adadelta",
    "rmsprop",
    "ftrl",
    "lamb",
}


class Collective:
    def __init__(self, nrings: int = 1):
        self.nrings = nrings
        self.nranks = 1

    def transpile(self, startup_program: Program, main_program: Program, rank: int, endpoints=None, current_endpoint=None, wait_port=True, nranks: int | None = None):
        self.nranks = nranks if nranks is not None else (len(endpoints) if endpoints else 1)
        self.rank = rank
        self._transpile_main(main_program)
        self._transpile_startup(startup_program)

    def _transpile_startup(self, program: Program):
        pass  # mesh construction replaces comm-init ops (c_comm_init_all no-op)

    def _transpile_main(self, program: Program):
        raise NotImplementedError


def _grad_op_positions(block):
    """[(index, param_name, grad_name)] of optimizer ops' (param, grad)."""
    out = []
    for i, op in enumerate(block.ops):
        if op.type in OPTIMIZER_OP_TYPES:
            out.append((i, op.input("Param")[0], op.input("Grad")[0]))
    return out


class GradAllReduce(Collective):
    """Insert mean-allreduce on every gradient consumed by an optimizer op
    (reference transpiler/collective.py:208 inserts scale(1/nranks) +
    c_allreduce_sum; here the scale is fused INTO the op via the `avg` attr so
    it only applies when a real reduction runs — a standalone scale would
    shrink grads nranks-fold in the GSPMD regime where the allreduce lowers to
    identity)."""

    def _transpile_main(self, program: Program):
        block = program.global_block
        targets = _grad_op_positions(block)
        # insert before the FIRST optimizer op, preserving order
        if not targets:
            return
        first_opt = targets[0][0]
        ring = 0
        inserts = []
        for _, _, g in targets:
            inserts.append(
                ("c_allreduce_sum", {"X": [g]}, {"Out": [g]}, {"ring_id": ring, "avg": True})
            )
            ring = (ring + 1) % self.nrings
        for j, (t, i_, o, a) in enumerate(inserts):
            block._insert_op(first_opt + j, t, i_, o, a)


class LocalSGD(Collective):
    """Per-step local updates + periodic param averaging (reference
    transpiler/collective.py:269): snapshot params, train K local steps, then
    allreduce (param - snapshot) deltas and re-apply."""

    def __init__(self, nrings: int = 1, k_steps: int = 1):
        super().__init__(nrings)
        self.k_steps = k_steps

    def _transpile_main(self, program: Program):
        block = program.global_block
        params = [p.name for p in program.all_parameters()]
        if not params:
            return
        # persistable step counter, incremented each run
        step_name = "@LOCAL_SGD_STEP@"
        block.create_var(name=step_name, shape=[], dtype="int64",
                         persistable=True, stop_gradient=True)
        block.append_op("increment", {"X": [step_name]}, {"Out": [step_name]},
                        {"step": 1.0})
        for p in params:
            snap = p + "@SNAPSHOT"
            pv = block.var(p)
            block.create_var(name=snap, shape=pv.shape, dtype=pv.dtype,
                             persistable=True, stop_gradient=True)
            block.append_op(
                "local_sgd_sync",
                {"Param": [p], "Snapshot": [snap], "Step": [step_name]},
                {"ParamOut": [p], "SnapshotOut": [snap]},
                {"k_steps": self.k_steps, "ring_id": 0},
            )

    def _transpile_startup(self, program: Program):
        block = program.global_block
        block.create_var(name="@LOCAL_SGD_STEP@", shape=[], dtype="int64",
                         persistable=True)
        block.append_op("fill_constant", {}, {"Out": ["@LOCAL_SGD_STEP@"]},
                        {"shape": [], "dtype": "int64", "value": 0.0})
        # snapshot starts equal to the freshly-initialized params
        for p in program.all_parameters():
            snap = p.name + "@SNAPSHOT"
            block.create_var(name=snap, shape=p.shape, dtype=p.dtype,
                             persistable=True)
            block.append_op("assign", {"X": [p.name]}, {"Out": [snap]}, {})
