"""Collective program transpilers: GradAllReduce / LocalSGD.

TPU-native re-design of /root/reference/python/paddle/fluid/transpiler/
collective.py (Collective:36, GradAllReduce:178, LocalSGD:269): same program
rewrite — find the grad vars produced by the backward pass, insert
mean-allreduce collectives between backward and optimizer ops — but the
inserted ops lower to mesh-axis psum under shard_map execution (or to
identity under GSPMD, where the partitioner already reduces).

Overlap (the multichip scaling campaign): instead of one `c_allreduce_sum`
per gradient parked before the optimizer ops (every reduce serializes after
the whole backward), GradAllReduce coalesces gradients into
reverse-topological BUCKETS of ~FLAGS_allreduce_bucket_mb megabytes and
inserts each bucket's `c_allreduce_coalesced` at the point where its last
member gradient is final (backward.grad_ready_index — below AMP unscale,
clip, and the guardrail sentinel), so a finished bucket's reduce overlaps
the backward compute still producing the next one (the reference's
fuse_all_reduce_op_pass + all_reduce_deps_pass, done in the program). The
bucket size is a per-(mesh, payload) schedule choice — under
FLAGS_tuning_mode it resolves through the PR 6 tuning DB
(`collective|mesh=..|payload=..` keys, swept by tools/_mc_ab.py) with the
flag as the analytic prior. With FLAGS_zero1, eligible gradients take the
ZeRO-1 reduce-scatter/shard-update/allgather path instead
(parallel/sharding.apply_zero1); the remainder still buckets here.
"""
from __future__ import annotations

import numpy as np

from ..framework import Program

__all__ = ["Collective", "GradAllReduce", "LocalSGD", "build_buckets",
           "resolve_bucket_mb"]

OPTIMIZER_OP_TYPES = {
    "sgd",
    "momentum",
    "lars_momentum",
    "adagrad",
    "adam",
    "adamax",
    "decayed_adagrad",
    "adadelta",
    "rmsprop",
    "ftrl",
    "lamb",
}


class Collective:
    def __init__(self, nrings: int = 1):
        self.nrings = nrings
        self.nranks = 1

    def transpile(self, startup_program: Program, main_program: Program, rank: int, endpoints=None, current_endpoint=None, wait_port=True, nranks: int | None = None):
        self.nranks = nranks if nranks is not None else (len(endpoints) if endpoints else 1)
        self.rank = rank
        self._transpile_main(main_program)
        self._transpile_startup(startup_program)

    def _transpile_startup(self, program: Program):
        pass  # mesh construction replaces comm-init ops (c_comm_init_all no-op)

    def _transpile_main(self, program: Program):
        raise NotImplementedError


def _grad_op_positions(block):
    """[(index, param_name, grad_name)] of optimizer ops' (param, grad)."""
    out = []
    for i, op in enumerate(block.ops):
        if op.type in OPTIMIZER_OP_TYPES:
            out.append((i, op.input("Param")[0], op.input("Grad")[0]))
    return out


def _grad_bytes(block, name: str) -> int:
    try:
        v = block.var(name)
    except KeyError:
        return 0
    shape = [abs(d) if d else 1 for d in v.shape] or [1]
    try:
        itemsize = np.dtype(v.np_dtype).itemsize
    except (TypeError, ValueError):
        itemsize = 4
    return int(np.prod(shape)) * itemsize


def resolve_bucket_mb(nranks: int, payload_bytes: int,
                      bucket_mb: float | None = None) -> tuple[float, str]:
    """Bucket size for this (mesh, payload), as (mb, provenance tier).

    Explicit `bucket_mb` (the transpiler/DistributedStrategy argument) wins
    outright. Otherwise under FLAGS_tuning_mode != off the decision routes
    through the three-tier tuner — `collective|mesh=..|payload=..` exact DB
    hit, else FLAGS_allreduce_bucket_mb as the analytic prior — so
    tools/_mc_ab.py sweeps land here; with tuning off the flag applies
    directly (pre-tuner behavior)."""
    from .. import flags

    if bucket_mb is not None:
        return float(bucket_mb), "explicit"
    flag_mb = float(flags.get_flag("allreduce_bucket_mb"))
    from .. import tuning
    from .mesh import axes_desc

    if tuning.mode() == "off":
        return flag_mb, "flag"
    key = tuning.canonical_key(
        "collective", tuning.collective_key(axes_desc(nranks), payload_bytes),
        "float32", tuning.device_kind())
    decision, tier = tuning.decide(
        "collective", key,
        prior=lambda: {"bucket_mb": flag_mb},
        default={"bucket_mb": flag_mb},
        validate=lambda d: "bucket_mb" in d)
    return float(decision.get("bucket_mb", flag_mb)), tier


def build_buckets(items, bucket_bytes: int):
    """Greedy reverse-topological bucketing: `items` is [(ready_index,
    grad_name, nbytes)] — grads in the order the backward FINISHES them
    (ascending last-writer index = descending layer depth, the DDP
    convention) — cut into consecutive groups of <= bucket_bytes (one
    oversized grad still gets its own bucket). bucket_bytes <= 0 degrades
    to one bucket per grad (the overlap-off arm)."""
    buckets: list[list] = []
    cur: list = []
    cur_bytes = 0
    for it in sorted(items, key=lambda t: (t[0], t[1])):
        if bucket_bytes <= 0:
            buckets.append([it])
            continue
        if cur and cur_bytes + it[2] > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(it)
        cur_bytes += it[2]
    if cur:
        buckets.append(cur)
    return buckets


class GradAllReduce(Collective):
    """Insert mean-allreduce on every gradient consumed by an optimizer op
    (reference transpiler/collective.py:208 inserts scale(1/nranks) +
    c_allreduce_sum; here the scale is fused INTO the op via the `avg` attr so
    it only applies when a real reduction runs — a standalone scale would
    shrink grads nranks-fold in the GSPMD regime where the allreduce lowers to
    identity).

    bucket_mb: gradient-bucket size in MB (None = resolve through the tuner /
    FLAGS_allreduce_bucket_mb; <= 0 = per-gradient reduces inserted before
    the optimizer ops, the overlap-off arm). zero1: route eligible params
    through ZeRO-1 sharding (None = FLAGS_zero1)."""

    def __init__(self, nrings: int = 1, bucket_mb: float | None = None,
                 zero1: bool | None = None):
        super().__init__(nrings)
        self.bucket_mb = bucket_mb
        self.zero1 = zero1
        # introspection for tests/tools: [(insert_pos, [grad names])] of the
        # last transpile, plus the resolved size and its provenance tier
        self.last_buckets: list[tuple[int, list[str]]] = []
        self.resolved_bucket_mb: float | None = None
        self.bucket_source: str = "none"
        self.zero1_params: list[str] = []

    def _transpile_main(self, program: Program):
        from .. import flags
        from ..backward import grad_ready_index

        block = program.global_block
        targets = _grad_op_positions(block)
        if not targets:
            return
        first_opt = targets[0][0]

        zero1 = (bool(flags.get_flag("zero1")) if self.zero1 is None
                 else bool(self.zero1))
        if zero1:
            from .sharding import _SHARD_SUFFIX, apply_zero1

            self.zero1_params = apply_zero1(program, self.nranks)
            # re-scan: zero1 rewrote its ops (Param/Grad now name shards) and
            # shifted indices; the shard-suffixed ops are already handled
            targets = [t for t in _grad_op_positions(block)
                       if not t[1].endswith(_SHARD_SUFFIX)]
            if not targets:
                self.last_buckets = []
                return
            first_opt = targets[0][0]

        items = []
        for _, _, g in targets:
            ready = grad_ready_index(block, g, first_opt)
            items.append((ready if ready >= 0 else first_opt - 1, g,
                          _grad_bytes(block, g)))
        payload = sum(b for _, _, b in items)
        self.last_payload_bytes = payload
        mb, tier = resolve_bucket_mb(self.nranks, payload, self.bucket_mb)
        self.resolved_bucket_mb, self.bucket_source = mb, tier
        buckets = build_buckets(items, int(mb * (1 << 20)))

        # per-bucket insert point: right after the bucket's LAST member is
        # final (overlap regime). bucket_mb <= 0 keeps the historical
        # placement — every per-grad reduce parked at the optimizer boundary,
        # i.e. serialized after the whole backward (the A/B baseline).
        inserts = []  # (position, [grad names])
        ring = 0
        for bucket in buckets:
            pos = (first_opt if mb <= 0
                   else max(r for r, _, _ in bucket) + 1)
            inserts.append((pos, [g for _, g, _ in bucket], ring))
            ring = (ring + 1) % self.nrings
        # insert bottom-up so earlier positions stay valid. Single-member
        # buckets keep the classic c_allreduce_sum spelling (same kernel,
        # and the fleet-regime assertions/tools that look for it still hold)
        self.last_buckets = []
        for pos, names, ring in sorted(inserts, key=lambda t: -t[0]):
            if len(names) == 1:
                block._insert_op(
                    pos, "c_allreduce_sum", {"X": names}, {"Out": names},
                    {"ring_id": ring, "avg": True})
            else:
                block._insert_op(
                    pos, "c_allreduce_coalesced", {"X": names},
                    {"Out": names}, {"ring_id": ring, "avg": True})
            self.last_buckets.append((pos, names))
        self.last_buckets.reverse()


class LocalSGD(Collective):
    """Per-step local updates + periodic param averaging (reference
    transpiler/collective.py:269): snapshot params, train K local steps, then
    allreduce (param - snapshot) deltas and re-apply."""

    def __init__(self, nrings: int = 1, k_steps: int = 1):
        super().__init__(nrings)
        self.k_steps = k_steps

    def _transpile_main(self, program: Program):
        block = program.global_block
        params = [p.name for p in program.all_parameters()]
        if not params:
            return
        # persistable step counter, incremented each run
        step_name = "@LOCAL_SGD_STEP@"
        block.create_var(name=step_name, shape=[], dtype="int64",
                         persistable=True, stop_gradient=True)
        block.append_op("increment", {"X": [step_name]}, {"Out": [step_name]},
                        {"step": 1.0})
        for p in params:
            snap = p + "@SNAPSHOT"
            pv = block.var(p)
            block.create_var(name=snap, shape=pv.shape, dtype=pv.dtype,
                             persistable=True, stop_gradient=True)
            block.append_op(
                "local_sgd_sync",
                {"Param": [p], "Snapshot": [snap], "Step": [step_name]},
                {"ParamOut": [p], "SnapshotOut": [snap]},
                {"k_steps": self.k_steps, "ring_id": 0},
            )

    def _transpile_startup(self, program: Program):
        block = program.global_block
        block.create_var(name="@LOCAL_SGD_STEP@", shape=[], dtype="int64",
                         persistable=True)
        block.append_op("fill_constant", {}, {"Out": ["@LOCAL_SGD_STEP@"]},
                        {"shape": [], "dtype": "int64", "value": 0.0})
        # snapshot starts equal to the freshly-initialized params
        for p in program.all_parameters():
            snap = p.name + "@SNAPSHOT"
            block.create_var(name=snap, shape=p.shape, dtype=p.dtype,
                             persistable=True)
            block.append_op("assign", {"X": [p.name]}, {"Out": [snap]}, {})
