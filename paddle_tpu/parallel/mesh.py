"""Device-mesh management — the TPU-native CommContext.

Replaces the reference's NCCL plumbing (collective_helper.h:62 NCCLCommContext
ring registry, nccl_helper.h:90 NCCLContextMap): instead of ring_id -> NCCL
communicator, we keep ring_id/axis-name -> mesh-axis mappings over a
`jax.sharding.Mesh`. ICI collectives are emitted by XLA from shardings or
explicit psum/all_gather calls in the collective ops — no runtime comm objects
exist at all.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "make_tp_mesh", "axes_desc", "CommContext", "get_comm_context", "DATA_AXIS", "MODEL_AXIS", "SEQ_AXIS", "PIPE_AXIS", "EXPERT_AXIS"]

DATA_AXIS = "dp"
MODEL_AXIS = "tp"
SEQ_AXIS = "sp"
PIPE_AXIS = "pp"
EXPERT_AXIS = "ep"


def make_mesh(shape: dict | None = None, places=None, devices=None) -> Mesh:
    """Build a Mesh. Default: all devices on one data-parallel axis.

    shape: ordered {axis_name: size} (use -1 for "remaining devices").
    """
    devs = devices if devices is not None else jax.devices()
    if places is not None and not isinstance(places, int):
        try:
            devs = list(places)
        except TypeError:
            pass
    elif isinstance(places, int):
        devs = devs[:places]
    if not shape:
        return Mesh(np.array(devs), (DATA_AXIS,))
    names, sizes = list(shape.keys()), list(shape.values())
    n = len(devs)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    arr = np.array(devs[: int(np.prod(sizes))]).reshape(sizes)
    return Mesh(arr, tuple(names))


def make_tp_mesh(tp: int, devices=None) -> Mesh:
    """A pure tensor-parallel mesh (the serving engine's sharded-decode
    regime, ISSUE 11): `tp` devices on the MODEL_AXIS and nothing else —
    feeds replicate (no dp axis to shard batches over) while head-sharded
    params/KV pools split per their annotations."""
    devs = list(devices if devices is not None else jax.devices())
    if int(tp) < 1:
        raise ValueError(f"tp degree must be >= 1, got {tp}")
    if len(devs) < int(tp):
        raise ValueError(
            f"tp degree {tp} exceeds the {len(devs)} visible devices "
            f"(off-TPU tests provision 8 via "
            f"--xla_force_host_platform_device_count)")
    return Mesh(np.array(devs[:int(tp)]), (MODEL_AXIS,))


def axes_desc(mesh_or_nranks) -> str:
    """Canonical mesh descriptor for tuning keys ('dp8', 'dp2tp2sp2'):
    the `mesh=` component of `collective|mesh=..|payload=..` decisions.
    One shared spelling so the transpiler's consult
    (parallel/collective.resolve_bucket_mb) and the sweeper's record
    (tools/_mc_ab.py) can never key-drift apart. Accepts a Mesh or a bare
    rank count (a dp-only ring)."""
    if isinstance(mesh_or_nranks, (int, np.integer)):
        return f"{DATA_AXIS}{int(mesh_or_nranks)}"
    m = mesh_or_nranks
    return "".join(f"{name}{int(m.shape[name])}" for name in m.axis_names)


class CommContext:
    """ring_id -> mesh axis registry (facade mirroring NCCLCommContext)."""

    def __init__(self):
        # ring 0's DATA_AXIS entry is a *default*, not a user registration —
        # executors may rebind unregistered rings to the mesh's data axis,
        # but must error on an explicit registration naming a missing axis
        self._rings: dict[int, str] = {}
        self.mesh: Mesh | None = None

    def register_ring(self, ring_id: int, axis: str):
        self._rings[ring_id] = axis

    def registered_rings(self):
        return self._rings.keys()

    def unregister_ring(self, ring_id: int):
        self._rings.pop(ring_id, None)

    def axis_of(self, ring_id: int) -> str:
        return self._rings.get(ring_id, DATA_AXIS)


_ctx = CommContext()


def get_comm_context() -> CommContext:
    return _ctx
