"""Pipeline parallelism: GPipe-style stage split + host-driven microbatch loop.

TPU-native redesign of the reference's pipeline stack:
  * PipelineOptimizer program split
    (/root/reference/python/paddle/fluid/optimizer.py:2683, split :2966)
  * PipelineTrainer / SectionWorker scope-queue runtime
    (/root/reference/paddle/fluid/framework/trainer.h:110,
     device_worker.h:262, pipeline_trainer.cc)

Redesign: the program is cut into per-stage sub-programs at user-chosen
boundary variables (the reference's cut_list). Each stage gets
  * a forward program (the stage's ops; boundary outputs are fetched),
  * a backward program (the stage's ops replayed + grad ops from
    `gradients()` — i.e. rematerialized backward, the TPU-friendly
    trade of FLOPs for HBM instead of the reference's stashed scopes),
  * an update program (the wrapped optimizer's ops over accumulated grads).
The runtime executes the GPipe schedule: all microbatches forward
stage-by-stage, all microbatches backward in reverse, gradient accumulation,
then one optimizer step — numerically equal to one large-batch step when the
loss is a mean (mean of equal-size microbatch means == full-batch mean).

Stage-to-device placement (`devices=`): each stage's programs run on its own
device from the `pp` axis — stage parameters and optimizer state are
device_put once, cross-stage boundary tensors transfer device-to-device
(jax.Arrays, no host round-trip; ICI on real hardware), and the microbatch
loop dispatches in GPipe clock-cycle order (cycle t runs stage s on
microbatch t-s), so stage s computes microbatch m while stage s+1 computes
m-1 — the SectionWorker concurrency (reference trainer.h:110,
pipeline_trainer.cc) carried by XLA async dispatch instead of section
threads + scope queues. Without `devices` the same schedule runs on one
device and buys only activation memory (peak / num_microbatches).

Schedules: "gpipe" (all forwards, then all backwards — activation stash
grows with num_microbatches) and "1f1b" (PipeDream-flush steady state —
stage s runs S-1-s warmup forwards then alternates one-forward-one-backward,
so at most ~n_stages microbatches are in flight and the boundary stash is
freed as each microbatch's backward completes; reference SectionWorker's
steady-state concurrency, trainer.h:110). Both schedules produce identical
numerics (same per-microbatch grads, one optimizer step on the mean).

RNG correctness: the backward program replays the stage's forward ops, and
both runs draw their per-op PRNG keys from the same caller-supplied
rng_counter (Executor.run rng_counter=...), so dropout masks in the
recompute are bit-identical to the forward's — the TPU analogue of the
reference stashing per-microbatch scopes and replaying them.
"""
from __future__ import annotations

import copy
from typing import Any

import numpy as np

from ..framework import (
    Parameter,
    Program,
    Variable,
    default_startup_program,
    grad_var_name,
    program_guard,
)

__all__ = ["PipelinePlan", "build_pipeline_plan", "bubble_fraction"]


def bubble_fraction(n_stages: int, num_microbatches: int) -> float:
    """Analytic pipeline-bubble fraction (S-1)/(M+S-1): the share of each
    stage's schedule spent idle during fill+drain. Identical for GPipe and
    1F1B — 1F1B's win is the BOUNDED STASH (peak <= S+1 live microbatches vs
    M), not fewer bubbles; the measured counterpart is
    PipelinePlan.last_bubble after a run_step."""
    s, m = int(n_stages), int(num_microbatches)
    if s <= 1:
        return 0.0
    return (s - 1) / (m + s - 1)

_GRAD_IN_SUFFIX = "@GRAD@IN"  # feed var carrying the next stage's cotangent


def _producer_index(block, name: str) -> int:
    last = -1
    for i, op in enumerate(block.ops):
        if name in op.output_names:
            last = i
    return last


def _copy_var(dst_block, src_var: Variable, as_feed: bool = False) -> Variable:
    if src_var.name in dst_block.vars:
        return dst_block.vars[src_var.name]
    if isinstance(src_var, Parameter):
        p = Parameter(
            dst_block, src_var.shape, src_var.dtype, name=src_var.name,
            trainable=src_var.trainable,
            regularizer=src_var.regularizer,
            gradient_clip_attr=src_var.gradient_clip_attr,
            do_model_average=src_var.do_model_average,
            optimize_attr=dict(src_var.optimize_attr or {}),
        )
        p.sharding = src_var.sharding  # keep tp/sp GSPMD annotations
        dst_block.vars[p.name] = p
        return p
    return dst_block.create_var(
        name=src_var.name,
        shape=src_var.shape,
        dtype=src_var.dtype,
        persistable=src_var.persistable,
        stop_gradient=src_var.stop_gradient and not as_feed,
        is_data=as_feed or src_var.is_data,
        sharding=src_var.sharding,
    )


def _replay_ops(src_block, indices, dst_prog: Program, feed_names: set,
                shield_state: bool = False):
    """Copy the ops at `indices` (and their vars) into dst_prog's block 0.

    With shield_state=True (the backward replay), writes to persistable
    non-parameter vars (batch-norm moving stats, counters, ...) are renamed to
    throwaway temps so the rematerialization doesn't update state a second
    time per microbatch; later reads inside the replay see the renamed value.
    """
    dst = dst_prog.global_block
    renames: dict[str, str] = {}
    for i in indices:
        op = src_block.ops[i]
        if "sub_block" in op.attrs:
            raise NotImplementedError(
                "pipeline stages containing control-flow sub-blocks are not "
                "supported yet; place While/StaticRNN fully inside one stage "
                "program built without cuts")
        inputs = {s: [renames.get(n, n) for n in ns] for s, ns in op.inputs.items()}
        for n in op.input_names:
            if n and src_block.has_var(n):
                _copy_var(dst, src_block.var(n), as_feed=n in feed_names)
        outputs = {s: list(ns) for s, ns in op.outputs.items()}
        for s, ns in outputs.items():
            for j, n in enumerate(ns):
                if not n or not src_block.has_var(n):
                    continue
                v = src_block.var(n)
                if (shield_state and v.persistable
                        and not isinstance(v, Parameter)):
                    tmp = renames.get(n) or (n + "@PIPE_SHIELD")
                    renames[n] = tmp
                    dst.create_var(name=tmp, shape=v.shape, dtype=v.dtype)
                    ns[j] = tmp
                else:
                    _copy_var(dst, v)
        nop = dst.append_op(op.type, inputs, outputs, copy.deepcopy(op.attrs))
        nop._callstack = op._callstack


class _Stage:
    def __init__(self, idx: int):
        self.idx = idx
        self.fwd: Program | None = None
        self.bwd: Program | None = None
        self.update: Program | None = None
        self.ext_inputs: list[str] = []   # runtime feeds: user data + cut-ins
        self.out_names: list[str] = []    # boundary outputs consumed later
        self.param_names: list[str] = []
        self.in_grad_names: dict[str, str] = {}   # ext input -> its @GRAD name
        self.param_grad_names: dict[str, str] = {}  # param -> its @GRAD name
        self.update_feed: dict[str, str] = {}     # param -> update-prog grad feed


def resolve_devices(place_list, n_stages: int):
    """Map a reference-style place_list to one jax.Device per stage.

    Entries may be jax.Device, an int device ordinal, or Place objects
    carrying a `device_id` (TPUPlace/CUDAPlace parity). None -> no placement
    (single-device GPipe)."""
    import jax

    if place_list is None:
        return None
    if len(place_list) != n_stages:
        raise ValueError(
            f"place_list has {len(place_list)} entries for {n_stages} "
            "pipeline stages (one device per stage)")
    pool = jax.devices()
    out = []
    for p in place_list:
        if hasattr(p, "id") and hasattr(p, "platform"):  # jax.Device
            out.append(p)
        elif isinstance(p, (int, np.integer)):
            out.append(pool[int(p)])
        elif hasattr(p, "device_id"):
            out.append(pool[p.device_id])
        elif type(p).__name__ == "CPUPlace":
            out.append(pool[0])
        else:
            raise TypeError(
                f"place_list entry {p!r} is not a jax.Device, int ordinal, "
                "CPUPlace, or a Place with `device_id` — refusing to guess "
                "(a silent default would collapse stages onto one device)")
    return out


def build_pipeline_plan(program: Program, loss: Variable, cut_vars,
                        inner_opt, num_microbatches: int,
                        startup_program: Program | None = None,
                        devices=None, schedule: str | None = None, mesh=None):
    """Split `program` (forward-only) at `cut_vars` into a PipelinePlan.

    schedule: "1f1b" | "gpipe"; None resolves FLAGS_pipeline_schedule."""
    from ..backward import gradients

    if schedule is None:
        from .. import flags

        schedule = str(flags.get_flag("pipeline_schedule")).strip().lower()
    if num_microbatches < 1:
        raise ValueError("num_microbatches must be >= 1")
    block = program.global_block
    startup = startup_program or default_startup_program()

    bounds = []
    for v in cut_vars:
        pos = _producer_index(block, v.name)
        if pos < 0:
            raise ValueError(f"cut var '{v.name}' is not produced by any op")
        bounds.append(pos)
    if bounds != sorted(bounds):
        raise ValueError("cut_list variables must be in program order")
    loss_pos = _producer_index(block, loss.name)
    if bounds and loss_pos <= bounds[-1]:
        raise ValueError("the loss must be produced after the last cut")

    n_stages = len(bounds) + 1
    ranges = []
    lo = 0
    for b in bounds:
        ranges.append(list(range(lo, b + 1)))
        lo = b + 1
    ranges.append(list(range(lo, len(block.ops))))

    # stage of the op producing each var
    producer_stage: dict[str, int] = {}
    for s, idxs in enumerate(ranges):
        for i in idxs:
            for n in block.ops[i].output_names:
                if n:
                    producer_stage[n] = s

    stages = [_Stage(s) for s in range(n_stages)]
    params = {p.name for p in program.all_parameters()}

    # classify external inputs per stage; boundary transfers are ANY var
    # produced in an earlier stage and read in a later one (the cut_list only
    # fixes the cut *positions*, reference split :2966 behaves the same way)
    for s, idxs in enumerate(ranges):
        defined: set[str] = set()
        ext: list[str] = []
        for i in idxs:
            op = block.ops[i]
            for n in op.input_names:
                if not n or n in defined or n in ext:
                    continue
                try:
                    v = block.var(n)
                except KeyError:
                    continue
                if v.persistable:
                    continue  # params/state come from the scope
                ps = producer_stage.get(n)
                if ps is not None and ps == s:
                    continue
                if ps is not None and ps < s:
                    ext.append(n)
                    if n not in stages[ps].out_names:
                        stages[ps].out_names.append(n)
                elif v.is_data:
                    ext.append(n)
            defined.update(n for n in op.output_names if n)
        stages[s].ext_inputs = ext
        stages[s].param_names = sorted(
            {n for i in idxs for n in block.ops[i].input_names if n in params}
        )

    # build per-stage programs
    for s, stage in enumerate(stages):
        is_last = s == n_stages - 1
        feed_set = set(stage.ext_inputs)

        stage.fwd = Program()
        stage.fwd.random_seed = program.random_seed
        _replay_ops(block, ranges[s], stage.fwd, feed_set)

        stage.bwd = Program()
        stage.bwd.random_seed = program.random_seed
        _replay_ops(block, ranges[s], stage.bwd, feed_set, shield_state=True)
        bblock = stage.bwd.global_block
        with program_guard(stage.bwd, startup):
            if is_last:
                targets = [bblock.var(loss.name)]
                tgs = None
            else:
                targets, tgs = [], []
                for n in stage.out_names:
                    ov = bblock.var(n)
                    targets.append(ov)
                    gv = bblock.create_var(
                        name=n + _GRAD_IN_SUFFIX, shape=ov.shape,
                        dtype=ov.dtype, is_data=True, stop_gradient=True)
                    tgs.append(gv)
            wrt = [bblock.var(n) for n in stage.ext_inputs
                   if _is_float(bblock.var(n))]
            wrt += [bblock.var(p) for p in stage.param_names]
            grads = gradients(targets, wrt, target_gradients=tgs)
        for v, g in zip(wrt, grads):
            if g is None:
                continue
            if v.name in params:
                stage.param_grad_names[v.name] = g.name
            else:
                stage.in_grad_names[v.name] = g.name

    # update programs: wrapped optimizer over accumulated grads. A param read
    # by several stages (tied weights) gets exactly ONE update — grad_acc
    # already holds its total gradient across all stages' backward runs.
    claimed: set[str] = set()
    for stage in stages:
        todo = [p for p in stage.param_names
                if p in stage.param_grad_names and p not in claimed]
        if not todo:
            continue
        claimed.update(todo)
        opt = copy.deepcopy(inner_opt)
        stage.update = Program()
        stage.update.random_seed = program.random_seed
        ublock = stage.update.global_block
        with program_guard(stage.update, startup):
            pairs = []
            for p in todo:
                pv = _copy_var(ublock, block.var(p))
                gname = grad_var_name(p)
                gv = ublock.create_var(
                    name=gname, shape=pv.shape, dtype=pv.dtype,
                    is_data=True, stop_gradient=True)
                stage.update_feed[p] = gname
                pairs.append((pv, gv))
            opt.apply_gradients(pairs)

    return PipelinePlan(stages, loss.name, num_microbatches,
                        devices=resolve_devices(devices, n_stages),
                        schedule=schedule, mesh=mesh)


def _is_float(v: Variable) -> bool:
    from ..core.types import is_floating

    return is_floating(v.dtype)


class PipelinePlan:
    """Executable GPipe schedule over the stage programs (the
    PipelineTrainer/SectionWorker equivalent, host-driven)."""

    def __init__(self, stages: list[_Stage], loss_name: str,
                 num_microbatches: int, devices=None,
                 schedule: str = "1f1b", mesh=None):
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown pipeline schedule '{schedule}'")
        if mesh is not None and devices is not None:
            raise NotImplementedError(
                "pipeline mesh= (tp-sharded stages over one shared mesh) "
                "and devices= (one device per stage) are mutually "
                "exclusive; per-stage sub-meshes are not supported yet")
        # tp x pp composition: every stage program is compiled GSPMD over
        # this shared mesh (the model's tp/sp annotations shard within the
        # stage; the microbatch loop provides pp). The mesh must not carry
        # a dp axis — feeds replicate, per-var annotations shard.
        self.mesh = mesh
        self._compiled_cache: dict | None = None
        self.stages = stages
        self.loss_name = loss_name
        self.num_microbatches = num_microbatches
        self.devices = devices
        self.schedule = schedule
        # dispatch order of the last run_step, [("f"|"b", stage, microbatch)]
        # — observable evidence of the clock-cycle interleave (tests assert
        # stage s+1 starts before stage s drains; the reference's analogue is
        # SectionWorker threads consuming scope queues concurrently)
        self.last_dispatch: list[tuple] = []
        # max #microbatches with live boundary stash during the last step —
        # the 1f1b memory claim is peak <= n_stages + 1 (vs M for gpipe)
        self.last_peak_stash: int = 0
        # explicit bubble accounting for the last run_step: per-stage idle
        # slots (cycles/rounds where the stage had pending work but its
        # dependencies weren't met — the fill/drain bubble made observable)
        # next to the analytic (S-1)/(M+S-1); bench --multichip records it
        self.last_bubble: dict = {}
        self._step_counter = 0
        if devices is not None:
            self._check_no_cross_stage_params()

    def _check_no_cross_stage_params(self):
        owner: dict[str, int] = {}
        for s, stage in enumerate(self.stages):
            for p in stage.param_names:
                if p in owner:
                    raise NotImplementedError(
                        f"parameter '{p}' is read by pipeline stages "
                        f"{owner[p]} and {s}; tied weights across "
                        "device-placed stages are not supported (each "
                        "parameter must live on exactly one stage device)")
                owner[p] = s

    def _to_dev(self, v, dev):
        import jax

        if dev is None:
            return v
        if isinstance(v, jax.Array) and dev not in v.devices():
            return jax.device_put(v, dev)
        return v

    def _stage_prog(self, s: int, which: str):
        """The runnable for stage s's `which` program: the raw Program, or
        (mesh mode) a CompiledProgram over the shared tp mesh, cached."""
        prog = getattr(self.stages[s], which)
        if self.mesh is None or prog is None:
            return prog
        if self._compiled_cache is None:
            self._compiled_cache = {}
        key = (s, which)
        if key not in self._compiled_cache:
            from ..compiler import CompiledProgram

            self._compiled_cache[key] = CompiledProgram(
                prog).with_data_parallel(mesh=self.mesh)
        return self._compiled_cache[key]

    def _place_stage_state(self, scope):
        """device_put each stage's scope-resident state (params, BN stats,
        optimizer accumulators — everything its programs read or write) onto
        the stage's device, once per value. Donated updates keep results on
        the same device, so this is a no-op after the first step."""
        import jax

        if not hasattr(self, "_stage_state_names"):
            self._stage_state_names = []
            for stage in self.stages:
                names: set[str] = set()
                for prog in (stage.fwd, stage.bwd, stage.update):
                    if prog is None:
                        continue
                    for op in prog.global_block.ops:
                        names.update(n for n in op.input_names if n)
                        names.update(n for n in op.output_names if n)
                self._stage_state_names.append(sorted(names))
        for names, dev in zip(self._stage_state_names, self.devices):
            for n in names:
                v = scope.find_var(n)
                if isinstance(v, jax.Array) and dev not in v.devices():
                    scope.set_var(n, jax.device_put(v, dev))

    def run_step(self, exe, scope, feed: dict, fetch_names: list[str]):
        from ..core.types import np_feed_dtype

        M = self.num_microbatches
        micro_feeds: list[dict[str, Any]] = [dict() for _ in range(M)]
        for name, val in feed.items():
            val = np.asarray(val)
            # narrow 64-bit host feeds on the HOST (explicit truncation):
            # an int64 chunk reaching device_put under x64-off jax would
            # warn-and-truncate per microbatch per stage (the MULTICHIP
            # dryrun-tail pollution; same discipline as Executor.run feeds)
            val = val.astype(np_feed_dtype(val.dtype), copy=False)
            if val.shape[0] % M != 0:
                raise ValueError(
                    f"feed '{name}' batch {val.shape[0]} is not divisible by "
                    f"num_microbatches={M}")
            for m, chunk in enumerate(np.split(val, M)):
                micro_feeds[m][name] = chunk

        # resolve fetches: prefer the stage whose fwd program PRODUCES the
        # name (an op output) over one that merely reads it; among producers
        # take the first, so a later stage re-using a temp name can't shadow
        # the intended tensor
        fetch_stage: dict[str, int] = {}
        for name in fetch_names:
            holder = None
            for s, stage in enumerate(self.stages):
                blk = stage.fwd.global_block
                if not blk.has_var(name):
                    continue
                if holder is None:
                    holder = s
                produced = any(
                    name in names
                    for op in blk.ops for names in op.outputs.values())
                if produced:
                    fetch_stage[name] = s
                    break
            if name not in fetch_stage:
                if holder is None:
                    raise KeyError(
                        f"fetch '{name}' not found in any pipeline stage")
                fetch_stage[name] = holder

        S = len(self.stages)
        devs = self.devices or [None] * S
        if self.devices is not None:
            self._place_stage_state(scope)
        self.last_dispatch = []
        self.last_peak_stash = 0
        # per-(step, stage, microbatch) PRNG counter shared by the forward
        # run and the backward replay: identical op prefix + identical key
        # => identical dropout masks in the recompute (Executor.run
        # rng_counter). The 2^30 offset keeps the range disjoint from the
        # scope's own small run counters used by non-pipeline runs
        # (fold_in requires uint32, so negatives are out).
        self._step_counter += 1
        base = (1 << 30) + self._step_counter * S * M

        def _rng(s, m):
            return base + s * M + m

        # the boundary stash entry for var n (produced at stage ps) is last
        # read by the backward of its LOWEST consumer stage — free it there
        free_at: dict[str, int] = {}
        for s, stage in enumerate(self.stages):
            for n in stage.ext_inputs:
                if any(n in st.out_names for st in self.stages[:s]):
                    free_at[n] = min(free_at.get(n, S), s)

        def _note_peak(stash):
            live = sum(1 for d in stash if d)
            self.last_peak_stash = max(self.last_peak_stash, live)

        # boundary shapes recorded at forward time: the backward's
        # zero-cotangent fallback needs them AFTER the stash entry may
        # already be freed by a lower consumer stage (r5 review fix)
        shape_of: dict[str, tuple] = {}

        def _fwd_one(s, m, stash, fetched):
            stage = self.stages[s]
            wanted = list(stage.out_names) + [
                n for n in fetch_names
                if fetch_stage[n] == s and n not in stage.out_names]
            f = {n: micro_feeds[m][n] for n in stage.ext_inputs
                 if n in micro_feeds[m]}
            f.update({n: self._to_dev(stash[m][n], devs[s])
                      for n in stage.ext_inputs if n in stash[m]})
            missing = [n for n in stage.ext_inputs if n not in f]
            if missing:
                raise KeyError(f"pipeline stage {s} needs feeds {missing}")
            outs = exe.run(self._stage_prog(s, "fwd"), feed=f,
                           fetch_list=wanted, scope=scope,
                           return_numpy=False, rng_counter=_rng(s, m))
            self.last_dispatch.append(("f", s, m))
            for n, v in zip(wanted, outs):
                if n in stage.out_names:
                    stash[m][n] = v
                    shape_of[n] = tuple(np.asarray(v).shape) \
                        if not hasattr(v, "shape") else tuple(v.shape)
                if n in fetched:
                    fetched[n].append(v)
            _note_peak(stash)

        def _bwd_one(s, m, stash, grad_stash, grad_acc):
            stage = self.stages[s]
            pg_names = sorted(stage.param_grad_names.items())
            ig_names = sorted(stage.in_grad_names.items())
            wanted = [g for _, g in pg_names] + [g for _, g in ig_names]
            if not wanted:
                return
            f = {n: micro_feeds[m][n] for n in stage.ext_inputs
                 if n in micro_feeds[m]}
            f.update({n: self._to_dev(stash[m][n], devs[s])
                      for n in stage.ext_inputs if n in stash[m]})
            for n in stage.out_names:
                g = grad_stash[m].get(n)
                if g is None:
                    g = np.zeros(shape_of[n],
                                 stage.fwd.global_block.var(n).np_feed_dtype)
                f[n + _GRAD_IN_SUFFIX] = self._to_dev(g, devs[s])
            outs = exe.run(self._stage_prog(s, "bwd"), feed=f,
                           fetch_list=wanted, scope=scope,
                           return_numpy=False, rng_counter=_rng(s, m))
            self.last_dispatch.append(("b", s, m))
            outs = list(outs)
            for (p, _), v in zip(pg_names, outs[: len(pg_names)]):
                prev = grad_acc.get(p)
                grad_acc[p] = v if prev is None else prev + v
            for (n, _), v in zip(ig_names, outs[len(pg_names):]):
                prev = grad_stash[m].get(n)
                if prev is not None:
                    v = self._to_dev(v, _device_of(prev))
                grad_stash[m][n] = v if prev is None else prev + v
            # this backward was the last reader of m's inputs at this stage
            # and of m's cotangents for this stage's outputs
            for n in [n for n, fs in free_at.items() if fs == s]:
                stash[m].pop(n, None)
            for n in stage.out_names:
                grad_stash[m].pop(n, None)

        stash: list[dict[str, Any]] = [dict() for _ in range(M)]
        fetched: dict[str, list] = {n: [] for n in fetch_names}
        grad_acc: dict[str, Any] = {}
        grad_stash: list[dict[str, Any]] = [dict() for _ in range(M)]

        stalls = [0] * S
        rounds = 0
        if self.schedule == "gpipe":
            # --- forward: GPipe clock cycles — cycle t dispatches stage s on
            # microbatch t-s, so with device placement stage s computes
            # microbatch m while stage s+1 computes m-1 (async XLA dispatch
            # on distinct devices = the SectionWorker overlap)
            for t in range(S + M - 1):
                rounds += 1
                for s in range(S):
                    m = t - s
                    if 0 <= m < M:
                        _fwd_one(s, m, stash, fetched)
                    else:
                        stalls[s] += 1  # fill/drain bubble slot
            # --- backward: reverse clock cycles (stage S-1 leads, stage s
            # runs microbatch m at cycle (S-1-s)+m); every consumer stage
            # s' > s of a boundary var finishes microbatch m strictly before
            # stage s needs its cotangent.
            for t in range(S + M - 1):
                rounds += 1
                for s in range(S - 1, -1, -1):
                    m = t - (S - 1 - s)
                    if 0 <= m < M:
                        _bwd_one(s, m, stash, grad_stash, grad_acc)
                    else:
                        stalls[s] += 1
        else:
            # --- 1F1B (PipeDream-flush): stage s runs min(S-1-s, M) warmup
            # forwards, then alternates forward/backward in steady state,
            # then drains. Dependency-driven dispatch: each round every
            # stage advances at most one op when its deps are met — fwd(s,m)
            # after fwd(s-1,m); bwd(s,m) after fwd(s,m) and bwd(s+1,m).
            local: list[list[str]] = []
            for s in range(S):
                w = min(S - 1 - s, M)
                local.append(["f"] * w + ["f", "b"] * (M - w) + ["b"] * w)
            pc = [0] * S
            fcnt = [0] * S
            bcnt = [0] * S
            fwd_done = [[False] * M for _ in range(S)]
            bwd_done = [[False] * M for _ in range(S)]
            while any(pc[s] < len(local[s]) for s in range(S)):
                rounds += 1
                progressed = False
                for s in range(S):
                    if pc[s] >= len(local[s]):
                        continue
                    kind = local[s][pc[s]]
                    if kind == "f":
                        m = fcnt[s]
                        if s > 0 and not fwd_done[s - 1][m]:
                            stalls[s] += 1  # warmup/dependency bubble
                            continue
                        _fwd_one(s, m, stash, fetched)
                        fwd_done[s][m] = True
                        fcnt[s] += 1
                    else:
                        m = bcnt[s]
                        if not fwd_done[s][m] or (
                                s < S - 1 and not bwd_done[s + 1][m]):
                            stalls[s] += 1  # drain/cotangent bubble
                            continue
                        _bwd_one(s, m, stash, grad_stash, grad_acc)
                        bwd_done[s][m] = True
                        bcnt[s] += 1
                    pc[s] += 1
                    progressed = True
                if not progressed:
                    raise RuntimeError(
                        "1F1B schedule deadlocked — dependency bug")
        # bubble accounting: stall slots per stage over the schedule's
        # rounds, next to the analytic (S-1)/(M+S-1) both schedules share
        total_slots = max(1, rounds * S)
        self.last_bubble = {
            "schedule": self.schedule,
            "n_stages": S,
            "num_microbatches": M,
            "analytic_frac": round(bubble_fraction(S, M), 4),
            "rounds": rounds,
            "stall_rounds_per_stage": list(stalls),
            "observed_frac": round(sum(stalls) / total_slots, 4),
            "peak_stash": self.last_peak_stash,
        }

        # --- update: one optimizer step on mean-of-microbatch grads ---------
        inv = 1.0 / M
        for stage in self.stages:
            if stage.update is None or not stage.update_feed:
                continue
            f = {g: grad_acc[p] * inv for p, g in stage.update_feed.items()}
            exe.run(self._stage_prog(stage.idx, "update"), feed=f,
                    scope=scope)

        # --- assemble fetches ------------------------------------------------
        # batch-dim fetches (declared leading dim -1) concatenate across
        # microbatches; everything else (loss, metrics) averages
        results = []
        for n in fetch_names:
            vals = [np.asarray(v) for v in fetched[n]]
            var = self.stages[fetch_stage[n]].fwd.global_block.var(n)
            if var.shape and var.shape[0] == -1:
                results.append(np.concatenate(vals, axis=0))
            else:
                results.append(np.mean(np.stack(vals), axis=0))
        return results


def _device_of(arr):
    import jax

    if isinstance(arr, jax.Array):
        (dev,) = arr.devices() if len(arr.devices()) == 1 else (None,)
        return dev
    return None
