"""GSPMD sharding assignment for compiled programs.

This module is the TPU-native core of data/model parallelism, replacing the
reference's multi-device SSA graph construction
(/root/reference/paddle/fluid/framework/ir/multi_devices_graph_pass/
multi_devices_graph_pass.cc:169 ApplyImpl, :594 InsertCollectiveOp): instead
of replicating ops per device and inserting AllReduceOpHandles, every variable
gets a `NamedSharding` and XLA's SPMD partitioner inserts the collectives.

Rules:
  * feed (data) vars shard their leading batch dim over the `dp` axis;
  * params/optimizer state follow their `Variable.sharding` annotation
    (set by parallel/transpilers or model code for TP/EP), else replicate;
  * fetches replicate (host reads them).
Gradient allreduce falls out: batch-sharded activations x replicated params
=> XLA inserts the psum on the grad path (the AllReduceSSAGraphBuilder
equivalent, chosen by the compiler not by a pass).
"""
from __future__ import annotations

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS

__all__ = ["build_shardings", "var_sharding", "annotate_sharding", "annotation_spec"]


def annotate_sharding(var, spec: tuple):
    """Attach a per-dim mesh-axis annotation to a Variable (TP/SP/EP)."""
    var.sharding = tuple(spec)
    return var


def annotation_spec(mesh: Mesh, var, strict: bool = False) -> P:
    """Normalize a Variable's sharding annotation to a PartitionSpec.

    strict=False (GSPMD regime): axes missing from the mesh are dropped —
    the partitioner still produces CORRECT results, just unsharded (running
    a tp-annotated model on a dp-only mesh is a designed fallback).
    strict=True (shard_map regime): a missing axis is an ERROR — shard_map
    in_specs change the VALUES each device sees, so silently replicating a
    seq-sharded feed computes the wrong thing.
    """
    if strict:
        missing = [a for a in var.sharding
                   if a is not None and a not in mesh.axis_names]
        if missing:
            raise ValueError(
                f"feed '{var.name}' is annotated with mesh axes {missing} "
                f"that this mesh {mesh.axis_names} does not have")
    axes = [a if a in mesh.axis_names else None for a in var.sharding]
    rank = len(var.shape)
    return P(*(list(axes) + [None] * rank)[:rank])


def var_sharding(mesh: Mesh, var, is_feed: bool) -> NamedSharding:
    if var is not None and var.sharding is not None:
        return NamedSharding(mesh, annotation_spec(mesh, var))
    if is_feed and var is not None and len(var.shape) >= 1 and DATA_AXIS in mesh.axis_names:
        spec = [DATA_AXIS] + [None] * (len(var.shape) - 1)
        return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())


def build_shardings(mesh, block, feed_names, ro_names, rw_names, extra_w, fetch_names):
    def _var(n):
        try:
            return block.var(n)
        except KeyError:
            return None

    feed_sh = tuple(var_sharding(mesh, _var(n), True) for n in feed_names)
    ro_sh = tuple(var_sharding(mesh, _var(n), False) for n in ro_names)
    rw_sh = tuple(var_sharding(mesh, _var(n), False) for n in rw_names)
    key_sh = NamedSharding(mesh, P())
    in_sh = (feed_sh, ro_sh, rw_sh, key_sh)
    fetch_sh = tuple(NamedSharding(mesh, P()) for _ in fetch_names)
    new_rw_sh = rw_sh
    extra_sh = tuple(var_sharding(mesh, _var(n), False) for n in extra_w)
    # 4th output: the scalar async completion token (executor._step_token)
    out_sh = (fetch_sh, new_rw_sh, extra_sh, NamedSharding(mesh, P()))
    return in_sh, out_sh
