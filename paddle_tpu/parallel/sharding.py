"""GSPMD sharding assignment for compiled programs.

This module is the TPU-native core of data/model parallelism, replacing the
reference's multi-device SSA graph construction
(/root/reference/paddle/fluid/framework/ir/multi_devices_graph_pass/
multi_devices_graph_pass.cc:169 ApplyImpl, :594 InsertCollectiveOp): instead
of replicating ops per device and inserting AllReduceOpHandles, every variable
gets a `NamedSharding` and XLA's SPMD partitioner inserts the collectives.

Rules:
  * feed (data) vars shard their leading batch dim over the `dp` axis;
  * params/optimizer state follow their `Variable.sharding` annotation
    (set by parallel/transpilers or model code for TP/EP), else replicate;
  * fetches replicate (host reads them).
Gradient allreduce falls out: batch-sharded activations x replicated params
=> XLA inserts the psum on the grad path (the AllReduceSSAGraphBuilder
equivalent, chosen by the compiler not by a pass).
"""
from __future__ import annotations

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS

__all__ = ["build_shardings", "var_sharding", "annotate_sharding",
           "annotation_spec", "apply_zero1", "ZERO1_OP_TYPES"]

# optimizer ops whose update rule is elementwise in (param, grad, moments) —
# the precondition for shard-update == full-update restricted to the shard.
# LARS/LAMB compute parameter-wide trust ratios (a norm over the FULL param),
# so a shard-local update would diverge; they stay on the allreduce path.
ZERO1_OP_TYPES = {"sgd", "momentum", "adam", "adagrad", "rmsprop", "adamax",
                  "adadelta", "decayed_adagrad"}

_SHARD_SUFFIX = "@ZERO1_SHARD"
_GRAD_SUFFIX = "@ZERO1_GRAD"


def apply_zero1(program, nranks: int) -> list[str]:
    """ZeRO-1 optimizer-state sharding, as a program rewrite (the shard_map
    complement of BuildStrategy.sharded_optimizer_states, which does the
    same thing through GSPMD annotations).

    For every eligible optimizer op (elementwise update rule, param leading
    dim divisible by nranks) the per-grad mean-allreduce becomes:

        c_reducescatter(grad)  -> grad shard        [d0/nranks, ...]
        zero1_shard(param/moments) -> state shards  (this rank's rows)
        <optimizer op over the shards>
        c_allgather(shards) -> full param + moments

    The reduce-scatter is inserted where the gradient is FINAL
    (backward.grad_ready_index — below AMP/clip/guardrails) so it overlaps
    the remaining backward like the bucketed allreduce; the allgathers sit
    directly after the update, at the program tail, where XLA's async
    collectives — and the run_async inflight window — overlap them with the
    next step's first buckets. Under GSPMD every inserted collective lowers
    to identity and the rewrite collapses to the plain full update.

    Returns the param names rewritten; everything else (indivisible leading
    dim, scalar params, non-elementwise optimizers) is left for the caller's
    bucketed-allreduce path."""
    from ..backward import grad_ready_index

    block = program.global_block
    handled: list[str] = []
    opt_ops = [op for op in block.ops if op.type in ZERO1_OP_TYPES]
    if not opt_ops:
        return handled
    first_opt = min(block.ops.index(op) for op in opt_ops)

    for op in reversed(opt_ops):
        pname = op.input("Param")[0]
        gname = op.input("Grad")[0]
        pvar = block.var(pname)
        d0 = pvar.shape[0] if pvar.shape else 0
        if len(pvar.shape) < 1 or d0 < nranks or d0 % nranks != 0:
            continue
        shard0 = d0 // nranks

        # classify state inputs: every non-Grad/LR input sharing the param's
        # leading dim shards with it (Param, Velocity, Moment1/2, ...);
        # scalars (Beta*Pow, LearningRate) stay replicated
        shard_of: dict[str, str] = {}
        for slot, names in op.inputs.items():
            if slot in ("Grad", "LearningRate"):
                continue
            for n in names:
                if not n or not block.has_var(n):
                    continue
                v = block.var(n)
                if v.shape and v.shape[0] == d0:
                    shard_of[n] = n + _SHARD_SUFFIX

        gshard = gname + _GRAD_SUFFIX
        gvar = block.var(gname)
        block.create_var(name=gshard, shape=[shard0] + list(gvar.shape[1:]),
                         dtype=gvar.dtype)
        for n, sn in shard_of.items():
            v = block.var(n)
            block.create_var(name=sn, shape=[shard0] + list(v.shape[1:]),
                             dtype=v.dtype)

        # rewrite the op in place: shard inputs, and every output aliasing a
        # sharded input writes the shard (ParamOut -> param@ZERO1_SHARD)
        op.inputs = {
            slot: [gshard if n == gname else shard_of.get(n, n)
                   for n in names]
            for slot, names in op.inputs.items()}
        op.outputs = {slot: [shard_of.get(n, n) for n in names]
                      for slot, names in op.outputs.items()}

        # allgathers AFTER the update (full names restored for the scope
        # write-back and the next forward)
        i = block.ops.index(op)
        for n, sn in sorted(shard_of.items(), reverse=True):
            block._insert_op(i + 1, "c_allgather", {"X": [sn]}, {"Out": [n]},
                            {"ring_id": 0})
        # state shards directly BEFORE the update
        for n, sn in sorted(shard_of.items(), reverse=True):
            block._insert_op(i, "zero1_shard", {"X": [n]}, {"Out": [sn]},
                            {"ring_id": 0})
        # mean reduce-scatter of the gradient at its readiness point
        ready = grad_ready_index(block, gname, first_opt)
        block._insert_op(
            (ready + 1) if ready >= 0 else block.ops.index(op),
            "c_reducescatter", {"X": [gname]}, {"Out": [gshard]},
            {"ring_id": 0, "avg": True})
        first_opt += 1  # the rs insert shifted everything at/above it
        handled.append(pname)

    handled.reverse()
    return handled


def annotate_sharding(var, spec: tuple):
    """Attach a per-dim mesh-axis annotation to a Variable (TP/SP/EP)."""
    var.sharding = tuple(spec)
    return var


def annotation_spec(mesh: Mesh, var, strict: bool = False) -> P:
    """Normalize a Variable's sharding annotation to a PartitionSpec.

    strict=False (GSPMD regime): axes missing from the mesh are dropped —
    the partitioner still produces CORRECT results, just unsharded (running
    a tp-annotated model on a dp-only mesh is a designed fallback).
    strict=True (shard_map regime): a missing axis is an ERROR — shard_map
    in_specs change the VALUES each device sees, so silently replicating a
    seq-sharded feed computes the wrong thing.
    """
    if strict:
        missing = [a for a in var.sharding
                   if a is not None and a not in mesh.axis_names]
        if missing:
            raise ValueError(
                f"feed '{var.name}' is annotated with mesh axes {missing} "
                f"that this mesh {mesh.axis_names} does not have")
    axes = [a if a in mesh.axis_names else None for a in var.sharding]
    rank = len(var.shape)
    return P(*(list(axes) + [None] * rank)[:rank])


def var_sharding(mesh: Mesh, var, is_feed: bool) -> NamedSharding:
    if var is not None and var.sharding is not None:
        return NamedSharding(mesh, annotation_spec(mesh, var))
    if is_feed and var is not None and len(var.shape) >= 1 and DATA_AXIS in mesh.axis_names:
        spec = [DATA_AXIS] + [None] * (len(var.shape) - 1)
        return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())


def build_shardings(mesh, block, feed_names, ro_names, rw_names, extra_w, fetch_names):
    def _var(n):
        try:
            return block.var(n)
        except KeyError:
            return None

    feed_sh = tuple(var_sharding(mesh, _var(n), True) for n in feed_names)
    ro_sh = tuple(var_sharding(mesh, _var(n), False) for n in ro_names)
    rw_sh = tuple(var_sharding(mesh, _var(n), False) for n in rw_names)
    key_sh = NamedSharding(mesh, P())
    in_sh = (feed_sh, ro_sh, rw_sh, key_sh)
    fetch_sh = tuple(NamedSharding(mesh, P()) for _ in fetch_names)
    new_rw_sh = rw_sh
    extra_sh = tuple(var_sharding(mesh, _var(n), False) for n in extra_w)
    # 4th output: the scalar async completion token (executor._step_token)
    out_sh = (fetch_sh, new_rw_sh, extra_sh, NamedSharding(mesh, P()))
    return in_sh, out_sh
