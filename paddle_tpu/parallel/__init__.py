from .mesh import (  # noqa: F401
    CommContext,
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    get_comm_context,
    make_mesh,
)
from .sharding import annotate_sharding, build_shardings, var_sharding  # noqa: F401
