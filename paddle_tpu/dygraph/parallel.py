"""Dygraph multi-process data parallelism.

TPU-native redesign of the reference's eager DP stack
(/root/reference/python/paddle/fluid/dygraph/parallel.py:84 DataParallel —
scale_loss + coalesced apply_collective_grads;
/root/reference/paddle/fluid/imperative/nccl_context.cc NCCLParallelContext):

  * rendezvous: `distributed.init_parallel_env` joins the PjRt coordination
    service (the gen-nccl-id analogue) — one global device topology.
  * the collective: gradients are COALESCED per dtype into one flat buffer
    (the reference fuses into 128 MB chunks before ncclAllReduce; one XLA
    collective gets the same wire efficiency), summed across processes by a
    jitted reduction over a 1-device-per-process mesh, and split back.
  * `scale_loss` divides by nranks BEFORE backward, so sum-allreduced grads
    equal the full-batch mean gradient (reference parallel.py:116).

Single-process (nranks == 1) DataParallel is a transparent wrapper — same
contract as the reference, which also no-ops there.
"""
from __future__ import annotations

import numpy as np

from . import Layer, VarBase, _dy_op

__all__ = ["DataParallel"]


class DataParallel(Layer):
    """Wraps a dygraph Layer for multi-process data-parallel training.

    Usage (reference parallel_dygraph_mnist.py pattern)::

        penv = init_parallel_env(backend="cpu", local_device_count=1)
        with dg.guard(seed):
            model = DataParallel(Net())
            ...
            loss = model.scale_loss(loss)
            loss.backward()
            model.apply_collective_grads()
            opt.minimize(loss)
    """

    def __init__(self, layers: Layer, strategy=None):
        super().__init__()
        self._layers = layers
        from ..distributed import ParallelEnv

        env = ParallelEnv()
        self.nranks = getattr(strategy, "nranks", 0) or env.world_size
        self._mesh = None
        self._reduce_fns: dict = {}

    # -- Layer delegation ----------------------------------------------------
    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self):
        return self._layers.state_dict()

    def set_dict(self, state):
        self._layers.set_dict(state)

    def train(self):
        self._layers.train()

    def eval(self):
        self._layers.eval()

    # -- collective plumbing -------------------------------------------------
    def scale_loss(self, loss: VarBase) -> VarBase:
        """loss / nranks — with sum-allreduced grads this yields the global
        mean gradient (reference parallel.py:116 scale_loss)."""
        if self.nranks <= 1:
            return loss
        return _dy_op("scale", {"X": [loss]},
                      {"scale": 1.0 / self.nranks})["Out"]

    def _global_sum(self, flat):
        """Sum a per-process flat buffer across all processes: each process
        contributes its row of a [world, n] global array over a
        1-device-per-process mesh; a jitted sum over the world axis returns
        a replicated result whose local shard is the total."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        if self._mesh is None:
            # one device PER PROCESS (not the first W devices — with
            # multiple local devices those could all belong to process 0,
            # leaving other processes unaddressable in the mesh)
            by_proc: dict[int, object] = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, d)
            if len(by_proc) != jax.process_count():
                raise RuntimeError(
                    f"DataParallel: {len(by_proc)} processes visible in the "
                    f"topology but jax.process_count()={jax.process_count()}")
            devs = np.array([by_proc[p] for p in sorted(by_proc)])
            self._mesh = Mesh(devs, ("dp",))
        key = (flat.shape, str(flat.dtype))
        fn = self._reduce_fns.get(key)
        if fn is None:
            fn = jax.jit(
                lambda x: jnp.sum(x, axis=0),
                out_shardings=NamedSharding(self._mesh, P()),
            )
            self._reduce_fns[key] = fn
        sharding = NamedSharding(self._mesh, P("dp"))
        stacked = jax.make_array_from_process_local_data(
            sharding, np.asarray(flat)[None])
        return fn(stacked).addressable_data(0)

    def apply_collective_grads(self):
        """Coalesced allreduce of every parameter gradient (reference
        parallel.py:84 apply_collective_grads: _coalesce_tensors →
        allreduce → _split_tensors)."""
        if self.nranks <= 1:
            return
        import jax.numpy as jnp

        params = [p for p in self.parameters() if p._grad is not None]
        by_dtype: dict = {}
        for p in params:
            by_dtype.setdefault(str(jnp.asarray(p._grad).dtype), []).append(p)
        for _, group in sorted(by_dtype.items()):
            flats = [jnp.ravel(jnp.asarray(p._grad)) for p in group]
            sizes = [f.shape[0] for f in flats]
            summed = self._global_sum(jnp.concatenate(flats))
            off = 0
            for p, n in zip(group, sizes):
                shp = jnp.asarray(p._grad).shape
                p._grad = jnp.reshape(summed[off:off + n], shp)
                off += n
