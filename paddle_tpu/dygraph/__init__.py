"""Dygraph (imperative) mode: eager op execution with a gradient tape.

TPU-native re-design of the reference imperative layer:
  * C++ tracer (/root/reference/paddle/fluid/imperative/tracer.cc:35 Trace,
    layer.cc OpBase/VarBase autograd graph)
  * python front (/root/reference/python/paddle/fluid/dygraph/base.py guard,
    layers.py Layer, nn.py FC/Conv2D/Embedding/..., tracer.py)

Design: ops execute eagerly through the SAME registry the static executor
uses (ops/registry.py) — each call runs the op's JAX compute on concrete
jax.Arrays (async-dispatched, so python stays ahead of the device) and
records (op, inputs, outputs) on a tape. `loss.backward()` walks the tape in
reverse, reusing the registry's derived-vjp grad kernels, so every static op
is automatically available in dygraph with identical semantics. The
reference's autograd DAG of OpBase/VarBase nodes collapses to this flat
tape: eager mode never reenters an op twice, so topological order IS
recording order.
"""
from __future__ import annotations

import contextlib
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import DType, np_dtype
from ..ops.registry import ExecContext, get_op_def

__all__ = [
    "guard",
    "enabled",
    "in_dygraph_mode",
    "to_variable",
    "no_grad",
    "VarBase",
    "Layer",
    "Linear",
    "FC",
    "Conv2D",
    "Conv2DTranspose",
    "Pool2D",
    "Embedding",
    "BatchNorm",
    "LayerNorm",
    "GRUUnit",
    "NCE",
    "PRelu",
    "BilinearTensorProduct",
    "GroupNorm",
    "SpectralNorm",
    "Conv3D",
    "Conv3DTranspose",
    "TreeConv",
]

_state = {"enabled": False, "tape": None, "no_grad": 0, "rng": None}


class _Tape:
    def __init__(self):
        self.entries = []  # (op_type, attrs, in_slots, out_slots)

    def record(self, op_type, attrs, in_slots, out_slots):
        self.entries.append((op_type, attrs, in_slots, out_slots))


@contextlib.contextmanager
def guard(seed: int = 0):
    """reference dygraph/base.py:guard — enable eager mode in the block."""
    old = dict(_state)
    _state.update(enabled=True, tape=_Tape(), no_grad=0,
                  rng=jax.random.PRNGKey(seed))
    try:
        yield
    finally:
        # clear() first: keys created inside the block (e.g. last_params)
        # must not outlive the guard pinning params/grads in device memory
        _state.clear()
        _state.update(old)


def enabled() -> bool:
    return _state["enabled"]


in_dygraph_mode = enabled


@contextlib.contextmanager
def no_grad():
    _state["no_grad"] += 1
    try:
        yield
    finally:
        _state["no_grad"] -= 1


def _next_key():
    _state["rng"], sub = jax.random.split(_state["rng"])
    return sub


class VarBase:
    """Eager tensor: a jax.Array plus autograd state (reference
    imperative/layer.h VarBase: var_ + grads_)."""

    _count = 0

    def __init__(self, value, name=None, stop_gradient=False,
                 persistable=False):
        if isinstance(value, VarBase):
            value = value._value
        self._value = jnp.asarray(value)
        VarBase._count += 1
        self.name = name or f"dyvar_{VarBase._count}"
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self._grad = None

    # -- reference VarBase API ----------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def gradient(self) -> np.ndarray | None:
        return None if self._grad is None else np.asarray(self._grad)

    def clear_gradient(self):
        self._grad = None

    def backward(self):
        backward(self)

    def detach(self) -> "VarBase":
        return VarBase(self._value, stop_gradient=True)

    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return DType.parse(str(self._value.dtype))

    def astype(self, dtype) -> "VarBase":
        return _dy_op("cast", {"X": [self]},
                      attrs={"out_dtype": str(dtype)})["Out"]

    def __repr__(self):
        return f"VarBase(name={self.name}, shape={self.shape})"

    # arithmetic sugar (reference dygraph/math_op_patch.py monkey_patch)
    def __add__(self, o):
        return _dy_op("elementwise_add", {"X": [self], "Y": [_lift(o)]})["Out"]

    def __sub__(self, o):
        return _dy_op("elementwise_sub", {"X": [self], "Y": [_lift(o)]})["Out"]

    def __mul__(self, o):
        return _dy_op("elementwise_mul", {"X": [self], "Y": [_lift(o)]})["Out"]

    def __truediv__(self, o):
        return _dy_op("elementwise_div", {"X": [self], "Y": [_lift(o)]})["Out"]

    def __pow__(self, o):
        return _dy_op("elementwise_pow", {"X": [self], "Y": [_lift(o)]})["Out"]

    def __neg__(self):
        return _dy_op("scale", {"X": [self]}, attrs={"scale": -1.0})["Out"]

    def __matmul__(self, o):
        return _dy_op("matmul", {"X": [self], "Y": [_lift(o)]})["Out"]

    def _lift_full(self, o) -> "VarBase":
        """Scalar operands on the LEFT must broadcast UP to self's shape
        (the reference elementwise rule requires rank(Y) <= rank(X))."""
        if isinstance(o, VarBase):
            return o
        arr = jnp.broadcast_to(jnp.asarray(o, self._value.dtype),
                               self._value.shape)
        return VarBase(arr, stop_gradient=True)

    def __rsub__(self, o):
        return _dy_op("elementwise_sub",
                      {"X": [self._lift_full(o)], "Y": [self]})["Out"]

    def __rtruediv__(self, o):
        return _dy_op("elementwise_div",
                      {"X": [self._lift_full(o)], "Y": [self]})["Out"]

    def _cmp(self, o, op_type):
        return _dy_op(op_type, {"X": [self], "Y": [_lift(o)]})["Out"]

    def __lt__(self, o):
        return self._cmp(o, "less_than")

    def __le__(self, o):
        return self._cmp(o, "less_equal")

    def __gt__(self, o):
        return self._cmp(o, "greater_than")

    def __ge__(self, o):
        return self._cmp(o, "greater_equal")

    __radd__ = __add__
    __rmul__ = __mul__


def _lift(v) -> VarBase:
    return v if isinstance(v, VarBase) else VarBase(v, stop_gradient=True)


def to_variable(value, name=None, zero_copy=None) -> VarBase:
    """reference dygraph/base.py:to_variable."""
    if isinstance(value, VarBase):
        return value
    return VarBase(np.asarray(value), name=name)


class _EagerOp:
    """Shim giving ExecContext the op-shaped view of an eager call."""

    def __init__(self, type, inputs, outputs, attrs):
        self.type = type
        self.inputs = inputs    # slot -> [names]
        self.outputs = outputs
        self.attrs = attrs


def _dy_op(op_type: str, inputs: dict, attrs: dict | None = None,
           n_outs: dict | None = None) -> dict:
    """Execute one registry op eagerly; returns {slot: VarBase|[VarBase]}.

    inputs: {slot: [VarBase]}. The tape records enough to replay the vjp.
    """
    if not enabled():
        raise RuntimeError("dygraph op outside dygraph.guard()")
    attrs = dict(attrs or {})
    opdef = get_op_def(op_type)
    env: dict[str, Any] = {}
    in_slots = {}
    name_to_var = {}
    op_in = {}
    for slot, vars_ in inputs.items():
        names = []
        for v in vars_:
            if v is None:
                continue
            names.append(v.name)
            env[v.name] = v._value
            name_to_var[v.name] = v
        op_in[slot] = names
        in_slots[slot] = [v for v in vars_ if v is not None]

    rng = _next_key() if opdef.needs_rng else None
    shim = _EagerOp(op_type, op_in, {}, attrs)
    ctx = ExecContext(shim, env, rng=rng)
    outs = opdef.compute(ctx)

    result, out_slots, op_out = {}, {}, {}
    for slot, val in outs.items():
        vals = val if isinstance(val, (list, tuple)) else [val]
        vbs = []
        for v in vals:
            if v is None:
                vbs.append(None)
                continue
            vb = VarBase(v)
            vb.stop_gradient = (
                _state["no_grad"] > 0
                or all(x.stop_gradient for vs in in_slots.values()
                       for x in vs)
                or opdef.no_grad
            )
            vbs.append(vb)
        op_out[slot] = [vb.name if vb is not None else "" for vb in vbs]
        out_slots[slot] = vbs
        result[slot] = vbs if isinstance(val, (list, tuple)) else vbs[0]

    record = not all(
        vb is None or vb.stop_gradient
        for vs in out_slots.values() for vb in vs)
    if record and _state["tape"] is not None:
        _state["tape"].record(op_type, attrs, in_slots, out_slots)
    return result


def backward(loss: VarBase):
    """Reverse-walk the tape accumulating grads into VarBase._grad
    (reference imperative/engine.cc BasicEngine + layer.cc ApplyGrad)."""
    tape: _Tape = _state["tape"]
    grads: dict[str, Any] = {
        loss.name: jnp.ones_like(loss._value)}

    for op_type, attrs, in_slots, out_slots in reversed(tape.entries):
        out_has_grad = any(
            vb is not None and vb.name in grads
            for vs in out_slots.values() for vb in vs)
        if not out_has_grad:
            continue
        opdef = get_op_def(op_type)
        if opdef.no_grad:
            continue
        gdef = get_op_def(op_type + "_grad")
        derived = getattr(gdef, "derived_vjp", False)
        # Grad-op view: forward inputs + Out@GRAD cotangents always; forward
        # OUTPUT slots only for custom grad kernels (they read e.g.
        # "Softmax"/"Mask" — a derived-vjp kernel must not see output slots
        # as replay primals)
        env: dict[str, Any] = {}
        op_in, op_out = {}, {}
        for slot, vs in in_slots.items():
            op_in[slot] = [v.name for v in vs]
            for v in vs:
                env[v.name] = v._value
        for slot, vs in out_slots.items():
            gnames = []
            for vb in vs:
                if vb is None:
                    gnames.append("")
                    continue
                gname = vb.name + "@GRAD"
                gnames.append(gname)
                if vb.name in grads:
                    env[gname] = grads[vb.name]
                env[vb.name] = vb._value
            if not derived:
                op_in[slot] = [vb.name if vb is not None else ""
                               for vb in vs]
            op_in[slot + "@GRAD"] = gnames
        for slot, vs in in_slots.items():
            op_out[slot + "@GRAD"] = [v.name + "@GRAD" for v in vs]

        gop = _EagerOp(op_type + "_grad", op_in, op_out, attrs)
        ctx = ExecContext(gop, env, rng=None)
        gouts = gdef.compute(ctx)

        for slot, val in (gouts or {}).items():
            if not slot.endswith("@GRAD"):
                continue
            fwd_slot = slot[: -len("@GRAD")]
            vs = in_slots.get(fwd_slot, [])
            vals = val if isinstance(val, (list, tuple)) else [val]
            for v, g in zip(vs, vals):
                if g is None or v.stop_gradient:
                    continue
                if v.name in grads:
                    grads[v.name] = grads[v.name] + g
                else:
                    grads[v.name] = g
                v._grad = grads[v.name]
    # remember which persistable leaves got grads this sweep (the default
    # parameter set for optimizer._dygraph_minimize)
    seen, params = set(), []
    for _, _, in_slots, _ in tape.entries:
        for vs in in_slots.values():
            for v in vs:
                if v.persistable and v._grad is not None and id(v) not in seen:
                    seen.add(id(v))
                    params.append(v)
    _state["last_params"] = params
    # the graph is consumed (reference BasicEngine frees op nodes after the
    # sweep): drop the tape so iteration N+1 doesn't re-walk N iterations of
    # entries or pin every past activation in device memory
    tape.entries.clear()


# ---------------------------------------------------------------------------
# Layer system (reference dygraph/layers.py Layer + nn.py built-ins)
# ---------------------------------------------------------------------------


class Layer:
    """reference dygraph/layers.py:Layer — parameter/sublayer registry with
    forward() dispatch via __call__."""

    def __init__(self, name_scope: str | None = None, dtype="float32"):
        self._parameters: dict[str, VarBase] = {}
        self._sub_layers: dict[str, Layer] = {}
        self._dtype = dtype
        self._full_name = name_scope or type(self).__name__.lower()
        self.training = True

    def full_name(self):
        return self._full_name

    def create_parameter(self, shape, dtype="float32", is_bias=False,
                         default_initializer=None):
        from ..initializer import Constant, Xavier, _fan_in_out

        init = default_initializer or (Constant(0.0) if is_bias else Xavier())

        class _ShapeOnly:  # _fan_in_out reads .shape (static-var fan rule)
            pass

        _ShapeOnly.shape = tuple(shape)
        fan_in, fan_out = _fan_in_out(_ShapeOnly)
        key = _next_key()
        val = init._dygraph_sample(key, shape, np_dtype(dtype),
                                   fan_in, fan_out)
        p = VarBase(val, persistable=True)
        return p

    def add_parameter(self, name, param: VarBase) -> VarBase:
        self._parameters[name] = param
        return param

    def add_sublayer(self, name, layer: "Layer") -> "Layer":
        self._sub_layers[name] = layer
        return layer

    def parameters(self, include_sublayers=True) -> list[VarBase]:
        ps = list(self._parameters.values())
        if include_sublayers:
            for sub in self._sub_layers.values():
                ps.extend(sub.parameters())
        return ps

    def sublayers(self, include_sublayers=True) -> list["Layer"]:
        subs = list(self._sub_layers.values())
        if include_sublayers:
            for s in self._sub_layers.values():
                subs.extend(s.sublayers())
        return subs

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def state_dict(self) -> dict:
        out = dict(self._parameters)
        for name, sub in self._sub_layers.items():
            for k, v in sub.state_dict().items():
                out[f"{name}.{k}"] = v
        return out

    def set_dict(self, state: dict):
        for name, p in self._parameters.items():
            if name in state:
                v = state[name]
                p._value = jnp.asarray(
                    v.numpy() if isinstance(v, VarBase) else v)
        for name, sub in self._sub_layers.items():
            prefix = name + "."
            sub.set_dict({k[len(prefix):]: v for k, v in state.items()
                          if k.startswith(prefix)})

    def __setattr__(self, name, value):
        if isinstance(value, VarBase) and value.persistable:
            self.__dict__.setdefault("_parameters", {})[name] = value
        elif isinstance(value, Layer):
            self.__dict__.setdefault("_sub_layers", {})[name] = value
        object.__setattr__(self, name, value)

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def train(self):
        self.training = True
        for s_ in self._sub_layers.values():
            s_.train()

    def eval(self):
        self.training = False
        for s_ in self._sub_layers.values():
            s_.eval()


class Linear(Layer):
    """reference dygraph FC/Linear (dygraph/nn.py:FC)."""

    def __init__(self, input_dim, output_dim, act=None, dtype="float32",
                 bias_attr=None):
        super().__init__()
        self.weight = self.add_parameter(
            "weight", self.create_parameter([input_dim, output_dim], dtype))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.add_parameter(
                "bias",
                self.create_parameter([output_dim], dtype, is_bias=True))
        self._act = act

    def forward(self, x: VarBase) -> VarBase:
        out = _dy_op("mul", {"X": [x], "Y": [self.weight]},
                     attrs={"x_num_col_dims": len(x.shape) - 1})["Out"]
        if self.bias is not None:
            out = _dy_op("elementwise_add",
                         {"X": [out], "Y": [self.bias]},
                         attrs={"axis": -1})["Out"]
        if self._act:
            out = _dy_op(self._act, {"X": [out]})["Out"]
        return out


class FC(Layer):
    """reference dygraph/nn.py:773 FC — the pre-Linear eager dense layer:
    the weight is created LAZILY at the first forward from the input's
    trailing dims (`[prod(shape[num_flatten_dims:]), size]`), with
    `num_flatten_dims` controlling the matmul's row/col split exactly like
    the static `layers.fc`."""

    def __init__(self, size, num_flatten_dims=1, act=None, dtype="float32",
                 bias_attr=None):
        super().__init__()
        self._size = int(size)
        self._num_flatten_dims = int(num_flatten_dims)
        self._dtype = dtype
        self._act = act
        self._with_bias = bias_attr is not False
        self.weight = None
        self.bias = None

    def forward(self, x: VarBase) -> VarBase:
        nfd = self._num_flatten_dims
        if nfd < 0:
            nfd += len(x.shape)
        if self.weight is None:
            in_dim = 1
            for d in x.shape[nfd:]:
                in_dim *= int(d)
            self.weight = self.add_parameter(
                "weight",
                self.create_parameter([in_dim, self._size], self._dtype))
            if self._with_bias:
                self.bias = self.add_parameter(
                    "bias", self.create_parameter([self._size], self._dtype,
                                                  is_bias=True))
        out = _dy_op("mul", {"X": [x], "Y": [self.weight]},
                     attrs={"x_num_col_dims": nfd})["Out"]
        if self.bias is not None:
            out = _dy_op("elementwise_add", {"X": [out], "Y": [self.bias]},
                         attrs={"axis": -1})["Out"]
        if self._act:
            out = _dy_op(self._act, {"X": [out]})["Out"]
        return out


class Conv2DTranspose(Layer):
    """reference dygraph/nn.py:1964 Conv2DTranspose (NCHW; filter layout
    [C_in, C_out, kh, kw] like the static conv2d_transpose layer)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, act=None, dtype="float32"):
        super().__init__()
        k = (filter_size if isinstance(filter_size, (tuple, list))
             else (filter_size, filter_size))
        self.weight = self.add_parameter(
            "weight", self.create_parameter(
                [num_channels, num_filters, k[0], k[1]], dtype))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([num_filters], dtype, is_bias=True))
        _2 = lambda v: list(v) if isinstance(v, (tuple, list)) else [v] * 2
        self._attrs = {"strides": _2(stride), "paddings": _2(padding),
                       "dilations": _2(dilation)}
        self._act = act

    def forward(self, x: VarBase) -> VarBase:
        out = _dy_op("conv2d_transpose",
                     {"Input": [x], "Filter": [self.weight]},
                     attrs=dict(self._attrs))["Output"]
        bias = _dy_op("reshape2", {"X": [self.bias]},
                      attrs={"shape": [1, -1, 1, 1]})["Out"]
        out = _dy_op("elementwise_add", {"X": [out], "Y": [bias]})["Out"]
        if self._act:
            out = _dy_op(self._act, {"X": [out]})["Out"]
        return out


class Conv2D(Layer):
    """reference dygraph/nn.py:Conv2D (NCHW)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, groups=1, act=None, dtype="float32"):
        super().__init__()
        k = filter_size if isinstance(filter_size, (tuple, list)) else (
            filter_size, filter_size)
        self.weight = self.add_parameter(
            "weight", self.create_parameter(
                [num_filters, num_channels // groups, k[0], k[1]], dtype))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([num_filters], dtype, is_bias=True))
        self._attrs = {"strides": [stride, stride],
                       "paddings": [padding, padding],
                       "groups": groups}
        self._act = act

    def forward(self, x: VarBase) -> VarBase:
        out = _dy_op("conv2d", {"Input": [x], "Filter": [self.weight]},
                     attrs=dict(self._attrs))["Output"]
        bias = _dy_op("reshape2", {"X": [self.bias]},
                      attrs={"shape": [1, -1, 1, 1]})["Out"]
        out = _dy_op("elementwise_add", {"X": [out], "Y": [bias]})["Out"]
        if self._act:
            out = _dy_op(self._act, {"X": [out]})["Out"]
        return out


class Pool2D(Layer):
    """reference dygraph/nn.py:Pool2D."""

    def __init__(self, pool_size=2, pool_type="max", pool_stride=2,
                 pool_padding=0, global_pooling=False):
        super().__init__()
        self._attrs = {
            "ksize": [pool_size, pool_size],
            "pooling_type": pool_type,
            "strides": [pool_stride, pool_stride],
            "paddings": [pool_padding, pool_padding],
            "global_pooling": global_pooling,
        }

    def forward(self, x: VarBase) -> VarBase:
        return _dy_op("pool2d", {"X": [x]}, attrs=dict(self._attrs))["Out"]


class Embedding(Layer):
    """reference dygraph/nn.py:Embedding."""

    def __init__(self, size, is_sparse=False, dtype="float32"):
        super().__init__()
        self.weight = self.add_parameter(
            "weight", self.create_parameter(list(size), dtype))

    def forward(self, ids: VarBase) -> VarBase:
        return _dy_op("lookup_table",
                      {"W": [self.weight], "Ids": [ids]})["Out"]


class BatchNorm(Layer):
    """reference dygraph/nn.py:BatchNorm (training statistics only; running
    stats update eagerly like the reference's momentum accumulation)."""

    def __init__(self, num_channels, momentum=0.9, epsilon=1e-5,
                 dtype="float32"):
        super().__init__()
        self.weight = self.add_parameter(
            "weight", self.create_parameter(
                [num_channels], dtype,
                default_initializer=_const_init(1.0)))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([num_channels], dtype,
                                          is_bias=True))
        # running stats: NOT persistable (persistable marks trainable
        # parameters for Layer.__setattr__ auto-registration)
        self._mean = VarBase(np.zeros(num_channels, np_dtype(dtype)),
                             stop_gradient=True)
        self._var = VarBase(np.ones(num_channels, np_dtype(dtype)),
                            stop_gradient=True)
        self._attrs = {"momentum": momentum, "epsilon": epsilon}

    def forward(self, x: VarBase) -> VarBase:
        attrs = dict(self._attrs)
        # eval(): normalize with running stats, do not update them
        # (reference batch_norm is_test semantics)
        attrs["is_test"] = not self.training
        outs = _dy_op(
            "batch_norm",
            {"X": [x], "Scale": [self.weight], "Bias": [self.bias],
             "Mean": [self._mean], "Variance": [self._var]},
            attrs=attrs)
        y = outs.get("Y")
        if self.training:
            if outs.get("MeanOut") is not None:
                self._mean._value = outs["MeanOut"]._value  # in place:
            if outs.get("VarianceOut") is not None:        # keep identity
                self._var._value = outs["VarianceOut"]._value
        return y


class LayerNorm(Layer):
    """reference dygraph LayerNorm."""

    def __init__(self, normalized_shape, epsilon=1e-5, dtype="float32"):
        super().__init__()
        n = (normalized_shape if isinstance(normalized_shape, int)
             else int(np.prod(normalized_shape)))
        self.weight = self.add_parameter(
            "weight", self.create_parameter(
                [n], dtype, default_initializer=_const_init(1.0)))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([n], dtype, is_bias=True))
        self._eps = epsilon

    def forward(self, x: VarBase) -> VarBase:
        return _dy_op(
            "layer_norm",
            {"X": [x], "Scale": [self.weight], "Bias": [self.bias]},
            attrs={"epsilon": self._eps,
                   "begin_norm_axis": len(x.shape) - 1})["Y"]


class GRUUnit(Layer):
    """reference dygraph/nn.py:1411 GRUUnit — one GRU step over the
    pre-projected input. forward(input [B,3H], hidden [B,H]) returns
    (updated_hidden, reset_hidden_pre, gate) like the reference (:1561)."""

    def __init__(self, size, activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__()
        H = size // 3
        self.weight = self.add_parameter(
            "weight", self.create_parameter([H, 3 * H], dtype))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([1, 3 * H], dtype, is_bias=True))
        self._attrs = {"activation": activation,
                       "gate_activation": gate_activation,
                       "origin_mode": origin_mode}

    def forward(self, input: VarBase, hidden: VarBase):
        outs = _dy_op("gru_unit",
                      {"Input": [input], "HiddenPrev": [hidden],
                       "Weight": [self.weight], "Bias": [self.bias]},
                      attrs=dict(self._attrs))
        return outs["Hidden"], outs["ResetHiddenPrev"], outs["Gate"]


class NCE(Layer):
    """reference dygraph/nn.py NCE — noise-contrastive estimation head.
    forward(input [B,D], label [B,1]) -> Cost [B,1]."""

    def __init__(self, num_total_classes, dim, num_neg_samples=5,
                 sampler="uniform", dtype="float32"):
        super().__init__()
        self.weight = self.add_parameter(
            "weight", self.create_parameter([num_total_classes, dim], dtype))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([num_total_classes], dtype,
                                          is_bias=True))
        self._attrs = {
            "num_total_classes": num_total_classes,
            "num_neg_samples": num_neg_samples,
            "sampler": {"uniform": 0, "log_uniform": 1}.get(sampler, 0),
        }

    def forward(self, input: VarBase, label: VarBase) -> VarBase:
        return _dy_op("nce",
                      {"Input": [input], "Label": [label],
                       "Weight": [self.weight], "Bias": [self.bias]},
                      attrs=dict(self._attrs))["Cost"]


class PRelu(Layer):
    """reference dygraph/nn.py PRelu. mode: all | channel | element;
    channel_or_shape: channel count for 'channel', full feature shape for
    'element' (ignored for 'all').

    Deliberate layout divergence from the reference in 'channel' mode: the
    alpha parameter is stored as [C] here, where the reference stores
    [1, C, 1, 1]. The prelu op broadcasts alpha over the channel axis
    either way, so numerics are identical, but the saved shapes differ —
    reference-trained PRelu checkpoints cannot be loaded into this layer
    directly (reshape the reference's [1, C, 1, 1] alpha to [C] — or [C]
    to [1, C, 1, 1] going the other way — when converting). Matches the
    layers.nn lstm flat-weight note."""

    def __init__(self, mode="all", channel_or_shape=None, dtype="float32"):
        super().__init__()
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [int(channel_or_shape)]
        elif mode == "element":
            shape = list(channel_or_shape)
        else:
            raise ValueError(f"unknown PRelu mode '{mode}'")
        self.weight = self.add_parameter(
            "weight", self.create_parameter(
                shape, dtype, default_initializer=_const_init(0.25)))
        self._mode = mode

    def forward(self, x: VarBase) -> VarBase:
        return _dy_op("prelu", {"X": [x], "Alpha": [self.weight]},
                      attrs={"mode": self._mode})["Out"]


class BilinearTensorProduct(Layer):
    """reference dygraph/nn.py BilinearTensorProduct:
    out[b,k] = x[b] W[k] y[b] + bias[k]."""

    def __init__(self, input1_dim, input2_dim, output_dim, dtype="float32"):
        super().__init__()
        self.weight = self.add_parameter(
            "weight", self.create_parameter(
                [output_dim, input1_dim, input2_dim], dtype))
        # bias [1, size] for reference checkpoint-shape parity
        self.bias = self.add_parameter(
            "bias", self.create_parameter([1, output_dim], dtype,
                                          is_bias=True))

    def forward(self, x: VarBase, y: VarBase) -> VarBase:
        return _dy_op("bilinear_tensor_product",
                      {"X": [x], "Y": [y], "Weight": [self.weight],
                       "Bias": [self.bias]})["Out"]


class GroupNorm(Layer):
    """reference dygraph/nn.py GroupNorm (NCHW)."""

    def __init__(self, channels, groups, epsilon=1e-5, dtype="float32"):
        super().__init__()
        self.weight = self.add_parameter(
            "weight", self.create_parameter(
                [channels], dtype, default_initializer=_const_init(1.0)))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([channels], dtype, is_bias=True))
        self._attrs = {"groups": groups, "epsilon": epsilon}

    def forward(self, x: VarBase) -> VarBase:
        return _dy_op("group_norm",
                      {"X": [x], "Scale": [self.weight],
                       "Bias": [self.bias]},
                      attrs=dict(self._attrs))["Y"]


class SpectralNorm(Layer):
    """reference dygraph/nn.py:2548 SpectralNorm: forward(weight) returns
    weight / sigma_max via power iteration; U/V persist across calls as
    non-trainable state (updated in place like BatchNorm running stats)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        h = int(weight_shape[dim])
        w = int(np.prod(weight_shape)) // h
        rng = np.random.default_rng(0)
        self._u = VarBase(rng.standard_normal(h).astype(np_dtype(dtype)),
                          stop_gradient=True)
        self._v = VarBase(rng.standard_normal(w).astype(np_dtype(dtype)),
                          stop_gradient=True)
        self._attrs = {"dim": dim, "power_iters": power_iters, "eps": eps}

    def forward(self, weight: VarBase) -> VarBase:
        outs = _dy_op("spectral_norm",
                      {"Weight": [weight], "U": [self._u], "V": [self._v]},
                      attrs=dict(self._attrs))
        if outs.get("UOut") is not None:
            self._u._value = outs["UOut"]._value
        if outs.get("VOut") is not None:
            self._v._value = outs["VOut"]._value
        return outs["Out"]


class Conv3D(Layer):
    """reference dygraph/nn.py Conv3D (NCDHW)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, groups=1, act=None, dtype="float32"):
        super().__init__()
        k = (filter_size if isinstance(filter_size, (tuple, list))
             else (filter_size,) * 3)
        self.weight = self.add_parameter(
            "weight", self.create_parameter(
                [num_filters, num_channels // groups, *k], dtype))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([num_filters], dtype, is_bias=True))
        _3 = lambda v: list(v) if isinstance(v, (tuple, list)) else [v] * 3
        self._attrs = {"strides": _3(stride), "paddings": _3(padding),
                       "groups": groups}
        self._act = act

    def forward(self, x: VarBase) -> VarBase:
        out = _dy_op("conv3d", {"Input": [x], "Filter": [self.weight]},
                     attrs=dict(self._attrs))["Output"]
        bias = _dy_op("reshape2", {"X": [self.bias]},
                      attrs={"shape": [1, -1, 1, 1, 1]})["Out"]
        out = _dy_op("elementwise_add", {"X": [out], "Y": [bias]})["Out"]
        if self._act:
            out = _dy_op(self._act, {"X": [out]})["Out"]
        return out


class TreeConv(Layer):
    """reference dygraph/nn.py TreeConv (TBCNN over continuous binary
    trees). forward(nodes_vector [B,N,F], edge_set [B,E,2]) -> [B,N,O,M]
    via the tree_conv registry op; max_depth bounds the patch walk."""

    def __init__(self, feature_size, output_size, num_filters=1,
                 max_depth=2, act="tanh", dtype="float32"):
        super().__init__()
        self.weight = self.add_parameter(
            "weight", self.create_parameter(
                [feature_size, 3, output_size, num_filters], dtype))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([num_filters], dtype,
                                          is_bias=True))
        self._attrs = {"max_depth": max_depth}
        self._act = act

    def forward(self, nodes_vector: VarBase, edge_set: VarBase) -> VarBase:
        out = _dy_op("tree_conv",
                     {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                      "Filter": [self.weight]},
                     attrs=dict(self._attrs))["Out"]
        # bias targets the TRAILING (filter) dim: axis=-1 broadcast, no
        # reshape needed (the Conv2D/3D reshape pattern is channel-dim only)
        out = _dy_op("elementwise_add", {"X": [out], "Y": [self.bias]},
                     attrs={"axis": -1})["Out"]
        if self._act:
            out = _dy_op(self._act, {"X": [out]})["Out"]
        return out


class Conv3DTranspose(Layer):
    """reference dygraph/nn.py Conv3DTranspose (NCDHW)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, groups=1, act=None, dtype="float32"):
        super().__init__()
        k = (filter_size if isinstance(filter_size, (tuple, list))
             else (filter_size,) * 3)
        self.weight = self.add_parameter(
            "weight", self.create_parameter(
                [num_channels, num_filters // groups, *k], dtype))
        self.bias = self.add_parameter(
            "bias", self.create_parameter([num_filters], dtype, is_bias=True))
        _3 = lambda v: list(v) if isinstance(v, (tuple, list)) else [v] * 3
        self._attrs = {"strides": _3(stride), "paddings": _3(padding),
                       "groups": groups}
        self._act = act

    def forward(self, x: VarBase) -> VarBase:
        out = _dy_op("conv3d_transpose",
                     {"Input": [x], "Filter": [self.weight]},
                     attrs=dict(self._attrs))["Output"]
        bias = _dy_op("reshape2", {"X": [self.bias]},
                      attrs={"shape": [1, -1, 1, 1, 1]})["Out"]
        out = _dy_op("elementwise_add", {"X": [out], "Y": [bias]})["Out"]
        if self._act:
            out = _dy_op(self._act, {"X": [out]})["Out"]
        return out


def _const_init(v):
    from ..initializer import Constant

    return Constant(v)


# multi-process DP + disk checkpoints live in submodules (import after the
# core so they can use Layer/VarBase/_dy_op)
from .parallel import DataParallel  # noqa: E402,F401
from .checkpoint import save_dygraph, load_dygraph  # noqa: E402,F401

__all__ += ["DataParallel", "save_dygraph", "load_dygraph"]
