"""Dygraph checkpointing: save_dygraph / load_dygraph.

Reference contract (/root/reference/python/paddle/fluid/dygraph/checkpoint.py):
`save_dygraph(state_dict, model_path)` writes `model_path + ".pdparams"`
(or ".pdopt" when the dict carries optimizer state), `load_dygraph(path)`
returns `(param_dict, opt_dict_or_None)` accepting the bare prefix.

Arrays are stored as a dict of numpy arrays (np.savez container renamed to
the reference's extension) — framework-independent on disk, loadable without
a device."""
from __future__ import annotations

import os
import zipfile

import numpy as np

__all__ = ["save_dygraph", "load_dygraph"]

_OPT_KEYS = ("LR_Scheduler", "global_step")


def _is_opt_state(state_dict: dict) -> bool:
    # reference save_dygraph picks ".pdopt" when the dict came from
    # optimizer.state_dict() — detectable by its bookkeeping keys or by the
    # exact accumulator-name suffix the optimizers generate
    # ("<param>_moment1_0", "<param>_velocity_0", ...). A suffix match, not
    # a substring one: a model parameter named "momentum_encoder.weight"
    # must still save as .pdparams.
    import re

    acc = re.compile(
        r"_(moment\d*|velocity|beta\d_pow_acc|pow_acc|mean_square|mean_grad|"
        r"accumulator|squared|linear)_\d+$")
    return any(k in state_dict for k in _OPT_KEYS) or any(
        acc.search(str(k)) for k in state_dict)


def save_dygraph(state_dict: dict, model_path: str):
    """Persist a Layer.state_dict() (-> .pdparams) or optimizer state
    (-> .pdopt). `model_path` is the extensionless prefix."""
    if not model_path:
        raise ValueError("model_path must be a non-empty path prefix")
    base = os.path.basename(model_path)
    if not base or base.startswith("."):
        raise ValueError(
            f"model_path '{model_path}' must end with a file prefix, not a "
            "directory or hidden name")
    arrays = {}
    for k, v in state_dict.items():
        arrays[k] = np.asarray(v.numpy() if hasattr(v, "numpy") else v)
    suffix = ".pdopt" if _is_opt_state(state_dict) else ".pdparams"
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    np.savez(model_path + suffix + ".npz", **arrays)
    os.replace(model_path + suffix + ".npz", model_path + suffix)


def load_dygraph(model_path: str):
    """Return (param_dict, opt_dict) for the prefix; either may be None if
    the corresponding file is absent (reference checkpoint.py load_dygraph)."""
    for ext in (".pdparams", ".pdopt"):
        if model_path.endswith(ext):
            model_path = model_path[: -len(ext)]
            break
    params = opt = None
    ppath, opath = model_path + ".pdparams", model_path + ".pdopt"
    if os.path.exists(ppath):
        params = _load_npz(ppath)
    if os.path.exists(opath):
        opt = _load_npz(opath)
    if params is None and opt is None:
        raise IOError(
            f"no checkpoint found at '{model_path}' (.pdparams/.pdopt)")
    return params, opt


def _load_npz(path: str) -> dict:
    """Read one checkpoint container, translating every failure mode into an
    IOError that names the path — a resume script's `except IOError` must
    catch a truncated file the same way it catches a missing one, not chase
    whatever zipfile/numpy internals happen to raise."""
    if not os.path.exists(path):
        raise IOError(f"checkpoint file '{path}' does not exist")
    if not zipfile.is_zipfile(path):
        raise IOError(
            f"checkpoint file '{path}' is corrupt or not a dygraph "
            f"checkpoint (not a valid npz container)")
    try:
        with np.load(path, allow_pickle=False) as z:
            return {k: z[k] for k in z.files}
    except IOError:
        raise
    except Exception as e:
        raise IOError(
            f"checkpoint file '{path}' is corrupt: failed to read arrays "
            f"({type(e).__name__}: {e})") from e
