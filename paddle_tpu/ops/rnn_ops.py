"""Recurrent cell + sequence-RNN ops.

TPU-native re-design of the reference recurrent operators:
  * gru_unit_op.h (one step; exact gate math reproduced below)
  * gru_op.cc / dynamic_gru  -> `gru`: whole-sequence lax.scan (the
    reference's LoD batch reordering becomes a scan over the padded time
    axis; XLA keeps weights resident across steps)
  * lstm_op.cc / dynamic_lstm -> `lstm`: same scan treatment

Scans carry [B, H] state; matmuls inside the body hit the MXU per step. The
reference's sequence->batch reorder machinery (math/sequence2batch.h) is
unnecessary: padding already gives a rectangular [B, T, ...] layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import ExecContext, register_op

_ACTS = {
    "identity": lambda x: x,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
}


def _act(name):
    try:
        return _ACTS[str(name)]
    except KeyError:
        raise ValueError(f"unsupported activation '{name}'")


def _gru_step(x_t, h_prev, weight, bias, act, gate_act, origin_mode):
    """Exact gru_unit_op.h math: gates = x + b + h_prev @ W[:, :2H];
    c = act(x_c + (r*h_prev) @ W[:, 2H:]); h = u*(c-h_prev)+h_prev."""
    H = h_prev.shape[-1]
    g = x_t
    if bias is not None:
        g = g + bias.reshape(1, 3 * H)
    g = g.at[:, : 2 * H].add(h_prev @ weight[:, : 2 * H])
    u = gate_act(g[:, :H])
    r = gate_act(g[:, H: 2 * H])
    r_h = r * h_prev
    c_pre = g[:, 2 * H:] + r_h @ weight[:, 2 * H:]
    c = act(c_pre)
    if origin_mode:
        h = c + u * (h_prev - c)
    else:
        h = u * (c - h_prev) + h_prev
    gates = jnp.concatenate([u, r, c], axis=-1)
    return h, r_h, gates


@register_op("gru_unit")
def gru_unit(ctx: ExecContext):
    x = ctx.input("Input")          # [B, 3H] = x @ W_x (+ x bias)
    h_prev = ctx.input("HiddenPrev")
    w = ctx.input("Weight")          # [H, 3H]
    b = ctx.input("Bias")
    h, r_h, gates = _gru_step(
        x, h_prev, w, b,
        _act(ctx.attr("activation", "tanh")),
        _act(ctx.attr("gate_activation", "sigmoid")),
        bool(ctx.attr("origin_mode", False)))
    return {"Hidden": h, "ResetHiddenPrev": r_h, "Gate": gates}


@register_op("gru")
def gru(ctx: ExecContext):
    """Whole-sequence GRU (reference gru_op.cc / layers.dynamic_gru).
    Input [B, T, 3H]; optional H0 [B, H]; Weight [H, 3H]; Bias [1, 3H].
    Output Hidden [B, T, H]."""
    x = ctx.input("Input")
    w = ctx.input("Weight")
    b = ctx.input("Bias")
    H = w.shape[0]
    B = x.shape[0]
    h0 = ctx.input("H0")
    if h0 is None:
        h0 = jnp.zeros((B, H), x.dtype)
    act = _act(ctx.attr("activation", "tanh"))
    gate_act = _act(ctx.attr("gate_activation", "sigmoid"))
    origin = bool(ctx.attr("origin_mode", False))
    reverse = bool(ctx.attr("is_reverse", False))

    def step(h, x_t):
        h2, _, _ = _gru_step(x_t, h, w, b, act, gate_act, origin)
        return h2, h2

    xs = jnp.swapaxes(x, 0, 1)  # [T, B, 3H]
    _, hs = jax.lax.scan(step, h0, xs, reverse=reverse)
    return {"Hidden": jnp.swapaxes(hs, 0, 1)}


@register_op("lstm_unit")
def lstm_unit(ctx: ExecContext):
    """One LSTM step (reference lstm_unit_op.h:63-71): X [B, 4H] pre-projected
    gates in the reference's (i, f, o, g) layout, C_prev [B, H]."""
    x = ctx.input("X")
    c_prev = ctx.input("C_prev")
    H = c_prev.shape[-1]
    forget_bias = float(ctx.attr("forget_bias", 0.0))
    i = jax.nn.sigmoid(x[:, :H])
    f = jax.nn.sigmoid(x[:, H: 2 * H] + forget_bias)
    o = jax.nn.sigmoid(x[:, 2 * H: 3 * H])
    g = jnp.tanh(x[:, 3 * H:])
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return {"C": c, "H": h}


@register_op("lstm")
def lstm(ctx: ExecContext):
    """Whole-sequence LSTM (reference lstm_op.cc / layers.dynamic_lstm).
    Input [B, T, 4H] pre-projected; Weight [H, 4H] recurrent weights; Bias
    [1, 4H]. Gate order (c_hat, i, f, o) follows the reference's
    Weight = {W_ch, W_ih, W_fh, W_oh} layout (lstm_op.cc:125)."""
    x = ctx.input("Input")
    w = ctx.input("Weight")
    b = ctx.input("Bias")
    H = w.shape[0]
    B = x.shape[0]
    h0 = ctx.input("H0")
    c0 = ctx.input("C0")
    if h0 is None:
        h0 = jnp.zeros((B, H), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, H), x.dtype)
    cand_act = _act(ctx.attr("candidate_activation", "tanh"))
    gate_act = _act(ctx.attr("gate_activation", "sigmoid"))
    cell_act = _act(ctx.attr("cell_activation", "tanh"))
    reverse = bool(ctx.attr("is_reverse", False))

    def step(carry, x_t):
        h, c = carry
        g = x_t + h @ w
        if b is not None:
            g = g + b.reshape(1, 4 * H)
        c_hat = cand_act(g[:, :H])
        i = gate_act(g[:, H: 2 * H])
        f = gate_act(g[:, 2 * H: 3 * H])
        o = gate_act(g[:, 3 * H:])
        c2 = f * c + i * c_hat
        h2 = o * cell_act(c2)
        return (h2, c2), (h2, c2)

    xs = jnp.swapaxes(x, 0, 1)
    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), xs, reverse=reverse)
    return {"Hidden": jnp.swapaxes(hs, 0, 1),
            "Cell": jnp.swapaxes(cs, 0, 1)}


@register_op("lstmp")
def lstmp(ctx: ExecContext):
    """Projection LSTM (reference lstmp_op.cc / layers.dynamic_lstmp).
    Input [B, T, 4H] pre-projected; Weight [P, 4H] recurrent over the
    PROJECTION r; ProjWeight [H, P]. r_t = proj_act(h_t @ ProjWeight).
    Gate order (c_hat, i, f, o) as lstm above. Returns Projection [B,T,P]
    and Cell [B,T,H]."""
    x = ctx.input("Input")
    w = ctx.input("Weight")
    pw = ctx.input("ProjWeight")
    b = ctx.input("Bias")
    H, P = pw.shape
    B = x.shape[0]
    cand_act = _act(ctx.attr("candidate_activation", "tanh"))
    gate_act = _act(ctx.attr("gate_activation", "sigmoid"))
    cell_act = _act(ctx.attr("cell_activation", "tanh"))
    proj_act_name = ctx.attr("proj_activation", "identity")
    proj_act = (lambda v: v) if proj_act_name == "identity" \
        else _act(proj_act_name)
    reverse = bool(ctx.attr("is_reverse", False))
    r0 = jnp.zeros((B, P), x.dtype)
    c0 = jnp.zeros((B, H), x.dtype)

    def step(carry, x_t):
        r, c = carry
        g = x_t + r @ w
        if b is not None:
            g = g + b.reshape(1, -1)[:, :4 * H]
        c_hat = cand_act(g[:, :H])
        i = gate_act(g[:, H: 2 * H])
        f = gate_act(g[:, 2 * H: 3 * H])
        o = gate_act(g[:, 3 * H:])
        c2 = f * c + i * c_hat
        h2 = o * cell_act(c2)
        r2 = proj_act(h2 @ pw)
        return (r2, c2), (r2, c2)

    xs = jnp.swapaxes(x, 0, 1)
    _, (rs, cs) = jax.lax.scan(step, (r0, c0), xs, reverse=reverse)
    return {"Projection": jnp.swapaxes(rs, 0, 1),
            "Cell": jnp.swapaxes(cs, 0, 1)}


@register_op("cudnn_lstm", needs_rng=True)
def cudnn_lstm(ctx: ExecContext):
    """Stacked (optionally bidirectional) LSTM (reference cudnn_lstm_op.cc /
    layers.lstm). Input [B, T, D]; the flat W packs per layer+direction:
    Wx [in, 4H], Wh [H, 4H], bias [4H] (gate order i, f, c, o — the cudnn
    convention, which differs from lstm_op's). InitH/InitC
    [L*dirs, B, H]. Inter-layer dropout (cudnn semantics: between stacked
    layers, never after the last) applies when dropout_prob > 0 and not
    is_test. Returns Out [B, T, H*dirs], LastH, LastC."""
    x = ctx.input("Input")
    flat = ctx.input("W").reshape(-1)
    init_h = ctx.input("InitH")
    init_c = ctx.input("InitC")
    L = int(ctx.attr("num_layers", 1))
    H = int(ctx.attr("hidden_size"))
    bidi = bool(ctx.attr("is_bidirec", False))
    dirs = 2 if bidi else 1
    B, T, D = x.shape

    def one_dir(inp, wx, wh, bias, h0, c0, reverse):
        def step(carry, x_t):
            h, c = carry
            g = x_t @ wx + h @ wh + bias
            i = jax.nn.sigmoid(g[:, :H])
            f = jax.nn.sigmoid(g[:, H:2 * H])
            c_hat = jnp.tanh(g[:, 2 * H:3 * H])
            o = jax.nn.sigmoid(g[:, 3 * H:])
            c2 = f * c + i * c_hat
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2

        xs = jnp.swapaxes(inp, 0, 1)
        (hT, cT), hs = jax.lax.scan(step, (h0, c0), xs, reverse=reverse)
        return jnp.swapaxes(hs, 0, 1), hT, cT

    off = 0

    def take(n, shape):
        nonlocal off
        v = flat[off:off + n].reshape(shape)
        off += n
        return v

    dropout = float(ctx.attr("dropout_prob", 0.0))
    train_dropout = dropout > 0.0 and not bool(ctx.attr("is_test", False))
    key = ctx.rng
    out = x
    last_h, last_c = [], []
    for layer in range(L):
        if layer > 0 and train_dropout:
            key, sub = jax.random.split(key)
            keep = jax.random.bernoulli(sub, 1.0 - dropout, out.shape)
            out = jnp.where(keep, out / (1.0 - dropout), 0.0)
        in_dim = D if layer == 0 else H * dirs
        outs = []
        for d in range(dirs):
            wx = take(in_dim * 4 * H, (in_dim, 4 * H))
            wh = take(H * 4 * H, (H, 4 * H))
            bias = take(4 * H, (4 * H,))
            idx = layer * dirs + d
            o, hT, cT = one_dir(out, wx, wh, bias, init_h[idx], init_c[idx],
                                reverse=(d == 1))
            outs.append(o)
            last_h.append(hT)
            last_c.append(cT)
        out = jnp.concatenate(outs, axis=-1) if dirs == 2 else outs[0]
    return {"Out": out, "LastH": jnp.stack(last_h),
            "LastC": jnp.stack(last_c)}


@register_op("row_conv")
def row_conv(ctx: ExecContext):
    """Lookahead row convolution (reference row_conv_op.cc): X [B, T, D],
    Filter [k+1, D]; out[t] = sum_{i=0..k} x[t+i] * filter[i] elementwise
    per feature (future context only, zero past the end)."""
    x = ctx.input("X")
    filt = ctx.input("Filter")
    k1 = filt.shape[0]
    B, T, D = x.shape
    t = jnp.arange(T, dtype=jnp.int32)
    out = jnp.zeros_like(x)
    for i in range(k1):
        src = t + i
        ok = src < T
        g = x[:, jnp.clip(src, 0, T - 1), :]
        out = out + jnp.where(ok[None, :, None], g, 0.0) * filt[i][None, None, :]
    return {"Out": out}
