"""Recurrent cell + sequence-RNN ops.

TPU-native re-design of the reference recurrent operators:
  * gru_unit_op.h (one step; exact gate math reproduced below)
  * gru_op.cc / dynamic_gru  -> `gru`: whole-sequence lax.scan (the
    reference's LoD batch reordering becomes a scan over the padded time
    axis; XLA keeps weights resident across steps)
  * lstm_op.cc / dynamic_lstm -> `lstm`: same scan treatment

Scans carry [B, H] state; matmuls inside the body hit the MXU per step. The
reference's sequence->batch reorder machinery (math/sequence2batch.h) is
unnecessary: padding already gives a rectangular [B, T, ...] layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import ExecContext, register_op

_ACTS = {
    "identity": lambda x: x,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
}


def _act(name):
    try:
        return _ACTS[str(name)]
    except KeyError:
        raise ValueError(f"unsupported activation '{name}'")


def _gru_step(x_t, h_prev, weight, bias, act, gate_act, origin_mode):
    """Exact gru_unit_op.h math: gates = x + b + h_prev @ W[:, :2H];
    c = act(x_c + (r*h_prev) @ W[:, 2H:]); h = u*(c-h_prev)+h_prev."""
    H = h_prev.shape[-1]
    g = x_t
    if bias is not None:
        g = g + bias.reshape(1, 3 * H)
    g = g.at[:, : 2 * H].add(h_prev @ weight[:, : 2 * H])
    u = gate_act(g[:, :H])
    r = gate_act(g[:, H: 2 * H])
    r_h = r * h_prev
    c_pre = g[:, 2 * H:] + r_h @ weight[:, 2 * H:]
    c = act(c_pre)
    if origin_mode:
        h = c + u * (h_prev - c)
    else:
        h = u * (c - h_prev) + h_prev
    gates = jnp.concatenate([u, r, c], axis=-1)
    return h, r_h, gates


@register_op("gru_unit")
def gru_unit(ctx: ExecContext):
    x = ctx.input("Input")          # [B, 3H] = x @ W_x (+ x bias)
    h_prev = ctx.input("HiddenPrev")
    w = ctx.input("Weight")          # [H, 3H]
    b = ctx.input("Bias")
    h, r_h, gates = _gru_step(
        x, h_prev, w, b,
        _act(ctx.attr("activation", "tanh")),
        _act(ctx.attr("gate_activation", "sigmoid")),
        bool(ctx.attr("origin_mode", False)))
    return {"Hidden": h, "ResetHiddenPrev": r_h, "Gate": gates}


@register_op("gru")
def gru(ctx: ExecContext):
    """Whole-sequence GRU (reference gru_op.cc / layers.dynamic_gru).
    Input [B, T, 3H]; optional H0 [B, H]; Weight [H, 3H]; Bias [1, 3H].
    Output Hidden [B, T, H]."""
    x = ctx.input("Input")
    w = ctx.input("Weight")
    b = ctx.input("Bias")
    H = w.shape[0]
    B = x.shape[0]
    h0 = ctx.input("H0")
    if h0 is None:
        h0 = jnp.zeros((B, H), x.dtype)
    act = _act(ctx.attr("activation", "tanh"))
    gate_act = _act(ctx.attr("gate_activation", "sigmoid"))
    origin = bool(ctx.attr("origin_mode", False))
    reverse = bool(ctx.attr("is_reverse", False))

    def step(h, x_t):
        h2, _, _ = _gru_step(x_t, h, w, b, act, gate_act, origin)
        return h2, h2

    xs = jnp.swapaxes(x, 0, 1)  # [T, B, 3H]
    _, hs = jax.lax.scan(step, h0, xs, reverse=reverse)
    return {"Hidden": jnp.swapaxes(hs, 0, 1)}


@register_op("lstm_unit")
def lstm_unit(ctx: ExecContext):
    """One LSTM step (reference lstm_unit_op.h:63-71): X [B, 4H] pre-projected
    gates in the reference's (i, f, o, g) layout, C_prev [B, H]."""
    x = ctx.input("X")
    c_prev = ctx.input("C_prev")
    H = c_prev.shape[-1]
    forget_bias = float(ctx.attr("forget_bias", 0.0))
    i = jax.nn.sigmoid(x[:, :H])
    f = jax.nn.sigmoid(x[:, H: 2 * H] + forget_bias)
    o = jax.nn.sigmoid(x[:, 2 * H: 3 * H])
    g = jnp.tanh(x[:, 3 * H:])
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return {"C": c, "H": h}


@register_op("lstm")
def lstm(ctx: ExecContext):
    """Whole-sequence LSTM (reference lstm_op.cc / layers.dynamic_lstm).
    Input [B, T, 4H] pre-projected; Weight [H, 4H] recurrent weights; Bias
    [1, 4H]. Gate order (c_hat, i, f, o) follows the reference's
    Weight = {W_ch, W_ih, W_fh, W_oh} layout (lstm_op.cc:125)."""
    x = ctx.input("Input")
    w = ctx.input("Weight")
    b = ctx.input("Bias")
    H = w.shape[0]
    B = x.shape[0]
    h0 = ctx.input("H0")
    c0 = ctx.input("C0")
    if h0 is None:
        h0 = jnp.zeros((B, H), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, H), x.dtype)
    cand_act = _act(ctx.attr("candidate_activation", "tanh"))
    gate_act = _act(ctx.attr("gate_activation", "sigmoid"))
    cell_act = _act(ctx.attr("cell_activation", "tanh"))
    reverse = bool(ctx.attr("is_reverse", False))

    def step(carry, x_t):
        h, c = carry
        g = x_t + h @ w
        if b is not None:
            g = g + b.reshape(1, 4 * H)
        c_hat = cand_act(g[:, :H])
        i = gate_act(g[:, H: 2 * H])
        f = gate_act(g[:, 2 * H: 3 * H])
        o = gate_act(g[:, 3 * H:])
        c2 = f * c + i * c_hat
        h2 = o * cell_act(c2)
        return (h2, c2), (h2, c2)

    xs = jnp.swapaxes(x, 0, 1)
    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), xs, reverse=reverse)
    return {"Hidden": jnp.swapaxes(hs, 0, 1),
            "Cell": jnp.swapaxes(cs, 0, 1)}
