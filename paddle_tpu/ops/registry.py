"""Operator registry: JAX-backed op definitions with derived shape inference
and derived gradients.

TPU-native re-design of the reference's operator machinery:
  * /root/reference/paddle/fluid/framework/op_registry.h (REGISTER_OPERATOR)
  * /root/reference/paddle/fluid/framework/operator.cc (kernel dispatch)
  * /root/reference/paddle/fluid/framework/grad_op_desc_maker.h

Departures, by design:
  * One implementation per op — a pure JAX function. There is no
    place/layout/dtype kernel-key dispatch (operator.cc:970): XLA owns layout
    and fusion; dtype specialization falls out of tracing.
  * Shape/dtype inference is DERIVED from the compute function via
    `jax.eval_shape` instead of hand-written InferShape — ops only override
    `infer` when the rule can't be traced (e.g. data-dependent reshape).
  * Gradients are DERIVED via `jax.vjp` over the forward compute: every op
    `foo` automatically has a `foo_grad` whose kernel re-runs the forward
    under vjp. Because forward and backward live in ONE jitted XLA block,
    XLA CSE folds the recomputation away (or keeps it as free rematerialization
    when that saves HBM). Ops override `grad_maker`/register a custom grad
    only when the math wants a different formula (e.g. softmax_with_xent).

A batch dim of -1 in Variable.shape is lowered through inference with a
sentinel extent and mapped back, so programs stay batch-size-polymorphic at
build time (each concrete batch size is a separate XLA compile, cached).
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import DType, np_dtype

# sentinel extent substituted for -1 during eval_shape-based inference
_DYN = 8191


class ExecContext:
    """Runtime view of one op invocation: resolved input arrays + attrs.

    The executor (and eval_shape-based inference) builds one per op. Inputs
    that name variables absent from the environment resolve to None (the op
    decides how to treat them, e.g. missing output-grads become zeros).
    """

    def __init__(self, op, env: dict, rng=None, lowerer=None):
        self.op = op
        self.env = env
        self.rng = rng  # jax PRNG key or None
        self.lowerer = lowerer  # callable(block_idx) -> python fn, for control flow

    def inputs(self, slot: str):
        return [self.env.get(n) for n in self.op.inputs.get(slot, [])]

    def input(self, slot: str, idx: int = 0):
        names = self.op.inputs.get(slot, [])
        if idx >= len(names):
            return None
        return self.env.get(names[idx])

    def has_input(self, slot: str) -> bool:
        names = self.op.inputs.get(slot, [])
        return bool(names) and any(n in self.env for n in names)

    def attr(self, name: str, default=None):
        return self.op.attrs.get(name, default)


class OpDef:
    def __init__(
        self,
        type: str,
        compute: Callable[[ExecContext], dict],
        infer: Callable | None = None,
        grad_maker: Callable | None = None,
        needs_rng: bool = False,
        no_grad: bool = False,
        stateful_outputs: tuple = (),
        host: bool = False,
    ):
        self.type = type
        self.compute = compute
        self.infer = infer
        self.grad_maker = grad_maker
        self.needs_rng = needs_rng
        self.no_grad = no_grad
        # output slots that alias an input (in-place update contract, e.g.
        # sgd's ParamOut) — used by the executor for donation bookkeeping
        self.stateful_outputs = stateful_outputs
        # host=True: side-effecting op that must run OUTSIDE jit (RPC
        # send/recv, print, py_func) — the executor splits the block into jit
        # segments around these (SURVEY §7: segment partitioning; the
        # reference's data_transform/host-op analogue)
        self.host = host


_REGISTRY: dict[str, OpDef] = {}


def register_op(
    type: str,
    *,
    infer=None,
    grad=None,
    needs_rng=False,
    no_grad=False,
    stateful_outputs=(),
    host=False,
):
    """Decorator: register `compute` for op `type`.

    grad: None -> derive via vjp; "none" -> non-differentiable;
          callable -> custom grad maker (op, block, no_grad_set) -> [op spec].
    """

    def deco(compute):
        grad_maker = None
        is_no_grad = no_grad or grad == "none"
        if callable(grad):
            grad_maker = grad
        _REGISTRY[type] = OpDef(
            type,
            compute,
            infer=infer,
            grad_maker=grad_maker,
            needs_rng=needs_rng,
            no_grad=is_no_grad,
            stateful_outputs=stateful_outputs,
            host=host,
        )
        return compute

    return deco


def register_grad_compute(fwd_type: str):
    """Register a hand-written kernel for `<fwd_type>_grad` (overrides vjp)."""

    def deco(compute):
        _REGISTRY[fwd_type + "_grad"] = OpDef(fwd_type + "_grad", compute, no_grad=True)
        return compute

    return deco


def get_op_def(type: str) -> OpDef:
    if type in _REGISTRY:
        return _REGISTRY[type]
    if type.endswith("_grad") and type[: -len("_grad")] in _REGISTRY:
        # derived vjp-based grad kernel, memoized into the registry
        fwd = _REGISTRY[type[: -len("_grad")]]
        d = OpDef(type, _make_vjp_grad_compute(fwd), no_grad=True)
        d.derived_vjp = True  # replays fwd from its INPUT slots only
        _REGISTRY[type] = d
        return d
    raise KeyError(f"No op registered with type '{type}'")


def has_op(type: str) -> bool:
    try:
        get_op_def(type)
        return True
    except KeyError:
        return False


def all_op_types():
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# Derived gradient: run the forward under jax.vjp.
# ---------------------------------------------------------------------------


def _make_vjp_grad_compute(fwd: OpDef, remat: bool = False):
    """remat=True wraps the forward replay in jax.checkpoint: XLA's CSE can
    then NOT share it with the original forward (optimization_barrier), so
    the segment's activations are genuinely recomputed in the backward pass
    instead of kept live — the RecomputeOptimizer contract."""

    def grad_compute(ctx: ExecContext):
        op = ctx.op
        fwd_in_slots = [s for s in op.inputs if not s.endswith("@GRAD")]
        # flatten differentiable (inexact) vs closed-over inputs
        prim_keys, prims, consts = [], [], {}
        for s in fwd_in_slots:
            for i, a in enumerate(ctx.inputs(s)):
                if a is not None and jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact):
                    prim_keys.append((s, i))
                    prims.append(a)
                else:
                    consts[(s, i)] = a

        meta = {"widths": None}  # [(slot, n_outputs)] in flattening order

        def fwd_fn(*xs):
            fake_inputs = {}
            for (s, i), x in zip(prim_keys, xs):
                fake_inputs.setdefault(s, {})[i] = x
            for (s, i), c in consts.items():
                if c is not None:
                    fake_inputs.setdefault(s, {})[i] = c

            # the replay must see the mesh-axis binding: without it a
            # collective fwd (ring_attention, c_allreduce inside a replayed
            # segment) silently lowers to LOCAL compute in the backward —
            # wrong grads with no error (the round-1 advisor's bug class,
            # which also applies to this shim)
            from .collective_ops import AXIS_ENV_KEY

            env = {}
            if AXIS_ENV_KEY in ctx.env:
                env[AXIS_ENV_KEY] = ctx.env[AXIS_ENV_KEY]

            class _Shim:
                inputs = {
                    s: [f"__in_{s}_{i}" for i in sorted(d)]
                    for s, d in fake_inputs.items()
                }
                outputs = {}
                attrs = op.attrs

            for s, d in fake_inputs.items():
                for i in sorted(d):
                    env[f"__in_{s}_{i}"] = d[i]
            shim_ctx = ExecContext(_Shim, env, rng=None, lowerer=ctx.lowerer)
            outs = fwd.compute(shim_ctx)
            widths, flat = [], []
            for s in sorted(outs):
                v = outs[s]
                lst = list(v) if isinstance(v, (list, tuple)) else [v]
                widths.append((s, len(lst)))
                flat.extend(lst)
            meta["widths"] = widths
            return tuple(flat)

        run_fwd = jax.checkpoint(fwd_fn) if remat else fwd_fn
        outs_flat, vjp = jax.vjp(run_fwd, *prims)
        # cotangents: supplied @GRAD inputs; zeros for forward outputs the
        # backward pass never produced a grad for
        cots, idx = [], 0
        for s, w in meta["widths"]:
            gnames = op.inputs.get(s + "@GRAD", [])
            for j in range(w):
                o = outs_flat[idx]
                idx += 1
                g = ctx.env.get(gnames[j]) if j < len(gnames) else None
                cots.append(jnp.zeros_like(o) if g is None else jnp.asarray(g, o.dtype))
        gins = vjp(tuple(cots))

        result = {}
        for (s, i), g in zip(prim_keys, gins):
            out_slot = s + "@GRAD"
            if out_slot in op.outputs:
                result.setdefault(out_slot, {})[i] = g
        # collapse index dicts to lists aligned with output name lists
        final = {}
        for s, d in result.items():
            width = len(op.outputs[s])
            lst = [None] * width
            for i, g in d.items():
                if i < width:
                    lst[i] = g
            final[s] = lst if width != 1 else lst[0]
        return final

    return grad_compute


def default_grad_maker(op, block, no_grad_set=frozenset()):
    """Build the generic `<type>_grad` op spec mirroring the forward slots.

    Mirrors the reference's DefaultGradOpDescMaker
    (/root/reference/paddle/fluid/framework/grad_op_desc_maker.h:159): forward
    inputs pass through; each forward output slot gets an `@GRAD` input slot;
    each differentiable forward input slot gets an `@GRAD` output slot.
    """
    from ..framework import grad_var_name
    from ..core.types import is_floating

    inputs = {s: list(ns) for s, ns in op.inputs.items()}
    for s, ns in op.outputs.items():
        inputs[s + "@GRAD"] = [grad_var_name(n) for n in ns]
    outputs = {}
    for s, ns in op.inputs.items():
        gns = []
        for n in ns:
            try:
                v = block.var(n)
                diff = is_floating(v.dtype) and not v.stop_gradient and n not in no_grad_set
            except KeyError:
                diff = False
            gns.append(grad_var_name(n) if diff else "")
        if any(gns):
            outputs[s + "@GRAD"] = gns
    if not outputs:
        return []
    return [
        {
            "type": op.type + "_grad",
            "inputs": inputs,
            "outputs": outputs,
            "attrs": dict(op.attrs),
        }
    ]


# ---------------------------------------------------------------------------
# Derived shape/dtype inference via eval_shape.
# ---------------------------------------------------------------------------


def infer_op(op, block) -> None:
    """Set shapes/dtypes of `op`'s outputs, creating missing output vars.

    Uses the opdef's custom `infer` when present, else traces the compute with
    ShapeDtypeStructs (batch dim -1 -> sentinel -> mapped back to -1).
    """
    try:
        opdef = get_op_def(op.type)
    except KeyError:
        return  # unknown op (e.g. feed/fetch markers) — nothing to infer
    if opdef.host:
        return  # host ops (RPC etc.) must never run at infer time
    if opdef.infer is not None:
        opdef.infer(op, block)
        return
    if op.type.endswith("_grad"):
        _infer_grad_from_forward(op, block)
        return

    env = {}
    for s, names in op.inputs.items():
        for n in names:
            if not n:
                continue
            try:
                v = block.var(n)
            except KeyError:
                continue
            shape = tuple(_DYN if d == -1 else d for d in v.shape)
            env[n] = jax.ShapeDtypeStruct(shape, np_dtype(v.dtype))

    rng = jax.ShapeDtypeStruct((2,), np.uint32) if opdef.needs_rng else None

    def f(env_vals, key):
        local = dict(zip(env.keys(), env_vals))
        ctx = ExecContext(op, local, rng=key)
        return opdef.compute(ctx)

    try:
        out = jax.eval_shape(f, tuple(env.values()), rng)
    except Exception as e:
        # Record instead of swallowing (reference op_call_stack.cc invests in
        # exactly this attribution path): some ops legitimately fail dry-run
        # inference (control flow needs the lowerer, collectives need the
        # mesh-axis env), so this is not fatal here — but if the op later
        # fails at trace time, the executor surfaces this recorded error
        # alongside the op's Python creation stack. Stored as a string so the
        # exception's frames aren't pinned for the Program's lifetime.
        op._infer_error = f"{type(e).__name__}: {e}"
        return
    _write_inferred(op, block, out)


def _write_inferred(op, block, out: dict):
    for slot, val in out.items():
        names = op.outputs.get(slot, [])
        vals = val if isinstance(val, (list, tuple)) else [val]
        for n, sd in zip(names, vals):
            if not n or sd is None:
                continue
            shape = tuple(-1 if d == _DYN else d for d in sd.shape)
            if n in block.vars:
                v = block.vars[n]
                v.shape = shape
                v.dtype = DType.parse(sd.dtype)
            else:
                try:
                    v = block.var(n)
                    v.shape = shape
                    v.dtype = DType.parse(sd.dtype)
                except KeyError:
                    block.create_var(name=n, shape=shape, dtype=sd.dtype)


def _infer_grad_from_forward(op, block) -> None:
    """A grad var has the shape/dtype of its forward var."""
    from ..framework import GRAD_SUFFIX

    for slot, names in op.outputs.items():
        for n in names:
            if not n or not n.endswith(GRAD_SUFFIX):
                continue
            fwd_name = n[: -len(GRAD_SUFFIX)]
            try:
                fv = block.var(fwd_name)
            except KeyError:
                continue
            if n in block.vars:
                block.vars[n].shape = fv.shape
                block.vars[n].dtype = fv.dtype
            else:
                block.create_var(name=n, shape=fv.shape, dtype=fv.dtype)
