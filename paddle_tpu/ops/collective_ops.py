"""Collective ops: the `c_*` family over ICI mesh axes.

TPU-native equivalents of /root/reference/paddle/fluid/operators/collective/
(c_allreduce_op.h:60 calls ncclAllReduce on ring `ring_id`; c_allgather,
c_reducescatter, c_broadcast, c_comm_init_all, c_sync_*_stream ops).

Two execution regimes (SURVEY.md §2.3):
  * GSPMD (default, `CompiledProgram.with_data_parallel`): XLA's partitioner
    inserts the gradient allreduce from shardings, so an explicit
    c_allreduce in the program must NOT reduce again — it lowers to identity.
  * shard_map (`CompiledProgram.with_collective`, the fleet/transpiler path):
    the executor binds mesh axes and sets the `__axis_env__` env key; here the
    ops emit real `lax.psum`/`all_gather`/`psum_scatter`/`ppermute` on the
    axis registered for their `ring_id` (mesh axes replace NCCL rings,
    reference collective_helper.h:50).

Sync ops are no-ops: XLA's dataflow replaces stream ordering
(c_sync_calc_stream / c_sync_comm_stream exist only for API parity).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import ExecContext, register_op

AXIS_ENV_KEY = "__axis_env__"  # env key: dict ring_id/axis info set by executor


def compat_shard_map(fn, mesh, in_specs, out_specs, check=False):
    """Version-tolerant shard_map: the entry point moved from
    jax.experimental.shard_map to jax.shard_map, and the replication-check
    kwarg was renamed check_rep -> check_vma across jax releases. One shim
    (the workbench discipline) so the executor, the ring-attention tests,
    and any future caller stop carrying private try/except ladders."""
    try:
        from jax import shard_map as shard_map_fn
    except ImportError:  # pragma: no cover - older jax layout
        from jax.experimental.shard_map import shard_map as shard_map_fn
    try:
        return shard_map_fn(fn, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, check_vma=check)
    except TypeError:  # 0.4.x spells the kwarg check_rep
        return shard_map_fn(fn, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, check_rep=check)


def _axis(ctx: ExecContext):
    env = ctx.env.get(AXIS_ENV_KEY)
    if env is None:
        return None
    ring = ctx.attr("ring_id", 0)
    return env.get(ring, env.get(0))


def _axis_size(axis):
    """jax.lax.axis_size where available (it landed after 0.4.x); else the
    shard_map-safe spelling — a psum of 1 over the axis."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    return jax.lax.psum(1, axis)


def _axis_index(axis):
    return jax.lax.axis_index(axis)


def _allreduce(red):
    def compute(ctx: ExecContext):
        from ..core.selected_rows import is_selected_rows

        x = ctx.input("X")
        if is_selected_rows(x):
            # SelectedRows grads belong to the pserver path (sparse send);
            # psum would sum row INDICES across ranks — reject loudly instead
            raise TypeError(
                f"c_allreduce_{red}: SelectedRows gradients cannot ride a "
                "collective allreduce — use the parameter-server path "
                "(DistributeTranspiler) for is_sparse=True embeddings, or "
                "build the model with is_sparse=False for collective mode")
        axis = _axis(ctx)
        if axis is None:
            return {"Out": x}  # GSPMD regime: partitioner owns the reduction
        if red == "sum":
            out = jax.lax.psum(x, axis)
            if ctx.attr("avg", False):
                # fused mean-allreduce: the 1/nranks scale lives INSIDE the op
                # so it only applies when a real reduction happens (a separate
                # scale op would corrupt grads in the GSPMD identity regime)
                out = out / _axis_size(axis)
            return {"Out": out}
        if red == "max":
            return {"Out": jax.lax.pmax(x, axis)}
        if red == "min":
            return {"Out": jax.lax.pmin(x, axis)}
        if red == "prod":
            # gather + prod: exp(psum(log)) NaNs on zero/negative elements
            return {"Out": jnp.prod(jax.lax.all_gather(x, axis), axis=0)}
        raise ValueError(red)

    return compute


register_op("c_allreduce_sum")(_allreduce("sum"))
register_op("c_allreduce_max", grad="none")(_allreduce("max"))
register_op("c_allreduce_min", grad="none")(_allreduce("min"))
register_op("c_allreduce_prod", grad="none")(_allreduce("prod"))
register_op("allreduce")(_allreduce("sum"))  # legacy dygraph DP op


@register_op("c_allreduce_coalesced", grad="none")
def c_allreduce_coalesced(ctx: ExecContext):
    """Bucketed mean-allreduce (the fuse_all_reduce_op_pass analogue, done
    in the program instead of the SSA graph): every gradient in the X list
    rides ONE flattened psum, so a bucket costs one collective launch and
    its reduce can overlap the backward compute that produces the NEXT
    bucket. Sum order per element is identical to the per-gradient
    c_allreduce_sum (psum over the same axis), so bucketing is bitwise
    payload-layout-invariant — the exactness contract the parity tests pin.
    Under GSPMD (no bound axis) it passes every input through untouched,
    matching c_allreduce_sum's identity regime."""
    from ..core.selected_rows import is_selected_rows

    xs = ctx.inputs("X")
    for x in xs:
        if is_selected_rows(x):
            raise TypeError(
                "c_allreduce_coalesced: SelectedRows gradients cannot ride "
                "a coalesced collective — use the parameter-server path for "
                "is_sparse=True embeddings, or build the model with "
                "is_sparse=False for collective mode")
    axis = _axis(ctx)
    if axis is None:
        return {"Out": list(xs)}
    # one VARIADIC psum: jax reduces the whole tuple in a single XLA
    # all-reduce (multi-operand), so the bucket pays one collective launch
    # with zero flatten/concat/split copies — per element the sum is the
    # same psum c_allreduce_sum emits, hence the bitwise parity contract
    red = jax.lax.psum(tuple(xs), axis)
    if ctx.attr("avg", False):
        n = _axis_size(axis)
        red = tuple(r / n for r in red)
    return {"Out": list(red)}


@register_op("zero1_shard", grad="none")
def zero1_shard(ctx: ExecContext):
    """This rank's 1/nranks leading-dim slice of X (ZeRO-1 optimizer-state
    sharding, parallel/sharding.py): rank i of the ring's axis owns rows
    [i*k, (i+1)*k). Under GSPMD (no bound axis) it degrades to identity —
    the whole ZeRO-1 rewrite then collapses to the plain update, which is
    the correct single-program semantics there."""
    x = ctx.input("X")
    axis = _axis(ctx)
    if axis is None:
        return {"Out": x}
    n = _axis_size(axis)
    k = x.shape[0] // n
    idx = _axis_index(axis)
    return {"Out": jax.lax.dynamic_slice_in_dim(x, idx * k, k, axis=0)}


@register_op("c_allgather")
def c_allgather(ctx: ExecContext):
    x = ctx.input("X")
    axis = _axis(ctx)
    if axis is None:
        return {"Out": x}
    return {"Out": jax.lax.all_gather(x, axis, axis=0, tiled=True)}


@register_op("c_reducescatter")
def c_reducescatter(ctx: ExecContext):
    from ..core.selected_rows import is_selected_rows

    x = ctx.input("X")
    if is_selected_rows(x):
        raise TypeError(
            "c_reducescatter: SelectedRows gradients cannot ride a "
            "reduce-scatter — use the parameter-server path for "
            "is_sparse=True embeddings (ZeRO-1 shards dense grads only)")
    axis = _axis(ctx)
    if axis is None:
        return {"Out": x}
    out = jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
    if ctx.attr("avg", False):
        # fused mean like c_allreduce_sum's `avg`: the scale only applies
        # when a real reduction runs (identity in the GSPMD regime above)
        out = out / _axis_size(axis)
    return {"Out": out}


@register_op("c_broadcast")
def c_broadcast(ctx: ExecContext):
    x = ctx.input("X")
    axis = _axis(ctx)
    if axis is None:
        return {"Out": x}
    root = ctx.attr("root", 0)
    # broadcast root's value: select root's shard on every member
    idx = jax.lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return {"Out": jax.lax.psum(masked, axis)}


@register_op("c_collective_permute")
def c_collective_permute(ctx: ExecContext):
    """Ring permute (TPU-first addition; backs ring attention / pipeline).
    attr `shift`: +1 sends to the next rank on the ring."""
    x = ctx.input("X")
    axis = _axis(ctx)
    if axis is None:
        return {"Out": x}
    n = _axis_size(axis)
    shift = ctx.attr("shift", 1)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return {"Out": jax.lax.ppermute(x, axis, perm)}


@register_op("local_sgd_sync", grad="none")
def local_sgd_sync(ctx: ExecContext):
    """LocalSGD periodic sync, fused and branchless (reference
    transpiler/collective.py:269): every `k_steps` steps, allreduce-average the
    (param - snapshot) deltas and fold them back; other steps pass through.
    Inputs: Param, Snapshot, Step (int64 scalar, already incremented).
    Outputs: ParamOut, SnapshotOut."""
    p = ctx.input("Param")
    snap = ctx.input("Snapshot")
    step = ctx.input("Step")
    k = ctx.attr("k_steps", 1)
    axis = _axis(ctx)
    delta = p - snap
    if axis is not None:
        delta = jax.lax.psum(delta, axis) / _axis_size(axis)
    synced = snap + delta
    do_sync = (step % k) == 0
    new_p = jnp.where(do_sync, synced, p)
    new_snap = jnp.where(do_sync, synced, snap)
    return {"ParamOut": new_p, "SnapshotOut": new_snap}


@register_op("c_sync_calc_stream", grad="none")
def c_sync_calc_stream(ctx: ExecContext):
    return {"Out": ctx.input("X")}


@register_op("c_sync_comm_stream", grad="none")
def c_sync_comm_stream(ctx: ExecContext):
    return {"Out": ctx.input("X")}


@register_op("c_comm_init_all", grad="none")
def c_comm_init_all(ctx: ExecContext):
    """NCCL-ring bootstrap has no TPU analogue (the mesh IS the communicator,
    reference c_comm_init_all_op.cc / gen_nccl_id RPC dance); no-op."""
    return {}


@register_op("c_gen_nccl_id", grad="none")
def c_gen_nccl_id(ctx: ExecContext):
    return {}
