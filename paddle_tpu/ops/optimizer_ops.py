"""Optimizer update ops.

TPU-native equivalents of /root/reference/paddle/fluid/operators/optimizers/
(sgd_op.cc, momentum_op.cc, adam_op.cc, adamax_op.cc, adagrad_op.cc,
rmsprop_op.cc, adadelta_op.cc, ftrl_op.cc, lamb_op.cc, lars_momentum_op.cc,
decayed_adagrad_op.cc). Each is a pure function param/state -> new param/state;
the executor writes outputs back to the same Scope entries (the in-place
ParamOut contract), with XLA buffer donation so updates happen in-place in HBM.

All moment arithmetic runs in fp32 even when params are bf16 (master-weight
behaviour lives in the AMP decorator, contrib/mixed_precision).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import ExecContext, register_op


def _lr(ctx):
    lr = ctx.input("LearningRate")
    return lr.reshape(()) if lr.ndim else lr


def _reject_sparse(ctx, g):
    from ..core.selected_rows import is_selected_rows

    if is_selected_rows(g):
        raise NotImplementedError(
            f"op '{ctx.op.type}' does not support SelectedRows (sparse) "
            f"gradients; use SGD for is_sparse embeddings, or "
            f"is_sparse=False (XLA fuses the dense scatter-add)")
    return g


@register_op("sgd", grad="none", stateful_outputs=("ParamOut",))
def sgd(ctx: ExecContext):
    """Dense update, or a sparse row-wise update for SelectedRows grads (the
    reference sgd_op.cc SelectedRows kernel): duplicates accumulate via
    scatter-add, rows untouched by the batch keep their values."""
    from ..core.selected_rows import is_selected_rows

    p, g = ctx.input("Param"), ctx.input("Grad")
    if is_selected_rows(g):
        upd = (_lr(ctx) * g.values).astype(p.dtype)
        # pre-sorting the rows makes XLA's TPU scatter ~1.5x faster for
        # CTR-sized updates (53k rows into 100k x 16: 9.6 -> 6.4 ms,
        # tools/ microbench PERF.md r5); the argsort itself is cheap
        order = jnp.argsort(g.rows)
        return {"ParamOut": p.at[g.rows[order]].add(
            -upd[order], indices_are_sorted=True)}
    return {"ParamOut": p - (_lr(ctx) * g).astype(p.dtype)}


@register_op("momentum", grad="none", stateful_outputs=("ParamOut", "VelocityOut"))
def momentum(ctx: ExecContext):
    p, g, v = ctx.input("Param"), _reject_sparse(ctx, ctx.input("Grad")), ctx.input("Velocity")
    mu = ctx.attr("mu")
    lr = _lr(ctx)
    v_new = mu * v + g
    if ctx.attr("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    return {"ParamOut": p_new.astype(p.dtype), "VelocityOut": v_new.astype(v.dtype)}


@register_op(
    "adam",
    grad="none",
    stateful_outputs=("ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"),
)
def adam(ctx: ExecContext):
    from ..core.selected_rows import is_selected_rows

    p = ctx.input("Param")
    g = ctx.input("Grad")
    m1 = ctx.input("Moment1")
    m2 = ctx.input("Moment2")
    b1p = ctx.input("Beta1Pow").reshape(())
    b2p = ctx.input("Beta2Pow").reshape(())
    b1, b2 = ctx.attr("beta1", 0.9), ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(ctx) * jnp.sqrt(1 - b2p) / (1 - b1p)
    if is_selected_rows(g):
        # lazy sparse Adam (reference adam_op.h SparseAdamFunctor with
        # lazy_mode=True): only the TOUCHED rows' moments decay and update —
        # the embedding-table behavior the dense form can't afford. Duplicate
        # rows first merge by sum (reference merge_add of the SelectedRows).
        rows = g.rows.astype(jnp.int32)
        merged = jnp.zeros((p.shape[0],) + g.values.shape[1:],
                           jnp.float32).at[rows].add(
                               g.values.astype(jnp.float32))
        touched = jnp.zeros((p.shape[0],), bool).at[rows].set(True)
        tmask = touched.reshape((-1,) + (1,) * (p.ndim - 1))
        m1n = jnp.where(tmask, b1 * m1 + (1 - b1) * merged, m1)
        m2n = jnp.where(tmask, b2 * m2 + (1 - b2) * jnp.square(merged), m2)
        upd = lr * (m1n / (jnp.sqrt(m2n) + eps))
        p_new = jnp.where(tmask, p.astype(jnp.float32) - upd,
                          p.astype(jnp.float32))
    else:
        gf = g.astype(jnp.float32)
        m1n = b1 * m1 + (1 - b1) * gf
        m2n = b2 * m2 + (1 - b2) * jnp.square(gf)
        p_new = p.astype(jnp.float32) - lr * (m1n / (jnp.sqrt(m2n) + eps))
    return {
        "ParamOut": p_new.astype(p.dtype),
        "Moment1Out": m1n,
        "Moment2Out": m2n,
        "Beta1PowOut": (b1p * b1).reshape(ctx.input("Beta1Pow").shape),
        "Beta2PowOut": (b2p * b2).reshape(ctx.input("Beta2Pow").shape),
    }


@register_op("adamax", grad="none", stateful_outputs=("ParamOut", "MomentOut", "InfNormOut"))
def adamax(ctx: ExecContext):
    p = ctx.input("Param")
    g = ctx.input("Grad").astype(jnp.float32)
    m = ctx.input("Moment")
    inf = ctx.input("InfNorm")
    b1p = ctx.input("Beta1Pow").reshape(())
    b1, b2 = ctx.attr("beta1", 0.9), ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf, jnp.abs(g))
    p_new = p.astype(jnp.float32) - (_lr(ctx) / (1 - b1p)) * (m_new / (inf_new + eps))
    return {
        "ParamOut": p_new.astype(p.dtype),
        "MomentOut": m_new,
        "InfNormOut": inf_new,
    }


@register_op("adagrad", grad="none", stateful_outputs=("ParamOut", "MomentOut"))
def adagrad(ctx: ExecContext):
    p = ctx.input("Param")
    g = ctx.input("Grad").astype(jnp.float32)
    m = ctx.input("Moment")
    eps = ctx.attr("epsilon", 1e-6)
    m_new = m + jnp.square(g)
    p_new = p.astype(jnp.float32) - _lr(ctx) * g / (jnp.sqrt(m_new) + eps)
    return {"ParamOut": p_new.astype(p.dtype), "MomentOut": m_new}


@register_op("decayed_adagrad", grad="none", stateful_outputs=("ParamOut", "MomentOut"))
def decayed_adagrad(ctx: ExecContext):
    p = ctx.input("Param")
    g = ctx.input("Grad").astype(jnp.float32)
    m = ctx.input("Moment")
    decay = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    m_new = decay * m + (1 - decay) * jnp.square(g)
    p_new = p.astype(jnp.float32) - _lr(ctx) * g / (jnp.sqrt(m_new) + eps)
    return {"ParamOut": p_new.astype(p.dtype), "MomentOut": m_new}


@register_op(
    "adadelta", grad="none", stateful_outputs=("ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut")
)
def adadelta(ctx: ExecContext):
    p = ctx.input("Param")
    g = ctx.input("Grad").astype(jnp.float32)
    ag = ctx.input("AvgSquaredGrad")
    au = ctx.input("AvgSquaredUpdate")
    rho = ctx.attr("rho", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    ag_new = rho * ag + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((au + eps) / (ag_new + eps)) * g
    au_new = rho * au + (1 - rho) * jnp.square(update)
    return {
        "ParamOut": (p.astype(jnp.float32) + update).astype(p.dtype),
        "AvgSquaredGradOut": ag_new,
        "AvgSquaredUpdateOut": au_new,
    }


@register_op(
    "rmsprop", grad="none", stateful_outputs=("ParamOut", "MomentOut", "MeanSquareOut", "MeanGradOut")
)
def rmsprop(ctx: ExecContext):
    p = ctx.input("Param")
    g = ctx.input("Grad").astype(jnp.float32)
    mom = ctx.input("Moment")
    ms = ctx.input("MeanSquare")
    rho = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    momentum = ctx.attr("momentum", 0.0)
    lr = _lr(ctx)
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    if ctx.attr("centered", False):
        mg = ctx.input("MeanGrad")
        mg_new = rho * mg + (1 - rho) * g
        denom = ms_new - jnp.square(mg_new) + eps
    else:
        mg_new = ctx.input("MeanGrad")
        denom = ms_new + eps
    mom_new = momentum * mom + lr * g / jnp.sqrt(denom)
    out = {
        "ParamOut": (p.astype(jnp.float32) - mom_new).astype(p.dtype),
        "MomentOut": mom_new,
        "MeanSquareOut": ms_new,
    }
    if mg_new is not None:
        out["MeanGradOut"] = mg_new
    return out


@register_op("ftrl", grad="none", stateful_outputs=("ParamOut", "SquaredAccumOut", "LinearAccumOut"))
def ftrl(ctx: ExecContext):
    p = ctx.input("Param").astype(jnp.float32)
    g = ctx.input("Grad").astype(jnp.float32)
    sq = ctx.input("SquaredAccumulator")
    lin = ctx.input("LinearAccumulator")
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr_power = ctx.attr("lr_power", -0.5)
    lr = _lr(ctx)
    new_sq = sq + jnp.square(g)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre_shrink = (l1 * jnp.sign(new_lin) - new_lin) / denom
    p_new = jnp.where(jnp.abs(new_lin) > l1, pre_shrink, jnp.zeros_like(p))
    return {
        "ParamOut": p_new.astype(ctx.input("Param").dtype),
        "SquaredAccumOut": new_sq,
        "LinearAccumOut": new_lin,
    }


@register_op(
    "lamb",
    grad="none",
    stateful_outputs=("ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"),
)
def lamb(ctx: ExecContext):
    p = ctx.input("Param").astype(jnp.float32)
    g = ctx.input("Grad").astype(jnp.float32)
    m1, m2 = ctx.input("Moment1"), ctx.input("Moment2")
    b1p = ctx.input("Beta1Pow").reshape(())
    b2p = ctx.input("Beta2Pow").reshape(())
    b1, b2 = ctx.attr("beta1", 0.9), ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-6)
    wd = ctx.attr("weight_decay", 0.01)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    mhat = m1n / (1 - b1p)
    vhat = m2n / (1 - b2p)
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    p_new = p - _lr(ctx) * trust * r
    return {
        "ParamOut": p_new.astype(ctx.input("Param").dtype),
        "Moment1Out": m1n,
        "Moment2Out": m2n,
        "Beta1PowOut": (b1p * b1).reshape(ctx.input("Beta1Pow").shape),
        "Beta2PowOut": (b2p * b2).reshape(ctx.input("Beta2Pow").shape),
    }


@register_op("lars_momentum", grad="none", stateful_outputs=("ParamOut", "VelocityOut"))
def lars_momentum(ctx: ExecContext):
    p = ctx.input("Param").astype(jnp.float32)
    g = ctx.input("Grad").astype(jnp.float32)
    v = ctx.input("Velocity")
    mu = ctx.attr("mu")
    coeff = ctx.attr("lars_coeff", 0.001)
    wd = ctx.attr("lars_weight_decay", 0.0005)
    eps = 1e-9
    lr = _lr(ctx)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * coeff * p_norm / (g_norm + wd * p_norm + eps),
        lr,
    )
    v_new = mu * v + local_lr * (g + wd * p)
    return {
        "ParamOut": (p - v_new).astype(ctx.input("Param").dtype),
        "VelocityOut": v_new,
    }


@register_op("check_finite_and_unscale", grad="none")
def check_finite_and_unscale(ctx: ExecContext):
    """AMP grad check: divide grads by Scale; FoundInfinite=1 if ANY grad has
    a nan/inf, in which case outputs are zeroed so the optimizer step is a
    (moment-polluting but parameter-safe) no-op — branchless XLA version of
    the reference's conditional skip (contrib/mixed_precision/decorator.py)."""
    xs = ctx.inputs("X")
    scale = ctx.input("Scale")
    inv = 1.0 / jnp.reshape(scale, ())
    found = jnp.zeros((), jnp.bool_)
    for x in xs:
        found = found | ~jnp.all(jnp.isfinite(x))
    outs = [jnp.where(found, jnp.zeros_like(x), x * inv.astype(x.dtype)) for x in xs]
    return {"Out": outs, "FoundInfinite": jnp.reshape(found, (1,))}


@register_op("update_loss_scaling", grad="none")
def update_loss_scaling(ctx: ExecContext):
    """Dynamic loss-scale state machine (reference update op semantics):
    after `incr_every_n_steps` consecutive finite steps multiply the scale by
    incr_ratio; after `decr_every_n_nan_or_inf` bad steps multiply by
    decr_ratio (floored at 1.0). Branchless jnp.where version."""
    scale = jnp.reshape(ctx.input("PrevLossScaling"), ())
    good = jnp.reshape(ctx.input("InGoodSteps"), ()).astype(jnp.int32)
    bad = jnp.reshape(ctx.input("InBadSteps"), ()).astype(jnp.int32)
    found = jnp.reshape(ctx.input("FoundInfinite"), ()).astype(jnp.bool_)
    incr_n = ctx.attr("incr_every_n_steps", 1000)
    decr_n = ctx.attr("decr_every_n_nan_or_inf", 2)
    incr_ratio = ctx.attr("incr_ratio", 2.0)
    decr_ratio = ctx.attr("decr_ratio", 0.5)

    good_next = jnp.where(found, 0, good + 1)
    bad_next = jnp.where(found, bad + 1, 0)
    do_incr = (~found) & (good_next >= incr_n)
    do_decr = found & (bad_next >= decr_n)
    new_scale = jnp.where(do_incr, scale * incr_ratio, scale)
    new_scale = jnp.where(do_decr, jnp.maximum(scale * decr_ratio, 1.0), new_scale)
    good_next = jnp.where(do_incr, 0, good_next)
    bad_next = jnp.where(do_decr, 0, bad_next)
    return {
        "LossScaling": jnp.reshape(new_scale, ()),
        "OutGoodSteps": jnp.reshape(good_next, (1,)).astype(jnp.int32),
        "OutBadSteps": jnp.reshape(bad_next, (1,)).astype(jnp.int32),
    }


@register_op("health_sentinel", grad="none")
def health_sentinel(ctx: ExecContext):
    """In-graph numeric health vector + branchless bad-step skip
    (resilience/guardrails.py). Generalizes check_finite_and_unscale's AMP
    found_inf skip to every fp32 run: inputs are the post-clip gradients and
    the loss; a step whose loss/grads are non-finite — or whose finite loss
    exceeds spike_factor times the in-graph loss EMA — has ALL its gradients
    zeroed (the optimizer ops then leave parameters bit-identical for SGD,
    moment-decay-only for Adam-family), and the verdict is emitted as a tiny
    Health vector the executor ships out with the async completion token:

        Health = [loss, global_grad_norm, nonfinite, bad]   (float32 [4])

    State is [ema, steps_seen]; the EMA only advances on good steps so one
    spike cannot drag the baseline up. An AMP program wires its own
    @FOUND_INF@ in through the optional FoundInfinite input so both skip
    mechanisms agree on one verdict."""
    from ..core.selected_rows import SelectedRows, is_selected_rows

    xs = ctx.inputs("X")
    loss = ctx.input("Loss")
    state = jnp.reshape(ctx.input("State"), (-1,)).astype(jnp.float32)
    spike_factor = float(ctx.attr("spike_factor", 0.0))
    ema_decay = float(ctx.attr("ema_decay", 0.9))

    loss32 = jnp.mean(loss.astype(jnp.float32))  # scalar whatever the rank
    nonfinite = ~jnp.isfinite(loss32)
    sq = jnp.zeros((), jnp.float32)
    for x in xs:
        v = x.values if is_selected_rows(x) else x
        v32 = v.astype(jnp.float32)
        nonfinite = nonfinite | ~jnp.all(jnp.isfinite(v32))
        sq = sq + jnp.sum(jnp.square(v32))
    gnorm = jnp.sqrt(sq)
    nonfinite = nonfinite | ~jnp.isfinite(gnorm)
    amp_found = ctx.input("FoundInfinite")
    if amp_found is not None:
        nonfinite = nonfinite | (jnp.reshape(amp_found, ()) != 0)

    ema, seen = state[0], state[1]
    spike = jnp.zeros((), jnp.bool_)
    if spike_factor > 0.0:
        spike = (seen > 0) & jnp.isfinite(loss32) & (loss32 > spike_factor * ema)
    bad = nonfinite | spike

    def _gate(x):
        if is_selected_rows(x):
            return SelectedRows(
                x.rows, jnp.where(bad, jnp.zeros_like(x.values), x.values),
                x.height)
        return jnp.where(bad, jnp.zeros_like(x), x)

    ema_next = jnp.where(bad, ema,
                         jnp.where(seen > 0,
                                   ema_decay * ema + (1.0 - ema_decay) * loss32,
                                   loss32))
    seen_next = jnp.where(bad, seen, seen + 1.0)
    health = jnp.stack([loss32, gnorm,
                        nonfinite.astype(jnp.float32),
                        bad.astype(jnp.float32)])
    return {
        "Out": [_gate(x) for x in xs],
        "Health": health,
        "StateOut": jnp.stack([ema_next, seen_next]),
    }


@register_op("dgc", grad="none", stateful_outputs=("UOut", "VOut"))
def dgc(ctx: ExecContext):
    """Deep Gradient Compression step (reference dgc_op.h /
    DGCMomentumOptimizer, arXiv:1712.01887): momentum correction + local
    accumulation + top-k sparsification with error feedback.

    u = m*u + g; v = v + u; thr = quantile(|v|, ratio);
    mask = |v| >= thr; GradOut = v*mask; v *= ~mask; u *= ~mask.
    GradOut is what rides the allreduce — fixed-shape but mostly zeros,
    which is the XLA-friendly equivalent of the reference's sparse send.

    With a CurrentStep input the per-step sparsity follows the reference
    warmup schedule (optimizer.py:805 get_sparsity) IN-GRAPH: 0 before
    rampup_begin_step (threshold at the min -> everything released = plain
    momentum through the error-feedback identity), then the sparsity_ramp
    list section-by-section across rampup_step steps, holding its last
    value. The quantile's q is a traced scalar, so one compiled step serves
    the whole schedule.
    """
    import jax.numpy as _jnp

    g = ctx.input("Grad")
    u = ctx.input("U")
    v = ctx.input("V")
    m = float(ctx.attr("momentum", 0.9))
    use_nesterov = bool(ctx.attr("use_nesterov", False))
    step = ctx.input("CurrentStep")
    if step is not None:
        ramp = [float(s) for s in
                (ctx.attr("sparsity_ramp", None)
                 or [ctx.attr("sparsity", 0.999)])]
        begin = float(ctx.attr("rampup_begin_step", 0))
        width = float(max(1, ctx.attr("rampup_step", 1)))
        s = step.reshape(()).astype(_jnp.float32)
        rel = s - begin
        idx = _jnp.clip(_jnp.floor(rel * len(ramp) / width),
                        0, len(ramp) - 1).astype(_jnp.int32)
        sparsity = _jnp.where(rel < 0, 0.0,
                              _jnp.asarray(ramp, _jnp.float32)[idx])
    else:
        sparsity = _jnp.asarray(float(ctx.attr("sparsity", 0.999)),
                                _jnp.float32)
    u = m * u + g
    if use_nesterov:
        v = v + (g + m * u)
    else:
        v = v + u
    thr = _jnp.quantile(_jnp.abs(v).reshape(-1).astype(_jnp.float32),
                        sparsity).astype(v.dtype)
    mask = _jnp.abs(v) >= thr
    grad_out = _jnp.where(mask, v, 0)
    v = _jnp.where(mask, 0, v)
    u = _jnp.where(mask, 0, u)
    return {"GradOut": grad_out, "UOut": u, "VOut": v,
            "Sparsity": sparsity.reshape(1)}


@register_op("model_average_accum", grad="none",
             stateful_outputs=("SumOut", "CntOut"))
def model_average_accum(ctx: ExecContext):
    """Sliding-window parameter accumulation (reference ModelAverage
    optimizer.py:2263, simplified three-sum rotation to one sum + count with
    max-window truncation — same average on the valid window)."""
    import jax.numpy as _jnp

    p = ctx.input("Param")
    s = ctx.input("Sum")
    cnt = ctx.input("Cnt")
    total = ctx.input("TotalUpdates")
    max_w = float(ctx.attr("max_average_window", 10000))
    min_w = float(ctx.attr("min_average_window", 10000))
    rate = float(ctx.attr("average_window_rate", 0.15))
    # reference window rule: truncate when the window exceeds
    # clip(total_updates * rate, min_window, max_window)
    if total is None:
        limit = max_w
    else:
        limit = _jnp.clip(total.reshape(()) * rate, min_w, max_w)
    cnt2 = cnt + 1.0
    reset = cnt2 > limit
    s2 = _jnp.where(reset, p, s + p)
    cnt2 = _jnp.where(reset, 1.0, cnt2)
    return {"SumOut": s2, "CntOut": cnt2}


@register_op("lookahead", grad="none",
             stateful_outputs=("ParamOut", "SlowOut"))
def lookahead(ctx: ExecContext):
    """Lookahead slow/fast sync (reference LookaheadOptimizer
    optimizer.py:2976, arXiv:1907.08610): every k steps
    slow += alpha*(fast-slow); fast = slow. Step is incremented ONCE by a
    separate increment op so every parameter syncs on the same tick."""
    import jax.numpy as _jnp

    fast = ctx.input("Param")
    slow = ctx.input("SlowParam")
    step = ctx.input("Step").reshape(())
    alpha = float(ctx.attr("alpha", 0.5))
    k = float(ctx.attr("k", 5))
    sync = _jnp.mod(step, k) == 0.0
    new_slow = _jnp.where(sync, slow + alpha * (fast - slow), slow)
    new_fast = _jnp.where(sync, new_slow.astype(fast.dtype), fast)
    return {"ParamOut": new_fast, "SlowOut": new_slow}
