"""Control-flow ops lowered to XLA structured control flow.

TPU-native re-design of the reference's scope-based interpreted loops:
  * while_op.cc (WhileOp::RunImpl runs the sub-block per iteration against a
    step scope) -> lax.while_loop over a carried tuple of named values
  * conditional_block_op.cc -> lax.cond (both branches traced; the false
    branch passes prior values through, so outputs must pre-exist)
  * recurrent_op.cc / StaticRNN -> lax.scan (time-major), which is
    REVERSE-DIFFERENTIABLE — the derived vjp grad (registry.py) gives
    backprop-through-time for free, replacing the reference's hand-built
    while_grad machinery (backward.py + while_op grad).

`while` itself is forward-only (lax.while_loop has no reverse rule); training
recurrences should use static_rnn/scan, matching XLA semantics (SURVEY.md §7
hard part (a)).

All carried values must keep static shape/dtype across iterations — that is
the XLA contract; ragged loops belong in host code or padded tensors.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import ExecContext, register_op

# Only the RNG key is stripped (it is re-threaded explicitly via the carry);
# __axis_env__ MUST propagate so collectives inside a sub-block (allreduce in
# a StaticRNN body under shard_map, ring_attention in a while, ...) still
# resolve their mesh axis instead of silently lowering to local compute.
_INTERNAL_KEYS = ("__rng_key",)


def _outer_env(ctx: ExecContext) -> dict:
    env = {k: v for k, v in ctx.env.items() if k not in _INTERNAL_KEYS}
    # Deps values may arrive under synthetic slot names (the derived-vjp grad
    # re-runs this compute through a shim whose env only holds per-slot fake
    # names) — rebind them to the REAL names the sub-block ops reference,
    # which travel via the dep_names attr.
    dep_names = ctx.attr("dep_names", None)
    if dep_names:
        for name, val in zip(dep_names, ctx.inputs("Deps")):
            if val is not None:
                env[name] = val
    return env


def _op_rng(ctx: ExecContext):
    return ctx.rng if ctx.rng is not None else jax.random.PRNGKey(0)


@register_op("while", grad="none", needs_rng=True)
def while_op(ctx: ExecContext):
    """inputs: X = carried var names (incl. the condition's producers' deps),
    Condition = [cond var]; attrs: sub_block; outputs: Out = carried names.
    The RNG key is loop-carried so randomness differs per iteration."""
    sub_idx = ctx.attr("sub_block")
    run_block = ctx.lowerer(sub_idx)
    cond_name = ctx.op.inputs["Condition"][0]
    carry_names = list(ctx.op.inputs.get("X", []))
    if cond_name not in carry_names:
        carry_names.append(cond_name)
    base_env = _outer_env(ctx)
    init_vals = tuple(jnp.asarray(ctx.env[n]) for n in carry_names)
    init = init_vals + (_op_rng(ctx),)

    def cond_fun(carry):
        env = dict(zip(carry_names, carry[:-1]))
        return jnp.reshape(env[cond_name], ()).astype(jnp.bool_)

    def body_fun(carry):
        env = dict(base_env)
        env.update(zip(carry_names, carry[:-1]))
        env["__rng_key"] = carry[-1]
        env = run_block(env)
        vals = tuple(
            jnp.asarray(env[n]).astype(i.dtype).reshape(i.shape)
            for n, i in zip(carry_names, init_vals)
        )
        return vals + (env.get("__rng_key", carry[-1]),)

    final = jax.lax.while_loop(cond_fun, body_fun, init)
    out_names = ctx.op.outputs.get("Out", [])
    result = dict(zip(carry_names, final[:-1]))
    return {"Out": [result.get(n) for n in out_names]}


@register_op("conditional_block", needs_rng=True)
def conditional_block(ctx: ExecContext):
    """inputs: Cond=[pred], X=[carried]; attrs: sub_block (+ optional
    sub_block_false); outputs: Out. With no false block, Out vars keep their
    prior values when pred is false (so they must already have values)."""
    pred = jnp.reshape(ctx.input("Cond"), ()).astype(jnp.bool_)
    out_names = ctx.op.outputs.get("Out", [])
    base_env = _outer_env(ctx)
    run_true = ctx.lowerer(ctx.attr("sub_block"))
    false_idx = ctx.attr("sub_block_false", None)
    run_false = ctx.lowerer(false_idx) if false_idx is not None else None

    key = _op_rng(ctx)

    def tb(_):
        env = dict(base_env)
        env["__rng_key"] = jax.random.fold_in(key, 0)
        env = run_true(env)
        return tuple(jnp.asarray(env[n]) for n in out_names)

    def fb(_):
        if run_false is not None:
            env = dict(base_env)
            env["__rng_key"] = jax.random.fold_in(key, 1)
            env = run_false(env)
            return tuple(jnp.asarray(env[n]) for n in out_names)
        missing = [n for n in out_names if n not in base_env]
        if missing:
            raise ValueError(
                f"conditional_block outputs {missing} have no prior value for "
                f"the false branch — assign them before the block or provide "
                f"a false block")
        return tuple(jnp.asarray(base_env[n]) for n in out_names)

    outs = jax.lax.cond(pred, tb, fb, None)
    return {"Out": list(outs)}


@register_op("static_rnn", needs_rng=True)
def static_rnn(ctx: ExecContext):
    """inputs: StepInputs (time-major [T, ...] arrays), InitMemories;
    attrs: sub_block, step_input_names (per-step var names inside the block),
    pre_names / post_names (memory pairs), output_names (per-step outputs);
    outputs: Outputs (stacked [T, ...]), FinalMemories."""
    run_block = ctx.lowerer(ctx.attr("sub_block"))
    step_in_names = list(ctx.attr("step_input_names", []))
    pre_names = list(ctx.attr("pre_names", []))
    post_names = list(ctx.attr("post_names", []))
    out_names = list(ctx.attr("output_names", []))
    xs = tuple(jnp.asarray(x) for x in ctx.inputs("StepInputs"))
    mems = tuple(jnp.asarray(m) for m in ctx.inputs("InitMemories"))
    base_env = _outer_env(ctx)
    T = xs[0].shape[0]
    step_keys = jax.random.split(_op_rng(ctx), T)  # per-timestep randomness

    def body(carry, x_t):
        env = dict(base_env)
        env.update(zip(pre_names, carry))
        env.update(zip(step_in_names, x_t[:-1]))
        env["__rng_key"] = x_t[-1]
        env = run_block(env)
        new_carry = tuple(
            jnp.asarray(env[p]).astype(c.dtype).reshape(c.shape)
            for p, c in zip(post_names, carry)
        )
        ys = tuple(env[n] for n in out_names)
        return new_carry, ys

    final_mems, stacked = jax.lax.scan(body, mems, xs + (step_keys,))
    return {"Outputs": list(stacked), "FinalMemories": list(final_mems)}


@register_op("switch_case", needs_rng=True)
def switch_case(ctx: ExecContext):
    """Case ladder (reference switch_op.cc / control_flow.py Switch:1622).

    inputs: Conds=[c1..cn], Deps; attrs: sub_blocks=[idx...] (one per case,
    last one is the default when has_default), dep_names, out_names (outer
    vars the cases write); outputs: Out (merged values).

    XLA-native lowering: every case body is traced and computed; the merged
    value is a nested select with FIRST-TRUE priority (exactly the
    reference's first-matching-case execution, minus side effects — case
    bodies must be functional, which LR schedules are).
    """
    conds = [jnp.reshape(c, ()).astype(jnp.bool_)
             for c in ctx.inputs("Conds")]
    blocks = list(ctx.attr("sub_blocks"))
    has_default = bool(ctx.attr("has_default", False))
    out_names = ctx.op.outputs.get("Out", [])
    base_env = _outer_env(ctx)
    key = _op_rng(ctx)

    branch_vals = []
    for i, idx in enumerate(blocks):
        env = dict(base_env)
        env["__rng_key"] = jax.random.fold_in(key, i)
        env = ctx.lowerer(idx)(env)
        branch_vals.append([jnp.asarray(env[n]) for n in out_names])

    if has_default:
        merged = list(branch_vals[-1])
        cased = branch_vals[:-1]
    else:
        missing = [n for n in out_names if n not in base_env]
        if missing:
            raise ValueError(
                f"switch_case outputs {missing} have no prior value and no "
                f"default() case")
        merged = [jnp.asarray(base_env[n]) for n in out_names]
        cased = branch_vals
    for cond, vals in reversed(list(zip(conds, cased))):
        merged = [jnp.where(cond, v, m) for v, m in zip(vals, merged)]
    return {"Out": merged}


@register_op("recompute", needs_rng=True)
def recompute(ctx: ExecContext):
    """Activation-recompute segment (reference RecomputeOptimizer lineage;
    TPU-native design in optimizer.py RecomputeOptimizer).

    inputs: Deps=[segment's external reads]; attrs: sub_block, dep_names,
    out_names; outputs: Out=[segment results read after the segment].

    Forward just runs the sub-block. The memory win happens in the derived
    grad: `recompute_grad` replays this compute under jax.checkpoint (see
    registry._make_vjp_grad_compute(remat=True)), so XLA rematerializes the
    segment's intermediates in the backward pass instead of keeping them
    live from the forward.
    """
    env = _outer_env(ctx)
    key = _op_rng(ctx)
    if key is not None:
        env["__rng_key"] = key
    env = ctx.lowerer(ctx.attr("sub_block"))(env)
    out_names = list(ctx.attr("out_names"))
    return {"Out": [jnp.asarray(env[n]) for n in out_names]}


# the grad must NOT be the plain derived vjp (XLA would CSE the replay with
# the forward and keep the activations anyway): register the remat variant
from .registry import _REGISTRY, OpDef, _make_vjp_grad_compute  # noqa: E402

_rc_grad = OpDef("recompute_grad",
                 _make_vjp_grad_compute(_REGISTRY["recompute"], remat=True),
                 no_grad=True)
_rc_grad.derived_vjp = True
_REGISTRY["recompute_grad"] = _rc_grad
