"""Fake-quantization ops (QAT).

TPU-native re-design of the reference's quantization kernels
(/root/reference/paddle/fluid/operators/fake_quantize_op.cc:
FakeQuantizeAbsMax, FakeQuantizeMovingAverageAbsMax, FakeDequantizeMaxAbs).

Quantize-dequantize runs fused in one op (the reference pairs separate
quant/dequant ops; XLA would fuse them anyway) with a straight-through
estimator gradient — the round()'s zero derivative is bypassed so QAT
training works, exactly the behavior the reference's QuantizationTransformPass
relies on.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import ExecContext, register_grad_compute, register_op


def _qdq(x, scale, bits):
    n = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    return jnp.clip(jnp.round(x / s * n), -n, n) * s / n


@register_op("fake_quantize_dequantize_abs_max")
def fake_quantize_dequantize_abs_max(ctx: ExecContext):
    """Per-tensor abs-max scale, quantize+dequantize (reference
    FakeQuantizeAbsMax + FakeDequantizeMaxAbs pair)."""
    x = ctx.input("X")
    bits = int(ctx.attr("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    return {"Out": _qdq(x, scale, bits).astype(x.dtype),
            "OutScale": scale.reshape(1)}


@register_grad_compute("fake_quantize_dequantize_abs_max")
def _fqdq_grad(ctx: ExecContext):
    # straight-through estimator: d out / d x ~= 1 inside the clip range
    return {"X@GRAD": ctx.input("Out@GRAD")}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             stateful_outputs=("OutScale",))
def fake_quantize_dequantize_moving_average_abs_max(ctx: ExecContext):
    """Activation quantization with a moving-average scale (reference
    FakeQuantizeMovingAverageAbsMax). InScale carries the running scale."""
    x = ctx.input("X")
    in_scale = ctx.input("InScale")
    bits = int(ctx.attr("bit_length", 8))
    rate = float(ctx.attr("moving_rate", 0.9))
    is_test = bool(ctx.attr("is_test", False))
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale = in_scale.reshape(())
    else:
        scale = rate * in_scale.reshape(()) + (1 - rate) * cur
    return {"Out": _qdq(x, scale, bits).astype(x.dtype),
            "OutScale": scale.reshape(1)}


@register_grad_compute("fake_quantize_dequantize_moving_average_abs_max")
def _fqdq_ma_grad(ctx: ExecContext):
    return {"X@GRAD": ctx.input("Out@GRAD")}


def _ste_grad_maker(op, block, no_grad_set=frozenset()):
    """Shared straight-through-estimator grad maker: every fake-quant
    variant's grad op is `<type>_grad` reading only Out@GRAD."""
    from ..framework import grad_var_name

    x = op.input("X")[0]
    if x in no_grad_set:
        return []
    return [{
        "type": op.type + "_grad",
        "inputs": {"Out@GRAD": [grad_var_name(op.output("Out")[0])]},
        "outputs": {"X@GRAD": [grad_var_name(x)]},
        "attrs": dict(op.attrs),
    }]


from .registry import get_op_def  # noqa: E402

get_op_def("fake_quantize_dequantize_abs_max").grad_maker = _ste_grad_maker
get_op_def(
    "fake_quantize_dequantize_moving_average_abs_max"
).grad_maker = _ste_grad_maker


@register_op("fake_quantize_dequantize_static")
def fake_quantize_dequantize_static(ctx: ExecContext):
    """Quantize-dequantize with a FIXED calibrated scale (the PTQ path:
    reference post-training calibration writes static scales where QAT
    learns moving averages)."""
    x = ctx.input("X")
    bits = int(ctx.attr("bit_length", 8))
    scale = jnp.asarray(float(ctx.attr("scale")), jnp.float32)
    return {"Out": _qdq(x, scale, bits).astype(x.dtype)}


@register_grad_compute("fake_quantize_dequantize_static")
def _fqdq_static_grad(ctx: ExecContext):
    return {"X@GRAD": ctx.input("Out@GRAD")}


get_op_def("fake_quantize_dequantize_static").grad_maker = _ste_grad_maker


@register_op("dequantize_abs_max", grad="none")
def dequantize_abs_max(ctx: ExecContext):
    """int8 weight -> float (reference fake_dequantize_op.cc
    FakeDequantizeMaxAbs): Out = X * Scale / (2^(bits-1)-1). Inserted by
    ConvertToInt8Pass so int8-stored models execute."""
    x, scale = ctx.input("X"), ctx.input("Scale")
    bits = int(ctx.attr("bit_length", 8))
    n = float(2 ** (bits - 1) - 1)
    return {"Out": x.astype(jnp.float32) * scale.reshape(()) / n}
