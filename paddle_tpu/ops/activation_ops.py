"""Activation ops (reference: /root/reference/paddle/fluid/operators/activation_op.cc).

Every activation is a one-liner over jnp/jax.nn; gradients derive via vjp and
XLA fuses them into neighbouring matmuls — the reference's hand-fused
fuse_relu_depthwise_conv / fused_elemwise_activation passes are unnecessary.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import ExecContext, register_op


def _act(fn):
    def compute(ctx: ExecContext):
        return {"Out": fn(ctx.input("X"))}

    return compute


register_op("relu")(_act(jax.nn.relu))
register_op("sigmoid")(_act(jax.nn.sigmoid))
register_op("tanh")(_act(jnp.tanh))
register_op("exp")(_act(jnp.exp))
register_op("log")(_act(jnp.log))
register_op("sqrt")(_act(jnp.sqrt))
register_op("rsqrt")(_act(lambda x: 1.0 / jnp.sqrt(x)))
register_op("square")(_act(jnp.square))
register_op("abs")(_act(jnp.abs))
register_op("reciprocal")(_act(lambda x: 1.0 / x))
register_op("softplus")(_act(jax.nn.softplus))
register_op("softsign")(_act(lambda x: x / (1.0 + jnp.abs(x))))
register_op("gelu")(_act(lambda x: jax.nn.gelu(x, approximate=False)))
@register_op("relu6")
def _relu6(ctx):
    x = ctx.input("X")
    t = jnp.asarray(ctx.attr("threshold", 6.0), x.dtype)
    return {"Out": jnp.clip(x, 0.0, t)}
register_op("ceil", no_grad=True)(_act(jnp.ceil))
register_op("floor", no_grad=True)(_act(jnp.floor))
register_op("round", no_grad=True)(_act(jnp.round))
register_op("sin")(_act(jnp.sin))
register_op("cos")(_act(jnp.cos))
register_op("sign", no_grad=True)(_act(jnp.sign))
register_op("logsigmoid")(_act(jax.nn.log_sigmoid))


@register_op("leaky_relu")
def leaky_relu(ctx: ExecContext):
    x = ctx.input("X")
    alpha = ctx.attr("alpha", 0.02)
    return {"Out": jnp.where(x >= 0, x, x * jnp.asarray(alpha, x.dtype))}


@register_op("elu")
def elu(ctx: ExecContext):
    x = ctx.input("X")
    alpha = jnp.asarray(ctx.attr("alpha", 1.0), x.dtype)
    return {"Out": jnp.where(x >= 0, x, alpha * (jnp.exp(x) - 1.0))}


@register_op("hard_sigmoid")
def hard_sigmoid(ctx: ExecContext):
    x = ctx.input("X")
    slope = jnp.asarray(ctx.attr("slope", 0.2), x.dtype)
    offset = jnp.asarray(ctx.attr("offset", 0.5), x.dtype)
    return {"Out": jnp.clip(x * slope + offset, 0.0, 1.0)}


@register_op("swish")
def swish(ctx: ExecContext):
    x = ctx.input("X")
    beta = jnp.asarray(ctx.attr("beta", 1.0), x.dtype)
    return {"Out": x * jax.nn.sigmoid(beta * x)}


@register_op("brelu")
def brelu(ctx: ExecContext):
    x = ctx.input("X")
    return {"Out": jnp.clip(x, ctx.attr("t_min", 0.0), ctx.attr("t_max", 24.0))}


@register_op("soft_relu")
def soft_relu(ctx: ExecContext):
    x = ctx.input("X")
    t = ctx.attr("threshold", 40.0)
    return {"Out": jnp.log1p(jnp.exp(jnp.clip(x, -t, t)))}


@register_op("thresholded_relu")
def thresholded_relu(ctx: ExecContext):
    x = ctx.input("X")
    return {"Out": jnp.where(x > ctx.attr("threshold", 1.0), x, jnp.zeros_like(x))}


@register_op("hard_swish")
def hard_swish(ctx: ExecContext):
    x = ctx.input("X")
    t = jnp.asarray(ctx.attr("threshold", 6.0), x.dtype)
    s = jnp.asarray(ctx.attr("scale", 6.0), x.dtype)
    o = jnp.asarray(ctx.attr("offset", 3.0), x.dtype)
    return {"Out": x * jnp.clip(x + o, 0.0, t) / s}


@register_op("stanh")
def stanh(ctx: ExecContext):
    x = ctx.input("X")
    a = jnp.asarray(ctx.attr("scale_a", 2.0 / 3.0), x.dtype)
    b = jnp.asarray(ctx.attr("scale_b", 1.7159), x.dtype)
    return {"Out": b * jnp.tanh(a * x)}


@register_op("selu")
def selu(ctx: ExecContext):
    x = ctx.input("X")
    scale = jnp.asarray(ctx.attr("scale", 1.0507009873554805), x.dtype)
    alpha = jnp.asarray(ctx.attr("alpha", 1.6732632423543772), x.dtype)
    return {"Out": scale * jnp.where(x >= 0, x, alpha * (jnp.exp(x) - 1.0))}
