"""Distributed (pserver-path) ops: send / recv / barriers / listen_and_serv.

TPU-native equivalents of /root/reference/paddle/fluid/operators/
distributed_ops/ (send_op.cc, recv_op.cc, send_barrier_op.cc,
fetch_barrier_op.cc, listen_and_serv_op.cc). These are HOST ops (host=True):
the executor runs them outside jit, splitting the block into XLA segments
around them — dense compute stays on-chip, the variable RPC rides host DCN.

Slicing: a dense var sent/recv'd with `sections`/`epmap` attrs is split by
rows across pservers (reference slice_variable contract); sparse
(SelectedRows) grads go whole to their assigned endpoint.
"""
from __future__ import annotations

import numpy as np

from .registry import ExecContext, register_op


def _client(ctx: ExecContext):
    from ..distributed.ps_rpc import PSClient

    eps = list(ctx.attr("endpoints", []))
    return PSClient.get(eps, int(ctx.attr("trainer_id", 0)))


@register_op("send", grad="none", host=True)
def send(ctx: ExecContext):
    """inputs X: vars to send; attrs: epmap (endpoint per section), sections
    (row counts per section, empty = whole var), endpoints, trainer_id.

    Async mode: when a Communicator is running and owns this gradient, the
    send ENQUEUES (merge-before-send + recv thread take over — reference
    send_op.cc routing through Communicator::GetInstance)."""
    from ..distributed.communicator import Communicator

    comm = Communicator.get_instance()
    client = _client(ctx)
    epmap = list(ctx.attr("epmap", []))
    sections = list(ctx.attr("sections", []))
    for name, val in zip(ctx.op.inputs.get("X", []), ctx.inputs("X")):
        if val is None:
            continue
        if comm is not None and comm.is_running and name in comm.send_ctx:
            comm.push(name, val)
            continue
        if hasattr(val, "rows"):  # SelectedRows: whole-table to one endpoint
            client.send_var(epmap[0], name, val)
            continue
        from ..distributed.ps_rpc import send_sections

        send_sections(client, name, np.asarray(val), epmap, sections)
    return {}


@register_op("send_barrier", grad="none", host=True)
def send_barrier(ctx: ExecContext):
    _client(ctx).send_barrier()
    return {}


@register_op("fetch_barrier", grad="none", host=True)
def fetch_barrier(ctx: ExecContext):
    _client(ctx).fetch_barrier()
    return {}


@register_op("recv", grad="none", host=True)
def recv(ctx: ExecContext):
    """outputs Out: vars to fill; attrs as `send`. Sliced vars concat by row
    (reference recv + concat pattern, distribute_transpiler.py get_trainer_program)."""
    from ..distributed.ps_rpc import fetch_sections

    client = _client(ctx)
    epmap = list(ctx.attr("epmap", []))
    sections = list(ctx.attr("sections", []))
    outs = [fetch_sections(client, name, epmap, sections)
            for name in ctx.op.outputs.get("Out", [])]
    return {"Out": outs}


@register_op("listen_and_serv", grad="none", host=True)
def listen_and_serv(ctx: ExecContext):
    """The pserver event loop (blocks until all trainers send_complete).
    attrs carry the serving spec; the optimize sub-programs arrive as
    serialized program dicts (Program.to_dict)."""
    from ..distributed.ps_rpc import PServerRuntime
    from ..executor import Executor, global_scope
    from ..framework import Program

    blocks = []
    for spec in ctx.attr("block_specs", []):
        blocks.append({
            "grad": spec["grad"],
            "param": spec["param"],
            "origin_param": spec.get("origin_param", spec["param"]),
            "begin": spec.get("begin", 0),
            "rows": spec.get("rows"),
            "sparse": spec.get("sparse", False),
            "optimize_program": Program.from_dict(spec["optimize_program"]),
        })
    rt = PServerRuntime(
        endpoint=ctx.attr("endpoint"),
        n_trainers=int(ctx.attr("Fanin", 1)),
        sync_mode=bool(ctx.attr("sync_mode", True)),
        blocks=blocks,
        scope=global_scope(),
        executor=Executor(),
    )
    rt.serve()
    return {}
