"""Distributed (pserver-path) ops: send / recv / barriers / listen_and_serv.

TPU-native equivalents of /root/reference/paddle/fluid/operators/
distributed_ops/ (send_op.cc, recv_op.cc, send_barrier_op.cc,
fetch_barrier_op.cc, listen_and_serv_op.cc). These are HOST ops (host=True):
the executor runs them outside jit, splitting the block into XLA segments
around them — dense compute stays on-chip, the variable RPC rides host DCN.

Slicing: a dense var sent/recv'd with `sections`/`epmap` attrs is split by
rows across pservers (reference slice_variable contract); sparse
(SelectedRows) grads go whole to their assigned endpoint.
"""
from __future__ import annotations

import numpy as np

from .registry import ExecContext, register_op


def _client(ctx: ExecContext):
    from ..distributed.ps_rpc import PSClient

    eps = list(ctx.attr("endpoints", []))
    return PSClient.get(eps, int(ctx.attr("trainer_id", 0)))


@register_op("send", grad="none", host=True)
def send(ctx: ExecContext):
    """inputs X: vars to send; attrs: epmap (endpoint per section), sections
    (row counts per section, empty = whole var), endpoints, trainer_id.

    Async mode: when a Communicator is running and owns this gradient, the
    send ENQUEUES (merge-before-send + recv thread take over — reference
    send_op.cc routing through Communicator::GetInstance)."""
    from ..distributed.communicator import Communicator

    comm = Communicator.get_instance()
    client = _client(ctx)
    epmap = list(ctx.attr("epmap", []))
    sections = list(ctx.attr("sections", []))
    for name, val in zip(ctx.op.inputs.get("X", []), ctx.inputs("X")):
        if val is None:
            continue
        if comm is not None and comm.is_running and name in comm.send_ctx:
            comm.push(name, val)
            continue
        if hasattr(val, "rows"):  # SelectedRows sparse grad
            from ..distributed.ps_rpc import send_sparse_sections

            send_sparse_sections(client, name, val, epmap,
                                 list(ctx.attr("begins", [0])), sections)
            continue
        from ..distributed.ps_rpc import send_sections

        send_sections(client, name, np.asarray(val), epmap, sections)
    return {}


@register_op("send_barrier", grad="none", host=True)
def send_barrier(ctx: ExecContext):
    _client(ctx).send_barrier()
    return {}


@register_op("fetch_barrier", grad="none", host=True)
def fetch_barrier(ctx: ExecContext):
    _client(ctx).fetch_barrier()
    return {}


@register_op("recv", grad="none", host=True)
def recv(ctx: ExecContext):
    """outputs Out: vars to fill; attrs as `send`. Sliced vars concat by row
    (reference recv + concat pattern, distribute_transpiler.py get_trainer_program)."""
    from ..distributed.ps_rpc import fetch_sections

    client = _client(ctx)
    epmap = list(ctx.attr("epmap", []))
    sections = list(ctx.attr("sections", []))
    outs = [fetch_sections(client, name, epmap, sections)
            for name in ctx.op.outputs.get("Out", [])]
    return {"Out": outs}


@register_op("listen_and_serv", grad="none", host=True)
def listen_and_serv(ctx: ExecContext):
    """The pserver event loop (blocks until all trainers send_complete).
    attrs carry the serving spec; the optimize sub-programs arrive as
    serialized program dicts (Program.to_dict)."""
    from ..distributed.ps_rpc import PServerRuntime
    from ..executor import Executor, global_scope
    from ..framework import Program

    blocks = []
    for spec in ctx.attr("block_specs", []):
        blocks.append({
            "grad": spec["grad"],
            "param": spec["param"],
            "origin_param": spec.get("origin_param", spec["param"]),
            "begin": spec.get("begin", 0),
            "rows": spec.get("rows"),
            "sparse": spec.get("sparse", False),
            "optimize_program": Program.from_dict(spec["optimize_program"]),
        })
    rt = PServerRuntime(
        endpoint=ctx.attr("endpoint"),
        n_trainers=int(ctx.attr("Fanin", 1)),
        sync_mode=bool(ctx.attr("sync_mode", True)),
        blocks=blocks,
        scope=global_scope(),
        executor=Executor(),
        dc_asgd=bool(ctx.attr("dc_asgd", False)),
        dc_asgd_lambda=float(ctx.attr("dc_asgd_lambda", 1.0)),
    )
    rt.serve()
    return {}


@register_op("prefetch", grad="none", host=True)
def prefetch(ctx: ExecContext):
    """Distributed-lookup-table forward (reference parameter_prefetch.cc +
    distribute_transpiler.py:1503 rewrite of lookup_table): gather only the
    batch's rows from the row-sharded server tables. inputs Ids [.., 1] or
    [..]; outputs Out [.., D]; attrs: table_name, epmap (per block), begins,
    sections (rows per block), padding_idx."""
    client = _client(ctx)
    epmap = list(ctx.attr("epmap", []))
    begins = list(ctx.attr("begins", [0]))
    sections = list(ctx.attr("sections", []))
    table = ctx.attr("table_name")
    padding_idx = int(ctx.attr("padding_idx", -1))

    ids = np.asarray(ctx.input("Ids"))
    idsq = ids.reshape(ids.shape[:-1]) if ids.shape and ids.shape[-1] == 1 else ids
    # host op: numpy int64 on the pserver wire (giant tables can out-range
    # int32 row ids; no jax truncation applies off-device)
    flat = idsq.reshape(-1).astype(np.int64)
    uniq, inv = np.unique(flat, return_inverse=True)
    if not sections:
        out_rows = client.prefetch(epmap[0], table, uniq)
    else:
        ends = [b + s for b, s in zip(begins, sections)]
        if uniq.size and (uniq.min() < begins[0] or uniq.max() >= ends[-1]):
            raise IndexError(
                f"prefetch: ids outside the sharded table '{table}' "
                f"[{begins[0]}, {ends[-1]}): min={uniq.min()} "
                f"max={uniq.max()} — corrupt data or wrong vocab size")
        # an empty-id batch still needs the embedding WIDTH for a
        # shape-correct [.., 0-rows, D] output: ask block0 for zero rows
        out_rows = None
        for j, (ep, b, e) in enumerate(zip(epmap, begins, ends)):
            mask = (uniq >= b) & (uniq < e)
            if not mask.any() and out_rows is not None:
                continue
            part = client.prefetch(ep, f"{table}.block{j}", uniq[mask] - b)
            if out_rows is None:
                out_rows = np.zeros((len(uniq), part.shape[1]), part.dtype)
            out_rows[mask] = part
    out = out_rows[inv].reshape(idsq.shape + (out_rows.shape[1],))
    if padding_idx >= 0:
        out = np.where((idsq == padding_idx)[..., None],
                       np.zeros_like(out), out)
    return {"Out": out}
