"""Vision / layout ops: resize, pooling variants, pixel shuffling, crops.

TPU-native equivalents of the reference operators
(/root/reference/paddle/fluid/operators/): interpolate_op.* (bilinear /
nearest resize), pool_op 3-D + adaptive paths, pixel_shuffle_op,
shuffle_channel_op, space_to_depth_op, temporal_shift_op, maxout_op, lrn_op,
affine_channel_op, multiplex_op, crop_op, pad_constant_like_op, unfold_op,
grid_sampler_op, conv3d from conv_op.*. Everything static-shaped jnp/lax;
grads derive via vjp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import ExecContext, register_op

from ..core.types import np_feed_dtype

# the runtime's index dtype: int32 under x64-off jax (an astype to
# int64 would warn-and-truncate on every trace), int64 when enabled
_INDEX_DTYPE = np_feed_dtype("int64")


def _resize_dims(ctx, x):
    out_h = int(ctx.attr("out_h", 0))
    out_w = int(ctx.attr("out_w", 0))
    scale = float(ctx.attr("scale", 0.0) or 0.0)
    if out_h <= 0 or out_w <= 0:
        if scale <= 0:
            raise ValueError("resize needs out_h/out_w or scale")
        out_h = int(x.shape[2] * scale)
        out_w = int(x.shape[3] * scale)
    return out_h, out_w


def _src_coords(out_len, in_len, align_corners, align_mode):
    """Source sampling coordinate per interpolate_op.h: align_corners ->
    d*(in-1)/(out-1); else mode 0 half-pixel (d+.5)*r-.5, mode 1 d*r."""
    d = jnp.arange(out_len, dtype=jnp.float32)
    if align_corners:
        r = (in_len - 1) / max(out_len - 1, 1)
        return d * r
    r = in_len / out_len
    if int(align_mode) == 0:
        return jnp.maximum((d + 0.5) * r - 0.5, 0.0)
    return d * r


def _resize(ctx, method):
    x = ctx.input("X")  # [N, C, H, W]
    out_h, out_w = _resize_dims(ctx, x)
    align_corners = bool(ctx.attr("align_corners", False))
    align_mode = int(ctx.attr("align_mode", 1))
    H, W = x.shape[2], x.shape[3]
    if method == "nearest":
        if align_corners:
            # reference: static_cast<int>(ratio*k + 0.5) — round half UP
            iy = jnp.floor(_src_coords(out_h, H, True, 0) + 0.5)
            ix = jnp.floor(_src_coords(out_w, W, True, 0) + 0.5)
        else:
            # reference: floor(k * in/out) — NOT half-pixel
            iy = jnp.floor(_src_coords(out_h, H, False, 1))
            ix = jnp.floor(_src_coords(out_w, W, False, 1))
        iy = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
        ix = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
        return {"Out": x[:, :, iy][:, :, :, ix]}
    if not align_corners and int(align_mode) == 0:
        # jax.image 'linear' is the half-pixel convention; antialias would
        # low-pass on downscale, which the point-sampled reference never does
        out = jax.image.resize(x, (x.shape[0], x.shape[1], out_h, out_w),
                               method="linear", antialias=False)
        return {"Out": out.astype(x.dtype)}
    f = x.astype(jnp.float32)
    sy = _src_coords(out_h, H, align_corners, align_mode)
    sx = _src_coords(out_w, W, align_corners, align_mode)
    y0 = jnp.clip(jnp.floor(sy).astype(jnp.int32), 0, H - 1)
    x0 = jnp.clip(jnp.floor(sx).astype(jnp.int32), 0, W - 1)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy = (sy - y0)[None, None, :, None]
    wx = (sx - x0)[None, None, None, :]
    top = f[:, :, y0][:, :, :, x0] * (1 - wx) + f[:, :, y0][:, :, :, x1] * wx
    bot = f[:, :, y1][:, :, :, x0] * (1 - wx) + f[:, :, y1][:, :, :, x1] * wx
    out = top * (1 - wy) + bot * wy
    return {"Out": out.astype(x.dtype)}


@register_op("bilinear_interp")
def bilinear_interp(ctx: ExecContext):
    """reference interpolate_op.* bilinear path, all three coordinate
    conventions (align_corners, align_mode 0/1)."""
    return _resize(ctx, "linear")


@register_op("nearest_interp")
def nearest_interp(ctx: ExecContext):
    return _resize(ctx, "nearest")


@register_op("pool3d")
def pool3d(ctx: ExecContext):
    x = ctx.input("X")  # [N, C, D, H, W]
    ptype = ctx.attr("pooling_type", "max")
    k = list(ctx.attr("ksize"))
    s = list(ctx.attr("strides", [1, 1, 1]))
    p = list(ctx.attr("paddings", [0, 0, 0]))
    if ctx.attr("global_pooling", False):
        k = list(x.shape[2:])
        s, p = k, [0, 0, 0]
    window = (1, 1, *k)
    strides = (1, 1, *s)
    pads = ((0, 0), (0, 0)) + tuple((pp, pp) for pp in p)
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                    strides, pads)
    else:
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides,
                                       pads)
        if ctx.attr("exclusive", True) and any(p):
            # reference pool_op exclusive=true: padded zeros do not count
            counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0,
                                           jax.lax.add, window, strides,
                                           pads)
            out = summed / counts
        else:
            out = summed / float(np.prod(k))
    return {"Out": out.astype(x.dtype)}


@register_op("conv3d")
def conv3d(ctx: ExecContext):
    x, w = ctx.input("Input"), ctx.input("Filter")
    s = list(ctx.attr("strides", [1, 1, 1]))
    p = list(ctx.attr("paddings", [0, 0, 0]))
    d = list(ctx.attr("dilations", [1, 1, 1]))
    out = jax.lax.conv_general_dilated(
        x, w, tuple(s), [(pp, pp) for pp in p], rhs_dilation=tuple(d),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=int(ctx.attr("groups", 1)))
    return {"Output": out}


@register_op("adaptive_pool2d")
def adaptive_pool2d(ctx: ExecContext):
    """reference pool_op adaptive=True: output bins partition the input
    evenly; requires divisible dims (the XLA-static case — the reference's
    uneven bins need data-dependent windows)."""
    x = ctx.input("X")
    oh, ow = [int(v) for v in ctx.attr("pooled_size")]
    ptype = ctx.attr("pooling_type", "avg")
    N, C, H, W = x.shape
    if H % oh or W % ow:
        raise ValueError(
            f"adaptive_pool2d: input {H}x{W} not divisible by output "
            f"{oh}x{ow} (uneven adaptive bins are not static-shaped)")
    r = x.reshape(N, C, oh, H // oh, ow, W // ow)
    out = r.max(axis=(3, 5)) if ptype == "max" else r.mean(axis=(3, 5))
    return {"Out": out.astype(x.dtype)}


@register_op("pixel_shuffle")
def pixel_shuffle(ctx: ExecContext):
    x = ctx.input("X")
    u = int(ctx.attr("upscale_factor"))
    N, C, H, W = x.shape
    out = x.reshape(N, C // (u * u), u, u, H, W)
    out = out.transpose(0, 1, 4, 2, 5, 3).reshape(N, C // (u * u),
                                                  H * u, W * u)
    return {"Out": out}


@register_op("shuffle_channel")
def shuffle_channel(ctx: ExecContext):
    x = ctx.input("X")
    g = int(ctx.attr("group"))
    N, C, H, W = x.shape
    out = x.reshape(N, g, C // g, H, W).transpose(0, 2, 1, 3, 4)
    return {"Out": out.reshape(N, C, H, W)}


@register_op("space_to_depth")
def space_to_depth(ctx: ExecContext):
    x = ctx.input("X")
    b = int(ctx.attr("blocksize"))
    N, C, H, W = x.shape
    out = x.reshape(N, C, H // b, b, W // b, b)
    out = out.transpose(0, 3, 5, 1, 2, 4).reshape(N, C * b * b, H // b, W // b)
    return {"Out": out}


@register_op("temporal_shift")
def temporal_shift(ctx: ExecContext):
    """reference temporal_shift_op.*: [N*T, C, H, W], shift 1/shift_ratio of
    channels one step back in time, the same share forward, rest static."""
    x = ctx.input("X")
    T = int(ctx.attr("seg_num"))
    ratio = float(ctx.attr("shift_ratio", 0.25))
    NT, C, H, W = x.shape
    N = NT // T
    c1 = int(C * ratio)
    c2 = int(C * 2 * ratio)
    xr = x.reshape(N, T, C, H, W)
    back = jnp.concatenate([xr[:, 1:, :c1], jnp.zeros_like(xr[:, :1, :c1])], 1)
    fwd = jnp.concatenate([jnp.zeros_like(xr[:, :1, c1:c2]),
                           xr[:, :-1, c1:c2]], 1)
    out = jnp.concatenate([back, fwd, xr[:, :, c2:]], axis=2)
    return {"Out": out.reshape(NT, C, H, W)}


@register_op("maxout")
def maxout(ctx: ExecContext):
    x = ctx.input("X")
    g = int(ctx.attr("groups"))
    N, C, H, W = x.shape
    return {"Out": x.reshape(N, C // g, g, H, W).max(axis=2)}


@register_op("lrn")
def lrn(ctx: ExecContext):
    """reference lrn_op.*: local response normalization across channels."""
    x = ctx.input("X")
    n = int(ctx.attr("n", 5))
    k = float(ctx.attr("k", 1.0))
    alpha = float(ctx.attr("alpha", 1e-4))
    beta = float(ctx.attr("beta", 0.75))
    sq = jnp.square(x)
    half = n // 2
    pads = ((0, 0), (half, n - 1 - half), (0, 0), (0, 0))
    acc = jax.lax.reduce_window(sq, 0.0, jax.lax.add, (1, n, 1, 1),
                                (1, 1, 1, 1), pads)
    mid = (k + alpha * acc) ** beta
    return {"Out": (x / mid).astype(x.dtype), "MidOut": mid}


@register_op("affine_channel")
def affine_channel(ctx: ExecContext):
    x = ctx.input("X")
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    sh = [1, -1] + [1] * (x.ndim - 2)
    return {"Out": x * scale.reshape(sh) + bias.reshape(sh)}


@register_op("multiplex")
def multiplex(ctx: ExecContext):
    """reference multiplex_op.*: row-wise select among N input tensors by
    per-row index."""
    ids = ctx.input("Ids").reshape(-1).astype(jnp.int32)
    xs = jnp.stack([x for x in ctx.inputs("X") if x is not None])  # [K,B,...]
    rows = jnp.arange(xs.shape[1])
    return {"Out": xs[ids, rows]}


@register_op("crop")
def crop(ctx: ExecContext):
    x = ctx.input("X")
    shape = [int(s) for s in ctx.attr("shape")]
    offsets = [int(o) for o in ctx.attr("offsets", [0] * x.ndim)]
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return {"Out": x[idx]}


@register_op("pad_constant_like")
def pad_constant_like(ctx: ExecContext):
    x, y = ctx.input("X"), ctx.input("Y")
    val = float(ctx.attr("pad_value", 0.0))
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": jnp.pad(y, pads, constant_values=val)}


@register_op("unfold")
def unfold(ctx: ExecContext):
    """reference unfold_op.* (im2col as an op): [N, C, H, W] ->
    [N, C*kh*kw, L]."""
    x = ctx.input("X")
    kh, kw = [int(v) for v in ctx.attr("kernel_sizes")]
    sh, sw = [int(v) for v in ctx.attr("strides", [1, 1])]
    ph, pw = [int(v) for v in ctx.attr("paddings", [0, 0])][:2]
    dh, dw = [int(v) for v in ctx.attr("dilations", [1, 1])]
    N, C, H, W = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    oh = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            patch = xp[:, :, i * dh:i * dh + sh * oh:sh,
                       j * dw:j * dw + sw * ow:sw]
            cols.append(patch)
    out = jnp.stack(cols, axis=2)  # [N, C, kh*kw, oh, ow]
    return {"Y": out.reshape(N, C * kh * kw, oh * ow)}


@register_op("grid_sampler")
def grid_sampler(ctx: ExecContext):
    """reference grid_sampler_op.*: bilinear sampling of X [N,C,H,W] at
    Grid [N,Ho,Wo,2] normalized coords (align_corners=True)."""
    x = ctx.input("X").astype(jnp.float32)
    grid = ctx.input("Grid").astype(jnp.float32)
    N, C, H, W = x.shape
    gx = (grid[..., 0] + 1) * (W - 1) / 2
    gy = (grid[..., 1] + 1) * (H - 1) / 2

    def sample(img, gx, gy):
        # out-of-bound corners contribute ZERO (reference grid_sampler_op.h
        # GetGridPointValue isInBound), not a clamped border value
        x0f, y0f = jnp.floor(gx), jnp.floor(gy)
        corners = []
        for dy in (0, 1):
            for dx in (0, 1):
                cx_, cy_ = x0f + dx, y0f + dy
                inb = (cx_ >= 0) & (cx_ <= W - 1) & (cy_ >= 0) & (cy_ <= H - 1)
                xi = jnp.clip(cx_, 0, W - 1).astype(jnp.int32)
                yi = jnp.clip(cy_, 0, H - 1).astype(jnp.int32)
                wgt = ((1 - jnp.abs(gx - cx_)) * (1 - jnp.abs(gy - cy_)))
                corners.append(jnp.where(inb, wgt, 0.0) * img[:, yi, xi])
        return corners[0] + corners[1] + corners[2] + corners[3]

    out = jax.vmap(sample)(x, gx, gy)
    return {"Output": out}


@register_op("bilinear_tensor_product")
def bilinear_tensor_product(ctx: ExecContext):
    """reference bilinear_tensor_product_op.*: out[b,k] = x[b] W[k] y[b]."""
    x, y, w = ctx.input("X"), ctx.input("Y"), ctx.input("Weight")
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if ctx.has_input("Bias"):
        out = out + ctx.input("Bias")
    return {"Out": out}


@register_op("shard_index", grad="none")
def shard_index(ctx: ExecContext):
    x = ctx.input("X")
    index_num = int(ctx.attr("index_num"))
    nshards = int(ctx.attr("nshards"))
    shard_id = int(ctx.attr("shard_id"))
    ignore = int(ctx.attr("ignore_value", -1))
    per = (index_num + nshards - 1) // nshards
    local = x - shard_id * per
    ok = (x // per) == shard_id
    return {"Out": jnp.where(ok, local, jnp.full_like(x, ignore))}


@register_op("sampling_id", grad="none", needs_rng=True)
def sampling_id(ctx: ExecContext):
    """reference sampling_id_op.*: sample one category per row of a
    probability matrix."""
    p = ctx.input("X")
    return {"Out": jax.random.categorical(
        ctx.rng, jnp.log(jnp.maximum(p, 1e-20)), axis=-1).astype(_INDEX_DTYPE)}


@register_op("trilinear_interp")
def trilinear_interp(ctx: ExecContext):
    """reference interpolate_op.* trilinear path on [N, C, D, H, W].
    Separable per-axis linear interpolation, so all three coordinate
    conventions share _src_coords with the 2-D ops."""
    x = ctx.input("X")
    out_d = int(ctx.attr("out_d", 0))
    out_h = int(ctx.attr("out_h", 0))
    out_w = int(ctx.attr("out_w", 0))
    scale = float(ctx.attr("scale", 0.0) or 0.0)
    if out_d <= 0 or out_h <= 0 or out_w <= 0:
        if scale <= 0:
            raise ValueError("trilinear resize needs out_d/h/w or scale")
        out_d = int(x.shape[2] * scale)
        out_h = int(x.shape[3] * scale)
        out_w = int(x.shape[4] * scale)
    align_corners = bool(ctx.attr("align_corners", False))
    align_mode = int(ctx.attr("align_mode", 1))
    out = x.astype(jnp.float32)
    for axis, out_len in ((2, out_d), (3, out_h), (4, out_w)):
        in_len = out.shape[axis]
        s = _src_coords(out_len, in_len, align_corners, align_mode)
        i0 = jnp.clip(jnp.floor(s).astype(jnp.int32), 0, in_len - 1)
        i1 = jnp.minimum(i0 + 1, in_len - 1)
        w = (s - i0).reshape((1,) * axis + (-1,) +
                             (1,) * (out.ndim - axis - 1))
        out = jnp.take(out, i0, axis=axis) * (1 - w) + \
            jnp.take(out, i1, axis=axis) * w
    return {"Out": out.astype(x.dtype)}


@register_op("conv3d_transpose")
def conv3d_transpose(ctx: ExecContext):
    """reference conv_transpose_op.* 3-D path (NCDHW, filter C_in-major like
    conv2d_transpose above)."""
    x, w = ctx.input("Input"), ctx.input("Filter")

    def trip(v):
        v = list(v) if isinstance(v, (list, tuple)) else [v] * 3
        return v if len(v) == 3 else v * 3

    strides = trip(ctx.attr("strides", [1, 1, 1]))
    p = trip(ctx.attr("paddings", [0, 0, 0]))
    d = trip(ctx.attr("dilations", [1, 1, 1]))
    # explicit padding applies to the dilated input (see conv2d_transpose):
    # each side pads d*(k-1) - p for the reference output extent
    ke = [d[i] * (w.shape[2 + i] - 1) for i in range(3)]
    out = jax.lax.conv_transpose(
        x, w, strides=strides,
        padding=[(ke[i] - p[i], ke[i] - p[i]) for i in range(3)],
        rhs_dilation=d,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        transpose_kernel=True,
    ).astype(x.dtype)
    return {"Output": out}


@register_op("adaptive_pool3d")
def adaptive_pool3d(ctx: ExecContext):
    """reference pool_op adaptive 3-D: even-bin partition (static shapes)."""
    x = ctx.input("X")
    od, oh, ow = [int(v) for v in ctx.attr("pooled_size")]
    ptype = ctx.attr("pooling_type", "avg")
    N, C, D, H, W = x.shape
    if D % od or H % oh or W % ow:
        raise ValueError(
            f"adaptive_pool3d: input {D}x{H}x{W} not divisible by output "
            f"{od}x{oh}x{ow}")
    r = x.reshape(N, C, od, D // od, oh, H // oh, ow, W // ow)
    out = r.max(axis=(3, 5, 7)) if ptype == "max" else r.mean(axis=(3, 5, 7))
    return {"Out": out.astype(x.dtype)}


@register_op("affine_grid")
def affine_grid(ctx: ExecContext):
    """reference affine_grid_op.*: Theta [N, 2, 3] -> sampling grid
    [N, H, W, 2] over the align_corners=True normalized [-1, 1] mesh (the
    reference's Linspace semantics)."""
    theta = ctx.input("Theta")
    shape = [int(v) for v in ctx.attr("output_shape")]
    H, W = shape[2], shape[3]
    ys = jnp.linspace(-1.0, 1.0, H, dtype=jnp.float32)
    xs = jnp.linspace(-1.0, 1.0, W, dtype=jnp.float32)
    gx, gy = jnp.meshgrid(xs, ys)                      # [H, W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)          # [H, W, 3]
    out = jnp.einsum("hwk,nck->nhwc", base, theta.astype(jnp.float32))
    return {"Output": out.astype(theta.dtype)}


@register_op("im2sequence", grad="none")
def im2sequence(ctx: ExecContext):
    """reference im2sequence_op.*: sliding-window im2col. X [B, C, H, W] ->
    Out [B, n_windows, C*kh*kw] (the reference emits the LoD-flattened
    [B*n, C*kh*kw]; the padded design keeps the batch axis)."""
    x = ctx.input("X")
    kh, kw = [int(v) for v in ctx.attr("kernels")]
    sh, sw = [int(v) for v in ctx.attr("strides", [1, 1])]
    pads = [int(v) for v in ctx.attr("paddings", [0, 0, 0, 0])]
    x = jnp.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])))
    B, C, H, W = x.shape
    oh = (H - kh) // sh + 1
    ow = (W - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))    # [B, C*kh*kw, oh, ow]
    out = patches.reshape(B, C * kh * kw, oh * ow).transpose(0, 2, 1)
    return {"Out": out}


@register_op("random_crop", needs_rng=True, grad="none")
def random_crop(ctx: ExecContext):
    """reference random_crop_op.*: per-sample random spatial crop to `shape`
    (trailing dims). Offsets draw from the op's RNG key."""
    x = ctx.input("X")
    shape = [int(v) for v in ctx.attr("shape")]
    n_crop = len(shape)
    B = x.shape[0]
    key = ctx.rng
    outs_axes = []
    for j, tgt in enumerate(shape):
        axis = x.ndim - n_crop + j
        extent = x.shape[axis]
        if tgt > extent:
            raise ValueError(f"random_crop: target {tgt} > extent {extent}")
        key, sub = jax.random.split(key)
        outs_axes.append(jax.random.randint(sub, (B,), 0, extent - tgt + 1))

    def crop_one(xb, starts):
        out = xb
        for j, (t, s) in enumerate(zip(shape, starts)):
            axis = xb.ndim - n_crop + j
            out = jax.lax.dynamic_slice_in_dim(out, s, t, axis=axis)
        return out

    starts = jnp.stack(outs_axes, axis=1)              # [B, n_crop]
    out = jax.vmap(crop_one)(x, starts)
    return {"Out": out}


@register_op("deformable_conv")
def deformable_conv(ctx: ExecContext):
    """reference deformable_conv_op.* (v2, modulated): each kernel tap of a
    standard conv samples the input at p + learned offset, scaled by a
    learned mask, via bilinear interpolation. X [B, Cin, H, W]; Offset
    [B, 2*dg*kh*kw, OH, OW] (y,x interleaved per tap); Mask
    [B, dg*kh*kw, OH, OW]; Filter [Cout, Cin/groups, kh, kw].
    deformable_groups splits channels over offset groups."""
    x = ctx.input("Input")
    offset = ctx.input("Offset")
    mask = ctx.input("Mask")
    w = ctx.input("Filter")
    sh, sw = _pair2(ctx.attr("strides", [1, 1]))
    ph, pw_ = _pair2(ctx.attr("paddings", [0, 0]))
    dh, dw = _pair2(ctx.attr("dilations", [1, 1]))
    groups = int(ctx.attr("groups", 1))
    dg = int(ctx.attr("deformable_groups", 1))
    B, Cin, H, W = x.shape
    Cout, _, kh, kw = w.shape
    OH = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    OW = (W + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1
    f = x.astype(jnp.float32)

    oy = jnp.arange(OH) * sh - ph
    ox = jnp.arange(OW) * sw - pw_
    cols = []
    for ky in range(kh):
        for kx in range(kw):
            tap = ky * kw + kx
            off_y = offset[:, 2 * tap::2 * kh * kw]    # [B, dg, OH, OW]
            off_x = offset[:, 2 * tap + 1::2 * kh * kw]
            m = mask[:, tap::kh * kw] if mask is not None else None
            py = oy[None, None, :, None] + ky * dh + off_y
            px = ox[None, None, None, :] + kx * dw + off_x
            y0 = jnp.floor(py)
            x0 = jnp.floor(px)
            wy = py - y0
            wx = px - x0
            vals = 0.0
            for (yy, wyy) in ((y0, 1 - wy), (y0 + 1, wy)):
                for (xx, wxx) in ((x0, 1 - wx), (x0 + 1, wx)):
                    ok = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
                    yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
                    xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
                    # gather per offset-group, then broadcast to its channels
                    def g(c_grp, yi=yi, xi=xi):
                        # c_grp: [B, Cg, H, W] -> sample at [B, dg, OH, OW]
                        bidx = jnp.arange(B)[:, None, None, None]
                        didx = jnp.arange(dg)[None, :, None, None]
                        return c_grp.reshape(B, dg, Cin // dg, H, W)[
                            bidx, didx, :, yi, xi]     # [B,dg,OH,OW,Cg]
                    sampled = g(f)                      # [B,dg,OH,OW,Cin/dg]
                    vals = vals + (ok * wyy * wxx)[..., None] * \
                        jnp.where(ok[..., None], sampled, 0.0)
            if m is not None:
                vals = vals * m[..., None]
            cols.append(vals.transpose(0, 1, 4, 2, 3).reshape(
                B, Cin, OH, OW))
    # cols: kh*kw entries of [B, Cin, OH, OW] -> conv as 1x1 over taps
    col = jnp.stack(cols, axis=2)                      # [B, Cin, kh*kw, OH, OW]
    col = col.reshape(B, Cin * kh * kw, OH, OW)
    wr = w.reshape(Cout, (Cin // groups) * kh * kw)
    if groups == 1:
        wk = w.transpose(1, 2, 3, 0).reshape(Cin * kh * kw, Cout)
        out = jnp.einsum("bkhw,kc->bchw",
                         col.reshape(B, Cin * kh * kw, OH, OW), wk)
    else:
        col_g = col.reshape(B, groups, (Cin // groups) * kh * kw, OH, OW)
        wg = wr.reshape(groups, Cout // groups, -1)
        out = jnp.einsum("bgkhw,gck->bgchw", col_g, wg).reshape(
            B, Cout, OH, OW)
    return {"Output": out.astype(x.dtype)}


def _pair2(v):
    v = list(v) if isinstance(v, (list, tuple)) else [v, v]
    return v if len(v) == 2 else v * 2
