"""Vision / layout ops: resize, pooling variants, pixel shuffling, crops.

TPU-native equivalents of the reference operators
(/root/reference/paddle/fluid/operators/): interpolate_op.* (bilinear /
nearest resize), pool_op 3-D + adaptive paths, pixel_shuffle_op,
shuffle_channel_op, space_to_depth_op, temporal_shift_op, maxout_op, lrn_op,
affine_channel_op, multiplex_op, crop_op, pad_constant_like_op, unfold_op,
grid_sampler_op, conv3d from conv_op.*. Everything static-shaped jnp/lax;
grads derive via vjp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import ExecContext, register_op


def _resize_dims(ctx, x):
    out_h = int(ctx.attr("out_h", 0))
    out_w = int(ctx.attr("out_w", 0))
    scale = float(ctx.attr("scale", 0.0) or 0.0)
    if out_h <= 0 or out_w <= 0:
        if scale <= 0:
            raise ValueError("resize needs out_h/out_w or scale")
        out_h = int(x.shape[2] * scale)
        out_w = int(x.shape[3] * scale)
    return out_h, out_w


def _src_coords(out_len, in_len, align_corners, align_mode):
    """Source sampling coordinate per interpolate_op.h: align_corners ->
    d*(in-1)/(out-1); else mode 0 half-pixel (d+.5)*r-.5, mode 1 d*r."""
    d = jnp.arange(out_len, dtype=jnp.float32)
    if align_corners:
        r = (in_len - 1) / max(out_len - 1, 1)
        return d * r
    r = in_len / out_len
    if int(align_mode) == 0:
        return jnp.maximum((d + 0.5) * r - 0.5, 0.0)
    return d * r


def _resize(ctx, method):
    x = ctx.input("X")  # [N, C, H, W]
    out_h, out_w = _resize_dims(ctx, x)
    align_corners = bool(ctx.attr("align_corners", False))
    align_mode = int(ctx.attr("align_mode", 1))
    H, W = x.shape[2], x.shape[3]
    if method == "nearest":
        if align_corners:
            # reference: static_cast<int>(ratio*k + 0.5) — round half UP
            iy = jnp.floor(_src_coords(out_h, H, True, 0) + 0.5)
            ix = jnp.floor(_src_coords(out_w, W, True, 0) + 0.5)
        else:
            # reference: floor(k * in/out) — NOT half-pixel
            iy = jnp.floor(_src_coords(out_h, H, False, 1))
            ix = jnp.floor(_src_coords(out_w, W, False, 1))
        iy = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
        ix = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
        return {"Out": x[:, :, iy][:, :, :, ix]}
    if not align_corners and int(align_mode) == 0:
        # jax.image 'linear' is the half-pixel convention; antialias would
        # low-pass on downscale, which the point-sampled reference never does
        out = jax.image.resize(x, (x.shape[0], x.shape[1], out_h, out_w),
                               method="linear", antialias=False)
        return {"Out": out.astype(x.dtype)}
    f = x.astype(jnp.float32)
    sy = _src_coords(out_h, H, align_corners, align_mode)
    sx = _src_coords(out_w, W, align_corners, align_mode)
    y0 = jnp.clip(jnp.floor(sy).astype(jnp.int32), 0, H - 1)
    x0 = jnp.clip(jnp.floor(sx).astype(jnp.int32), 0, W - 1)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy = (sy - y0)[None, None, :, None]
    wx = (sx - x0)[None, None, None, :]
    top = f[:, :, y0][:, :, :, x0] * (1 - wx) + f[:, :, y0][:, :, :, x1] * wx
    bot = f[:, :, y1][:, :, :, x0] * (1 - wx) + f[:, :, y1][:, :, :, x1] * wx
    out = top * (1 - wy) + bot * wy
    return {"Out": out.astype(x.dtype)}


@register_op("bilinear_interp")
def bilinear_interp(ctx: ExecContext):
    """reference interpolate_op.* bilinear path, all three coordinate
    conventions (align_corners, align_mode 0/1)."""
    return _resize(ctx, "linear")


@register_op("nearest_interp")
def nearest_interp(ctx: ExecContext):
    return _resize(ctx, "nearest")


@register_op("pool3d")
def pool3d(ctx: ExecContext):
    x = ctx.input("X")  # [N, C, D, H, W]
    ptype = ctx.attr("pooling_type", "max")
    k = list(ctx.attr("ksize"))
    s = list(ctx.attr("strides", [1, 1, 1]))
    p = list(ctx.attr("paddings", [0, 0, 0]))
    if ctx.attr("global_pooling", False):
        k = list(x.shape[2:])
        s, p = k, [0, 0, 0]
    window = (1, 1, *k)
    strides = (1, 1, *s)
    pads = ((0, 0), (0, 0)) + tuple((pp, pp) for pp in p)
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                    strides, pads)
    else:
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides,
                                       pads)
        if ctx.attr("exclusive", True) and any(p):
            # reference pool_op exclusive=true: padded zeros do not count
            counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0,
                                           jax.lax.add, window, strides,
                                           pads)
            out = summed / counts
        else:
            out = summed / float(np.prod(k))
    return {"Out": out.astype(x.dtype)}


@register_op("conv3d")
def conv3d(ctx: ExecContext):
    x, w = ctx.input("Input"), ctx.input("Filter")
    s = list(ctx.attr("strides", [1, 1, 1]))
    p = list(ctx.attr("paddings", [0, 0, 0]))
    d = list(ctx.attr("dilations", [1, 1, 1]))
    out = jax.lax.conv_general_dilated(
        x, w, tuple(s), [(pp, pp) for pp in p], rhs_dilation=tuple(d),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=int(ctx.attr("groups", 1)))
    return {"Output": out}


@register_op("adaptive_pool2d")
def adaptive_pool2d(ctx: ExecContext):
    """reference pool_op adaptive=True: output bins partition the input
    evenly; requires divisible dims (the XLA-static case — the reference's
    uneven bins need data-dependent windows)."""
    x = ctx.input("X")
    oh, ow = [int(v) for v in ctx.attr("pooled_size")]
    ptype = ctx.attr("pooling_type", "avg")
    N, C, H, W = x.shape
    if H % oh or W % ow:
        raise ValueError(
            f"adaptive_pool2d: input {H}x{W} not divisible by output "
            f"{oh}x{ow} (uneven adaptive bins are not static-shaped)")
    r = x.reshape(N, C, oh, H // oh, ow, W // ow)
    out = r.max(axis=(3, 5)) if ptype == "max" else r.mean(axis=(3, 5))
    return {"Out": out.astype(x.dtype)}


@register_op("pixel_shuffle")
def pixel_shuffle(ctx: ExecContext):
    x = ctx.input("X")
    u = int(ctx.attr("upscale_factor"))
    N, C, H, W = x.shape
    out = x.reshape(N, C // (u * u), u, u, H, W)
    out = out.transpose(0, 1, 4, 2, 5, 3).reshape(N, C // (u * u),
                                                  H * u, W * u)
    return {"Out": out}


@register_op("shuffle_channel")
def shuffle_channel(ctx: ExecContext):
    x = ctx.input("X")
    g = int(ctx.attr("group"))
    N, C, H, W = x.shape
    out = x.reshape(N, g, C // g, H, W).transpose(0, 2, 1, 3, 4)
    return {"Out": out.reshape(N, C, H, W)}


@register_op("space_to_depth")
def space_to_depth(ctx: ExecContext):
    x = ctx.input("X")
    b = int(ctx.attr("blocksize"))
    N, C, H, W = x.shape
    out = x.reshape(N, C, H // b, b, W // b, b)
    out = out.transpose(0, 3, 5, 1, 2, 4).reshape(N, C * b * b, H // b, W // b)
    return {"Out": out}


@register_op("temporal_shift")
def temporal_shift(ctx: ExecContext):
    """reference temporal_shift_op.*: [N*T, C, H, W], shift 1/shift_ratio of
    channels one step back in time, the same share forward, rest static."""
    x = ctx.input("X")
    T = int(ctx.attr("seg_num"))
    ratio = float(ctx.attr("shift_ratio", 0.25))
    NT, C, H, W = x.shape
    N = NT // T
    c1 = int(C * ratio)
    c2 = int(C * 2 * ratio)
    xr = x.reshape(N, T, C, H, W)
    back = jnp.concatenate([xr[:, 1:, :c1], jnp.zeros_like(xr[:, :1, :c1])], 1)
    fwd = jnp.concatenate([jnp.zeros_like(xr[:, :1, c1:c2]),
                           xr[:, :-1, c1:c2]], 1)
    out = jnp.concatenate([back, fwd, xr[:, :, c2:]], axis=2)
    return {"Out": out.reshape(NT, C, H, W)}


@register_op("maxout")
def maxout(ctx: ExecContext):
    x = ctx.input("X")
    g = int(ctx.attr("groups"))
    N, C, H, W = x.shape
    return {"Out": x.reshape(N, C // g, g, H, W).max(axis=2)}


@register_op("lrn")
def lrn(ctx: ExecContext):
    """reference lrn_op.*: local response normalization across channels."""
    x = ctx.input("X")
    n = int(ctx.attr("n", 5))
    k = float(ctx.attr("k", 1.0))
    alpha = float(ctx.attr("alpha", 1e-4))
    beta = float(ctx.attr("beta", 0.75))
    sq = jnp.square(x)
    half = n // 2
    pads = ((0, 0), (half, n - 1 - half), (0, 0), (0, 0))
    acc = jax.lax.reduce_window(sq, 0.0, jax.lax.add, (1, n, 1, 1),
                                (1, 1, 1, 1), pads)
    mid = (k + alpha * acc) ** beta
    return {"Out": (x / mid).astype(x.dtype), "MidOut": mid}


@register_op("affine_channel")
def affine_channel(ctx: ExecContext):
    x = ctx.input("X")
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    sh = [1, -1] + [1] * (x.ndim - 2)
    return {"Out": x * scale.reshape(sh) + bias.reshape(sh)}


@register_op("multiplex")
def multiplex(ctx: ExecContext):
    """reference multiplex_op.*: row-wise select among N input tensors by
    per-row index."""
    ids = ctx.input("Ids").reshape(-1).astype(jnp.int32)
    xs = jnp.stack([x for x in ctx.inputs("X") if x is not None])  # [K,B,...]
    rows = jnp.arange(xs.shape[1])
    return {"Out": xs[ids, rows]}


@register_op("crop")
def crop(ctx: ExecContext):
    x = ctx.input("X")
    shape = [int(s) for s in ctx.attr("shape")]
    offsets = [int(o) for o in ctx.attr("offsets", [0] * x.ndim)]
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return {"Out": x[idx]}


@register_op("pad_constant_like")
def pad_constant_like(ctx: ExecContext):
    x, y = ctx.input("X"), ctx.input("Y")
    val = float(ctx.attr("pad_value", 0.0))
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": jnp.pad(y, pads, constant_values=val)}


@register_op("unfold")
def unfold(ctx: ExecContext):
    """reference unfold_op.* (im2col as an op): [N, C, H, W] ->
    [N, C*kh*kw, L]."""
    x = ctx.input("X")
    kh, kw = [int(v) for v in ctx.attr("kernel_sizes")]
    sh, sw = [int(v) for v in ctx.attr("strides", [1, 1])]
    ph, pw = [int(v) for v in ctx.attr("paddings", [0, 0])][:2]
    dh, dw = [int(v) for v in ctx.attr("dilations", [1, 1])]
    N, C, H, W = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    oh = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            patch = xp[:, :, i * dh:i * dh + sh * oh:sh,
                       j * dw:j * dw + sw * ow:sw]
            cols.append(patch)
    out = jnp.stack(cols, axis=2)  # [N, C, kh*kw, oh, ow]
    return {"Y": out.reshape(N, C * kh * kw, oh * ow)}


@register_op("grid_sampler")
def grid_sampler(ctx: ExecContext):
    """reference grid_sampler_op.*: bilinear sampling of X [N,C,H,W] at
    Grid [N,Ho,Wo,2] normalized coords (align_corners=True)."""
    x = ctx.input("X").astype(jnp.float32)
    grid = ctx.input("Grid").astype(jnp.float32)
    N, C, H, W = x.shape
    gx = (grid[..., 0] + 1) * (W - 1) / 2
    gy = (grid[..., 1] + 1) * (H - 1) / 2

    def sample(img, gx, gy):
        # out-of-bound corners contribute ZERO (reference grid_sampler_op.h
        # GetGridPointValue isInBound), not a clamped border value
        x0f, y0f = jnp.floor(gx), jnp.floor(gy)
        corners = []
        for dy in (0, 1):
            for dx in (0, 1):
                cx_, cy_ = x0f + dx, y0f + dy
                inb = (cx_ >= 0) & (cx_ <= W - 1) & (cy_ >= 0) & (cy_ <= H - 1)
                xi = jnp.clip(cx_, 0, W - 1).astype(jnp.int32)
                yi = jnp.clip(cy_, 0, H - 1).astype(jnp.int32)
                wgt = ((1 - jnp.abs(gx - cx_)) * (1 - jnp.abs(gy - cy_)))
                corners.append(jnp.where(inb, wgt, 0.0) * img[:, yi, xi])
        return corners[0] + corners[1] + corners[2] + corners[3]

    out = jax.vmap(sample)(x, gx, gy)
    return {"Output": out}


@register_op("bilinear_tensor_product")
def bilinear_tensor_product(ctx: ExecContext):
    """reference bilinear_tensor_product_op.*: out[b,k] = x[b] W[k] y[b]."""
    x, y, w = ctx.input("X"), ctx.input("Y"), ctx.input("Weight")
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if ctx.has_input("Bias"):
        out = out + ctx.input("Bias")
    return {"Out": out}


@register_op("shard_index", grad="none")
def shard_index(ctx: ExecContext):
    x = ctx.input("X")
    index_num = int(ctx.attr("index_num"))
    nshards = int(ctx.attr("nshards"))
    shard_id = int(ctx.attr("shard_id"))
    ignore = int(ctx.attr("ignore_value", -1))
    per = (index_num + nshards - 1) // nshards
    local = x - shard_id * per
    ok = (x // per) == shard_id
    return {"Out": jnp.where(ok, local, jnp.full_like(x, ignore))}


@register_op("sampling_id", grad="none", needs_rng=True)
def sampling_id(ctx: ExecContext):
    """reference sampling_id_op.*: sample one category per row of a
    probability matrix."""
    p = ctx.input("X")
    return {"Out": jax.random.categorical(
        ctx.rng, jnp.log(jnp.maximum(p, 1e-20)), axis=-1).astype(jnp.int64)}
