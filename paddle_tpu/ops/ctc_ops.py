"""CTC loss (warpctc) on padded batches.

TPU-native replacement for the reference's warp-ctc binding
(/root/reference/paddle/fluid/operators/warpctc_op.h, which calls the
baidu-research warp-ctc CUDA/CPU library): the alpha recursion runs in log
space as one lax.scan over time — fixed shapes, fully batched, differentiable
by jax AD (so `warpctc_grad` falls out of the registry's derived vjp instead
of the library's hand-written backward).

Contract (padding design): Logits [B, T, V] raw (un-softmaxed) activations,
Label [B, S] int ids (padded with anything), LogitsLength [B], LabelLength
[B]. blank id is attr `blank` (default 0). Output Loss [B, 1].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import ExecContext, register_op

_NEG = -1e30


def _logsumexp2(a, b):
    m = jnp.maximum(a, b)
    m_safe = jnp.where(m <= _NEG, 0.0, m)
    return jnp.where(
        m <= _NEG, _NEG,
        m_safe + jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe)))


def _logsumexp3(a, b, c):
    return _logsumexp2(_logsumexp2(a, b), c)


@register_op("warpctc")
def warpctc(ctx: ExecContext):
    logits = ctx.input("Logits")
    label = ctx.input("Label").astype(jnp.int32)
    lg_len = ctx.input("LogitsLength")
    lb_len = ctx.input("LabelLength")
    blank = int(ctx.attr("blank", 0))
    norm_by_times = bool(ctx.attr("norm_by_times", False))

    B, T, V = logits.shape
    S = label.shape[1]
    lg_len = (jnp.full((B,), T, jnp.int32) if lg_len is None
              else lg_len.reshape(-1).astype(jnp.int32))
    lb_len = (jnp.full((B,), S, jnp.int32) if lb_len is None
              else lb_len.reshape(-1).astype(jnp.int32))

    logp = jax.nn.log_softmax(logits, axis=-1)           # [B, T, V]

    # extended sequence l' = [blank, l1, blank, l2, ..., blank]; 2S+1 slots
    L = 2 * S + 1
    pos = jnp.arange(L)
    lbl_idx = (pos - 1) // 2
    ext = jnp.where(pos % 2 == 1,
                    jnp.take_along_axis(
                        label, jnp.broadcast_to(
                            jnp.clip(lbl_idx, 0, S - 1)[None, :], (B, L)),
                        axis=1),
                    blank)                                # [B, L]
    ext_len = 2 * lb_len + 1                              # [B]

    # skip connection allowed when ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :L]
    can_skip = (pos[None, :] % 2 == 1) & (ext != ext_m2)  # [B, L]

    def emit(t):
        return jnp.take_along_axis(logp[:, t], ext, axis=1)  # [B, L]

    alpha0 = jnp.full((B, L), _NEG)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    first_lbl = jnp.take_along_axis(logp[:, 0], ext[:, 1:2], axis=1)[:, 0]
    alpha0 = alpha0.at[:, 1].set(jnp.where(lb_len > 0, first_lbl, _NEG))

    def step(alpha, t):
        a_prev = alpha
        a_m1 = jnp.pad(a_prev, ((0, 0), (1, 0)),
                       constant_values=_NEG)[:, :L]
        a_m2 = jnp.pad(a_prev, ((0, 0), (2, 0)),
                       constant_values=_NEG)[:, :L]
        a = _logsumexp3(a_prev, a_m1,
                        jnp.where(can_skip, a_m2, _NEG)) + emit(t)
        # frames beyond a sample's logits length keep the old alpha
        live = (t < lg_len)[:, None]
        a = jnp.where(live, a, a_prev)
        return a, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))

    # total prob = alpha[ext_len-1] + alpha[ext_len-2]
    last = jnp.take_along_axis(alpha, (ext_len - 1)[:, None], axis=1)[:, 0]
    last2 = jnp.take_along_axis(
        alpha, jnp.maximum(ext_len - 2, 0)[:, None], axis=1)[:, 0]
    ll = _logsumexp2(last, jnp.where(ext_len >= 2, last2, _NEG))
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(lg_len.astype(loss.dtype), 1)
    return {"Loss": loss[:, None].astype(logits.dtype)}
