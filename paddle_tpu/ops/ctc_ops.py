"""CTC loss (warpctc) on padded batches.

TPU-native replacement for the reference's warp-ctc binding
(/root/reference/paddle/fluid/operators/warpctc_op.h, which calls the
baidu-research warp-ctc CUDA/CPU library): the alpha recursion runs in log
space as one lax.scan over time — fixed shapes, fully batched, differentiable
by jax AD (so `warpctc_grad` falls out of the registry's derived vjp instead
of the library's hand-written backward).

Contract (padding design): Logits [B, T, V] raw (un-softmaxed) activations,
Label [B, S] int ids (padded with anything), LogitsLength [B], LabelLength
[B]. blank id is attr `blank` (default 0). Output Loss [B, 1].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import ExecContext, register_op

from ..core.types import np_feed_dtype

# the runtime's index dtype: int32 under x64-off jax (an astype to
# int64 would warn-and-truncate on every trace), int64 when enabled
_INDEX_DTYPE = np_feed_dtype("int64")

_NEG = -1e30


def _logsumexp2(a, b):
    m = jnp.maximum(a, b)
    m_safe = jnp.where(m <= _NEG, 0.0, m)
    return jnp.where(
        m <= _NEG, _NEG,
        m_safe + jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe)))


def _logsumexp3(a, b, c):
    return _logsumexp2(_logsumexp2(a, b), c)


@register_op("warpctc")
def warpctc(ctx: ExecContext):
    logits = ctx.input("Logits")
    label = ctx.input("Label").astype(jnp.int32)
    lg_len = ctx.input("LogitsLength")
    lb_len = ctx.input("LabelLength")
    blank = int(ctx.attr("blank", 0))
    norm_by_times = bool(ctx.attr("norm_by_times", False))

    B, T, V = logits.shape
    S = label.shape[1]
    lg_len = (jnp.full((B,), T, jnp.int32) if lg_len is None
              else lg_len.reshape(-1).astype(jnp.int32))
    lb_len = (jnp.full((B,), S, jnp.int32) if lb_len is None
              else lb_len.reshape(-1).astype(jnp.int32))

    logp = jax.nn.log_softmax(logits, axis=-1)           # [B, T, V]

    # extended sequence l' = [blank, l1, blank, l2, ..., blank]; 2S+1 slots
    L = 2 * S + 1
    pos = jnp.arange(L)
    lbl_idx = (pos - 1) // 2
    ext = jnp.where(pos % 2 == 1,
                    jnp.take_along_axis(
                        label, jnp.broadcast_to(
                            jnp.clip(lbl_idx, 0, S - 1)[None, :], (B, L)),
                        axis=1),
                    blank)                                # [B, L]
    ext_len = 2 * lb_len + 1                              # [B]

    # skip connection allowed when ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :L]
    can_skip = (pos[None, :] % 2 == 1) & (ext != ext_m2)  # [B, L]

    def emit(t):
        return jnp.take_along_axis(logp[:, t], ext, axis=1)  # [B, L]

    alpha0 = jnp.full((B, L), _NEG)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    first_lbl = jnp.take_along_axis(logp[:, 0], ext[:, 1:2], axis=1)[:, 0]
    alpha0 = alpha0.at[:, 1].set(jnp.where(lb_len > 0, first_lbl, _NEG))

    def step(alpha, t):
        a_prev = alpha
        a_m1 = jnp.pad(a_prev, ((0, 0), (1, 0)),
                       constant_values=_NEG)[:, :L]
        a_m2 = jnp.pad(a_prev, ((0, 0), (2, 0)),
                       constant_values=_NEG)[:, :L]
        a = _logsumexp3(a_prev, a_m1,
                        jnp.where(can_skip, a_m2, _NEG)) + emit(t)
        # frames beyond a sample's logits length keep the old alpha
        live = (t < lg_len)[:, None]
        a = jnp.where(live, a, a_prev)
        return a, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))

    # total prob = alpha[ext_len-1] + alpha[ext_len-2]
    last = jnp.take_along_axis(alpha, (ext_len - 1)[:, None], axis=1)[:, 0]
    last2 = jnp.take_along_axis(
        alpha, jnp.maximum(ext_len - 2, 0)[:, None], axis=1)[:, 0]
    ll = _logsumexp2(last, jnp.where(ext_len >= 2, last2, _NEG))
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(lg_len.astype(loss.dtype), 1)
    return {"Loss": loss[:, None].astype(logits.dtype)}


@register_op("ctc_align", grad="none")
def ctc_align(ctx: ExecContext):
    """CTC greedy decode (reference ctc_align_op.*, layers.ctc_greedy_decoder
    after the argmax): merge repeats, drop blanks. Input [B, T] int tokens
    (already argmaxed) + InputLength [B] -> Output [B, T] left-compacted,
    padded with -1 (the reference's empty-result convention), OutputLength
    [B]. The data-dependent compaction is an argsort on (dropped, position)
    keys — static shapes."""
    x = ctx.input("Input")
    if x.ndim == 3 and x.shape[-1] == 1:
        x = x.reshape(x.shape[:-1])
    x = x.astype(jnp.int32)
    blank = int(ctx.attr("blank", 0))
    B, T = x.shape
    if ctx.has_input("InputLength"):
        ln = ctx.input("InputLength").reshape(-1).astype(jnp.int32)
    else:
        ln = jnp.full((B,), T, jnp.int32)
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32), x[:, :-1]],
                           axis=1)
    keep = (x != blank) & (x != prev) & (t < ln[:, None])
    # stable sort: kept tokens (key 0) first, in time order
    order = jnp.argsort(jnp.where(keep, 0, 1), axis=1, stable=True)
    compacted = jnp.take_along_axis(x, order, axis=1)
    n_keep = keep.sum(axis=1).astype(jnp.int32)
    pad = jnp.asarray(int(ctx.attr("padding_value", -1)), compacted.dtype)
    out = jnp.where(t < n_keep[:, None], compacted, pad)
    return {"Output": out.astype(_INDEX_DTYPE),
            "OutputLength": n_keep.astype(_INDEX_DTYPE)}


@register_op("edit_distance", grad="none")
def edit_distance(ctx: ExecContext):
    """Levenshtein distance (reference edit_distance_op.*): Hyps [B, Th] int
    + HypsLength [B], Refs [B, Tr] + RefsLength [B] -> Out [B, 1] float
    distances (normalized by ref length when attr normalized) and
    SequenceNum [1]. DP over the hyp axis as one lax.scan; each scan step
    updates the full ref-axis row vectorized over the batch."""
    hyp = ctx.input("Hyps")
    ref = ctx.input("Refs")
    if hyp.ndim == 3 and hyp.shape[-1] == 1:
        hyp = hyp.reshape(hyp.shape[:-1])
    if ref.ndim == 3 and ref.shape[-1] == 1:
        ref = ref.reshape(ref.shape[:-1])
    hyp = hyp.astype(jnp.int32)
    ref = ref.astype(jnp.int32)
    B, Th = hyp.shape
    Tr = ref.shape[1]
    if ctx.has_input("HypsLength"):
        hl = ctx.input("HypsLength").reshape(-1).astype(jnp.int32)
    else:
        hl = jnp.full((B,), Th, jnp.int32)
    if ctx.has_input("RefsLength"):
        rl = ctx.input("RefsLength").reshape(-1).astype(jnp.int32)
    else:
        rl = jnp.full((B,), Tr, jnp.int32)

    j = jnp.arange(Tr + 1, dtype=jnp.int32)[None, :]          # [1, Tr+1]
    row0 = jnp.broadcast_to(j, (B, Tr + 1)).astype(jnp.float32)

    def step(row, i):
        # row: D[i-1, :]; compute D[i, :]
        sub_cost = (hyp[:, i - 1][:, None] != ref).astype(jnp.float32)
        # candidates for D[i, j]: deletion D[i-1, j] + 1;
        # substitution D[i-1, j-1] + cost; insertion D[i, j-1] + 1 (scan
        # along j via associative min is overkill — do the standard
        # two-candidate pass then one cummin-style fix-up)
        del_ = row + 1.0
        sub = row[:, :-1] + sub_cost
        base = jnp.concatenate(
            [row[:, :1] + 1.0, jnp.minimum(del_[:, 1:], sub)], axis=1)
        # insertion closure: D[i,j] = min_k (base[i,k] + (j-k)) for k<=j —
        # prefix min of (base - j) plus j (associative_scan, O(log Tr))
        shifted = jax.lax.associative_scan(
            jnp.minimum, base - j.astype(jnp.float32), axis=1)
        newrow = jnp.minimum(base, shifted + j.astype(jnp.float32))
        # beyond this hyp's length the row must stay frozen
        newrow = jnp.where((i <= hl)[:, None], newrow, row)
        return newrow, None

    last, _ = jax.lax.scan(step, row0, jnp.arange(1, Th + 1, dtype=jnp.int32))
    dist = jnp.take_along_axis(last, rl[:, None].astype(jnp.int32), axis=1)
    if bool(ctx.attr("normalized", True)):
        dist = dist / jnp.maximum(rl[:, None].astype(jnp.float32), 1.0)
    return {"Out": dist.astype(jnp.float32),
            "SequenceNum": jnp.asarray([B], _INDEX_DTYPE)}


@register_op("chunk_eval", grad="none", host=True)
def chunk_eval(ctx: ExecContext):
    """Chunking precision/recall/F1 (reference chunk_eval_op.*): decode
    IOB/IOE/IOBES/plain tag sequences into typed chunks and count matches.
    Host op — the chunk walk is irregular control flow the reference also
    runs on CPU; metrics never sit on the training path."""
    import numpy as np

    inf = np.asarray(ctx.input("Inference")).reshape(
        ctx.input("Inference").shape[0], -1).astype(_INDEX_DTYPE)
    lab = np.asarray(ctx.input("Label")).reshape(inf.shape[0], -1).astype(
        np.int64)
    scheme = ctx.attr("chunk_scheme", "IOB")
    n_types = int(ctx.attr("num_chunk_types"))
    excluded = set(ctx.attr("excluded_chunk_types", []) or [])
    B, T = inf.shape
    if ctx.has_input("SeqLength"):
        ln = np.asarray(ctx.input("SeqLength")).reshape(-1).astype(_INDEX_DTYPE)
    else:
        ln = np.full((B,), T, np.int64)

    tag_n = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]

    def decode(seq):
        """tag id -> (type, pos); pos within scheme. Returns set of
        (type, start, end) chunks."""
        chunks = []
        start = None
        cur_type = None
        for i, v in enumerate(seq):
            if v < 0 or v >= n_types * tag_n:
                t_, p = None, None
            else:
                t_, p = int(v) // tag_n, int(v) % tag_n
            if scheme == "plain":
                begin = t_ is not None and t_ != cur_type
                end_prev = cur_type is not None and t_ != cur_type
            elif scheme == "IOB":
                begin = t_ is not None and (p == 0 or t_ != cur_type)
                end_prev = cur_type is not None and (t_ is None or p == 0
                                                    or t_ != cur_type)
            elif scheme == "IOE":
                begin = t_ is not None and (start is None or t_ != cur_type)
                end_prev = cur_type is not None and t_ != cur_type
            else:  # IOBES: pos 0=B 1=I 2=E 3=S
                begin = t_ is not None and p in (0, 3)
                end_prev = cur_type is not None and (t_ is None
                                                    or p in (0, 3))
            if end_prev and start is not None:
                chunks.append((cur_type, start, i - 1))
                start, cur_type = None, None
            if begin:
                start, cur_type = i, t_
            if scheme == "IOE" and t_ is not None and p == 1:
                chunks.append((t_, start if start is not None else i, i))
                start, cur_type = None, None
            if scheme == "IOBES" and t_ is not None and p in (2, 3):
                chunks.append((t_, start if start is not None else i, i))
                start, cur_type = None, None
        if start is not None:
            chunks.append((cur_type, start, len(seq) - 1))
        return {c for c in chunks if c[0] not in excluded}

    n_inf = n_lab = n_correct = 0
    for b in range(B):
        ic = decode(inf[b, :ln[b]])
        lc = decode(lab[b, :ln[b]])
        n_inf += len(ic)
        n_lab += len(lc)
        n_correct += len(ic & lc)
    p = n_correct / n_inf if n_inf else 0.0
    r = n_correct / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    return {"Precision": np.asarray([p], np.float32),
            "Recall": np.asarray([r], np.float32),
            "F1-Score": np.asarray([f1], np.float32),
            "NumInferChunks": np.asarray([n_inf], np.int64),
            "NumLabelChunks": np.asarray([n_lab], np.int64),
            "NumCorrectChunks": np.asarray([n_correct], np.int64)}
