"""Large-vocabulary loss ops: NCE and hierarchical sigmoid.

TPU-native re-design of:
  * /root/reference/paddle/fluid/operators/nce_op.h (sampled softmax-free
    noise-contrastive estimation; uniform/log-uniform samplers)
  * /root/reference/paddle/fluid/operators/hierarchical_sigmoid_op.h +
    math/matrix_bit_code.h SimpleCode (complete-binary-tree path codes:
    encoding of class c is c + num_classes; weight index = prefix >> (bit+1)
    - 1, path bit = suffix bit)

Both are fixed-shape and batched for the MXU: negatives are drawn once per
step with the counter-based PRNG, path tables are computed with static
max-depth and masked, and the per-node dot products run as one gather +
batched matmul-ish reduction instead of the reference's per-row loops.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .registry import ExecContext, register_grad_compute, register_op


def _nce_loss(x, label, w, b, samples, C, k, sampler):
    """Differentiable NCE objective given the drawn negatives `samples`
    [B, k] (reference nce_op.h:71: logistic vs the noise distribution)."""
    label = label.reshape(-1)

    if sampler == 1:
        # P(c) = log((c+2)/(c+1)) / log(C+1)  =>  log P needs the OUTER log
        logq = (jnp.log(jnp.log((samples + 2.0) / (samples + 1.0)))
                - math.log(math.log(C + 1)))
        pos_q = (jnp.log(jnp.log((label + 2.0) / (label + 1.0)))
                 - math.log(math.log(C + 1)))
    else:
        logq = jnp.full(samples.shape, -math.log(C))
        pos_q = jnp.full(label.shape, -math.log(C))

    def logits_of(idx):
        wi = w[idx]                       # [..., D]
        out = jnp.einsum("bd,b...d->b...", x, wi)
        if b is not None:
            out = out + b.reshape(-1)[idx]
        return out

    pos_s = logits_of(label[:, None])[:, 0] - (pos_q + math.log(k))
    neg_s = logits_of(samples) - (logq + math.log(k))
    return (jax.nn.softplus(-pos_s)
            + jax.nn.softplus(neg_s).sum(axis=1))[:, None].astype(x.dtype)


@register_op("nce", needs_rng=True)
def nce(ctx: ExecContext):
    """Noise-contrastive estimation loss (reference nce_op.h:71 forward).

    Inputs: Input [B, D], Label [B, 1] int, Weight [C, D], Bias [C]?
    Attrs: num_total_classes, num_neg_samples, sampler (0=uniform,
    1=log_uniform). Outputs: Cost [B, 1] and the drawn SampleLabels [B, k]
    (the reference also emits its samples; the grad kernel replays from them
    so forward and backward see the SAME negatives).
    """
    x = ctx.input("Input")
    label = ctx.input("Label")
    w, b = ctx.input("Weight"), ctx.input("Bias")
    C = int(ctx.attr("num_total_classes"))
    k = int(ctx.attr("num_neg_samples", 5))
    sampler = int(ctx.attr("sampler", 0))
    B = x.shape[0]

    if sampler == 1:
        # log-uniform (Zipfian) via inverse CDF: P(c) ∝ log((c+2)/(c+1))
        u = jax.random.uniform(ctx.rng, (B, k))
        neg = (jnp.exp(u * math.log(C + 1)) - 1).astype(jnp.int32)
        neg = jnp.clip(neg, 0, C - 1)
    else:
        neg = jax.random.randint(ctx.rng, (B, k), 0, C)

    cost = _nce_loss(x, label, w, b, neg, C, k, sampler)
    return {"Cost": cost, "SampleLabels": neg.astype(_INDEX_DTYPE)}


@register_grad_compute("nce")
def nce_grad(ctx: ExecContext):
    """Replay the objective with the SAVED samples under jax.vjp."""
    x = ctx.input("Input")
    label = ctx.input("Label")
    w, b = ctx.input("Weight"), ctx.input("Bias")
    samples = ctx.input("SampleLabels").astype(jnp.int32)
    dcost = ctx.input("Cost@GRAD")
    C = int(ctx.attr("num_total_classes"))
    k = int(ctx.attr("num_neg_samples", 5))
    sampler = int(ctx.attr("sampler", 0))

    if b is None:
        fn = lambda x_, w_: _nce_loss(x_, label, w_, None, samples, C, k,
                                      sampler)
        _, vjp = jax.vjp(fn, x, w)
        dx, dw = vjp(dcost)
        return {"Input@GRAD": dx, "Weight@GRAD": dw}
    fn = lambda x_, w_, b_: _nce_loss(x_, label, w_, b_, samples, C, k,
                                      sampler)
    _, vjp = jax.vjp(fn, x, w, b)
    dx, dw, db = vjp(dcost)
    return {"Input@GRAD": dx, "Weight@GRAD": dw, "Bias@GRAD": db}


def nce_grad_maker(op, block, no_grad_set=frozenset()):
    from ..framework import grad_var_name

    ins = {
        "Input": op.input("Input"),
        "Label": op.input("Label"),
        "Weight": op.input("Weight"),
        "SampleLabels": op.output("SampleLabels"),
        "Cost@GRAD": [grad_var_name(op.output("Cost")[0])],
    }
    outs = {}
    for slot in ("Input", "Weight", "Bias"):
        names = op.input(slot)
        if names and names[0] not in no_grad_set:
            outs[slot + "@GRAD"] = [grad_var_name(names[0])]
    if op.input("Bias"):
        ins["Bias"] = op.input("Bias")
    if not outs:
        return []
    return [{"type": "nce_grad", "inputs": ins, "outputs": outs,
             "attrs": dict(op.attrs)}]


from .registry import get_op_def  # noqa: E402

from ..core.types import np_feed_dtype

# the runtime's index dtype: int32 under x64-off jax (an astype to
# int64 would warn-and-truncate on every trace), int64 when enabled
_INDEX_DTYPE = np_feed_dtype("int64")

get_op_def("nce").grad_maker = nce_grad_maker


@register_op("hierarchical_sigmoid")
def hierarchical_sigmoid(ctx: ExecContext):
    """Complete-binary-tree hsigmoid (reference hierarchical_sigmoid_op.h +
    SimpleCode). Inputs: X [B, D], Label [B, 1], W [C-1, D], Bias [C-1]?
    Attr num_classes=C. Output: Out [B, 1] loss; PreOut kept for parity.
    """
    x = ctx.input("X")
    label = ctx.input("Label").reshape(-1).astype(jnp.int32)
    w = ctx.input("W")
    bias = ctx.input("Bias")
    C = int(ctx.attr("num_classes"))
    # SimpleCode: c_ = label + C; levels below the MSB are the path
    max_len = max(1, int(math.ceil(math.log2(C))) + 1)
    c = label + C                                        # [B]
    bits = jnp.arange(max_len)
    # get_length = FindLastSet(c)-1 = floor(log2(c))
    length = jnp.floor(jnp.log2(c.astype(jnp.float32))).astype(jnp.int32)
    valid = bits[None, :] < length[:, None]              # [B, L]
    idx = jnp.where(valid, (c[:, None] >> (bits[None, :] + 1)) - 1, 0)
    bit = jnp.where(valid, (c[:, None] >> bits[None, :]) & 1, 0)

    wn = w[idx]                                          # [B, L, D]
    pre = jnp.einsum("bd,bld->bl", x, wn)
    if bias is not None:
        pre = pre + bias.reshape(-1)[idx]
    # sigmoid cross entropy per node with target = bit
    per_node = jax.nn.softplus(pre) - bit * pre
    loss = jnp.where(valid, per_node, 0.0).sum(axis=1)
    return {"Out": loss[:, None].astype(x.dtype),
            "PreOut": pre.astype(x.dtype)}


@register_op("gaussian_random_batch_size_like", grad="none", needs_rng=True)
def gaussian_random_batch_size_like(ctx: ExecContext):
    """reference gaussian_random_batch_size_like_op.cc: normal(mean, std)
    with the batch dim taken from Input."""
    from ..core.types import np_dtype

    x = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    shape[int(ctx.attr("output_dim_idx", 0))] = \
        x.shape[int(ctx.attr("input_dim_idx", 0))]
    mean = float(ctx.attr("mean", 0.0))
    std = float(ctx.attr("std", 1.0))
    dt = np_dtype(ctx.attr("dtype", "float32"))
    return {"Out": mean + std * jax.random.normal(
        ctx.rng, tuple(int(s) for s in shape), dt)}


def _log_uniform_prob(ids, range_max):
    """LogUniformSampler class probability (reference math/sampler.cc):
    p(c) = log((c+2)/(c+1)) / log(range_max+1)."""
    c = ids.astype(jnp.float32)
    return jnp.log((c + 2.0) / (c + 1.0)) / jnp.log(float(range_max) + 1.0)


def _sample_logits_grad_maker(op, block, no_grad_set=frozenset()):
    """Custom maker: the backward scatter needs the forward's Samples
    OUTPUT (which the default mirror-slots maker never passes)."""
    from ..framework import grad_var_name

    lname = op.inputs["Logits"][0]
    if lname in no_grad_set:
        return []
    return [{
        "type": "sample_logits_grad",
        "inputs": {
            "Logits": list(op.inputs["Logits"]),
            "Samples": list(op.outputs["Samples"]),
            "SampledLogits@GRAD":
                [grad_var_name(op.outputs["SampledLogits"][0])],
        },
        "outputs": {"Logits@GRAD": [grad_var_name(lname)]},
        "attrs": dict(op.attrs),
    }]


@register_op("sample_logits", needs_rng=True, grad=_sample_logits_grad_maker)
def sample_logits(ctx: ExecContext):
    """reference sample_logits_op.*: subsample the softmax vocabulary.
    Logits [B, V], Labels [B, NT] -> Samples [B, NT+S] (true labels first,
    then S log-uniform draws), SampledLogits [B, NT+S] with each logit
    adjusted by -log(expected_prob) (the sampled-softmax correction), and
    SampledLabel [B, NT] = arange(NT). remove_accidental_hits pushes
    negatives that collide with a true label to -inf. Sampling is
    with-replacement log-uniform (the reference's unique-draw retry loop is
    a host pattern; collisions are rare at CTR/NLP vocab sizes)."""
    logits = ctx.input("Logits")
    labels = ctx.input("Labels").astype(jnp.int32)
    if labels.ndim == 1:
        labels = labels[:, None]
    B, V = logits.shape
    NT = labels.shape[1]
    S = int(ctx.attr("num_samples"))
    u = jax.random.uniform(ctx.rng, (B, S), jnp.float32, 1e-9, 1.0)
    draws = (jnp.exp(u * jnp.log(float(V) + 1.0)) - 1.0).astype(jnp.int32)
    draws = jnp.clip(draws, 0, V - 1)
    samples = jnp.concatenate([labels, draws], axis=1)      # [B, NT+S]
    q = _log_uniform_prob(samples, V)
    picked = jnp.take_along_axis(logits, samples, axis=1)
    adjusted = picked - jnp.log(q + 1e-20)
    if bool(ctx.attr("remove_accidental_hits", True)):
        hit = (draws[:, :, None] == labels[:, None, :]).any(-1)  # [B, S]
        pad = jnp.concatenate(
            [jnp.zeros((B, NT), bool), hit], axis=1)
        adjusted = jnp.where(pad, adjusted - 1e20, adjusted)
    return {"Samples": samples.astype(_INDEX_DTYPE),
            "SampledLogits": adjusted.astype(logits.dtype),
            "SampledLabel": jnp.broadcast_to(
                jnp.arange(NT, dtype=_INDEX_DTYPE)[None, :], (B, NT)),
            "Probabilities": q.astype(logits.dtype)}


@register_grad_compute("sample_logits")
def sample_logits_grad(ctx: ExecContext):
    """dLogits = scatter of dSampledLogits back to the sampled columns."""
    logits = ctx.input("Logits")
    samples = ctx.input("Samples").astype(jnp.int32)
    g = ctx.input("SampledLogits@GRAD")
    if g is None:
        return {"Logits@GRAD": jnp.zeros_like(logits)}
    B = logits.shape[0]
    bidx = jnp.broadcast_to(jnp.arange(B)[:, None], samples.shape)
    return {"Logits@GRAD": jnp.zeros_like(logits).at[bidx, samples].add(
        g.astype(logits.dtype))}
