"""Fused attention ops: Pallas flash attention + ring attention (SP).

The reference has no fused attention — its scaled_dot_product_attention
(nets.py:345) materializes the full [B,nh,S,S] score matrix through separate
matmul/softmax/dropout ops. On TPU one fused op boundary for the whole
QK^T -> softmax -> PV block is the single biggest transformer win
(SURVEY.md §2.3), so:

  * `fused_attention` dispatches per measured winner (PERF.md): at train
    sizes (S <= 1024) the jnp einsum composition — XLA's attention fusion
    with fp32 softmax statistics, recompute-in-backward via the derived
    vjp; with `use_pallas` the hand-tuned short-seq Pallas kernel
    (ops/pallas_kernels/attention.py, O(S) residuals); at S > 1024 jax's
    bundled flash-attention kernel (the only O(S)-memory option there).
  * `ring_attention` is the sequence-parallel form: K/V shards rotate around
    the `sp` mesh axis via collective-permute while each device keeps a
    running online-softmax merge (m, l, acc). Pure differentiable jnp +
    lax.ppermute — XLA overlaps the permute with the local block math over
    ICI. Used under shard_map (CompiledProgram.with_collective) or inside
    GSPMD manual regions; with no axis bound it degrades to fused_attention.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .collective_ops import _axis
from .registry import ExecContext, register_op

_NEG_INF = -1e9


def _reference_attention(q, k, v, bias=None, causal=False, sm_scale=1.0):
    """Plain jnp attention, the numeric oracle (and the measured-fastest
    TPU path at train sizes). q,k,v: [B, nh, S, dh]. Softmax statistics are
    fp32 even for bf16 operands (the AMP white-list invariant); XLA fuses
    the boundary casts so this costs no extra HBM traffic."""
    # scores materialize in the operand dtype (bf16 under AMP — half the
    # HBM bytes); the fp32 upcast happens inside the softmax so the
    # max/exp/sum statistics are fp32 yet XLA fuses the casts for free
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
    scores = scores.astype(jnp.float32)
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), jnp.bool_), sk - sq)
        scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _block_multiple_ok(s: int) -> bool:
    # the bundled kernel wants seq divisible by its block sizes (>=128 lanes)
    return s % 128 == 0


def _pallas_short_ok(q_shape, k_shape, bias) -> bool:
    from .pallas_kernels import attention as psa
    from .pallas_kernels import workbench

    return (workbench.runnable(psa)
            and psa.short_seq_supported(q_shape, k_shape, bias))


def _pallas_short128_ok(q_shape, k_shape, bias) -> bool:
    from .pallas_kernels import short_attention as s128
    from .pallas_kernels import workbench

    return (workbench.runnable(s128)
            and s128.short128_supported(q_shape, k_shape, bias))


def _flash_bundled_ok(q_shape, k_shape, dtype) -> bool:
    sq, sk = q_shape[2], k_shape[2]
    return (_on_tpu() and _block_multiple_ok(sq) and _block_multiple_ok(sk)
            and dtype != jnp.float64)


def attention_backend(q_shape, k_shape, dtype, bias=None, causal=False,
                      use_pallas=False):
    """Which kernel carries this attention shape. Returns (backend, tier)
    with backend in {"xla", "pallas_short", "flash_bundled"}.

    The analytic prior is the measured v5e dispatch rule (PERF.md): XLA's
    own attention fusion at train sizes, the hand-tuned short-seq Pallas
    kernel when the caller forces O(S) memory (`use_pallas`) and the shape
    qualifies, the bundled flash kernel past S=1024 where the [S,S] scores
    outgrow the chip. Under FLAGS_tuning_mode=consult a swept-DB entry for
    the exact (shape, dtype, device) overrides the rule — this is where the
    measured BENCH_r05 split (XLA wins at seq<=128, the Pallas kernel wins
    ~9% at s512) becomes a cache entry instead of a per-model flag. A
    swept backend the current build cannot execute is degraded at dispatch
    time (flash_attention), never obeyed blindly.

    The seq<=128 regime additionally carries the `pallas_short128` arm
    (pallas_kernels/short_attention.py — ISSUE 9): the analytic prior keeps
    XLA there (that is what r4/r5 measured), so the kernel engages only via
    a swept keep or FLAGS_attention_force_backend (the A/B harness lever,
    which precedes every tier and still degrades when un-runnable)."""
    from .. import flags as pt_flags

    B, nh, sq, dh = q_shape
    sk = k_shape[2]

    forced = str(pt_flags.get_flag("attention_force_backend")).strip()
    if forced:
        return forced, "forced"

    def analytic():
        if use_pallas and _pallas_short_ok(q_shape, k_shape, bias):
            return {"backend": "pallas_short"}
        # an O(S)-memory kernel is mandatory past S=1024 and honored
        # whenever the caller asked for one (`use_pallas`) but the
        # short-seq kernel's gate rejected the shape — falling to the
        # O(S^2) reference there would silently undo the flag's documented
        # purpose (memory-bound configs).
        if ((sq > 1024 or (use_pallas and sq > 512))
                and _flash_bundled_ok(q_shape, k_shape, dtype)):
            return {"backend": "flash_bundled"}
        return {"backend": "xla"}

    from .. import tuning

    if tuning.mode() == "off":
        return analytic()["backend"], "analytic"
    key = tuning.canonical_key(
        "attention", tuning.attention_key(B, nh, sq, sk, dh, causal),
        str(jnp.dtype(dtype)), tuning.device_kind())
    decision, tier = tuning.decide(
        "attention", key, prior=analytic, default={"backend": "xla"},
        validate=lambda dd: dd.get("backend") in ("xla", "pallas_short",
                                                  "pallas_short128",
                                                  "flash_bundled"))
    return decision.get("backend", "xla"), tier


def flash_attention(q, k, v, bias=None, causal=False, sm_scale=1.0,
                    use_pallas=False):
    """Dispatch per `attention_backend` (each branch measured on v5e,
    PERF.md):
      * "xla": the jnp einsum composition — XLA's own attention fusion is
        the fastest at S<=512 (beats both the bundled flash kernel and the
        custom short-seq Pallas kernel at train sizes);
      * "pallas_short": the hand-tuned short-seq kernel (O(S) memory with a
        no-residual fused backward — for memory-bound configs);
      * "flash_bundled": jax's bundled flash kernel (the only O(S) option
        once the [S,S] scores outgrow VMEM/HBM budgets).
    A swept-DB backend the current platform/shape cannot run (e.g. a Pallas
    verdict replayed off-TPU) degrades to the reference path here.
    """
    backend, _tier = attention_backend(q.shape, k.shape, q.dtype, bias,
                                       causal, use_pallas)
    if backend == "pallas_short" and _pallas_short_ok(q.shape, k.shape, bias):
        from .pallas_kernels import attention as psa

        return psa.short_seq_attention(q, k, v, causal=causal,
                                       sm_scale=float(sm_scale))
    if backend == "pallas_short128" and _pallas_short128_ok(
            q.shape, k.shape, bias):
        from .pallas_kernels import short_attention as s128

        return s128.short128_attention(q, k, v, causal=causal,
                                       sm_scale=float(sm_scale))
    if backend == "flash_bundled" and _flash_bundled_ok(q.shape, k.shape,
                                                        q.dtype):
        from jax.experimental.pallas.ops.tpu import flash_attention as fa

        return fa.flash_attention(q, k, v, ab=bias, causal=causal,
                                  sm_scale=float(sm_scale))
    return _reference_attention(q, k, v, bias, causal, sm_scale)


@register_op("fused_attention")
def fused_attention(ctx: ExecContext):
    """inputs: Q, K, V [B, nh, S, dh], optional Bias (broadcastable to
    [B, nh, Sq, Sk]); attrs: causal, sm_scale. Output: [B, nh, Sq, dh]."""
    q, k, v = ctx.input("Q"), ctx.input("K"), ctx.input("V")
    bias = ctx.input("Bias") if ctx.has_input("Bias") else None
    out = flash_attention(q, k, v, bias,
                          causal=ctx.attr("causal", False),
                          sm_scale=ctx.attr("sm_scale", 1.0),
                          use_pallas=ctx.attr("use_pallas", False))
    return {"Out": out.astype(q.dtype)}


# ---------------------------------------------------------------------------
# Paged KV-cache decode attention (the serving/ runtime's core op)
# ---------------------------------------------------------------------------


def _pallas_paged_ok(q_shape, pool_shape) -> bool:
    from .pallas_kernels import paged_attention as ppa

    return ((_on_tpu() or ppa.INTERPRET)
            and ppa.paged_supported(tuple(q_shape), tuple(pool_shape)))


def _shard_paged_shapes(q_shape, pool_shape, tp=1):
    """The PER-SHARD view of a paged decode shape under tp-way head
    sharding: GSPMD hands each shard nh/tp heads of BOTH the query and the
    pool, so the tuning key and every executability check must see the same
    nh/tp shapes — a verdict decided at one head count and dispatched at
    another is wrong in both directions."""
    tp = max(1, int(tp))
    B, nh, dh = q_shape
    q = (B, max(1, int(nh) // tp), dh)
    if pool_shape is None:
        return q, None
    num_pages, ps, p_nh, p_dh = pool_shape
    return q, (num_pages, ps, max(1, int(p_nh) // tp), p_dh)


def paged_attention_backend(batch, num_heads, kv_slots, head_dim, dtype,
                            pool_shape=None, tp=1):
    """Which kernel carries one ragged decode-attention shape (sq=1, sk =
    the padded slot count P*page_size). Returns (backend, tier) with backend
    in {"xla", "pallas_paged"}.

    Same three-tier contract as `attention_backend` (the PR 6 lever): the
    analytic prior prefers the Pallas paged kernel wherever it can run (the
    gather-free DMA path is the whole point of paging, arXiv:2604.15464),
    a swept DB entry for the exact (b, nh, 1, sk, dh) key overrides it —
    tools/tune.py's decode sweep writes those — and a swept backend the
    current build cannot execute degrades at dispatch, never obeyed blindly.

    tp > 1 (ISSUE 11): the op traces at the GLOBAL shape but under GSPMD
    each tp shard executes nh/tp heads, so the DB key is the PER-SHARD
    shape — exactly what tools/tune.py's head-sharded decode sweep records.
    """
    (batch, num_heads, head_dim), pool_shape = _shard_paged_shapes(
        (batch, num_heads, head_dim), pool_shape, tp)

    def analytic():
        if pool_shape is not None and _pallas_paged_ok(
                (batch, num_heads, head_dim), pool_shape):
            return {"backend": "pallas_paged"}
        return {"backend": "xla"}

    from .. import tuning
    from .registry import _DYN

    # build-time shape inference dry-runs the compute with the dynamic-batch
    # sentinel; that fake shape must not consult the DB nor be recorded as a
    # sweep candidate (it is not a real dispatch)
    if tuning.mode() == "off" or batch == _DYN:
        return analytic()["backend"], "analytic"
    key = tuning.canonical_key(
        "attention",
        tuning.attention_key(batch, num_heads, 1, kv_slots, head_dim, True),
        str(jnp.dtype(dtype)), tuning.device_kind())
    decision, tier = tuning.decide(
        "attention", key, prior=analytic, default={"backend": "xla"},
        validate=lambda dd: dd.get("backend") in ("xla", "pallas_paged"))
    return decision.get("backend", "xla"), tier


def _paged_attention_reference(q, k_pool, v_pool, page_table, kv_lens,
                               sm_scale=1.0):
    """XLA gather-based paged decode attention — the numeric oracle and the
    dispatch fallback. Gathers every row's pages into a dense
    [B, P*ps, nh, dh] view (XLA fuses the gather into the matmuls, but the
    materialized bytes still move); fp32 softmax statistics, slots past a
    row's kv_len masked with the framework-wide -1e9 convention so a padded
    row (kv_len 0) stays finite."""
    B, nh, dh = q.shape
    num_pages, ps = k_pool.shape[0], k_pool.shape[1]
    P = page_table.shape[1]
    pt = jnp.clip(page_table, 0, num_pages - 1)
    k = k_pool[pt].reshape(B, P * ps, nh, dh)
    v = v_pool[pt].reshape(B, P * ps, nh, dh)
    s = jnp.einsum("bhd,bkhd->bhk", q, k) * sm_scale
    s = s.astype(jnp.float32)
    pos = jnp.arange(P * ps, dtype=jnp.int32)
    s = jnp.where(pos[None, None, :] < kv_lens[:, None, None], s, _NEG_INF)
    probs = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", probs.astype(q.dtype), v)


def paged_decode_attention_fn(q, k_pool, v_pool, page_table, kv_lens,
                              sm_scale=1.0, tp=1):
    """Dispatch per `paged_attention_backend`: the Pallas page-DMA kernel
    where it can run (and the tuner has not retired it for this shape), the
    XLA gather reference everywhere else — including when a swept-DB verdict
    names a kernel this platform cannot execute."""
    B, nh, dh = q.shape
    P, ps = page_table.shape[1], k_pool.shape[1]
    backend, _tier = paged_attention_backend(B, nh, P * ps, dh, q.dtype,
                                             pool_shape=k_pool.shape, tp=tp)
    # re-check executability at the SAME per-shard shapes the decision saw
    # (under tp > 1 the global q/pool head counts are not what a shard runs)
    shard_q, shard_pool = _shard_paged_shapes(q.shape, k_pool.shape, tp)
    if backend == "pallas_paged" and _pallas_paged_ok(shard_q, shard_pool):
        from .pallas_kernels import paged_attention as ppa

        return ppa.paged_decode_attention(q, k_pool, v_pool, page_table,
                                          kv_lens, sm_scale=float(sm_scale))
    return _paged_attention_reference(q, k_pool, v_pool, page_table, kv_lens,
                                      sm_scale)


# sentinel page index far past any real pool: scatters routed here are
# dropped (mode="drop"), which is how masked rows / padded positions skip
# their KV write without a branch
_DROP_PAGE = 1 << 30


def kv_cache_append_fn(k_pool, v_pool, k, v, page_table, positions,
                       live=None):
    """Write one decode step's K/V into the paged pool.

    k/v: [B, nh, dh] (this token's projections); positions: [B] int32 — the
    logical slot each row writes (its current context length); live: [B]
    0/1 mask (rows the scheduler padded in write nowhere). Returns the
    updated pools; the executor's donation makes the update in-place in HBM.
    """
    ps = k_pool.shape[1]
    P = page_table.shape[1]
    page_of = jnp.clip(positions // ps, 0, P - 1)
    page_idx = jnp.take_along_axis(page_table, page_of[:, None], axis=1)[:, 0]
    slot = positions % ps
    if live is not None:
        page_idx = jnp.where(jnp.reshape(live, (-1,)) > 0, page_idx,
                             _DROP_PAGE)
    k_pool = k_pool.at[page_idx, slot].set(k.astype(k_pool.dtype),
                                           mode="drop")
    v_pool = v_pool.at[page_idx, slot].set(v.astype(v_pool.dtype),
                                           mode="drop")
    return k_pool, v_pool


def kv_cache_prefill_write_fn(k_pool, v_pool, k, v, page_table, lens,
                              start=None):
    """Write a prefill window's K/V into the paged pool.

    k/v: [B, nh, S, dh] (the prefill attention's per-layer projections, in
    head-major layout as the encoder produces them).

    Without `start` (the PR 7 whole-prompt prefill): local index s writes
    slot s; lens [B] are actual prompt lengths, positions s >= lens[b]
    (bucket padding) are dropped.

    With `start` [B] int32 (ISSUE 11 — suffix prefill past a cached prefix,
    and the speculative-decode verify window): local index s writes slot
    start[b] + s, and lens[b] counts the VALID LOCAL positions, so only
    s < lens[b] writes. Rows the scheduler padded pass lens 0 and write
    nothing — the batch_mask convention without needing a second feed.
    """
    B, nh, S, dh = k.shape
    ps = k_pool.shape[1]
    P = page_table.shape[1]
    pos = jnp.arange(S, dtype=jnp.int32)
    if start is None:
        gpos = jnp.broadcast_to(pos[None, :], (B, S))     # [B, S]
        valid = pos[None, :] < lens[:, None]
    else:
        gpos = jnp.reshape(start, (-1,))[:, None] + pos[None, :]
        valid = pos[None, :] < lens[:, None]
    page_idx = jnp.take_along_axis(
        page_table, jnp.clip(gpos // ps, 0, P - 1), axis=1)  # [B, S]
    page_idx = jnp.where(valid, page_idx, _DROP_PAGE)
    slot = gpos % ps
    k_bs = jnp.transpose(k, (0, 2, 1, 3))                 # [B, S, nh, dh]
    v_bs = jnp.transpose(v, (0, 2, 1, 3))
    k_pool = k_pool.at[page_idx, slot].set(k_bs.astype(k_pool.dtype),
                                           mode="drop")
    v_pool = v_pool.at[page_idx, slot].set(v_bs.astype(v_pool.dtype),
                                           mode="drop")
    return k_pool, v_pool


def paged_prefill_attention_fn(q, k_pool, v_pool, page_table, start,
                               sm_scale=1.0):
    """Windowed causal attention OVER THE POOL: query s of row b (global
    position start[b] + s) attends pool slots 0..start[b]+s inclusive.

    The one attention primitive both new multi-tenant stages need
    (arXiv:2104.05755's reusable-primitive argument): suffix prefill past a
    shared prefix (the suffix's K/V is appended to the pool first, so the
    whole context — cached prefix + fresh suffix — is read from one place),
    and the speculative-decode verify window (k+1 queries per row in one
    step). XLA gather reference; fp32 softmax statistics; garbage slots
    past the window are masked with the framework-wide -1e9 convention.
    q: [B, nh, S, dh] -> out [B, nh, S, dh].
    """
    B, nh, S, dh = q.shape
    num_pages, ps = k_pool.shape[0], k_pool.shape[1]
    P = page_table.shape[1]
    pt = jnp.clip(page_table, 0, num_pages - 1)
    k = k_pool[pt].reshape(B, P * ps, nh, dh)
    v = v_pool[pt].reshape(B, P * ps, nh, dh)
    s = jnp.einsum("bhsd,bkhd->bhsk", q, k) * sm_scale
    s = s.astype(jnp.float32)
    slot = jnp.arange(P * ps, dtype=jnp.int32)
    limit = (jnp.reshape(start, (-1,))[:, None]
             + jnp.arange(S, dtype=jnp.int32)[None, :])   # [B, S]
    mask = slot[None, None, None, :] <= limit[:, None, :, None]
    s = jnp.where(mask, s, _NEG_INF)
    probs = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhsk,bkhd->bhsd", probs.astype(q.dtype), v)


@register_op("paged_decode_attention", grad="none")
def paged_decode_attention_op(ctx: ExecContext):
    """inputs: Q [B, nh, dh], KPool/VPool [pages, ps, nh, dh], PageTable
    [B, P] int32, Positions [B] int32 (current slot index; the context this
    step attends over is 0..Positions inclusive — the just-appended token
    attends to itself); attrs: sm_scale. Output: [B, nh, dh]."""
    q = ctx.input("Q")
    kp, vp = ctx.input("KPool"), ctx.input("VPool")
    out = paged_decode_attention_fn(
        q, kp, vp, ctx.input("PageTable"),
        ctx.input("Positions").astype(jnp.int32) + 1,
        sm_scale=ctx.attr("sm_scale", 1.0),
        tp=ctx.attr("tp_degree", 1))
    return {"Out": out.astype(q.dtype)}


@register_op("kv_cache_append", grad="none")
def kv_cache_append_op(ctx: ExecContext):
    """inputs: KPool/VPool, K/V [B, nh, dh], PageTable [B, P], Positions
    [B], optional Mask [B, 1] (the batch_mask row-mask convention: masked
    rows write nothing). Outputs KPoolOut/VPoolOut — the serving programs
    name these the SAME vars as the inputs, so the executor classifies the
    pools read-write and donates their buffers (in-place HBM update)."""
    live = ctx.input("Mask") if ctx.has_input("Mask") else None
    kp, vp = kv_cache_append_fn(
        ctx.input("KPool"), ctx.input("VPool"), ctx.input("K"),
        ctx.input("V"), ctx.input("PageTable"),
        ctx.input("Positions").astype(jnp.int32), live)
    return {"KPoolOut": kp, "VPoolOut": vp}


@register_op("kv_cache_prefill_write", grad="none")
def kv_cache_prefill_write_op(ctx: ExecContext):
    """inputs: KPool/VPool, K/V [B, nh, S, dh], PageTable [B, P], Lens [B],
    optional Start [B] (windowed write at slots Start+s, Lens counts local
    valid positions — the suffix-prefill/verify regime). Same in-place
    output aliasing contract as kv_cache_append."""
    start = (ctx.input("Start").astype(jnp.int32)
             if ctx.has_input("Start") else None)
    kp, vp = kv_cache_prefill_write_fn(
        ctx.input("KPool"), ctx.input("VPool"), ctx.input("K"),
        ctx.input("V"), ctx.input("PageTable"),
        ctx.input("Lens").astype(jnp.int32), start)
    return {"KPoolOut": kp, "VPoolOut": vp}


@register_op("paged_prefill_attention", grad="none")
def paged_prefill_attention_op(ctx: ExecContext):
    """inputs: Q [B, nh, S, dh], KPool/VPool, PageTable [B, P], Start [B]
    int32 (query s's global position is Start+s; it attends pool slots
    0..Start+s inclusive — its own just-written KV included); attrs:
    sm_scale. Output: [B, nh, S, dh]."""
    q = ctx.input("Q")
    out = paged_prefill_attention_fn(
        q, ctx.input("KPool"), ctx.input("VPool"), ctx.input("PageTable"),
        ctx.input("Start").astype(jnp.int32),
        sm_scale=ctx.attr("sm_scale", 1.0))
    return {"Out": out.astype(q.dtype)}


@register_op("kv_cache_copy_page", grad="none")
def kv_cache_copy_page_op(ctx: ExecContext):
    """Copy-on-write's copy: inputs KPool/VPool, Src [1] int32, Dst [1]
    int32 — pool[Dst] := pool[Src] for K and V, in place via the same
    output-aliasing donation contract as the other cache ops. The engine
    runs this once per COW'd page BEFORE the write that would have landed
    on a shared page."""
    kp, vp = ctx.input("KPool"), ctx.input("VPool")
    src = ctx.input("Src").astype(jnp.int32)[0]
    dst = ctx.input("Dst").astype(jnp.int32)[0]
    kp = kp.at[dst].set(kp[src])
    vp = vp.at[dst].set(vp[src])
    return {"KPoolOut": kp, "VPoolOut": vp}


@register_op("gather_token_logits", grad="none")
def gather_token_logits_op(ctx: ExecContext):
    """inputs: X [B, S, V], Lens [B] — output [B, V]: row b's logits at
    position Lens[b]-1 (the last real token of a bucket-padded prefill)."""
    x = ctx.input("X")
    lens = ctx.input("Lens").astype(jnp.int32)
    idx = jnp.clip(lens - 1, 0, x.shape[1] - 1)[:, None, None]
    return {"Out": jnp.take_along_axis(x, idx, axis=1)[:, 0, :]}


# ---------------------------------------------------------------------------
# Ring attention (sequence parallelism over the `sp` axis)
# ---------------------------------------------------------------------------


def ring_attention_local(q, k, v, axis_name, causal=False, sm_scale=1.0):
    """Blockwise ring attention (Liu et al., Ring Attention; public
    algorithm). Each device holds the full batch/head dims but a 1/p slice of
    the sequence. K/V blocks rotate p times around `axis_name`; the local
    online-softmax state (acc, m, l) merges each incoming block, giving exact
    softmax attention over the full sequence with O(S/p) memory per device.

    q, k, v: [B, nh, S_local, dh] (this device's shard). Causal masking uses
    the ring rank to compute each block's global offset.
    """
    p = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, nh, s_loc, dh = q.shape
    q32 = q.astype(jnp.float32) * sm_scale

    def block_scores(kb, src_rank):
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32, kb.astype(jnp.float32))
        if causal:
            q_pos = rank * s_loc + jnp.arange(s_loc)[:, None]
            k_pos = src_rank * s_loc + jnp.arange(s_loc)[None, :]
            scores = jnp.where(q_pos >= k_pos, scores, _NEG_INF)
        return scores

    def step(carry, _):
        acc, m, l, kb, vb, src = carry
        s = block_scores(kb, src)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)
        pexp = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + pexp.sum(-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", pexp, vb.astype(jnp.float32))
        # rotate kv to the next device on the ring
        perm = [(i, (i + 1) % p) for i in range(p)]
        kb_next = jax.lax.ppermute(kb, axis_name, perm)
        vb_next = jax.lax.ppermute(vb, axis_name, perm)
        src_next = (src - 1) % p
        return (acc_new, m_new, l_new, kb_next, vb_next, src_next), None

    acc0 = jnp.zeros((B, nh, s_loc, dh), jnp.float32)
    m0 = jnp.full((B, nh, s_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, nh, s_loc), jnp.float32)
    (acc, m, l, _, _, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v, rank), None, length=p)
    return (acc / l[..., None]).astype(q.dtype)


@register_op("ring_attention")
def ring_attention(ctx: ExecContext):
    """Sequence-parallel attention over the axis bound to `ring_id` (shard_map
    regime). With no axis bound (single device / GSPMD handles it), falls back
    to fused_attention semantics on the local (full) sequence."""
    q, k, v = ctx.input("Q"), ctx.input("K"), ctx.input("V")
    causal = ctx.attr("causal", False)
    sm_scale = ctx.attr("sm_scale", 1.0)
    axis = _axis(ctx)
    if axis is None:
        out = flash_attention(q, k, v, None, causal=causal, sm_scale=sm_scale)
    else:
        out = ring_attention_local(q, k, v, axis, causal=causal,
                                   sm_scale=sm_scale)
    return {"Out": out.astype(q.dtype)}
