"""Fused attention ops: Pallas flash attention + ring attention (SP).

The reference has no fused attention — its scaled_dot_product_attention
(nets.py:345) materializes the full [B,nh,S,S] score matrix through separate
matmul/softmax/dropout ops. On TPU one fused op boundary for the whole
QK^T -> softmax -> PV block is the single biggest transformer win
(SURVEY.md §2.3), so:

  * `fused_attention` dispatches per measured winner (PERF.md): at train
    sizes (S <= 1024) the jnp einsum composition — XLA's attention fusion
    with fp32 softmax statistics, recompute-in-backward via the derived
    vjp; with `use_pallas` the hand-tuned short-seq Pallas kernel
    (ops/pallas_kernels/attention.py, O(S) residuals); at S > 1024 jax's
    bundled flash-attention kernel (the only O(S)-memory option there).
  * `ring_attention` is the sequence-parallel form: K/V shards rotate around
    the `sp` mesh axis via collective-permute while each device keeps a
    running online-softmax merge (m, l, acc). Pure differentiable jnp +
    lax.ppermute — XLA overlaps the permute with the local block math over
    ICI. Used under shard_map (CompiledProgram.with_collective) or inside
    GSPMD manual regions; with no axis bound it degrades to fused_attention.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .collective_ops import _axis
from .registry import ExecContext, register_op

_NEG_INF = -1e9


def _reference_attention(q, k, v, bias=None, causal=False, sm_scale=1.0):
    """Plain jnp attention, the numeric oracle (and the measured-fastest
    TPU path at train sizes). q,k,v: [B, nh, S, dh]. Softmax statistics are
    fp32 even for bf16 operands (the AMP white-list invariant); XLA fuses
    the boundary casts so this costs no extra HBM traffic."""
    # scores materialize in the operand dtype (bf16 under AMP — half the
    # HBM bytes); the fp32 upcast happens inside the softmax so the
    # max/exp/sum statistics are fp32 yet XLA fuses the casts for free
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
    scores = scores.astype(jnp.float32)
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), jnp.bool_), sk - sq)
        scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _block_multiple_ok(s: int) -> bool:
    # the bundled kernel wants seq divisible by its block sizes (>=128 lanes)
    return s % 128 == 0


def _pallas_short_ok(q_shape, k_shape, bias) -> bool:
    from .pallas_kernels import attention as psa

    return ((_on_tpu() or psa.INTERPRET)
            and psa.short_seq_supported(q_shape, k_shape, bias))


def _flash_bundled_ok(q_shape, k_shape, dtype) -> bool:
    sq, sk = q_shape[2], k_shape[2]
    return (_on_tpu() and _block_multiple_ok(sq) and _block_multiple_ok(sk)
            and dtype != jnp.float64)


def attention_backend(q_shape, k_shape, dtype, bias=None, causal=False,
                      use_pallas=False):
    """Which kernel carries this attention shape. Returns (backend, tier)
    with backend in {"xla", "pallas_short", "flash_bundled"}.

    The analytic prior is the measured v5e dispatch rule (PERF.md): XLA's
    own attention fusion at train sizes, the hand-tuned short-seq Pallas
    kernel when the caller forces O(S) memory (`use_pallas`) and the shape
    qualifies, the bundled flash kernel past S=1024 where the [S,S] scores
    outgrow the chip. Under FLAGS_tuning_mode=consult a swept-DB entry for
    the exact (shape, dtype, device) overrides the rule — this is where the
    measured BENCH_r05 split (XLA wins at seq<=128, the Pallas kernel wins
    ~9% at s512) becomes a cache entry instead of a per-model flag. A
    swept backend the current build cannot execute is degraded at dispatch
    time (flash_attention), never obeyed blindly."""
    B, nh, sq, dh = q_shape
    sk = k_shape[2]

    def analytic():
        if use_pallas and _pallas_short_ok(q_shape, k_shape, bias):
            return {"backend": "pallas_short"}
        # an O(S)-memory kernel is mandatory past S=1024 and honored
        # whenever the caller asked for one (`use_pallas`) but the
        # short-seq kernel's gate rejected the shape — falling to the
        # O(S^2) reference there would silently undo the flag's documented
        # purpose (memory-bound configs).
        if ((sq > 1024 or (use_pallas and sq > 512))
                and _flash_bundled_ok(q_shape, k_shape, dtype)):
            return {"backend": "flash_bundled"}
        return {"backend": "xla"}

    from .. import tuning

    if tuning.mode() == "off":
        return analytic()["backend"], "analytic"
    key = tuning.canonical_key(
        "attention", tuning.attention_key(B, nh, sq, sk, dh, causal),
        str(jnp.dtype(dtype)), tuning.device_kind())
    decision, tier = tuning.decide(
        "attention", key, prior=analytic, default={"backend": "xla"},
        validate=lambda dd: dd.get("backend") in ("xla", "pallas_short",
                                                  "flash_bundled"))
    return decision.get("backend", "xla"), tier


def flash_attention(q, k, v, bias=None, causal=False, sm_scale=1.0,
                    use_pallas=False):
    """Dispatch per `attention_backend` (each branch measured on v5e,
    PERF.md):
      * "xla": the jnp einsum composition — XLA's own attention fusion is
        the fastest at S<=512 (beats both the bundled flash kernel and the
        custom short-seq Pallas kernel at train sizes);
      * "pallas_short": the hand-tuned short-seq kernel (O(S) memory with a
        no-residual fused backward — for memory-bound configs);
      * "flash_bundled": jax's bundled flash kernel (the only O(S) option
        once the [S,S] scores outgrow VMEM/HBM budgets).
    A swept-DB backend the current platform/shape cannot run (e.g. a Pallas
    verdict replayed off-TPU) degrades to the reference path here.
    """
    backend, _tier = attention_backend(q.shape, k.shape, q.dtype, bias,
                                       causal, use_pallas)
    if backend == "pallas_short" and _pallas_short_ok(q.shape, k.shape, bias):
        from .pallas_kernels import attention as psa

        return psa.short_seq_attention(q, k, v, causal=causal,
                                       sm_scale=float(sm_scale))
    if backend == "flash_bundled" and _flash_bundled_ok(q.shape, k.shape,
                                                        q.dtype):
        from jax.experimental.pallas.ops.tpu import flash_attention as fa

        return fa.flash_attention(q, k, v, ab=bias, causal=causal,
                                  sm_scale=float(sm_scale))
    return _reference_attention(q, k, v, bias, causal, sm_scale)


@register_op("fused_attention")
def fused_attention(ctx: ExecContext):
    """inputs: Q, K, V [B, nh, S, dh], optional Bias (broadcastable to
    [B, nh, Sq, Sk]); attrs: causal, sm_scale. Output: [B, nh, Sq, dh]."""
    q, k, v = ctx.input("Q"), ctx.input("K"), ctx.input("V")
    bias = ctx.input("Bias") if ctx.has_input("Bias") else None
    out = flash_attention(q, k, v, bias,
                          causal=ctx.attr("causal", False),
                          sm_scale=ctx.attr("sm_scale", 1.0),
                          use_pallas=ctx.attr("use_pallas", False))
    return {"Out": out.astype(q.dtype)}


# ---------------------------------------------------------------------------
# Ring attention (sequence parallelism over the `sp` axis)
# ---------------------------------------------------------------------------


def ring_attention_local(q, k, v, axis_name, causal=False, sm_scale=1.0):
    """Blockwise ring attention (Liu et al., Ring Attention; public
    algorithm). Each device holds the full batch/head dims but a 1/p slice of
    the sequence. K/V blocks rotate p times around `axis_name`; the local
    online-softmax state (acc, m, l) merges each incoming block, giving exact
    softmax attention over the full sequence with O(S/p) memory per device.

    q, k, v: [B, nh, S_local, dh] (this device's shard). Causal masking uses
    the ring rank to compute each block's global offset.
    """
    p = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, nh, s_loc, dh = q.shape
    q32 = q.astype(jnp.float32) * sm_scale

    def block_scores(kb, src_rank):
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32, kb.astype(jnp.float32))
        if causal:
            q_pos = rank * s_loc + jnp.arange(s_loc)[:, None]
            k_pos = src_rank * s_loc + jnp.arange(s_loc)[None, :]
            scores = jnp.where(q_pos >= k_pos, scores, _NEG_INF)
        return scores

    def step(carry, _):
        acc, m, l, kb, vb, src = carry
        s = block_scores(kb, src)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)
        pexp = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + pexp.sum(-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", pexp, vb.astype(jnp.float32))
        # rotate kv to the next device on the ring
        perm = [(i, (i + 1) % p) for i in range(p)]
        kb_next = jax.lax.ppermute(kb, axis_name, perm)
        vb_next = jax.lax.ppermute(vb, axis_name, perm)
        src_next = (src - 1) % p
        return (acc_new, m_new, l_new, kb_next, vb_next, src_next), None

    acc0 = jnp.zeros((B, nh, s_loc, dh), jnp.float32)
    m0 = jnp.full((B, nh, s_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, nh, s_loc), jnp.float32)
    (acc, m, l, _, _, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v, rank), None, length=p)
    return (acc / l[..., None]).astype(q.dtype)


@register_op("ring_attention")
def ring_attention(ctx: ExecContext):
    """Sequence-parallel attention over the axis bound to `ring_id` (shard_map
    regime). With no axis bound (single device / GSPMD handles it), falls back
    to fused_attention semantics on the local (full) sequence."""
    q, k, v = ctx.input("Q"), ctx.input("K"), ctx.input("V")
    causal = ctx.attr("causal", False)
    sm_scale = ctx.attr("sm_scale", 1.0)
    axis = _axis(ctx)
    if axis is None:
        out = flash_attention(q, k, v, None, causal=causal, sm_scale=sm_scale)
    else:
        out = ring_attention_local(q, k, v, axis, causal=causal,
                                   sm_scale=sm_scale)
    return {"Out": out.astype(q.dtype)}
