"""Neural-net ops: conv, pooling, normalization, dropout, softmax, losses,
embedding lookup.

TPU-native equivalents of /root/reference/paddle/fluid/operators/ conv_op.*,
pool_op.*, batch_norm_op.*, layer_norm_op.*, group_norm_op.cc, dropout_op.*,
softmax_op.*, cross_entropy_op.*, softmax_with_cross_entropy_op.*,
lookup_table_op.*, metrics/accuracy_op.cc, smooth_l1_loss_op, sigmoid_xent.

Layout: NCHW to match the reference's Python API contract; XLA relayouts to
TPU-preferred internally. Matmuls/convs accumulate in fp32
(`preferred_element_type`) so bf16 training keeps fp32 accumulation on the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import flags
from .registry import ExecContext, register_op, register_grad_compute


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _conv_pads(praw):
    # 2-element [ph, pw] (symmetric) or 4-element [top, bottom, left, right]
    # (asymmetric — needed e.g. by the space-to-depth ResNet stem; an
    # explicit pad op in front of the conv measures 2.4x slower on TPU v5e
    # because XLA does not fold it into the convolution).
    if isinstance(praw, (list, tuple)) and len(praw) == 4:
        return [(praw[0], praw[1]), (praw[2], praw[3])]
    p = _pair(praw)
    return [(p[0], p[0]), (p[1], p[1])]


# Implicit-GEMM cost-model constants — the measured single-chip rooflines
# this repo's perf campaign is calibrated against (PERF.md r4: matmul
# 157-162 TF/s sustained, HBM 476-522 GB/s; conv MXU efficiency ~0.7-0.75 of
# the matmul ceiling at >=half lane fill). The model only has to rank two
# lowerings of the SAME conv, so absolute calibration error mostly cancels;
# tools/_rn_igemm.py is the end-to-end A/B that checks it per shape.
_IGEMM_MXU_FLOPS = 157e12
_IGEMM_HBM_BPS = 450e9
_IGEMM_MXU_EFF = 0.75
_IGEMM_WIN_MARGIN = 0.9  # predicted igemm time must beat direct by >=10%


def _igemm_predict_win(n, hout, wout, cin, cout, kh, kw, itemsize) -> bool:
    """Tile-fill vs HBM-traffic model (PAPERS.md: A Learned Performance Model
    for TPUs, 2008.01040 — the fill term; TVM, 1802.04799 — the layout-
    rewrite framing): direct conv contracts K=C_in per tap (under-filling
    the 128-lane MXU when C_in < 128), implicit GEMM folds K=C_in*kh*kw but
    must materialize the kh*kw-times-larger patch tensor through HBM."""
    m = n * hout * wout
    k_fold = cin * kh * kw
    flops = 2.0 * m * k_fold * cout

    def fill(k):
        return min(1.0, k / 128.0)

    t_direct = flops / (_IGEMM_MXU_FLOPS * fill(cin) * _IGEMM_MXU_EFF)
    patch_bytes = 2.0 * m * k_fold * itemsize  # write at im2col + read at dot
    t_igemm = (flops / (_IGEMM_MXU_FLOPS * fill(k_fold) * _IGEMM_MXU_EFF)
               + patch_bytes / _IGEMM_HBM_BPS)
    return t_igemm < _IGEMM_WIN_MARGIN * t_direct


def _igemm_mode() -> str:
    mode = str(flags.get_flag("conv_implicit_gemm")).lower()
    if mode in ("on", "always", "all", "1", "true"):
        return "on"
    if mode in ("off", "never", "0", "false"):
        return "off"
    return "auto"


def _igemm_take(x, w, strides, pads, d, groups, fmt) -> bool:
    """Per-shape gate for the implicit-GEMM lowering.

    'on'/'off' stay hard forces (the A/B arms must be able to override any
    cache). 'auto' resolves through the autotuner when FLAGS_tuning_mode is
    not 'off': exact swept-DB hit -> the analytic cost model above as the
    prior -> direct conv as the conservative default. With tuning off, auto
    is the bare analytic model — bit-for-bit the PR 5 behavior."""
    mode = _igemm_mode()
    if mode == "off" or groups != 1:
        return False
    if not (jnp.issubdtype(x.dtype, jnp.floating)
            and jnp.issubdtype(w.dtype, jnp.floating)):
        return False
    if fmt == "NCHW":
        n, cin, h, wi = x.shape
        kh, kw = w.shape[2], w.shape[3]
    else:
        n, h, wi, cin = x.shape
        kh, kw = w.shape[0], w.shape[1]
    (pt, pb), (pl, pr) = pads
    hout = (h + pt + pb - ((kh - 1) * d[0] + 1)) // strides[0] + 1
    wout = (wi + pl + pr - ((kw - 1) * d[1] + 1)) // strides[1] + 1
    if hout <= 0 or wout <= 0:
        return False
    if mode == "on":
        return True
    cout = w.shape[0] if fmt == "NCHW" else w.shape[3]
    itemsize = jnp.dtype(x.dtype).itemsize

    from .. import tuning

    if tuning.mode() == "off":
        return _igemm_predict_win(n, hout, wout, cin, cout, kh, kw, itemsize)
    key = tuning.canonical_key(
        "conv2d", tuning.conv_key(n, hout, wout, cin, cout, kh, kw,
                                  strides, d, fmt),
        str(jnp.dtype(x.dtype)), tuning.device_kind())
    decision, _tier = tuning.decide(
        "conv2d", key,
        prior=lambda: {"lowering": "igemm" if _igemm_predict_win(
            n, hout, wout, cin, cout, kh, kw, itemsize) else "direct"},
        default={"lowering": "direct"},
        # a swept verdict naming a lowering this build doesn't have falls
        # through to the prior instead of being obeyed blindly
        validate=lambda dd: dd.get("lowering") in ("direct", "igemm",
                                                   "matmul_1x1"))
    # matmul_1x1 IS the implicit-GEMM path at kh=kw=1 (the im2col collapses
    # to a reshape, leaving the bare GEMM)
    return decision.get("lowering") in ("igemm", "matmul_1x1")


def _conv2d_igemm_f32(x, w, strides, pads, d, fmt):
    """im2col + GEMM lowering, returning the fp32 accumulator [*, C_out]
    in the output layout. The kh*kw shifted strided slices of the padded
    input concatenate tap-major along the channel dim, matching a plain
    reshape of the HWIO (NHWC) / tap-major-transposed OIHW (NCHW) filter —
    so one lax.dot_general carries the whole conv with K = C_in*kh*kw.
    Backward derives via vjp: dX is the transposed GEMM scattered by the
    slice transposes (col2im), dW the patches^T @ dOut GEMM — both ride the
    MXU at the same folded fill."""
    sh, sw = strides
    dh, dw = d
    if fmt == "NCHW":
        n, cin, h, wi = x.shape
        cout, _, kh, kw = w.shape
        xp = jnp.pad(x, ((0, 0), (0, 0), pads[0], pads[1]))
        hout = (h + sum(pads[0]) - ((kh - 1) * dh + 1)) // sh + 1
        wout = (wi + sum(pads[1]) - ((kw - 1) * dw + 1)) // sw + 1
        taps = [
            jax.lax.slice(
                xp,
                (0, 0, i * dh, j * dw),
                (n, cin, i * dh + (hout - 1) * sh + 1,
                 j * dw + (wout - 1) * sw + 1),
                (1, 1, sh, sw))
            for i in range(kh) for j in range(kw)
        ]
        patches = jnp.concatenate(taps, axis=1)  # [N, kh*kw*Cin, H', W']
        wmat = jnp.transpose(w, (2, 3, 1, 0)).reshape(kh * kw * cin, cout)
        acc = jax.lax.dot_general(
            patches, wmat, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [N, H', W', Cout]
        return jnp.transpose(acc, (0, 3, 1, 2))
    n, h, wi, cin = x.shape
    kh, kw, _, cout = w.shape
    xp = jnp.pad(x, ((0, 0), pads[0], pads[1], (0, 0)))
    hout = (h + sum(pads[0]) - ((kh - 1) * dh + 1)) // sh + 1
    wout = (wi + sum(pads[1]) - ((kw - 1) * dw + 1)) // sw + 1
    taps = [
        jax.lax.slice(
            xp,
            (0, i * dh, j * dw, 0),
            (n, i * dh + (hout - 1) * sh + 1,
             j * dw + (wout - 1) * sw + 1, cin),
            (1, sh, sw, 1))
        for i in range(kh) for j in range(kw)
    ]
    patches = jnp.concatenate(taps, axis=-1)  # [N, H', W', kh*kw*Cin]
    wmat = w.reshape(kh * kw * cin, cout)
    return jax.lax.dot_general(
        patches.reshape(n * hout * wout, kh * kw * cin), wmat,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).reshape(n, hout, wout, cout)


def _conv2d_forward(ctx: ExecContext):
    """Shared conv lowering: returns (out_in_x_dtype, fp32_acc_or_None).
    The fp32 accumulator is only materialized on the implicit-GEMM path
    (the dot's natural output); conv2d_bn reads it for epilogue statistics."""
    x, w = ctx.input("Input"), ctx.input("Filter")
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _conv_pads(ctx.attr("paddings", [0, 0]))
    d = _pair(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1)
    # data_format NHWC keeps the whole activation chain channels-last on
    # TPU (reference conv2d's data_format attr) and carries its weights in
    # HWIO (the layers allocate them that way): OIHW weights fed straight
    # into an NHWC conv measure ~25-40% slower (XLA picks a worse
    # algorithm) and an in-step transpose still costs ~6%/conv (PERF r5).
    fmt = ctx.attr("data_format", "NCHW")
    if _igemm_take(x, w, strides, pads, d, groups, fmt):
        acc = _conv2d_igemm_f32(x, w, strides, pads, d, fmt)
        return acc.astype(x.dtype), acc
    rhs = "OIHW" if fmt == "NCHW" else "HWIO"
    # No preferred_element_type=f32 + astype pair here: the TPU MXU already
    # accumulates bf16 convs in fp32 internally, and the astype's transpose
    # rule would hand lax's conv grad an fp32 cotangent against bf16 operands
    # (lax.conv_general_dilated requires matching dtypes), breaking AMP
    # backward passes.
    out = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=pads,
        rhs_dilation=d,
        dimension_numbers=(fmt, rhs, fmt),
        feature_group_count=groups,
    )
    return out, None


@register_op("conv2d")
def conv2d(ctx: ExecContext):
    out, _ = _conv2d_forward(ctx)
    return {"Output": out}


@register_op("depthwise_conv2d")
def depthwise_conv2d(ctx: ExecContext):
    # reference conv_op.cc registers depthwise as its own type; groups == C_in
    return conv2d(ctx)


@register_op("conv2d_transpose")
def conv2d_transpose(ctx: ExecContext):
    x, w = ctx.input("Input"), ctx.input("Filter")
    strides = _pair(ctx.attr("strides", [1, 1]))
    p = _pair(ctx.attr("paddings", [0, 0]))
    d = _pair(ctx.attr("dilations", [1, 1]))
    # filter layout for transpose in the reference is (C_in, C_out, H, W).
    # With transpose_kernel=True jax swaps the kernel's I/O axes and flips
    # its spatial dims, so the spec must name dim 0 "O" and dim 1 "I" for
    # the post-swap conv to contract C_in against the input.
    #
    # jax's explicit padding applies to the DILATED input directly; the
    # reference output extent (in-1)*s + d*(k-1)+1 - 2p needs each side
    # padded by d*(k-1) - p (conv_transpose_op.cc output formula).
    ke = [d[i] * (w.shape[2 + i] - 1) for i in range(2)]
    out = jax.lax.conv_transpose(
        x,
        w,
        strides=strides,
        padding=[(ke[0] - p[0], ke[0] - p[0]), (ke[1] - p[1], ke[1] - p[1])],
        rhs_dilation=d,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        transpose_kernel=True,
    ).astype(x.dtype)
    return {"Output": out}


@register_op("pool2d")
def pool2d(ctx: ExecContext):
    x = ctx.input("X")
    ptype = ctx.attr("pooling_type", "max")
    k = _pair(ctx.attr("ksize", [2, 2]))
    s = _pair(ctx.attr("strides", [2, 2]))
    p = _pair(ctx.attr("paddings", [0, 0]))
    nhwc = ctx.attr("data_format", "NCHW") == "NHWC"
    hax = 1 if nhwc else 2
    if ctx.attr("global_pooling", False):
        k = (x.shape[hax], x.shape[hax + 1])
        s, p = k, (0, 0)
    if nhwc:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    else:
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides, pads)
    else:
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
        if ctx.attr("exclusive", True) and (p[0] or p[1]):
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
            out = summed / counts
        else:
            out = summed / (k[0] * k[1])
    return {"Out": out.astype(x.dtype)}


@register_op("softmax")
def softmax(ctx: ExecContext):
    return {"Out": jax.nn.softmax(ctx.input("X"), axis=ctx.attr("axis", -1))}


@register_op("log_softmax")
def log_softmax(ctx: ExecContext):
    return {"Out": jax.nn.log_softmax(ctx.input("X"), axis=ctx.attr("axis", -1))}


def _xent_from_softmax(sm, label, soft_label, ignore_index):
    eps = 1e-12
    if soft_label:
        return -jnp.sum(label * jnp.log(sm + eps), axis=-1, keepdims=True)
    lbl = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 else label
    picked = jnp.take_along_axis(sm, lbl[..., None].astype(np.int32), axis=-1)
    loss = -jnp.log(picked + eps)
    if ignore_index is not None and ignore_index >= 0:
        loss = jnp.where(lbl[..., None] == ignore_index, jnp.zeros_like(loss), loss)
    return loss


@register_op("cross_entropy")
def cross_entropy(ctx: ExecContext):
    x, label = ctx.input("X"), ctx.input("Label")
    return {
        "Y": _xent_from_softmax(
            x, label, ctx.attr("soft_label", False), ctx.attr("ignore_index", -100)
        )
    }


def _xent_pallas_eligible(logits, soft, ignore) -> bool:
    """Large-vocab hard-label xent on TPU routes to the fused Pallas kernel
    (pallas_kernels/xent.py): the fwd never materializes the softmax and
    the bwd recomputes stats in-VMEM — one logits read fwd, one read + one
    dlogits write bwd. FLAGS_pallas_xent stays the master switch (measured
    and retired r5); with the flag ON and the tuner consulting, a swept
    per-shape verdict can still retire the kernel for a specific
    (rows, vocab) tile — the workbench contract that every kernel's
    dispatch resolves through a tuning decision key."""
    if soft or ignore >= 0 or not flags.get_flag("pallas_xent"):
        return False  # flag off (the default): never pay the pallas import
    from .pallas_kernels import xent as px

    if not (px.INTERPRET or jax.default_backend() in ("tpu", "axon")):
        return False
    n = int(np.prod(logits.shape[:-1]))
    if not px.xent_supported((n, logits.shape[-1]), logits.shape[-1],
                             dtype=logits.dtype):
        return False
    from .. import tuning

    if tuning.mode() == "off":
        return True  # flag on + no tuner: the pre-workbench behavior
    key = tuning.canonical_key(
        "xent", tuning.xent_key(n, logits.shape[-1]),
        str(jnp.dtype(logits.dtype)), tuning.device_kind())
    decision, _tier = tuning.decide(
        "xent", key, prior=lambda: {"backend": "pallas"},
        default={"backend": "pallas"},
        validate=lambda dd: dd.get("backend") in ("xla", "pallas"))
    return decision.get("backend", "pallas") == "pallas"


@register_op("softmax_with_cross_entropy")
def softmax_with_cross_entropy(ctx: ExecContext):
    logits, label = ctx.input("Logits"), ctx.input("Label")
    soft = ctx.attr("soft_label", False)
    ignore = ctx.attr("ignore_index", -100)
    if _xent_pallas_eligible(logits, soft, ignore):
        from .pallas_kernels import xent as px

        lbl = (label.reshape(label.shape[:-1])
               if label.shape[-1] == 1 else label)
        n = int(np.prod(logits.shape[:-1]))
        loss = px.xent_loss_fwd(logits.reshape(n, logits.shape[-1]),
                                lbl.reshape(n).astype(jnp.int32))
        loss = loss.reshape(*logits.shape[:-1], 1).astype(logits.dtype)
        # Softmax output as a PLAIN jnp expression: dead-code-eliminated by
        # XLA when nothing consumes it (the usual case — the pallas grad
        # branch below recomputes instead of reading it), exact when a user
        # fetches it.
        sm = jax.nn.softmax(logits.astype(jnp.float32),
                            axis=-1).astype(logits.dtype)
        return {"Softmax": sm, "Loss": loss}
    # fp32 statistics INTERNALLY (gray-listed under AMP): bf16 in/out,
    # fp32 softmax math — the layer_norm/batch_norm discipline
    lsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    sm = jnp.exp(lsm).astype(logits.dtype)
    if soft:
        loss = -jnp.sum(label.astype(jnp.float32) * lsm, axis=-1,
                        keepdims=True)
    else:
        lbl = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 else label
        loss = -jnp.take_along_axis(lsm, lbl[..., None].astype(np.int32), axis=-1)
        if ignore >= 0:
            loss = jnp.where(lbl[..., None] == ignore, jnp.zeros_like(loss), loss)
    return {"Softmax": sm, "Loss": loss.astype(logits.dtype)}


@register_grad_compute("softmax_with_cross_entropy")
def softmax_with_cross_entropy_grad(ctx: ExecContext):
    """dLogits = (softmax - onehot(label)) * dLoss — the classic fused form
    (reference softmax_with_cross_entropy_op.cu)."""
    sm = ctx.input("Softmax")
    label = ctx.input("Label")
    dloss = ctx.input("Loss@GRAD")
    soft = ctx.attr("soft_label", False)
    logits = ctx.input("Logits")
    if (logits is not None
            and _xent_pallas_eligible(logits, soft,
                                      ctx.attr("ignore_index", -100))):
        # same predicate as the forward: recompute stats in-VMEM from the
        # logits instead of reading the (never-materialized) softmax
        from .pallas_kernels import xent as px

        lbl = (label.reshape(label.shape[:-1])
               if label.shape[-1] == 1 else label)
        n = int(np.prod(logits.shape[:-1]))
        dx = px.xent_grad(logits.reshape(n, logits.shape[-1]),
                          lbl.reshape(n).astype(jnp.int32),
                          dloss.reshape(n))
        return {"Logits@GRAD": dx.reshape(logits.shape)}
    if soft:
        grad = (sm - label) * dloss
    else:
        lbl = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 else label
        onehot = jax.nn.one_hot(lbl, sm.shape[-1], dtype=sm.dtype)
        grad = (sm - onehot) * dloss
        ignore = ctx.attr("ignore_index", -100)
        if ignore >= 0:
            grad = jnp.where((lbl == ignore)[..., None], jnp.zeros_like(grad), grad)
    return {"Logits@GRAD": grad}


def softmax_with_cross_entropy_grad_maker(op, block, no_grad_set=frozenset()):
    from ..framework import grad_var_name

    logits = op.input("Logits")[0]
    if logits in no_grad_set:
        return []
    return [
        {
            "type": "softmax_with_cross_entropy_grad",
            "inputs": {
                "Softmax": op.output("Softmax"),
                # Logits feed the Pallas fast path's in-VMEM stat recompute;
                # the classic path ignores them
                "Logits": op.input("Logits"),
                "Label": op.input("Label"),
                "Loss@GRAD": [grad_var_name(op.output("Loss")[0])],
            },
            "outputs": {"Logits@GRAD": [grad_var_name(logits)]},
            "attrs": dict(op.attrs),
        }
    ]


# wire the custom maker in (registered after the op exists)
from .registry import get_op_def  # noqa: E402

get_op_def("softmax_with_cross_entropy").grad_maker = softmax_with_cross_entropy_grad_maker


@register_op("sigmoid_cross_entropy_with_logits")
def sigmoid_cross_entropy_with_logits(ctx: ExecContext):
    x, label = ctx.input("X"), ctx.input("Label")
    # numerically stable: max(x,0) - x*z + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = ctx.attr("ignore_index", -100)
    loss = jnp.where(label == ignore, jnp.zeros_like(loss), loss)
    if ctx.attr("normalize", False):
        n = jnp.maximum(jnp.sum((label != ignore).astype(x.dtype)), 1.0)
        loss = loss / n
    return {"Out": loss}


@register_op("smooth_l1_loss")
def smooth_l1_loss(ctx: ExecContext):
    x, y = ctx.input("X"), ctx.input("Y")
    sigma = ctx.attr("sigma", 1.0)
    s2 = sigma * sigma
    d = x - y
    if ctx.has_input("InsideWeight"):
        d = d * ctx.input("InsideWeight")
    a = jnp.abs(d)
    loss = jnp.where(a < 1.0 / s2, 0.5 * d * d * s2, a - 0.5 / s2)
    if ctx.has_input("OutsideWeight"):
        loss = loss * ctx.input("OutsideWeight")
    return {"Out": jnp.sum(loss, axis=-1, keepdims=True), "Diff": d}


# ---------------------------------------------------------------------------
# Fused epilogue dispatch (ISSUE 9): normalize+affine+activation(+residual)
# ---------------------------------------------------------------------------

_EPILOGUE_ACTS = {"identity": lambda z: z,
                  "relu": lambda z: jnp.maximum(z, 0.0)}


def _epilogue_backend(kind, rows, channels, channel_pos, act, has_res,
                      dtype) -> str:
    """Which implementation carries one fused-epilogue apply: the Pallas
    kernel or the XLA composition. Same three-tier contract as the conv/
    attention levers (PR 6): FLAGS_pallas_epilogue 'on'/'off' are hard
    forces for the A/B arms; 'auto' consults the tuning DB with the XLA
    composition as the analytic prior — the kernel ships off until a swept
    verdict keeps it for the exact shape (the r5 rule). Callers still gate
    on `_epilogue_ok`, so a swept/forced kernel the platform cannot run
    degrades to XLA at dispatch."""
    mode = str(flags.get_flag("pallas_epilogue")).strip().lower()
    if mode == "off":
        return "xla"
    if mode == "on":
        return "pallas"
    from .. import tuning

    if tuning.mode() == "off":
        return "xla"
    key = tuning.canonical_key(
        "epilogue",
        tuning.epilogue_key(kind, rows, channels, channel_pos, act, has_res),
        str(jnp.dtype(dtype)), tuning.device_kind())
    decision, _tier = tuning.decide(
        "epilogue", key, prior=lambda: {"backend": "xla"},
        default={"backend": "xla"},
        validate=lambda dd: dd.get("backend") in ("xla", "pallas"))
    return decision.get("backend", "xla")


def _epilogue_ok(shape, dtype, channel_last, act) -> bool:
    from .pallas_kernels import epilogue as ep
    from .pallas_kernels import workbench

    return (workbench.runnable(ep)
            and ep.epilogue_supported(shape, dtype, channel_last, act))


def _bn_epilogue(x_for_apply, scale, bias, use_mean, inv, act, residual,
                 channel_last, bshape):
    """One fused-epilogue finish for batch_norm/conv2d_bn: dispatch per
    `_epilogue_backend`, Pallas kernel where a verdict keeps it and the
    shape/platform can run it, the fp32 jnp composition (bit-identical to
    the pre-fusion op chain) everywhere else."""
    act = act or "identity"
    C = x_for_apply.shape[-1 if channel_last else 1]
    rows = int(np.prod(x_for_apply.shape)) // max(1, C)
    backend = _epilogue_backend(
        "bn", rows, C, "last" if channel_last else "row", act,
        residual is not None, x_for_apply.dtype)
    if (backend == "pallas"
            and act in _EPILOGUE_ACTS
            and _epilogue_ok(x_for_apply.shape, x_for_apply.dtype,
                             channel_last, act)):
        from .pallas_kernels import epilogue as ep

        return ep.bn_apply_act(x_for_apply, scale, bias, use_mean, inv,
                               act=act, residual=residual,
                               channel_last=channel_last)
    y = (x_for_apply.astype(jnp.float32) - use_mean.reshape(bshape)) \
        * inv.reshape(bshape)
    y = y * scale.reshape(bshape) + bias.reshape(bshape)
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    y = _EPILOGUE_ACTS.get(act, _EPILOGUE_ACTS["identity"])(y)
    return y.astype(x_for_apply.dtype)


@register_op("batch_norm", stateful_outputs=("MeanOut", "VarianceOut"))
def batch_norm(ctx: ExecContext):
    x = ctx.input("X")
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    mean, var = ctx.input("Mean"), ctx.input("Variance")
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    is_test = ctx.attr("is_test", False)
    layout = ctx.attr("data_layout", "NCHW")
    axes = tuple(i for i in range(x.ndim) if i != (1 if layout == "NCHW" else x.ndim - 1))
    bshape = [1] * x.ndim
    bshape[1 if layout == "NCHW" else x.ndim - 1] = -1

    if is_test:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = jnp.zeros_like(mean)
        saved_var = jnp.zeros_like(var)
    else:
        xf = x.astype(jnp.float32)
        use_mean = jnp.mean(xf, axis=axes)
        use_var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(use_mean)
        mean_out = mean * momentum + use_mean.astype(mean.dtype) * (1 - momentum)
        var_out = var * momentum + use_var.astype(var.dtype) * (1 - momentum)
        saved_mean = use_mean.astype(mean.dtype)
        saved_var = (1.0 / jnp.sqrt(use_var + eps)).astype(var.dtype)
    inv = 1.0 / jnp.sqrt(use_var.astype(jnp.float32) + eps)
    # fused epilogue (ISSUE 9): the minimize()-time pass may have folded a
    # trailing activation (attr `act`) and/or a residual add (input
    # `Residual`) into this op; _bn_epilogue dispatches the whole apply
    # chain per the tuning DB (Pallas kernel only where a swept verdict
    # keeps it — XLA composition, bit-identical to the unfused chain,
    # everywhere else)
    res = ctx.input("Residual") if ctx.has_input("Residual") else None
    y = _bn_epilogue(x, scale, bias,
                     use_mean.astype(jnp.float32), inv,
                     ctx.attr("act", ""), res,
                     channel_last=layout != "NCHW", bshape=bshape)
    return {
        "Y": y,
        "MeanOut": mean_out,
        "VarianceOut": var_out,
        "SavedMean": saved_mean,
        "SavedVariance": saved_var,
    }


@register_op("conv2d_bn", stateful_outputs=("MeanOut", "VarianceOut"))
def conv2d_bn(ctx: ExecContext):
    """Fused conv2d -> batch_norm(training) with one-pass epilogue
    statistics (passes.fuse_conv_bn_stats rewrites eligible pairs to this).

    The separate batch_norm op re-reads the conv output from HBM to reduce
    E[x]/E[x^2] — measured at 17-35% of ResNet stage time (PERF.md r5,
    tools/_rn_diag.py). Here both statistics are computed as siblings of the
    conv's own result — on the implicit-GEMM path directly from the fp32 GEMM
    accumulator before the bf16 down-cast — so XLA's multi-output fusion can
    emit them in the producer's epilogue while the tile is still on-chip,
    instead of a second HBM traversal. Statistics stay fp32 regardless of the
    activation dtype (the AMP gray-list discipline; bf16 in/out is safe
    because nothing below fp32 ever carries a running statistic)."""
    out, acc = _conv2d_forward(ctx)
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    mean, var = ctx.input("Mean"), ctx.input("Variance")
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    fmt = ctx.attr("data_format", "NCHW")
    cax = 1 if fmt == "NCHW" else out.ndim - 1
    axes = tuple(i for i in range(out.ndim) if i != cax)
    bshape = [1] * out.ndim
    bshape[cax] = -1

    # one-pass statistics from the highest-precision view available: the
    # implicit-GEMM fp32 accumulator when the conv took that path (exact
    # pre-rounding moments), else an fp32 upcast of the conv result (the
    # same values batch_norm would see, now adjacent to the producer)
    xf = acc if acc is not None else out.astype(jnp.float32)
    use_mean = jnp.mean(xf, axis=axes)
    use_var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(use_mean)
    mean_out = mean * momentum + use_mean.astype(mean.dtype) * (1 - momentum)
    var_out = var * momentum + use_var.astype(var.dtype) * (1 - momentum)
    inv = 1.0 / jnp.sqrt(use_var + eps)
    # fused epilogue (ISSUE 9): same contract as batch_norm — the apply
    # chain (normalize+affine[+residual][+act]) dispatches through the
    # tuning DB. The Pallas arm normalizes the fp32 accumulator view so the
    # one-read-one-write kernel sees the exact pre-rounding values the
    # statistics came from.
    res = ctx.input("Residual") if ctx.has_input("Residual") else None
    y = _bn_epilogue(xf, scale, bias, use_mean, inv,
                     ctx.attr("act", ""), res,
                     channel_last=fmt != "NCHW", bshape=bshape)
    return {
        "Y": y.astype(out.dtype),
        "MeanOut": mean_out,
        "VarianceOut": var_out,
        "SavedMean": use_mean.astype(mean.dtype),
        "SavedVariance": (1.0 / jnp.sqrt(use_var + eps)).astype(var.dtype),
    }


@register_op("layer_norm")
def layer_norm(ctx: ExecContext):
    x = ctx.input("X")
    eps = ctx.attr("epsilon", 1e-5)
    begin = ctx.attr("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    act = ctx.attr("act", "") or "identity"
    scale = ctx.input("Scale") if ctx.has_input("Scale") else None
    bias = ctx.input("Bias") if ctx.has_input("Bias") else None
    xf = x.astype(jnp.float32)
    # Mean/Variance outputs stay PLAIN jnp expressions on every backend:
    # XLA dead-code-eliminates them when nothing consumes them (the usual
    # case), and gradient contributions through them flow via this jnp
    # path even when Y comes from the Pallas kernel (whose own backward
    # recomputes row statistics on-chip and never sees these cotangents)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    R = int(np.prod(x.shape[:begin])) if begin else 1
    K = int(np.prod(x.shape[begin:]))
    backend = _epilogue_backend("ln", R, K, "last", act, False, x.dtype)
    if backend == "pallas" and _epilogue_ok((R, K), x.dtype, True, act):
        from .pallas_kernels import epilogue as ep

        y = ep.layer_norm_act(
            x.reshape(R, K),
            scale.reshape(-1) if scale is not None else None,
            bias.reshape(-1) if bias is not None else None,
            eps=eps, act=act).reshape(x.shape)
    else:
        norm_shape = x.shape[begin:]
        y = (xf - mean) / jnp.sqrt(var + eps)
        if scale is not None:
            y = y * scale.reshape(norm_shape).astype(jnp.float32)
        if bias is not None:
            y = y + bias.reshape(norm_shape).astype(jnp.float32)
        y = _EPILOGUE_ACTS.get(act, _EPILOGUE_ACTS["identity"])(y)
        y = y.astype(x.dtype)
    return {
        "Y": y,
        "Mean": mean.reshape(x.shape[:begin]).astype(jnp.float32),
        "Variance": var.reshape(x.shape[:begin]).astype(jnp.float32),
    }


@register_op("group_norm")
def group_norm(ctx: ExecContext):
    x = ctx.input("X")  # NCHW
    groups = ctx.attr("groups")
    eps = ctx.attr("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape(n, groups, c // groups, *x.shape[2:]).astype(jnp.float32)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) / jnp.sqrt(var + eps)).reshape(x.shape)
    bshape = [1, c] + [1] * (x.ndim - 2)
    if ctx.has_input("Scale"):
        y = y * ctx.input("Scale").reshape(bshape)
    if ctx.has_input("Bias"):
        y = y + ctx.input("Bias").reshape(bshape)
    return {
        "Y": y.astype(x.dtype),
        "Mean": mean.reshape(n, groups),
        "Variance": var.reshape(n, groups),
    }


@register_op("instance_norm")
def instance_norm(ctx: ExecContext):
    x = ctx.input("X")  # NCHW
    eps = ctx.attr("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) / jnp.sqrt(var + eps)
    c = x.shape[1]
    bshape = [1, c] + [1] * (x.ndim - 2)
    if ctx.has_input("Scale"):
        y = y * ctx.input("Scale").reshape(bshape)
    if ctx.has_input("Bias"):
        y = y + ctx.input("Bias").reshape(bshape)
    return {"Y": y.astype(x.dtype)}


@register_op("dropout", needs_rng=True)
def dropout(ctx: ExecContext):
    x = ctx.input("X")
    p = ctx.attr("dropout_prob", 0.5)
    impl = ctx.attr("dropout_implementation", "downgrade_in_infer")
    if ctx.attr("is_test", False):
        if impl == "upscale_in_train":
            return {"Out": x, "Mask": jnp.ones_like(x)}
        return {"Out": x * jnp.asarray(1.0 - p, x.dtype), "Mask": jnp.ones_like(x)}
    keep = jax.random.bernoulli(ctx.rng, 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        mask = keep.astype(x.dtype) / jnp.asarray(max(1.0 - p, 1e-8), x.dtype)
    else:
        mask = keep.astype(x.dtype)
    return {"Out": x * mask, "Mask": mask}


@register_grad_compute("dropout")
def dropout_grad(ctx: ExecContext):
    return {"X@GRAD": ctx.input("Out@GRAD") * ctx.input("Mask")}


def dropout_grad_maker(op, block, no_grad_set=frozenset()):
    from ..framework import grad_var_name

    x = op.input("X")[0]
    if x in no_grad_set:
        return []
    return [
        {
            "type": "dropout_grad",
            "inputs": {
                "Mask": op.output("Mask"),
                "Out@GRAD": [grad_var_name(op.output("Out")[0])],
            },
            "outputs": {"X@GRAD": [grad_var_name(x)]},
            "attrs": dict(op.attrs),
        }
    ]


get_op_def("dropout").grad_maker = dropout_grad_maker


@register_op("lookup_table")
def lookup_table(ctx: ExecContext):
    w, ids = ctx.input("W"), ctx.input("Ids")
    idsq = ids.reshape(ids.shape[:-1]) if ids.shape and ids.shape[-1] == 1 else ids
    out = jnp.take(w, idsq.astype(np.int32), axis=0)
    padding_idx = ctx.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((idsq == padding_idx)[..., None], jnp.zeros_like(out), out)
    return {"Out": out}


@register_op("lookup_table_v2")
def lookup_table_v2(ctx: ExecContext):
    return lookup_table(ctx)


@register_grad_compute("lookup_table")
def lookup_table_grad(ctx: ExecContext):
    """W grad: dense scatter-add, or a SelectedRows row-set when is_sparse —
    the reference's SelectedRows grad path (lookup_table_op.cc grad kernel +
    selected_rows.h:32), kept fixed-shape for XLA."""
    from ..core.selected_rows import SelectedRows

    w, ids, og = ctx.input("W"), ctx.input("Ids"), ctx.input("Out@GRAD")
    if og is None:
        return {"W@GRAD": jnp.zeros_like(w)}
    idsq = ids.reshape(ids.shape[:-1]) if ids.shape and ids.shape[-1] == 1 else ids
    idsq = idsq.astype(np.int32)
    width = og.shape[-1]
    padding_idx = ctx.attr("padding_idx", -1)
    rows = idsq.reshape(-1)
    vals = og.reshape(-1, width)
    if padding_idx is not None and padding_idx >= 0:
        vals = jnp.where((rows == padding_idx)[:, None], jnp.zeros_like(vals), vals)
    if ctx.attr("is_sparse", False):
        return {"W@GRAD": SelectedRows(rows, vals, height=w.shape[0])}
    dense = jnp.zeros_like(w).at[rows].add(vals.astype(w.dtype))
    return {"W@GRAD": dense}


register_grad_compute("lookup_table_v2")(lookup_table_grad)


def _no_grad_ops_maker(op, block, no_grad_set=frozenset()):
    """Grad maker for state-plumbing ops that sit ON the gradient path but
    contribute no gradient ops of their own (the tiered cache install: the
    cache gradient is produced entirely by tiered_lookup_grad and applied by
    the optimizer to the post-install value)."""
    return []


@register_op("emb_cache_install", grad=_no_grad_ops_maker)
def emb_cache_install(ctx: ExecContext):
    """Land this batch's prefetched host rows in the device cache (tiered
    embeddings, ISSUE 10). Writes its output back to the SAME cache var name
    (the executor's rw/donation path — the PR 7 paged-KV pattern), and emits
    the PRE-install contents of the overwritten slots: those are exactly the
    evicted rows, carrying every optimizer update they ever received, which
    the engine writes back to the host tier when the step's output
    materializes. Padding entries point at the masked scratch slot."""
    cache, rows, slots = (ctx.input("Cache"), ctx.input("Rows"),
                          ctx.input("Slots"))
    slots = slots.astype(np.int32)
    evicted = jnp.take(cache, slots, axis=0)
    new_cache = cache.at[slots].set(rows.astype(cache.dtype))
    return {"Out": new_cache, "Evicted": evicted}


@register_op("tiered_lookup")
def tiered_lookup(ctx: ExecContext):
    """lookup_table over the hot-ID cache: ids were mapped to cache slots by
    the host-side resolver (embedding/engine.py), so the compiled step is one
    HBM gather. Slot `scratch_slot` (the cache's last row) marks padding /
    unresolvable positions and reads as zeros."""
    cache, slot_ids = ctx.input("Cache"), ctx.input("SlotIds")
    idsq = slot_ids.reshape(slot_ids.shape[:-1]) \
        if slot_ids.shape and slot_ids.shape[-1] == 1 else slot_ids
    idsq = idsq.astype(np.int32)
    out = jnp.take(cache, idsq, axis=0)
    scratch = int(ctx.attr("scratch_slot"))
    out = jnp.where((idsq == scratch)[..., None], jnp.zeros_like(out), out)
    return {"Out": out}


@register_grad_compute("tiered_lookup")
def tiered_lookup_grad(ctx: ExecContext):
    """Cache grad: dense scatter-add over the [slots+1, dim] cache — small by
    construction (the cache, not the table), so the optimizer's dense row
    update stays one fused XLA kernel. Scratch-slot positions (padding)
    contribute nothing, mirroring lookup_table's padding_idx contract."""
    cache, slot_ids, og = (ctx.input("Cache"), ctx.input("SlotIds"),
                           ctx.input("Out@GRAD"))
    if og is None:
        return {"Cache@GRAD": jnp.zeros_like(cache)}
    idsq = slot_ids.reshape(slot_ids.shape[:-1]) \
        if slot_ids.shape and slot_ids.shape[-1] == 1 else slot_ids
    rows = idsq.reshape(-1).astype(np.int32)
    width = og.shape[-1]
    vals = og.reshape(-1, width)
    scratch = int(ctx.attr("scratch_slot"))
    vals = jnp.where((rows == scratch)[:, None], jnp.zeros_like(vals), vals)
    dense = jnp.zeros_like(cache).at[rows].add(vals.astype(cache.dtype))
    return {"Cache@GRAD": dense}


@register_op("accuracy", grad="none")
def accuracy(ctx: ExecContext):
    idx, label = ctx.input("Indices"), ctx.input("Label")
    lbl = label.reshape(-1, 1)
    correct = jnp.any(idx == lbl, axis=1)
    num_correct = jnp.sum(correct.astype(np.int32))
    total = jnp.asarray(lbl.shape[0], np.int32)
    return {
        "Accuracy": (num_correct / total).astype(np.float32).reshape(1),
        "Correct": num_correct.reshape(1),
        "Total": total.reshape(1),
    }


@register_op("label_smooth")
def label_smooth(ctx: ExecContext):
    x = ctx.input("X")
    eps = ctx.attr("epsilon", 0.0)
    if ctx.has_input("PriorDist"):
        prior = ctx.input("PriorDist")
        return {"Out": (1 - eps) * x + eps * prior}
    return {"Out": (1 - eps) * x + eps / x.shape[-1]}


@register_op("prelu")
def prelu(ctx: ExecContext):
    x, alpha = ctx.input("X"), ctx.input("Alpha")
    mode = ctx.attr("mode", "all")
    if mode == "all":
        a = alpha.reshape(())
    elif mode == "channel":
        a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:
        a = alpha.reshape((1,) + x.shape[1:])
    return {"Out": jnp.where(x >= 0, x, x * a)}


@register_op("softmax_mask_fuse_upper_triangle")
def softmax_mask_fuse_upper_triangle(ctx: ExecContext):
    """Causal-masked softmax — fused attention helper (TPU-first addition)."""
    x = ctx.input("X")
    q, k = x.shape[-2], x.shape[-1]
    mask = jnp.tril(jnp.ones((q, k), bool))
    neg = jnp.asarray(-1e9 if x.dtype != jnp.float16 else -6e4, x.dtype)
    return {"Out": jax.nn.softmax(jnp.where(mask, x, neg), axis=-1)}


@register_op("lookup_table_grad_rows", grad="none")
def lookup_table_grad_rows(ctx: ExecContext):
    """Gradient for a DISTRIBUTED lookup table (transpiler-rewritten from
    lookup_table_grad): builds the SelectedRows row-gradient from Ids +
    Out@GRAD alone — the table itself lives on the pservers and is not in
    the trainer scope (reference lookup_table rewrite,
    distribute_transpiler.py:1503)."""
    from ..core.selected_rows import SelectedRows

    ids, og = ctx.input("Ids"), ctx.input("Out@GRAD")
    height = int(ctx.attr("height"))
    idsq = ids.reshape(ids.shape[:-1]) if ids.shape and ids.shape[-1] == 1 else ids
    if og is None:
        # output's grad never materialized (grad-pruned consumer): an empty
        # row set, same degrade as lookup_table_grad's zeros
        return {"W@GRAD": SelectedRows(
            jnp.zeros((0,), jnp.int32), jnp.zeros((0, 1), jnp.float32),
            height=height)}
    width = og.shape[-1]
    rows = idsq.reshape(-1).astype(np.int32)
    vals = og.reshape(-1, width)
    padding_idx = ctx.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        vals = jnp.where((rows == padding_idx)[:, None],
                         jnp.zeros_like(vals), vals)
    return {"W@GRAD": SelectedRows(rows, vals, height=height)}


def data_norm(ctx: ExecContext):
    """CTR data normalization (reference data_norm_op.cc:193): channel stats
    come from ACCUMULATED batch counters, not this batch: means =
    BatchSum/BatchSize, scales = sqrt(BatchSize/BatchSquareSum); y =
    (x - means) * scales. The counters are trainable parameters whose
    "gradients" (see data_norm_grad below) are the batch's contribution."""
    x = ctx.input("X")
    bsize = ctx.input("BatchSize").astype(jnp.float32)
    bsum = ctx.input("BatchSum").astype(jnp.float32)
    bsq = ctx.input("BatchSquareSum").astype(jnp.float32)
    means = bsum / bsize
    scales = jnp.sqrt(bsize / bsq)
    y = (x.astype(jnp.float32) - means[None, :]) * scales[None, :]
    return {"Y": y.astype(x.dtype), "Means": means, "Scales": scales}


def _data_norm_grad_maker(op, block, no_grad_set=frozenset()):
    from ..framework import grad_var_name

    outs = {}
    for slot in ("X", "BatchSize", "BatchSum", "BatchSquareSum"):
        n = op.inputs[slot][0]
        if n not in no_grad_set:
            outs[slot + "@GRAD"] = [grad_var_name(n)]
    if not outs:
        return []
    return [{
        "type": "data_norm_grad",
        "inputs": {
            "X": list(op.inputs["X"]),
            "BatchSize": list(op.inputs["BatchSize"]),
            "BatchSum": list(op.inputs["BatchSum"]),
            "BatchSquareSum": list(op.inputs["BatchSquareSum"]),
            "Y@GRAD": [grad_var_name(op.outputs["Y"][0])],
        },
        "outputs": outs,
        "attrs": dict(op.attrs),
    }]


register_op("data_norm", grad=_data_norm_grad_maker)(data_norm)


@register_grad_compute("data_norm")
def data_norm_grad(ctx: ExecContext):
    """reference data_norm_op.cc:280 — dX = dY*scales; the counter 'grads'
    are the batch statistics themselves (count N, sum x, sum (x-mean)^2 +
    N*eps), which the optimizer's minus-lr step folds into the running
    accumulators (the reference trains them with a dedicated negative-lr
    stanza; parity keeps the same contract)."""
    x = ctx.input("X").astype(jnp.float32)
    bsize = ctx.input("BatchSize").astype(jnp.float32)
    bsum = ctx.input("BatchSum").astype(jnp.float32)
    bsq = ctx.input("BatchSquareSum").astype(jnp.float32)
    gy = ctx.input("Y@GRAD")
    eps = float(ctx.attr("epsilon", 1e-4))
    N = x.shape[0]
    means = bsum / bsize
    scales = jnp.sqrt(bsize / bsq)
    out = {}
    if "X@GRAD" in ctx.op.outputs:
        out["X@GRAD"] = (gy.astype(jnp.float32) *
                         scales[None, :]).astype(gy.dtype)
    if "BatchSize@GRAD" in ctx.op.outputs:
        out["BatchSize@GRAD"] = jnp.full_like(bsize, float(N))
    if "BatchSum@GRAD" in ctx.op.outputs:
        out["BatchSum@GRAD"] = x.sum(axis=0)
    if "BatchSquareSum@GRAD" in ctx.op.outputs:
        out["BatchSquareSum@GRAD"] = \
            ((x - means[None, :]) ** 2).sum(axis=0) + float(N) * eps
    return out


@register_op("spectral_norm", stateful_outputs=("UOut", "VOut"))
def spectral_norm(ctx: ExecContext):
    """reference spectral_norm_op.*: W / sigma_max(W) via power iteration.
    Weight reshaped to [h, w] around attr dim; U [h], V [w] persist across
    steps (UOut/VOut write back). Gradients flow to Weight only (u, v are
    stop-gradient auxiliaries, like the reference's)."""
    w = ctx.input("Weight")
    u = ctx.input("U").reshape(-1).astype(jnp.float32)
    v = ctx.input("V").reshape(-1).astype(jnp.float32)
    dim = int(ctx.attr("dim", 0))
    iters = int(ctx.attr("power_iters", 1))
    eps = float(ctx.attr("eps", 1e-12))
    perm = [dim] + [i for i in range(w.ndim) if i != dim]
    wm = jnp.transpose(w, perm).reshape(w.shape[dim], -1).astype(jnp.float32)

    def norm(a):
        return a / (jnp.linalg.norm(a) + eps)

    u = jax.lax.stop_gradient(u)
    v = jax.lax.stop_gradient(v)
    for _ in range(iters):
        v = norm(wm.T @ u)
        u = norm(wm @ v)
    u = jax.lax.stop_gradient(u)
    v = jax.lax.stop_gradient(v)
    sigma = u @ wm @ v
    out = (w.astype(jnp.float32) / sigma).astype(w.dtype)
    return {"Out": out, "UOut": u.astype(w.dtype), "VOut": v.astype(w.dtype)}


@register_op("tree_conv")
def tree_conv(ctx: ExecContext):
    """Tree-based convolution, TBCNN (reference tree_conv_op.* +
    math/tree2col.cc). NodesVector [B, N, F] (node i at row i-1 — edges are
    1-indexed, 0 marks padding), EdgeSet [B, E, 2] int (parent, child),
    Filter [F, 3, O, M] with the triplet order (eta_l, eta_r, eta_t).
    The reference's stack-walk patch construction becomes dense [N+1, N+1]
    eta matrices: reachability powers give rel-depth, per-edge child
    index/pclen give the continuous weights — one einsum per component."""
    feat = ctx.input("NodesVector")
    edges = ctx.input("EdgeSet").astype(jnp.int32)
    filt = ctx.input("Filter").astype(jnp.float32)
    D = int(ctx.attr("max_depth", 2))
    B, N, F = feat.shape
    E = edges.shape[1]

    def one(fb, eb):
        u, v = eb[:, 0], eb[:, 1]
        valid = (u > 0) & (v > 0)
        uc = jnp.where(valid, u, 0)
        vc = jnp.where(valid, v, 0)
        A = jnp.zeros((N + 1, N + 1), jnp.float32).at[uc, vc].add(
            jnp.where(valid, 1.0, 0.0))
        A = A.at[0, 0].set(0.0)
        # rel[u, v] = path length u->v (tree: unique), sentinel D if >= D
        reach = jnp.eye(N + 1, dtype=jnp.float32)
        rel = jnp.where(jnp.eye(N + 1, dtype=bool), 0, D)
        for r in range(1, D):
            reach = reach @ A
            rel = jnp.where((reach > 0) & (rel == D), r, rel)
        in_patch = rel < D
        # per-node child index (1-based among siblings) and parent fanout
        same_parent = (u[:, None] == u[None, :]) & valid[None, :] & \
            valid[:, None]
        earlier = same_parent & (jnp.arange(E)[None, :] < jnp.arange(E)[:, None])
        idx_e = earlier.sum(axis=1).astype(jnp.float32) + 1.0   # per edge
        pclen_e = same_parent.sum(axis=1).astype(jnp.float32)
        node_index = jnp.ones((N + 1,), jnp.float32).at[vc].set(
            jnp.where(valid, idx_e, 1.0))
        node_pclen = jnp.ones((N + 1,), jnp.float32).at[vc].set(
            jnp.where(valid, pclen_e, 1.0))
        temp = jnp.where(node_pclen <= 1.0, 0.5,
                         (node_index - 1.0) / jnp.maximum(
                             node_pclen - 1.0, 1.0))
        eta_t = (D - rel.astype(jnp.float32)) / float(D)
        # the patch ROOT enters as TreeNode(root,1,1,0): index=pclen=1
        temp_uv = jnp.where(jnp.eye(N + 1, dtype=bool), 0.5, temp[None, :])
        eta_l = (1.0 - eta_t) * temp_uv
        eta_r = (1.0 - eta_t) * (1.0 - temp_uv)
        mask = in_patch.astype(jnp.float32)
        # node existence: referenced by any valid edge (or is node 1, the root)
        exists = jnp.zeros((N + 1,), bool).at[uc].set(valid).at[vc].set(
            valid).at[1].set(True).at[0].set(False)
        mask = mask * exists[None, :] * exists[:, None]
        fpad = jnp.concatenate(
            [jnp.zeros((1, F), jnp.float32), fb.astype(jnp.float32)], axis=0)
        patches = [ (eta_l * mask) @ fpad,      # [N+1, F] component l
                    (eta_r * mask) @ fpad,
                    (eta_t * mask) @ fpad ]
        patch = jnp.stack(patches, axis=-1)[1:]  # [N, F, 3]
        return jnp.einsum("nfc,fcom->nom", patch, filt)

    out = jax.vmap(one)(feat, edges)
    return {"Out": out.astype(feat.dtype)}
