"""Tensor manipulation + creation ops.

TPU-native equivalents of the reference's fill_constant_op.cc,
uniform_random_op.cc, gaussian_random_op.cc, assign_op.cc, reshape_op.cc,
transpose_op.cc, concat_op.cc, split_op.cc, slice_op.cc, squeeze/unsqueeze,
stack_op.cc, expand_op.cc, gather_op.cc, scatter_op.cc, cum_op, arg_min_max,
top_k_op.cc, one_hot_op.cc, range_op.cc, compare/logical ops, shape_op.cc
(/root/reference/paddle/fluid/operators/). Random ops use JAX's counter-based
PRNG (key threaded by the executor) rather than a stateful generator — that is
what keeps them safe under XLA tracing and SPMD sharding.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import np_dtype, np_feed_dtype

# index outputs (argmax/top_k/argsort/hash) are int64 in the reference API;
# with jax x64 off that dtype does not exist on the device and every
# `.astype(int64)` on a tracer emits jax's "will be truncated" UserWarning
# (one per bench tail). Request the runtime's actual index dtype instead —
# int32 under x32, true int64 when x64 is enabled.
_INDEX_DTYPE = np_feed_dtype("int64")
from .registry import (
    ExecContext,
    get_op_def,
    register_grad_compute,
    register_op,
)


@register_op("fill_constant", grad="none")
def fill_constant(ctx: ExecContext):
    shape = tuple(ctx.attr("shape", []))
    # np_feed_dtype: int64 fills narrow to int32 under x64-off jax without
    # the per-trace truncation warning (jnp.full would warn-and-truncate)
    dtype = np_feed_dtype(ctx.attr("dtype", "float32"))
    return {"Out": jnp.full(shape, ctx.attr("value", 0.0), dtype)}


@register_op("fill_zeros_like", grad="none")
def fill_zeros_like(ctx: ExecContext):
    return {"Out": jnp.zeros_like(ctx.input("X"))}


@register_op("fill_any_like", grad="none")
def fill_any_like(ctx: ExecContext):
    return {"Out": jnp.full_like(ctx.input("X"), ctx.attr("value", 0.0))}


@register_op("uniform_random", grad="none", needs_rng=True)
def uniform_random(ctx: ExecContext):
    shape = tuple(ctx.attr("shape"))
    dtype = np_dtype(ctx.attr("dtype", "float32"))
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    return {"Out": jax.random.uniform(ctx.rng, shape, jnp.float32, lo, hi).astype(dtype)}


@register_op("gaussian_random", grad="none", needs_rng=True)
def gaussian_random(ctx: ExecContext):
    shape = tuple(ctx.attr("shape"))
    dtype = np_dtype(ctx.attr("dtype", "float32"))
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    out = jax.random.normal(ctx.rng, shape, jnp.float32) * std + mean
    return {"Out": out.astype(dtype)}


@register_op("truncated_gaussian_random", grad="none", needs_rng=True)
def truncated_gaussian_random(ctx: ExecContext):
    shape = tuple(ctx.attr("shape"))
    dtype = np_dtype(ctx.attr("dtype", "float32"))
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    out = jax.random.truncated_normal(ctx.rng, -2.0, 2.0, shape, jnp.float32)
    return {"Out": (out * std + mean).astype(dtype)}


@register_op("assign")
def assign(ctx: ExecContext):
    return {"Out": ctx.input("X")}


@register_op("shape", grad="none")
def shape_op(ctx: ExecContext):
    return {"Out": jnp.asarray(ctx.input("X").shape, np.int32)}


@register_op("reshape2")
def reshape2(ctx: ExecContext):
    x = ctx.input("X")
    shape = list(ctx.attr("shape"))
    # reference semantics (reshape_op.cc): 0 means "copy this input dim"
    shape = [x.shape[i] if d == 0 else d for i, d in enumerate(shape[: x.ndim])] + [
        d for d in shape[x.ndim :]
    ]
    return {"Out": jnp.reshape(x, shape)}


@register_op("flatten2")
def flatten2(ctx: ExecContext):
    x = ctx.input("X")
    axis = ctx.attr("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return {"Out": x.reshape(lead, -1)}


@register_op("transpose2")
def transpose2(ctx: ExecContext):
    return {"Out": jnp.transpose(ctx.input("X"), ctx.attr("axis"))}


@register_op("concat")
def concat(ctx: ExecContext):
    xs = [x for x in ctx.inputs("X") if x is not None]
    return {"Out": jnp.concatenate(xs, axis=ctx.attr("axis", 0))}


@register_op("split")
def split(ctx: ExecContext):
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    num = ctx.attr("num", 0)
    sections = ctx.attr("sections", [])
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register_op("slice")
def slice_op(ctx: ExecContext):
    x = ctx.input("Input")
    axes = ctx.attr("axes")
    starts = ctx.attr("starts")
    ends = ctx.attr("ends")
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        dim = x.shape[ax]
        st = max(st + dim, 0) if st < 0 else min(st, dim)
        en = max(en + dim, 0) if en < 0 else min(en, dim)
        idx[ax] = slice(st, en)
    return {"Out": x[tuple(idx)]}


@register_op("strided_slice")
def strided_slice(ctx: ExecContext):
    x = ctx.input("Input")
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(
        ctx.attr("axes"), ctx.attr("starts"), ctx.attr("ends"), ctx.attr("strides")
    ):
        idx[ax] = slice(st, en, sd)
    return {"Out": x[tuple(idx)]}


@register_op("squeeze2")
def squeeze2(ctx: ExecContext):
    x = ctx.input("X")
    axes = ctx.attr("axes", [])
    if not axes:
        return {"Out": jnp.squeeze(x)}
    return {"Out": jnp.squeeze(x, axis=tuple(a % x.ndim for a in axes))}


@register_op("unsqueeze2")
def unsqueeze2(ctx: ExecContext):
    x = ctx.input("X")
    for a in sorted(ctx.attr("axes")):
        x = jnp.expand_dims(x, a)
    return {"Out": x}


@register_op("stack")
def stack(ctx: ExecContext):
    xs = [x for x in ctx.inputs("X") if x is not None]
    return {"Y": jnp.stack(xs, axis=ctx.attr("axis", 0))}


@register_op("unstack")
def unstack(ctx: ExecContext):
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    return {"Y": [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis)]}


@register_op("expand")
def expand(ctx: ExecContext):
    x = ctx.input("X")
    times = ctx.attr("expand_times")
    return {"Out": jnp.tile(x, times)}


@register_op("gather")
def gather(ctx: ExecContext):
    x, idx = ctx.input("X"), ctx.input("Index")
    return {"Out": jnp.take(x, idx.reshape(-1), axis=0)}


@register_op("gather_nd")
def gather_nd(ctx: ExecContext):
    x, idx = ctx.input("X"), ctx.input("Index")
    return {"Out": x[tuple(jnp.moveaxis(idx, -1, 0))]}


@register_op("scatter")
def scatter(ctx: ExecContext):
    x, ids, upd = ctx.input("X"), ctx.input("Ids"), ctx.input("Updates")
    ids = ids.reshape(-1)
    if ctx.attr("overwrite", True):
        return {"Out": x.at[ids].set(upd)}
    return {"Out": x.at[ids].add(upd)}


@register_op("cum", grad=None)
def cumsum(ctx: ExecContext):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    out = jnp.cumsum(jnp.flip(x, axis) if ctx.attr("reverse", False) else x, axis=axis)
    if ctx.attr("reverse", False):
        out = jnp.flip(out, axis)
    if ctx.attr("exclusive", False):
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        out = jnp.pad(out, pad)[
            tuple(slice(0, -1) if i == axis % x.ndim else slice(None) for i in range(x.ndim))
        ]
    return {"Out": out}


@register_op("arg_max", grad="none")
def arg_max(ctx: ExecContext):
    return {"Out": jnp.argmax(ctx.input("X"), axis=ctx.attr("axis", -1)).astype(_INDEX_DTYPE)}


@register_op("arg_min", grad="none")
def arg_min(ctx: ExecContext):
    return {"Out": jnp.argmin(ctx.input("X"), axis=ctx.attr("axis", -1)).astype(_INDEX_DTYPE)}


@register_op("top_k", grad="none")
def top_k(ctx: ExecContext):
    x = ctx.input("X")
    k = ctx.attr("k", 1)
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": vals, "Indices": idx.astype(_INDEX_DTYPE)}


@register_op("one_hot", grad="none")
def one_hot(ctx: ExecContext):
    x = ctx.input("X")
    depth = ctx.attr("depth")
    x = x.reshape(x.shape[:-1]) if x.shape and x.shape[-1] == 1 else x
    return {"Out": jax.nn.one_hot(x, depth, dtype=np.float32)}


@register_op("range", grad="none")
def range_op(ctx: ExecContext):
    start, end, step = ctx.attr("start"), ctx.attr("end"), ctx.attr("step")
    # np_feed_dtype: an int64 range request narrows to int32 under x64-off
    # jax explicitly, instead of jnp.arange warning-and-truncating per call
    dtype = np_feed_dtype(ctx.attr("dtype", "int64"))
    return {"Out": jnp.arange(start, end, step, dtype)}


@register_op("increment")
def increment(ctx: ExecContext):
    x = ctx.input("X")
    return {"Out": x + jnp.asarray(ctx.attr("step", 1.0), x.dtype)}


@register_op("pad2d")
def pad2d(ctx: ExecContext):
    x = ctx.input("X")
    p = ctx.attr("paddings")  # [top, bottom, left, right], NCHW
    mode = ctx.attr("mode", "constant")
    pads = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        return {"Out": jnp.pad(x, pads, constant_values=ctx.attr("pad_value", 0.0))}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": jnp.pad(x, pads, mode=jmode)}


@register_op("pad")
def pad(ctx: ExecContext):
    x = ctx.input("X")
    p = ctx.attr("paddings")
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, pads, constant_values=ctx.attr("pad_value", 0.0))}


# -- comparison / logical (no grad) -----------------------------------------
def _cmp(fn):
    def compute(ctx: ExecContext):
        x, y = ctx.input("X"), ctx.input("Y")
        return {"Out": fn(x, y)}

    return compute


register_op("equal", grad="none")(_cmp(jnp.equal))
register_op("not_equal", grad="none")(_cmp(jnp.not_equal))
register_op("less_than", grad="none")(_cmp(jnp.less))
register_op("less_equal", grad="none")(_cmp(jnp.less_equal))
register_op("greater_than", grad="none")(_cmp(jnp.greater))
register_op("greater_equal", grad="none")(_cmp(jnp.greater_equal))
register_op("logical_and", grad="none")(_cmp(jnp.logical_and))
register_op("logical_or", grad="none")(_cmp(jnp.logical_or))
register_op("logical_xor", grad="none")(_cmp(jnp.logical_xor))


@register_op("logical_not", grad="none")
def logical_not(ctx: ExecContext):
    return {"Out": jnp.logical_not(ctx.input("X"))}


@register_op("where")
def where(ctx: ExecContext):
    return {"Out": jnp.where(ctx.input("Condition"), ctx.input("X"), ctx.input("Y"))}


@register_op("argsort", grad="none")
def argsort(ctx: ExecContext):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    return {"Out": jnp.sort(x, axis=axis), "Indices": idx.astype(_INDEX_DTYPE)}


@register_op("linspace", grad="none")
def linspace(ctx: ExecContext):
    return {
        "Out": jnp.linspace(
            ctx.attr("start"), ctx.attr("stop"), ctx.attr("num"),
            dtype=np_dtype(ctx.attr("dtype", "float32")),
        )
    }


@register_op("assign_value", grad="none")
def assign_value(ctx: ExecContext):
    vals = np.asarray(ctx.attr("values"), np_dtype(ctx.attr("dtype", "float32")))
    return {"Out": jnp.asarray(vals.reshape(ctx.attr("shape")))}


@register_op("fill_constant_batch_size_like", grad="none")
def fill_constant_batch_size_like(ctx: ExecContext):
    x = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    shape[ctx.attr("output_dim_idx", 0)] = x.shape[ctx.attr("input_dim_idx", 0)]
    return {"Out": jnp.full(shape, ctx.attr("value", 0.0), np_dtype(ctx.attr("dtype", "float32")))}


@register_op("uniform_random_batch_size_like", grad="none", needs_rng=True)
def uniform_random_batch_size_like(ctx: ExecContext):
    """reference uniform_random_batch_size_like_op.cc: shape from attr with
    the batch dim taken from Input."""
    x = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    shape[ctx.attr("output_dim_idx", 0)] = x.shape[ctx.attr("input_dim_idx", 0)]
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    dt = np_dtype(ctx.attr("dtype", "float32"))
    return {"Out": jax.random.uniform(
        ctx.rng, tuple(int(s) for s in shape), dt, lo, hi)}


@register_op("piecewise_decay", grad="none")
def piecewise_decay(ctx: ExecContext):
    """LR piecewise constant schedule, fused (reference
    learning_rate_scheduler.py:243 builds it from control-flow ops; on TPU a
    searchsorted gather is one fused XLA op)."""
    step = ctx.input("Step")
    bounds = jnp.asarray(ctx.attr("boundaries"), jnp.float32)
    values = jnp.asarray(ctx.attr("values"), jnp.float32)
    idx = jnp.searchsorted(bounds, jnp.reshape(step, ()), side="right")
    return {"Out": jnp.reshape(values[idx], (1,))}


def _print_value(ctx, x, phase_tag=""):
    # the first_n counter lives ON the Operator object: stable across
    # program rebuilds (an id()-keyed module dict would leak and could
    # inherit a dead op's exhausted count after id reuse)
    count = getattr(ctx.op, "_print_count", 0)
    first_n = int(ctx.attr("first_n", -1))
    if first_n < 0 or count < first_n:
        ctx.op._print_count = count + 1
        msg = ctx.attr("message", "") or ""
        arr = np.asarray(x)
        summarize = int(ctx.attr("summarize", 20))
        flat = arr.reshape(-1)
        shown = flat if summarize < 0 else flat[:summarize]
        print(f"{msg}{phase_tag}  shape={arr.shape} dtype={arr.dtype} "
              f"values={np.array2string(shown, precision=6)}", flush=True)


@register_op("print", host=True)
def print_op(ctx: ExecContext):
    """In-graph tensor printing (reference operators/print_op.cc): a host op
    that logs the value and passes it through unchanged, honoring
    first_n/message/summarize. NOTE: host ops split the jit and cannot run
    under a device mesh (use jax.debug.print inside custom ops for
    mesh-compatible tracing)."""
    x = ctx.input("In")
    if ctx.attr("print_phase", "both") in ("forward", "both"):
        _print_value(ctx, x)
    return {"Out": x}


@register_grad_compute("print")
def print_grad(ctx: ExecContext):
    """Identity gradient + optional backward-phase printing (reference
    print_op.cc PrintOpGradientMaker: the grad of print is print of grad)."""
    g = ctx.input("Out@GRAD")
    if ctx.attr("print_phase", "both") in ("backward", "both"):
        _print_value(ctx, g, phase_tag=" [backward]")
    return {"In@GRAD": g}


def _print_grad_maker(op, block, no_grad_set=frozenset()):
    from ..framework import grad_var_name

    x = op.input("In")[0]
    if x in no_grad_set:
        return []
    return [{
        "type": "print_grad",
        "inputs": {"Out@GRAD": [grad_var_name(op.output("Out")[0])]},
        "outputs": {"In@GRAD": [grad_var_name(x)]},
        "attrs": dict(op.attrs),
    }]


get_op_def("print").grad_maker = _print_grad_maker
get_op_def("print_grad").host = True


@register_op("scatter_nd_add")
def scatter_nd_add(ctx: ExecContext):
    x, idx, upd = ctx.input("X"), ctx.input("Index"), ctx.input("Updates")
    return {"Out": x.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)}


@register_op("scatter_nd", grad="none")
def scatter_nd(ctx: ExecContext):
    idx, upd = ctx.input("Index"), ctx.input("Updates")
    shape = [int(s) for s in ctx.attr("shape")]
    z = jnp.zeros(shape, upd.dtype)
    return {"Out": z.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)}


@register_op("hash", grad="none")
def hash_op(ctx: ExecContext):
    """reference hash_op.* (xxHash of the id bytes mod mod_by): num_hash
    independent hashes of each input row's int ids. The TPU redesign uses a
    splitmix64-style integer mix (hashing only needs dispersion, not the
    exact xxhash bit pattern) — one fused integer pipeline, no host trip."""
    x = ctx.input("X")
    num_hash = int(ctx.attr("num_hash", 1))
    mod_by = int(ctx.attr("mod_by", 100000))
    # BOTH 32-bit halves of the int64 id must participate (the reference
    # xxhashes all 8 id bytes): truncating to uint32 collides every pair of
    # ids differing only above bit 31 in ALL buckets (ADVICE r4)
    lo32 = x.astype(jnp.uint32)  # wraps mod 2^32 == low half
    if jnp.dtype(x.dtype).itemsize >= 8:  # true 64-bit ids (x64 enabled)
        hi32 = (x >> 32).astype(jnp.uint32)
    else:  # x32 mode: ids are 32-bit on device; no upper half exists
        hi32 = jnp.zeros_like(lo32)
    outs = []
    for seed in range(num_hash):
        h = lo32 ^ jnp.uint32((0x9E3779B9 * (seed + 1)) & 0xFFFFFFFF)
        h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
        h = h ^ (hi32 * jnp.uint32(0x27D4EB2F))  # fold in the upper half
        h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> 16)
        # fold the last-dim id vector into ONE bucket per row (the
        # reference hashes the whole row's bytes)
        row = jnp.zeros(h.shape[:-1], jnp.uint32)
        for j in range(x.shape[-1]):
            row = row * jnp.uint32(31) + h[..., j]
        outs.append((row % jnp.uint32(mod_by)).astype(_INDEX_DTYPE))
    return {"Out": jnp.stack(outs, axis=-1)[..., None]}  # [.., num_hash, 1]


def cvm(ctx: ExecContext):
    """reference cvm_op.h: continuous-value-model feature transform. X
    [B, D] with the first two columns (show, click); use_cvm=True keeps
    width and rewrites col0=log(show+1), col1=log(click+1)-log(show+1);
    False strips both columns."""
    x = ctx.input("X")
    if bool(ctx.attr("use_cvm", True)):
        c0 = jnp.log(x[:, :1] + 1.0)
        c1 = jnp.log(x[:, 1:2] + 1.0) - c0
        return {"Y": jnp.concatenate([c0, c1, x[:, 2:]], axis=1)}
    return {"Y": x[:, 2:]}


def _cvm_grad_maker(op, block, no_grad_set=frozenset()):
    from ..framework import grad_var_name

    xname = op.inputs["X"][0]
    if xname in no_grad_set:
        return []
    return [{
        "type": "cvm_grad",
        "inputs": {"X": list(op.inputs["X"]),
                   "CVM": list(op.inputs.get("CVM", [])),
                   "Y@GRAD": [grad_var_name(op.outputs["Y"][0])]},
        "outputs": {"X@GRAD": [grad_var_name(xname)]},
        "attrs": dict(op.attrs),
    }]


register_op("cvm", grad=_cvm_grad_maker)(cvm)


@register_grad_compute("cvm")
def cvm_grad(ctx: ExecContext):
    """reference CvmGradComputeKernel: pass-through for the non-cvm columns;
    the two cvm columns take the raw CVM feature values (not a chain-rule
    term — the reference's deliberate straight-through)."""
    x = ctx.input("X")
    gy = ctx.input("Y@GRAD")
    cvm_in = ctx.input("CVM")
    B = x.shape[0]
    if cvm_in is None:
        cvm_in = jnp.zeros((B, 2), x.dtype)
    if bool(ctx.attr("use_cvm", True)):
        body = gy[:, 2:]
    else:
        body = gy
    return {"X@GRAD": jnp.concatenate(
        [cvm_in[:, :2].astype(x.dtype), body], axis=1)}


def _unique_ordered(ctx):
    """First-occurrence-order dedup (np.unique sorts; the reference keeps
    encounter order). Index dtype follows the op's dtype attr."""
    import numpy as np

    x = np.asarray(ctx.input("X")).reshape(-1)
    first = np.sort(np.unique(x, return_index=True)[1])
    ordered = x[first]
    remap = {v: i for i, v in enumerate(ordered.tolist())}
    idx_dt = np.int64 if str(ctx.attr("dtype", "int32")).endswith("64") \
        else np.int32
    index = np.asarray([remap[v] for v in x.tolist()], idx_dt)
    return ordered, index


@register_op("unique", grad="none", host=True)
def unique(ctx: ExecContext):
    """reference unique_op.*: dynamic-shaped dedup. Host op — the output
    extent is data-dependent, which XLA cannot express; unique feeds host
    paths (sparse-id preprocessing) in practice."""
    ordered, index = _unique_ordered(ctx)
    return {"Out": ordered, "Index": index}


@register_op("unique_with_counts", grad="none", host=True)
def unique_with_counts(ctx: ExecContext):
    import numpy as np

    ordered, index = _unique_ordered(ctx)
    counts = np.bincount(index, minlength=len(ordered)).astype(_INDEX_DTYPE)
    return {"Out": ordered, "Index": index, "Count": counts}


@register_op("merge_selected_rows", grad="none", host=True)
def merge_selected_rows(ctx: ExecContext):
    """reference merge_selected_rows_op.cc: sum duplicate rows of a
    SelectedRows. Host op (SelectedRows live on the host side of the
    executor; their dense payloads are device arrays)."""
    import numpy as np

    from ..core.selected_rows import SelectedRows

    sr = ctx.input("X")
    rows = np.asarray(sr.rows)
    vals = np.asarray(sr.values)
    uniq, inv = np.unique(rows, return_inverse=True)
    merged = np.zeros((len(uniq),) + vals.shape[1:], vals.dtype)
    np.add.at(merged, inv, vals)
    return {"Out": SelectedRows(uniq, merged, sr.height)}


@register_op("get_tensor_from_selected_rows", grad="none", host=True)
def get_tensor_from_selected_rows(ctx: ExecContext):
    """reference get_tensor_from_selected_rows_op.cc: expose the value
    tensor of a SelectedRows."""
    import numpy as np

    sr = ctx.input("X")
    return {"Out": np.asarray(sr.values)}


@register_op("filter_by_instag", grad="none", host=True)
def filter_by_instag(ctx: ExecContext):
    """reference filter_by_instag_op.*: keep rows whose tag set intersects
    the filter tags. Host op (data-dependent output extent). Ins [B, D],
    Ins_tag [B, T] (padded with -1), Filter_tag [K] -> Out (kept rows),
    LossWeight [kept, 1], IndexMap [kept, 2] (out row -> in row)."""
    import numpy as np

    ins = np.asarray(ctx.input("Ins"))
    tags = np.asarray(ctx.input("Ins_tag"))
    filt = set(np.asarray(ctx.input("Filter_tag")).reshape(-1).tolist())
    keep = [b for b in range(ins.shape[0])
            if filt & set(tags[b].reshape(-1).tolist())]
    if not keep:
        out = np.zeros((1,) + ins.shape[1:], ins.dtype)
        return {"Out": out,
                "LossWeight": np.zeros((1, 1), np.float32),
                "IndexMap": np.zeros((1, 2), np.int64)}
    keep = np.asarray(keep, np.int64)
    return {"Out": ins[keep],
            "LossWeight": np.ones((len(keep), 1), np.float32),
            "IndexMap": np.stack([np.arange(len(keep)), keep], axis=1)}


# --------------------------------------------------------------------------
# py_func: the user-extensibility escape hatch (reference py_func_op.cc).
# Callables register process-locally by integer id; the op is a HOST op, so
# the executor splits the jit around it and hands it real arrays.
# --------------------------------------------------------------------------

PY_FUNC_REGISTRY: list = []


def register_py_func(fn) -> int:
    PY_FUNC_REGISTRY.append(fn)
    return len(PY_FUNC_REGISTRY) - 1


def py_func(ctx: ExecContext):
    """reference py_func_op.cc: call a registered Python callable on the
    input arrays; outputs map positionally onto the Out slot."""
    import numpy as np

    fn = PY_FUNC_REGISTRY[int(ctx.attr("forward_callable_id"))]
    args = [None if v is None else np.asarray(v) for v in ctx.inputs("X")]
    res = fn(*args)
    if res is None:
        res = ()
    if not isinstance(res, (list, tuple)):
        res = (res,)
    outs = list(ctx.op.outputs.get("Out", []))
    if len(res) != len(outs):
        raise ValueError(
            f"py_func returned {len(res)} values for {len(outs)} output "
            f"variables")
    return {"Out": [np.asarray(r) for r in res]}


def _py_func_grad_maker(op, block, no_grad_set=frozenset()):
    from ..framework import grad_var_name

    if int(op.attrs.get("backward_callable_id", -1)) < 0:
        return []
    gouts = []
    for n in op.inputs.get("X", []):
        gouts.append("" if n in no_grad_set else grad_var_name(n))
    if not any(gouts):
        return []
    return [{
        "type": "py_func_grad",
        "inputs": {
            "X": list(op.inputs["X"]),
            "Out": list(op.outputs["Out"]),
            "Out@GRAD": [grad_var_name(n) for n in op.outputs["Out"]],
        },
        "outputs": {"X@GRAD": gouts},
        "attrs": dict(op.attrs),
    }]


register_op("py_func", host=True, grad=_py_func_grad_maker)(py_func)


@register_op("py_func_grad", host=True, no_grad=True)
def py_func_grad(ctx: ExecContext):
    """Backward escape hatch: backward_func(*(X + Out + Out@GRAD), minus the
    names listed in skip_vars_in_backward_input) -> grads aligned with X."""
    import numpy as np

    fn = PY_FUNC_REGISTRY[int(ctx.attr("backward_callable_id"))]
    skip = set(ctx.attr("skip_names", []) or [])
    args = []
    for slot in ("X", "Out", "Out@GRAD"):
        for n, v in zip(ctx.op.inputs.get(slot, []), ctx.inputs(slot)):
            if n in skip:
                continue
            args.append(None if v is None else np.asarray(v))
    res = fn(*args)
    if not isinstance(res, (list, tuple)):
        res = (res,)
    width = len(ctx.op.outputs.get("X@GRAD", []))
    res = list(res) + [None] * (width - len(res))
    return {"X@GRAD": [None if r is None else np.asarray(r)
                       for r in res[:width]]}
