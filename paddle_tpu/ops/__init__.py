"""Op registry + kernel modules. Importing this package registers all ops."""
from .registry import (
    ExecContext,
    OpDef,
    all_op_types,
    default_grad_maker,
    get_op_def,
    has_op,
    infer_op,
    register_grad_compute,
    register_op,
)

from . import math_ops  # noqa: F401
from . import activation_ops  # noqa: F401
from . import tensor_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import collective_ops  # noqa: F401
from . import control_flow_ops  # noqa: F401
from . import attention_ops  # noqa: F401
from . import vision_ops  # noqa: F401
from . import crf_ops  # noqa: F401
from . import distributed_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import sampling_ops  # noqa: F401
from . import ctc_ops  # noqa: F401
from . import quant_ops  # noqa: F401
from . import detection_ops  # noqa: F401
