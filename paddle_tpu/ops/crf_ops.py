"""Linear-chain CRF: log-likelihood + Viterbi decoding.

TPU-native re-design of the reference CRF pair
(/root/reference/paddle/fluid/operators/linear_chain_crf_op.{h,cc} and
crf_decoding_op.{h,cc}): the reference walks LoD sequences with explicit
alpha tables; here the forward recursion is a lax.scan over the padded time
axis in LOG space (no exp-table bookkeeping — the derived vjp through
logsumexp IS the backward the reference hand-writes), masked by Length.

Transition layout (reference contract): [N+2, N] — row 0 start weights,
row 1 stop weights, rows 2..N+1 the NxN transition matrix.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import ExecContext, register_op

from ..core.types import np_feed_dtype

# the runtime's index dtype: int32 under x64-off jax (an astype to
# int64 would warn-and-truncate on every trace), int64 when enabled
_INDEX_DTYPE = np_feed_dtype("int64")

_NEG = -1e30


def _split_transition(w):
    return w[0], w[1], w[2:]  # start [N], stop [N], trans [N, N]


def _crf_nll(emission, label, length, w):
    """Negative log-likelihood per sequence (the reference op's
    LogLikelihood output is the COST users feed to mean()).
    emission [T, N] fp32, label [T] int, length scalar int, w [N+2, N]."""
    T, N = emission.shape
    start, stop, trans = _split_transition(w)
    t_idx = jnp.arange(T)
    valid = t_idx < length

    # partition function: alpha recursion in log space
    alpha0 = start + emission[0]

    def step(alpha, t):
        nxt = jax.scipy.special.logsumexp(
            alpha[:, None] + trans, axis=0) + emission[t]
        return jnp.where(valid[t], nxt, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    last = jnp.maximum(length - 1, 0)
    log_z = jax.scipy.special.logsumexp(alpha + stop)

    # gold path score
    lbl = label.astype(jnp.int32)
    em_score = jnp.sum(jnp.where(valid, emission[t_idx, lbl], 0.0))
    prev, cur = lbl[:-1], lbl[1:]
    tr_score = jnp.sum(jnp.where(valid[1:], trans[prev, cur], 0.0))
    score = start[lbl[0]] + em_score + tr_score + stop[lbl[last]]
    return log_z - score


@register_op("linear_chain_crf")
def linear_chain_crf(ctx: ExecContext):
    """inputs: Emission [B, T, N], Transition [N+2, N], Label [B, T] (or
    [B, T, 1]), optional Length [B]. outputs: LogLikelihood [B, 1]."""
    em = ctx.input("Emission").astype(jnp.float32)
    w = ctx.input("Transition").astype(jnp.float32)
    label = ctx.input("Label")
    if label.ndim == 3 and label.shape[-1] == 1:
        label = label.reshape(label.shape[:-1])
    B, T = em.shape[0], em.shape[1]
    if ctx.has_input("Length"):
        length = ctx.input("Length").reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((B,), T, jnp.int32)
    nll = jax.vmap(_crf_nll, in_axes=(0, 0, 0, None))(em, label, length, w)
    return {"LogLikelihood": nll[:, None]}


@register_op("crf_decoding", grad="none")
def crf_decoding(ctx: ExecContext):
    """Viterbi decode (reference crf_decoding_op.h): best path per sequence.
    With a Label input the output is the per-position MISMATCH indicator
    (the reference's "compare with ground truth" mode); padding positions
    emit 0."""
    em = ctx.input("Emission").astype(jnp.float32)
    w = ctx.input("Transition").astype(jnp.float32)
    B, T, N = em.shape
    if ctx.has_input("Length"):
        length = ctx.input("Length").reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((B,), T, jnp.int32)
    start, stop, trans = _split_transition(w)

    def decode(e, ln):
        valid = jnp.arange(T) < ln
        v0 = start + e[0]

        def step(v, t):
            cand = v[:, None] + trans             # [from, to]
            best = jnp.max(cand, axis=0) + e[t]
            bp = jnp.argmax(cand, axis=0).astype(jnp.int32)
            v_new = jnp.where(valid[t], best, v)
            bp = jnp.where(valid[t], bp,
                           jnp.arange(N, dtype=jnp.int32))  # identity ptr
            return v_new, bp

        v_last, bps = jax.lax.scan(step, v0, jnp.arange(1, T))
        last_tag = jnp.argmax(v_last + stop).astype(jnp.int32)

        def back(tag, bp):
            prev = bp[tag]
            return prev, prev

        _, path_rev = jax.lax.scan(back, last_tag, bps, reverse=True)
        path = jnp.concatenate([path_rev, last_tag[None]])
        return jnp.where(valid, path, 0)

    paths = jax.vmap(decode)(em, length)
    if ctx.has_input("Label"):
        label = ctx.input("Label")
        if label.ndim == 3 and label.shape[-1] == 1:
            label = label.reshape(label.shape[:-1])
        valid = jnp.arange(T)[None, :] < length[:, None]
        mism = (paths != label.astype(jnp.int32)) & valid
        return {"ViterbiPath": mism.astype(_INDEX_DTYPE)}
    return {"ViterbiPath": paths.astype(_INDEX_DTYPE)}
