"""Math ops: matmul/mul, elementwise family, reductions, scale/sum/mean.

TPU-native equivalents of the reference kernels under
/root/reference/paddle/fluid/operators/ (mul_op.cc, matmul_op.cc,
elementwise/elementwise_*_op.*, reduce_ops/, scale_op.cc, sum_op.cc,
mean_op.cc, clip_op.cc, cast_op.cc). Each op is one pure JAX function; XLA
fuses elementwise chains into matmul epilogues on the MXU, so there is no
hand-written fusion pass equivalent to fuse_elewise_add_act.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import ExecContext, register_op, register_grad_compute


def _flatten_2d(x, num_col_dims: int):
    """Flatten to 2D the way the reference mul_op does (mul_op.cc)."""
    lead = int(np.prod(x.shape[:num_col_dims])) if num_col_dims > 0 else 1
    return x.reshape(lead, -1)


@register_op("mul")
def mul(ctx: ExecContext):
    x, y = ctx.input("X"), ctx.input("Y")
    xn = ctx.attr("x_num_col_dims", 1)
    yn = ctx.attr("y_num_col_dims", 1)
    if x.shape[xn:] == y.shape[:yn]:
        # rank-preserving contraction: no flatten/unflatten reshapes, so XLA
        # never has to reconcile [B,S,H] and [B*S,H] tilings with physical
        # copies (measured as one of the big per-step HBM costs, PERF.md)
        dims = (tuple(range(xn, x.ndim)), tuple(range(yn)))
        out = jax.lax.dot_general(x, y, (dims, ((), ())),
                                  preferred_element_type=jnp.float32)
        return {"Out": out.astype(x.dtype)}
    x2 = _flatten_2d(x, xn)
    y2 = y.reshape(int(np.prod(y.shape[:yn])), -1)
    out = jnp.matmul(x2, y2, preferred_element_type=jnp.float32).astype(x.dtype)
    out_shape = x.shape[:xn] + y.shape[yn:]
    return {"Out": out.reshape(out_shape)}


@register_op("matmul")
def matmul(ctx: ExecContext):
    x, y = ctx.input("X"), ctx.input("Y")
    if ctx.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2)
    if ctx.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y, preferred_element_type=jnp.float32).astype(x.dtype)
    alpha = ctx.attr("alpha", 1.0)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    return {"Out": out}


# -- elementwise family with the reference's axis-broadcast rule -------------
def _bcast_y(x, y, axis: int):
    """Reference broadcast (elementwise_op_function.h): align y's dims to
    x[axis : axis+y.ndim], padding trailing 1s."""
    if x.shape == y.shape:
        return y
    if y.ndim > x.ndim:
        raise ValueError(
            f"elementwise op: Y rank {y.ndim} exceeds X rank {x.ndim} "
            f"(shapes {y.shape} vs {x.shape}) — the reference broadcast rule "
            f"requires rank(Y) <= rank(X)")
    if axis == -1 or axis is None:
        axis = x.ndim - y.ndim
    new_shape = [1] * x.ndim
    for i, d in enumerate(y.shape):
        new_shape[axis + i] = d
    return y.reshape(new_shape)


def _ew(fn):
    def compute(ctx: ExecContext):
        x, y = ctx.input("X"), ctx.input("Y")
        y = _bcast_y(x, y, ctx.attr("axis", -1))
        return {"Out": fn(x, y)}

    return compute


register_op("elementwise_add")(_ew(jnp.add))
register_op("elementwise_sub")(_ew(jnp.subtract))
register_op("elementwise_mul")(_ew(jnp.multiply))
register_op("elementwise_div")(_ew(jnp.divide))
register_op("elementwise_max")(_ew(jnp.maximum))
register_op("elementwise_min")(_ew(jnp.minimum))
register_op("elementwise_pow")(_ew(jnp.power))
register_op("elementwise_mod", no_grad=True)(_ew(jnp.mod))
register_op("elementwise_floordiv", no_grad=True)(_ew(jnp.floor_divide))


@register_op("scale")
def scale(ctx: ExecContext):
    x = ctx.input("X")
    s = jnp.asarray(ctx.attr("scale", 1.0), x.dtype)
    b = jnp.asarray(ctx.attr("bias", 0.0), x.dtype)
    if ctx.attr("bias_after_scale", True):
        return {"Out": x * s + b}
    return {"Out": (x + b) * s}


@register_op("sum")
def sum_op(ctx: ExecContext):
    """Adds its inputs. SelectedRows inputs merge by row concatenation
    (reference math/selected_rows_functor.cc add semantics) — all-sparse
    stays sparse; a sparse/dense mix densifies."""
    from ..core.selected_rows import SelectedRows, is_selected_rows

    xs = [x for x in ctx.inputs("X") if x is not None]
    if any(is_selected_rows(x) for x in xs):
        if all(is_selected_rows(x) for x in xs):
            return {"Out": SelectedRows(
                jnp.concatenate([x.rows for x in xs]),
                jnp.concatenate([x.values for x in xs]),
                xs[0].height,
            )}
        xs = [x.to_dense() if is_selected_rows(x) else x for x in xs]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("mean")
def mean(ctx: ExecContext):
    return {"Out": jnp.mean(ctx.input("X"))}


def _reduce(fn):
    def compute(ctx: ExecContext):
        x = ctx.input("X")
        dims = ctx.attr("dim", [0])
        keep = ctx.attr("keep_dim", False)
        if ctx.attr("reduce_all", False):
            axes = tuple(range(x.ndim))
        else:
            axes = tuple(d % x.ndim for d in (dims if isinstance(dims, (list, tuple)) else [dims]))
        return {"Out": fn(x, axis=axes, keepdims=keep)}

    return compute


register_op("reduce_sum")(_reduce(jnp.sum))
register_op("reduce_mean")(_reduce(jnp.mean))
register_op("reduce_max")(_reduce(jnp.max))
register_op("reduce_min")(_reduce(jnp.min))
register_op("reduce_prod")(_reduce(jnp.prod))
register_op("reduce_all", no_grad=True)(_reduce(jnp.all))
register_op("reduce_any", no_grad=True)(_reduce(jnp.any))


@register_op("clip")
def clip(ctx: ExecContext):
    x = ctx.input("X")
    return {"Out": jnp.clip(x, ctx.attr("min"), ctx.attr("max"))}


@register_op("clip_by_norm")
def clip_by_norm(ctx: ExecContext):
    x = ctx.input("X")
    max_norm = jnp.asarray(ctx.attr("max_norm"), x.dtype)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return {"Out": jnp.where(norm > max_norm, x * (max_norm / norm), x)}


@register_op("cast")
def cast(ctx: ExecContext):
    # np_feed_dtype: a cast-to-int64 request resolves to the runtime's
    # actual wide-int dtype (int32 under x64-off jax) instead of jax
    # warning-and-truncating on every traced astype
    from ..core.types import np_feed_dtype

    return {"Out": ctx.input("X").astype(np_feed_dtype(ctx.attr("out_dtype")))}


@register_op("dot")
def dot(ctx: ExecContext):
    x, y = ctx.input("X"), ctx.input("Y")
    return {"Out": jnp.sum(x * y, axis=-1, keepdims=True)}


@register_op("squared_l2_norm")
def squared_l2_norm(ctx: ExecContext):
    return {"Out": jnp.sum(jnp.square(ctx.input("X"))).reshape(1)}


@register_op("norm")
def norm(ctx: ExecContext):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    eps = ctx.attr("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": x / n, "Norm": n}


@register_op("log_loss")
def log_loss(ctx: ExecContext):
    p = ctx.input("Predicted")
    y = ctx.input("Labels")
    eps = ctx.attr("epsilon", 1e-4)
    return {"Loss": -y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps)}


@register_op("huber_loss")
def huber_loss(ctx: ExecContext):
    x, y = ctx.input("X"), ctx.input("Y")
    delta = ctx.attr("delta", 1.0)
    r = y - x
    a = jnp.abs(r)
    quad = 0.5 * r * r
    lin = delta * (a - 0.5 * delta)
    out = jnp.where(a <= delta, quad, lin)
    return {"Out": out, "Residual": r}


@register_op("square_error_cost")
def square_error_cost(ctx: ExecContext):
    x, y = ctx.input("X"), ctx.input("Y")
    return {"Out": jnp.square(x - y)}


@register_op("cos_sim")
def cos_sim(ctx: ExecContext):
    x, y = ctx.input("X"), ctx.input("Y")
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    return {
        "Out": jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn),
        "XNorm": xn,
        "YNorm": yn,
    }


@register_op("pow")
def pow_op(ctx: ExecContext):
    x = ctx.input("X")
    return {"Out": jnp.power(x, jnp.asarray(ctx.attr("factor", 1.0), x.dtype))}


@register_op("isfinite", no_grad=True)
def isfinite(ctx: ExecContext):
    # reference isfinite_op.cc reduces to a single bool
    return {"Out": jnp.all(jnp.isfinite(ctx.input("X"))).reshape(1)}


@register_op("kldiv_loss")
def kldiv_loss(ctx: ExecContext):
    """reference kldiv_loss_op.*: target * (log(target) - input), input is
    LOG-probabilities; reduction applied by the layer."""
    x, t = ctx.input("X"), ctx.input("Target")
    loss = t * (jnp.log(jnp.maximum(t, 1e-10)) - x)
    red = ctx.attr("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss)
    elif red == "sum":
        loss = jnp.sum(loss)
    elif red == "batchmean":
        loss = jnp.sum(loss) / x.shape[0]
    return {"Loss": loss}


@register_op("rank_loss")
def rank_loss(ctx: ExecContext):
    """reference rank_loss_op.*: RankNet pairwise loss."""
    label = ctx.input("Label")
    left, right = ctx.input("Left"), ctx.input("Right")
    d = left - right
    return {"Out": jnp.logaddexp(0.0, d) - label * d}


@register_op("margin_rank_loss")
def margin_rank_loss(ctx: ExecContext):
    """reference margin_rank_loss_op.*: max(0, -label*(x1-x2)+margin)."""
    label = ctx.input("Label")
    x1, x2 = ctx.input("X1"), ctx.input("X2")
    margin = float(ctx.attr("margin", 0.0))
    out = jnp.maximum(-label * (x1 - x2) + margin, 0.0)
    return {"Out": out, "Activated": (out > 0).astype(x1.dtype)}


@register_op("bpr_loss")
def bpr_loss(ctx: ExecContext):
    """reference bpr_loss_op.*: Bayesian personalized ranking over logits
    [B, C] with positive-label column [B, 1]."""
    x, label = ctx.input("X"), ctx.input("Label")
    lbl = label.reshape(-1).astype(jnp.int32)
    pos = jnp.take_along_axis(x, lbl[:, None], axis=1)
    diff = pos - x  # [B, C]
    lse = -jnp.log(jax.nn.sigmoid(diff) + 1e-10)
    C = x.shape[1]
    mask = jax.nn.one_hot(lbl, C, dtype=x.dtype)
    out = (lse * (1 - mask)).sum(axis=1, keepdims=True) / (C - 1)
    return {"Y": out}


@register_op("mean_iou", grad="none")
def mean_iou(ctx: ExecContext):
    """reference mean_iou_op.*: mean intersection-over-union across classes."""
    pred = ctx.input("Predictions").reshape(-1).astype(jnp.int32)
    label = ctx.input("Labels").reshape(-1).astype(jnp.int32)
    n = int(ctx.attr("num_classes"))
    inter = jnp.zeros((n,), jnp.float32).at[pred].add(
        (pred == label).astype(jnp.float32))
    pred_c = jnp.zeros((n,), jnp.float32).at[pred].add(1.0)
    lbl_c = jnp.zeros((n,), jnp.float32).at[label].add(1.0)
    union = pred_c + lbl_c - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
    miou = iou.sum() / jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
    # reference mean_iou_op.h:96-98 increments wrong for BOTH the predicted
    # and the label class on a mismatch, so wrong + correct == union
    return {"OutMeanIou": miou,
            "OutWrong": (pred_c - inter) + (lbl_c - inter),
            "OutCorrect": inter}


@register_op("center_loss", stateful_outputs=("CentersOut",))
def center_loss(ctx: ExecContext):
    """reference center_loss_op.h: loss_i = 0.5*||x_i - c_{y_i}||^2; when
    update_center, CentersOut = Centers - alpha * sum_i(c_{y_i} - x_i) /
    (1 + count(y_i)) (the per-class mean-shift with the reference's +1
    denominator). Centers are stop-gradient; dX comes from the loss term."""
    x = ctx.input("X")
    label = ctx.input("Label").reshape(-1).astype(jnp.int32)
    centers = ctx.input("Centers")
    rate = ctx.input("CenterUpdateRate")
    alpha = (rate.reshape(-1)[0] if rate is not None
             else jnp.asarray(float(ctx.attr("alpha", 0.1))))
    c = jax.lax.stop_gradient(centers)[label]                 # [B, D]
    diff = x - c
    loss = 0.5 * jnp.sum(diff.astype(jnp.float32) ** 2, axis=1,
                         keepdims=True)
    out = {"Loss": loss.astype(x.dtype), "SampleCenterDiff": diff}
    if bool(ctx.attr("need_update", True)):
        nclass = centers.shape[0]
        cnt = jnp.ones((nclass,), jnp.float32).at[label].add(1.0)
        acc = jnp.zeros_like(centers).at[label].add(
            jax.lax.stop_gradient(-diff))                      # c - x summed
        new_c = centers - (alpha / cnt)[:, None] * acc
        out["CentersOut"] = jax.lax.stop_gradient(new_c)
    else:
        out["CentersOut"] = centers
    return out


@register_op("teacher_student_sigmoid_loss")
def teacher_student_sigmoid_loss(ctx: ExecContext):
    """reference teacher_student_sigmoid_loss_op.h: distillation CTR loss.
    label encodes click + optional teacher score q in {-2, -1, [0, 2]}:
      no q, clk=0: label = -2    ->  y = softplus(x)
      no q, clk=1: label = -1    ->  y = softplus(x) - x
      q,   clk=0: label = q      ->  y = 2*softplus(x) - x*label
      q,   clk=1: label = 1 + q  ->  y = 2*softplus(x) - x*label
    (the kernel's label>=1 branch softplus-x + softplus-x*(label-1) is
    algebraically the same 2*softplus(x) - x*label). The FORWARD is
    unclipped; soft_max_up/lower_bound clip only the BACKWARD's sigmoid
    argument, with dX zeroed at saturation (grad kernel :95-111)."""
    x_in = ctx.input("X")
    label = ctx.input("Label").reshape(-1).astype(jnp.float32)
    up = float(ctx.attr("soft_max_up_bound", 15.0))
    lo = float(ctx.attr("soft_max_lower_bound", -15.0))

    @jax.custom_vjp
    def _loss(x, label):
        sp = jnp.logaddexp(0.0, x)
        return jnp.where(
            label < -1.0, sp,
            jnp.where(label < 0.0, sp - x, 2.0 * sp - x * label))

    def _fwd(x, label):
        return _loss(x, label), (x, label)

    def _bwd(res, dy):
        x, label = res
        z = jnp.clip(x, lo, up)
        pred = jax.nn.sigmoid(z)
        dydx = jnp.where(label < -1.0, pred,
                         jnp.where(label < 0.0, pred - 1.0,
                                   2.0 * pred - label))
        dydx = jnp.where((x >= up) | (x <= lo), 0.0, dydx)
        return (dydx * dy, jnp.zeros_like(label))

    _loss.defvjp(_fwd, _bwd)
    y = _loss(x_in.reshape(-1).astype(jnp.float32), label)
    return {"Y": y.reshape(-1, 1).astype(x_in.dtype)}


@register_op("cross_entropy2")
def cross_entropy2(ctx: ExecContext):
    """reference cross_entropy_op.cc (cross_entropy2 kernel): hard-label CE
    that also emits MatchX = x[label] for the fast backward dX = -dY/MatchX."""
    x = ctx.input("X")
    label = ctx.input("Label")
    if label.ndim == x.ndim:
        label = label.reshape(label.shape[:-1])
    label = label.astype(jnp.int32)
    ignore = label == int(ctx.attr("ignore_index", -100))
    safe = jnp.where(ignore, 0, label)
    match = jnp.take_along_axis(x, safe[..., None], axis=-1)
    match = jnp.where(ignore[..., None], 1.0, match)  # -> loss 0, dX 0
    loss = -jnp.log(jnp.maximum(match, 1e-20))
    return {"Y": loss.astype(x.dtype), "MatchX": match,
            "XShape": jnp.zeros((0,), x.dtype)}
