"""Short-sequence fused attention: a hand-tuned Pallas TPU kernel.

Why this exists (PERF.md): BERT-base at seq 128 spends ~54 ms of a 171.8 ms
step in the attention block, only ~20 ms of which is matmul — the rest is
the [B, nh, S, S] score/softmax tensors and the [B,S,nh,dh]<->[B,nh,S,dh]
transposes round-tripping HBM between XLA fusions. jax's bundled
flash-attention kernel is tuned for long sequences (KV-block pipelines) and
measures *slower* than XLA at S<=512 on v5e.

Design — exploit that for short S the ENTIRE per-head problem fits in VMEM:
  * grid over (batch, head-block): each step DMAs [gh, S, dh] slabs of
    Q/K/V once, runs batched-over-heads QK^T -> softmax -> PV entirely
    on-chip, writes only the output. The S x S scores NEVER touch HBM.
  * batched `dot_general` over the head dim keeps the MXU pipelined
    across heads (per-head [S,dh] matmuls would drain it every head).
  * fp32 softmax statistics; bf16 MXU operands; fp32 accumulation.
  * the backward saves NO residuals beyond q/k/v: with whole rows in
    VMEM it recomputes softmax exactly, and the softmax-vjp identity
    delta = rowsum(dP (.) P) removes the need for O. One kernel fuses all
    five gradient matmuls.

Reference role: replaces the reference's scaled_dot_product_attention
composition (python/paddle/fluid/nets.py:345) on the TPU hot path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import workbench

_NEG_INF = -1e30
# per-step VMEM budget for the head-block (bytes); leaves room for double
# buffering + score scratch inside ~16 MB of VMEM
_VMEM_BUDGET = 3 * 1024 * 1024

# tests flip this to run the kernels through the Pallas interpreter on CPU
INTERPRET = False


def short_seq_supported(q_shape, k_shape, bias, dropout_rate=0.0) -> bool:
    """Shapes this kernel handles: self-attention, S multiple of 128 with
    the score matrix VMEM-resident, dh lane-friendly, no additive bias."""
    if bias is not None or dropout_rate:
        return False
    B, nh, sq, dh = q_shape
    sk = k_shape[2]
    # S cap from the bwd kernel's VMEM needs at gh=1: ~5 fp32/bf16 [S,S]
    # intermediates (s, p, pb, dp, ds) must fit alongside the slabs — fine
    # at S=512 (~5 MB), not at S=1024 (~18 MB > VMEM)
    return (sq == sk and sq % 128 == 0 and sq <= 512
            and dh % 8 == 0 and dh <= 256)


def _head_block(nh: int, s: int, dh: int, itemsize: int, n_tensors: int) -> int:
    """Largest divisor of nh whose per-step slab fits the VMEM budget."""
    per_head = s * dh * itemsize * n_tensors + 3 * s * s * 4
    gh = nh
    while gh > 1 and gh * per_head > _VMEM_BUDGET:
        gh -= 1
        while nh % gh:
            gh -= 1
    return gh


def _causal_mask(s):
    row = jax.lax.broadcasted_iota(jnp.int32, (1, s, s), 1)
    col = jax.lax.broadcasted_iota(jnp.int32, (1, s, s), 2)
    return row >= col


def _scores(q, k, sm_scale, causal):
    """Batched QK^T over the head dim: [gh,S,dh] x [gh,S,dh] -> [gh,S,S]."""
    s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
    s = s * sm_scale
    if causal:
        s = jnp.where(_causal_mask(s.shape[-1]), s, _NEG_INF)
    return s


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale, causal):
    q, k, v = q_ref[0], k_ref[0], v_ref[0]            # [gh, S, dh]
    s = _scores(q, k, sm_scale, causal)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(p.astype(v.dtype), v,
                            (((2,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
    o_ref[0] = (o / l).astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, dq_ref, dk_ref, dv_ref,
                *, sm_scale, causal):
    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    s = _scores(q, k, sm_scale, causal)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)        # [gh, Sq, Sk] fp32
    pb = p.astype(q.dtype)
    # dV = P^T dO  (contract the query dim per head)
    dv = jax.lax.dot_general(pb, do, (((1,), (1,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    # dP = dO V^T
    dp = jax.lax.dot_general(do, v, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    # softmax vjp: dS = P (.) (dP - rowsum(dP (.) P)); the rowsum equals
    # rowsum(dO (.) O), so O is never needed
    delta = jnp.sum(dp * p, axis=-1, keepdims=True)
    ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
    dq = jax.lax.dot_general(ds, k, (((2,), (1,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    dk = jax.lax.dot_general(ds, q, (((1,), (1,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    dq_ref[0] = dq.astype(dq_ref.dtype)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _hb_spec(gh, s, dh):
    return pl.BlockSpec((1, gh, s, dh), lambda b, h: (b, h, 0, 0))


def _params():
    # version-tolerant CompilerParams via the workbench shim: the bare
    # pltpu.CompilerParams spelling broke on jax 0.4.x (TPUCompilerParams
    # there) and took test_pallas_attention with it
    return workbench.compiler_params(("parallel", "parallel"))


def _fwd(q, k, v, sm_scale, causal, interpret):
    B, nh, s, dh = q.shape
    gh = _head_block(nh, s, dh, q.dtype.itemsize, 4)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal)
    return pl.pallas_call(
        kernel,
        grid=(B, nh // gh),
        in_specs=[_hb_spec(gh, s, dh)] * 3,
        out_specs=_hb_spec(gh, s, dh),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=B * nh * 2 * 2 * s * s * dh,
            bytes_accessed=4 * B * nh * s * dh * q.dtype.itemsize,
            transcendentals=B * nh * s * s),
        compiler_params=_params(),
        interpret=interpret,
    )(q, k, v)


def _bwd(q, k, v, do, sm_scale, causal, interpret):
    B, nh, s, dh = q.shape
    gh = _head_block(nh, s, dh, q.dtype.itemsize, 7)
    kernel = functools.partial(_bwd_kernel, sm_scale=sm_scale, causal=causal)
    return pl.pallas_call(
        kernel,
        grid=(B, nh // gh),
        in_specs=[_hb_spec(gh, s, dh)] * 4,
        out_specs=[_hb_spec(gh, s, dh)] * 3,
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)] * 3,
        cost_estimate=pl.CostEstimate(
            flops=B * nh * 5 * 2 * s * s * dh,
            bytes_accessed=7 * B * nh * s * dh * q.dtype.itemsize,
            transcendentals=B * nh * s * s),
        compiler_params=_params(),
        interpret=interpret,
    )(q, k, v, do)


@functools.lru_cache(maxsize=None)
def _make(sm_scale: float, causal: bool, interpret: bool):
    @jax.custom_vjp
    def attn(q, k, v):
        return _fwd(q, k, v, sm_scale, causal, interpret)

    def fwd(q, k, v):
        return _fwd(q, k, v, sm_scale, causal, interpret), (q, k, v)

    def bwd(res, do):
        q, k, v = res
        return _bwd(q, k, v, do, sm_scale, causal, interpret)

    attn.defvjp(fwd, bwd)
    return attn


def _reference(q, k, v, causal=False, sm_scale=1.0):
    """XLA reference for the registry lint/equivalence contract — the
    einsum composition from ops/attention_ops (duplicated minimally here to
    avoid a circular import)."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), jnp.bool_), sk - sq)
        scores = jnp.where(mask, scores, -1e9)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)


@workbench.register_kernel(
    "attention_short_seq",
    reference=_reference,
    supported=short_seq_supported,
    decision_op="attention",
    equivalence_test="test_fwd_matches_reference",
    note="fused self-attention for S in {128, 256, 384, 512} (S % 128 == 0;"
         " head-blocked VMEM slabs, fused no-residual backward)")
def short_seq_attention(q, k, v, causal=False, sm_scale=1.0):
    """Fused attention for VMEM-resident sequence lengths.

    q, k, v: [B, nh, S, dh] (S == Sk, S % 128 == 0, S <= 512 — the bwd
    kernel's ~5 fp32 [S,S] intermediates outgrow VMEM past that; callers
    must gate on `short_seq_supported`). Returns
    [B, nh, S, dh] in q's dtype. Differentiable (fused Pallas backward that
    saves no score-sized residuals — softmax is recomputed on-chip).
    """
    return _make(float(sm_scale), bool(causal), bool(INTERPRET))(q, k, v)
