"""Fused softmax-cross-entropy over a large vocab: a Pallas TPU kernel.

STATUS: measured and RETIRED (PERF.md r5, FLAGS_pallas_xent default off).
Isolated chained microbenchmarks suggested XLA ran the lm-head xent at
~55% of the HBM roofline, but the harness's own chain-add costs ~7 ms per
1 GB iteration and FUSES into the op under value_and_grad, poisoning every
isolated number. The decisive experiment is end-to-end: BERT-base b128
s128 measures 166.9k tok/s with XLA's fused path vs 152.8k tok/s with
this kernel (-8.5%) — XLA's cross-op fusion (lm-head matmul epilogue +
xent + weighted-mean consumer) beats the opaque pallas_call boundary,
the same verdict as the r4 conv-chain lever. Kept (default-off) as the
measured artifact and for interpreter-mode regression coverage.

Design:
  * grid over row tiles [TN, Vp]: one DMA of the tile; max, sum-exp, and
    the label pick (one-hot select, VMEM-local) in a single visit; only
    per-row loss/max/lse [TN] leave the chip.
  * vocab padded to a lane multiple by the CALLER (jnp.pad fuses into the
    producing matmul's epilogue); the kernel masks padding columns by
    index, so pad values are irrelevant.
  * backward recomputes p = exp(x - m - lse) from the saved [TN] stats —
    one read of logits, one write of dlogits, no other residuals.

Reference role: replaces softmax_with_cross_entropy
(reference softmax_with_cross_entropy_op.* fused CUDA kernel) on the TPU
hot path for 2-D hard-label calls.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import workbench

# tests flip this to run through the Pallas interpreter on CPU
INTERPRET = False


def xent_reference(logits, labels):
    """XLA reference defining the kernel's numerics: fp32 log-softmax
    hard-label row losses."""
    lsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(
        lsm, labels.astype(jnp.int32)[:, None], axis=-1)[:, 0]

_VC = 2048  # inner vocab chunk: fp32 temporaries are [TN, VC] so the
# ~16 MB scoped-VMEM limit holds; the block is visited chunkwise


def _tn(dtype, bwd=False) -> int:
    """Row tile sized for the ~16 MB scoped-VMEM limit: the fwd holds one
    double-buffered [TN, Vp] block; the bwd holds an input AND an output
    block, so it halves the tile."""
    tn = 64 if jnp.dtype(dtype).itemsize <= 2 else 32
    return tn // 2 if bwd else tn


def xent_supported(logits_shape, vocab_real: int, dtype=jnp.bfloat16) -> bool:
    n, v = logits_shape
    return n % 64 == 0 and v >= 512  # 64 covers every tile choice


def _chunks(vp):
    return [(j, min(_VC, vp - j)) for j in range(0, vp, _VC)]


def _fwd_kernel(x_ref, lab_ref, loss_ref, m_ref, lse_ref, *, v_real):
    tn, vp = x_ref.shape
    lab = lab_ref[...].reshape(tn)                           # [TN] int32
    # online softmax over vocab chunks: fp32 temporaries stay [TN, VC]
    m = jnp.full((tn,), -jnp.inf, jnp.float32)
    s = jnp.zeros((tn,), jnp.float32)
    picked = jnp.zeros((tn,), jnp.float32)
    # padding columns carry -1e30 (the caller pads): exp underflows to 0
    # and max ignores them, so no per-chunk index masking is needed
    for j, w in _chunks(vp):
        xj = x_ref[:, j:j + w].astype(jnp.float32)
        col = jax.lax.broadcasted_iota(jnp.int32, (tn, w), 1) + j
        mj = jnp.max(xj, axis=1)
        m_new = jnp.maximum(m, mj)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(xj - m_new[:, None]), axis=1)
        m = m_new
        picked = picked + jnp.sum(
            jnp.where(col == lab[:, None], xj, 0.0), axis=1)
    lse = jnp.log(s)
    loss_ref[...] = (-(picked - m - lse))[:, None]
    m_ref[...] = m[:, None]
    lse_ref[...] = lse[:, None]


def _bwd_kernel(x_ref, lab_ref, m_ref, lse_ref, g_ref, dx_ref, *, v_real):
    tn, vp = x_ref.shape
    m = m_ref[...].reshape(tn)
    lse = lse_ref[...].reshape(tn)
    lab = lab_ref[...].reshape(tn)
    g = g_ref[...].reshape(tn)
    for j, w in _chunks(vp):
        xj = x_ref[:, j:j + w].astype(jnp.float32)
        col = jax.lax.broadcasted_iota(jnp.int32, (tn, w), 1) + j
        # pad cols: xj = -1e30 -> p = 0; labels < v_real so onehot is 0
        p = jnp.exp(xj - (m + lse)[:, None])
        onehot = col == lab[:, None]
        dx_ref[:, j:j + w] = ((p - onehot.astype(jnp.float32))
                              * g[:, None]).astype(dx_ref.dtype)


def _pad_to_lanes(logits):
    n, v = logits.shape
    vp = (v + 127) // 128 * 128
    if vp != v:
        # -1e30: padding behaves as "never the max, exp == 0" so the
        # kernels need no per-chunk index masking (VPU cost, PERF r5)
        logits = jnp.pad(logits, ((0, 0), (0, vp - v)),
                         constant_values=-1e30)
    return logits, vp


def _run_fwd(logits, labels, interpret):
    n, v = logits.shape
    xp, vp = _pad_to_lanes(logits)
    tn = _tn(logits.dtype)
    grid = (n // tn,)
    loss, m, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, v_real=v),
        grid=grid,
        in_specs=[pl.BlockSpec((tn, vp), lambda i: (i, 0)),
                  pl.BlockSpec((tn, 1), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((tn, 1), lambda i: (i, 0))] * 3,
        out_shape=[jax.ShapeDtypeStruct((n, 1), jnp.float32)] * 3,
        interpret=interpret,
    )(xp, labels.reshape(n, 1).astype(jnp.int32))
    return loss[:, 0], m, lse


def _run_bwd(logits, labels, m, lse, g, interpret):
    n, v = logits.shape
    xp, vp = _pad_to_lanes(logits)
    tn = _tn(logits.dtype, bwd=True)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, v_real=v),
        grid=(n // tn,),
        in_specs=[pl.BlockSpec((tn, vp), lambda i: (i, 0)),
                  pl.BlockSpec((tn, 1), lambda i: (i, 0)),
                  pl.BlockSpec((tn, 1), lambda i: (i, 0)),
                  pl.BlockSpec((tn, 1), lambda i: (i, 0)),
                  pl.BlockSpec((tn, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tn, vp), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, vp), logits.dtype),
        interpret=interpret,
    )(xp, labels.reshape(n, 1).astype(jnp.int32), m, lse,
      g.reshape(n, 1))
    return dx[:, :v]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_xent_rows(logits, labels, interpret=False):
    """Per-row hard-label cross entropy: [N, V] x [N] -> loss [N] fp32.
    Gradients flow to logits only."""
    loss, _, _ = _run_fwd(logits, labels, interpret or INTERPRET)
    return loss


def _vjp_fwd(logits, labels, interpret):
    loss, m, lse = _run_fwd(logits, labels, interpret or INTERPRET)
    return loss, (logits, labels, m, lse)


def _vjp_bwd(interpret, res, g):
    logits, labels, m, lse = res
    dx = _run_bwd(logits, labels, m, lse, g.astype(jnp.float32),
                  interpret or INTERPRET)
    return dx, None


softmax_xent_rows.defvjp(_vjp_fwd, _vjp_bwd)

# registry record: measured and RETIRED (PERF.md r5 — default off behind
# FLAGS_pallas_xent); stays registered so the lint keeps its reference,
# equivalence test, and tuning key honest while it serves as regression
# coverage
workbench.register_kernel(
    "softmax_xent",
    reference=xent_reference,
    supported=xent_supported,
    decision_op="xent",
    equivalence_test="test_xent_kernel_matches_reference",
    note="fused large-vocab hard-label softmax-xent; RETIRED r5 "
         "(-8.5% end-to-end vs XLA's fusion), kept default-off")(
    softmax_xent_rows)


def _bwd_kernel_nostats(x_ref, lab_ref, g_ref, dx_ref, *, v_real):
    """dx without saved stats: the block is VMEM-resident, so m/lse are
    recomputed chunkwise with NO extra HBM traffic (one read, one write)."""
    tn, vp = x_ref.shape
    lab = lab_ref[...].reshape(tn)
    g = g_ref[...].reshape(tn)
    m = jnp.full((tn,), -jnp.inf, jnp.float32)
    s = jnp.zeros((tn,), jnp.float32)
    for j, w in _chunks(vp):
        xj = x_ref[:, j:j + w].astype(jnp.float32)
        mj = jnp.max(xj, axis=1)
        m_new = jnp.maximum(m, mj)
        s = s * jnp.exp(m - m_new) + jnp.sum(jnp.exp(xj - m_new[:, None]),
                                             axis=1)
        m = m_new
    mlse = m + jnp.log(s)
    for j, w in _chunks(vp):
        xj = x_ref[:, j:j + w].astype(jnp.float32)
        col = jax.lax.broadcasted_iota(jnp.int32, (tn, w), 1) + j
        p = jnp.exp(xj - mlse[:, None])
        onehot = col == lab[:, None]
        dx_ref[:, j:j + w] = ((p - onehot.astype(jnp.float32))
                              * g[:, None]).astype(dx_ref.dtype)


def xent_loss_fwd(logits, labels, interpret=False):
    """Program-op forward: per-row loss only (no saved stats — the
    program-level grad op recomputes them in-kernel)."""
    loss, _, _ = _run_fwd(logits, labels, interpret or INTERPRET)
    return loss


def xent_grad(logits, labels, g, interpret=False):
    """Program-op backward: dlogits from logits + labels + per-row dloss."""
    n, v = logits.shape
    xp, vp = _pad_to_lanes(logits)
    tn = _tn(logits.dtype, bwd=True)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel_nostats, v_real=v),
        grid=(n // tn,),
        in_specs=[pl.BlockSpec((tn, vp), lambda i: (i, 0)),
                  pl.BlockSpec((tn, 1), lambda i: (i, 0)),
                  pl.BlockSpec((tn, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tn, vp), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, vp), logits.dtype),
        interpret=interpret or INTERPRET,
    )(xp, labels.reshape(n, 1).astype(jnp.int32),
      g.astype(jnp.float32).reshape(n, 1))
    return dx[:, :v]
