"""Short-sequence (S <= 128) fused attention: a Pallas TPU kernel.

Why this exists (PERF.md r4/r5, ISSUE 9): the bundled flash-attention
kernel measures 42-52% SLOWER than XLA's own attention fusion at seq <= 128
on v5e — its KV-block pipeline is built for long sequences and pays its
grid/DMA overhead per tiny block. The existing short_seq kernel
(attention.py) starts at S = 128 exactly (S % 128 == 0); BERT-style
training at s64/s96 and every ragged tail below 128 had no custom arm at
all. This kernel owns that regime:

  * one grid step per batch row: the ENTIRE [nh, S, dh] Q/K/V slab of a
    row fits VMEM at S <= 128 (12 heads x 128 x 64 fp32 = 384 KB/tensor),
    so scores never touch HBM and the MXU stays pipelined across heads via
    batched dot_general — the attention.py design pushed below its 128
    floor by letting Pallas pad the [S, S] tile instead of requiring lane
    multiples.
  * ragged rows: an optional kv_lens [B] masks key slots >= len inside
    the fp32 softmax (the framework-wide batch_mask convention); a fully
    masked row emits zeros, not NaN (the paged_attention.py discipline),
    so bucket-padded batches ride through unchanged.
  * backward saves nothing but q/k/v (softmax recomputed on-chip), fusing
    all five gradient matmuls in one kernel, ragged mask included.

Dispatch: the `pallas_short128` arm of ops/attention_ops.attention_backend.
Ships OFF by default (the r5 rule) — the analytic prior keeps XLA at short
sequences because that is what was measured; only a swept tuning-DB verdict
(or FLAGS_attention_force_backend, the A/B harness override) routes here.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import workbench

_NEG_INF = -1e30
# clamp floor for the row max: a fully-masked row's scores are all
# _NEG_INF; clamping m keeps exp(s - m) == 0 there so l == 0 and the
# output is emitted as zeros instead of a uniform average (or NaN)
_M_FLOOR = -0.5e30

# tests flip this to run the kernel through the Pallas interpreter on CPU
INTERPRET = False


def short128_supported(q_shape, k_shape, bias=None, dropout_rate=0.0) -> bool:
    """Shapes this kernel handles: self-attention with sq == sk <= 128
    (any length — Pallas pads the tile), dh sublane-aligned and <= 128,
    no additive bias/dropout (those change the softmax the kernel fuses)."""
    if bias is not None or dropout_rate:
        return False
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    B, nh, sq, dh = q_shape
    sk = k_shape[2]
    return sq == sk and 1 <= sq <= 128 and dh % 8 == 0 and dh <= 128


def _masked_scores(q, k, sm_scale, causal, kv_len):
    """Batched-over-heads QK^T [nh,S,dh] x [nh,S,dh] -> [nh,S,S] fp32 with
    the causal and ragged masks applied in the score domain."""
    s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
    s = s * sm_scale
    S = s.shape[-1]
    col = jax.lax.broadcasted_iota(jnp.int32, (1, S, S), 2)
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (1, S, S), 1)
        s = jnp.where(row >= col, s, _NEG_INF)
    if kv_len is not None:
        s = jnp.where(col < kv_len, s, _NEG_INF)
    return s


def _softmax(s):
    """Row softmax returning (p, l): fully-masked rows get p == 0, l == 0
    (see _M_FLOOR), so the caller divides by max(l, tiny) and emits zeros."""
    m = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), _M_FLOOR)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    return p, l


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, sm_scale, causal, ragged):
    if ragged:
        kl_ref, o_ref = rest
        kv_len = kl_ref[0, 0]
    else:
        (o_ref,) = rest
        kv_len = None
    q, k, v = q_ref[0], k_ref[0], v_ref[0]              # [nh, S, dh]
    s = _masked_scores(q, k, sm_scale, causal, kv_len)
    p, l = _softmax(s)
    o = jax.lax.dot_general(p.astype(v.dtype), v,
                            (((2,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, *rest, sm_scale, causal, ragged):
    if ragged:
        kl_ref, do_ref, dq_ref, dk_ref, dv_ref = rest
        kv_len = kl_ref[0, 0]
    else:
        do_ref, dq_ref, dk_ref, dv_ref = rest
        kv_len = None
    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    s = _masked_scores(q, k, sm_scale, causal, kv_len)
    e, l = _softmax(s)
    p = e / jnp.maximum(l, 1e-30)                       # [nh, S, S] fp32
    pb = p.astype(q.dtype)
    # dV = P^T dO
    dv = jax.lax.dot_general(pb, do, (((1,), (1,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    # dP = dO V^T
    dp = jax.lax.dot_general(do, v, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    # softmax vjp: dS = P (.) (dP - rowsum(dP (.) P)) — masked slots have
    # P == 0, so no second masking pass is needed
    delta = jnp.sum(dp * p, axis=-1, keepdims=True)
    ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
    dq = jax.lax.dot_general(ds, k, (((2,), (1,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    dk = jax.lax.dot_general(ds, q, (((1,), (1,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    dq_ref[0] = dq.astype(dq_ref.dtype)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _specs(nh, s, dh, ragged, n_io):
    qspec = pl.BlockSpec((1, nh, s, dh), lambda b: (b, 0, 0, 0))
    klspec = pl.BlockSpec((1, 1), lambda b: (b, 0))
    in_specs = [qspec] * n_io + ([klspec] if ragged else [])
    return qspec, in_specs


def _fwd(q, k, v, kv_lens, sm_scale, causal, interpret):
    B, nh, s, dh = q.shape
    ragged = kv_lens is not None
    qspec, in_specs = _specs(nh, s, dh, ragged, 3)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, ragged=ragged)
    args = (q, k, v) + ((kv_lens.reshape(B, 1).astype(jnp.int32),)
                        if ragged else ())
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=in_specs,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=B * nh * 2 * 2 * s * s * dh,
            bytes_accessed=4 * B * nh * s * dh * q.dtype.itemsize,
            transcendentals=B * nh * s * s),
        compiler_params=workbench.compiler_params(("parallel",)),
        interpret=interpret,
    )(*args)


def _bwd(q, k, v, kv_lens, do, sm_scale, causal, interpret):
    B, nh, s, dh = q.shape
    ragged = kv_lens is not None
    qspec, in_specs = _specs(nh, s, dh, ragged, 3)
    kernel = functools.partial(_bwd_kernel, sm_scale=sm_scale,
                               causal=causal, ragged=ragged)
    args = (q, k, v) + ((kv_lens.reshape(B, 1).astype(jnp.int32),)
                        if ragged else ()) + (do,)
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=in_specs + [qspec],
        out_specs=[qspec] * 3,
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)] * 3,
        cost_estimate=pl.CostEstimate(
            flops=B * nh * 5 * 2 * s * s * dh,
            bytes_accessed=7 * B * nh * s * dh * q.dtype.itemsize,
            transcendentals=B * nh * s * s),
        compiler_params=workbench.compiler_params(("parallel",)),
        interpret=interpret,
    )(*args)


@functools.lru_cache(maxsize=None)
def _make(sm_scale: float, causal: bool, ragged: bool, interpret: bool):
    if ragged:
        @jax.custom_vjp
        def attn(q, k, v, kv_lens):
            return _fwd(q, k, v, kv_lens, sm_scale, causal, interpret)

        def fwd(q, k, v, kv_lens):
            return _fwd(q, k, v, kv_lens, sm_scale, causal, interpret), \
                (q, k, v, kv_lens)

        def bwd(res, do):
            q, k, v, kv_lens = res
            dq, dk, dv = _bwd(q, k, v, kv_lens, do, sm_scale, causal,
                              interpret)
            return dq, dk, dv, None
    else:
        @jax.custom_vjp
        def attn(q, k, v):
            return _fwd(q, k, v, None, sm_scale, causal, interpret)

        def fwd(q, k, v):
            return _fwd(q, k, v, None, sm_scale, causal, interpret), \
                (q, k, v)

        def bwd(res, do):
            q, k, v = res
            return _bwd(q, k, v, None, do, sm_scale, causal, interpret)

    attn.defvjp(fwd, bwd)
    return attn


def _reference(q, k, v, causal=False, sm_scale=1.0, kv_lens=None):
    """The XLA composition defining the kernel's numerics — the
    attention_ops reference with the ragged-key mask added."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    scores = scores * sm_scale
    S = scores.shape[-1]
    col = jnp.arange(S, dtype=jnp.int32)
    if causal:
        scores = jnp.where(col[None, None, None, :] <= col[None, None, :, None],
                           scores, _NEG_INF)
    if kv_lens is not None:
        live = col[None, None, None, :] < kv_lens[:, None, None, None]
        scores = jnp.where(live, scores, _NEG_INF)
    m = jnp.maximum(jnp.max(scores, axis=-1, keepdims=True), _M_FLOOR)
    p = jnp.exp(scores - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.maximum(l, 1e-30)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


@workbench.register_kernel(
    "attention_short128",
    reference=_reference,
    supported=short128_supported,
    decision_op="attention",
    equivalence_test="test_short128_attention_matches_reference",
    note="fused self-attention for sq == sk <= 128 (whole row in VMEM, "
         "ragged kv_lens masking, fused no-residual backward)")
def short128_attention(q, k, v, causal=False, sm_scale=1.0, kv_lens=None):
    """Fused attention for sequence lengths up to 128.

    q, k, v: [B, nh, S, dh] with S == Sk <= 128, dh % 8 == 0, dh <= 128
    (callers gate on `short128_supported`). kv_lens: optional [B] int32 —
    key slots >= kv_lens[b] are masked out of row b's softmax; a row with
    kv_lens 0 emits zeros. Returns [B, nh, S, dh] in q's dtype;
    differentiable in q/k/v (softmax recomputed on-chip, no residuals)."""
    fn = _make(float(sm_scale), bool(causal), kv_lens is not None,
               bool(INTERPRET))
    if kv_lens is not None:
        return fn(q, k, v, kv_lens)
    return fn(q, k, v)
