"""Ragged paged decode attention: a Pallas TPU kernel over the KV-page pool.

Why this exists (ROADMAP item 1, "Ragged Paged Attention", arXiv:2604.15464):
the serving runtime's decode step is one query token per request attending
over that request's whole context, which lives scattered across fixed-size
pages of the preallocated HBM pool. The XLA reference path
(attention_ops._paged_attention_reference) gathers every row's pages into a
dense [B, P*ps, nh, dh] tensor first — at long contexts that materialized
gather IS the decode step's HBM bill. This kernel never materializes it:

  * grid (batch row, page): the page index for each grid step comes from the
    request's page table via scalar prefetch — the BlockSpec index_map reads
    `page_table[b, p]` and DMAs exactly that [ps, nh, dh] page slab from the
    pool, so HBM traffic is the used pages once, nothing else.
  * the ragged part: rows in one batch have different context lengths
    (`kv_lens`, also scalar-prefetched). Slots past a row's length are masked
    to -1e9 inside the online-softmax update; rows the continuous-batching
    scheduler padded in (kv_len 0) produce finite garbage nobody reads — the
    batch_mask convention from PR 2.
  * online softmax state (m, l, acc) lives in VMEM scratch across the page
    steps of one row (grid dims are ("parallel", "arbitrary")); the output
    block is written once, on the row's last page step.

Decode q is a single token per row, so there is no backward pass: the kernel
is forward-only (serving never differentiates), which keeps it free of the
residual bookkeeping the short-seq training kernel needs.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# tests flip this to run the kernel through the Pallas interpreter on CPU
INTERPRET = False


def paged_supported(q_shape, pool_shape) -> bool:
    """Shapes this kernel handles: q [B, nh, dh] against a pool
    [num_pages, page_size, nh, dh]. dh must be sublane-aligned; the per-page
    slab [ps, nh, dh] must be modest enough to double-buffer in VMEM."""
    if len(q_shape) != 3 or len(pool_shape) != 4:
        return False
    B, nh, dh = q_shape
    num_pages, ps, p_nh, p_dh = pool_shape
    return (nh == p_nh and dh == p_dh and dh % 8 == 0 and dh <= 256
            and ps * nh * dh * 4 <= 2 * 1024 * 1024)


def _compiler_params():
    # version-tolerant spelling via the shared workbench shim
    from . import workbench

    return workbench.compiler_params(("parallel", "arbitrary"))


def _decode_kernel(pt_ref, kl_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, sm_scale, page_size, num_pages_p):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32) * sm_scale      # [nh, dh]
    k = k_ref[0].astype(jnp.float32)                 # [ps, nh, dh]
    v = v_ref[0].astype(jnp.float32)
    # batched-over-heads q.k^T: [nh, dh] x [ps, nh, dh] -> [nh, ps]
    s = jax.lax.dot_general(q, k, (((1,), (2,)), ((0,), (1,))),
                            preferred_element_type=jnp.float32)
    # ragged mask: slot p*ps + j is live iff it is below this row's context
    pos = p * page_size + jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
    s = jnp.where(pos < kl_ref[b], s, _NEG_INF)

    m_prev, l_prev = m_ref[...], l_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    pexp = jnp.exp(s - m_new)
    m_ref[...] = m_new
    l_ref[...] = l_prev * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
    # [nh, ps] x [ps, nh, dh] -> [nh, dh]
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        pexp, v, (((1,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32)

    @pl.when(p == num_pages_p - 1)
    def _emit():
        # a padded row (kv_len 0) has l == 0: emit zeros, not NaN — the
        # scheduler's batch_mask guarantees nobody reads it either way
        o_ref[0] = (acc_ref[...]
                    / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _call(q, k_pool, v_pool, page_table, kv_lens, sm_scale, interpret):
    B, nh, dh = q.shape
    num_pages, ps = k_pool.shape[0], k_pool.shape[1]
    P = page_table.shape[1]
    # clamp so a padded/garbage table entry DMAs a real page (its slots are
    # masked by kv_lens anyway) instead of reading out of bounds
    page_table = jnp.clip(page_table, 0, num_pages - 1).astype(jnp.int32)
    kv_lens = kv_lens.astype(jnp.int32)
    kernel = functools.partial(_decode_kernel, sm_scale=float(sm_scale),
                               page_size=ps, num_pages_p=P)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, P),
        in_specs=[
            pl.BlockSpec((1, nh, dh), lambda b, p, pt, kl: (b, 0, 0)),
            pl.BlockSpec((1, ps, nh, dh),
                         lambda b, p, pt, kl: (pt[b, p], 0, 0, 0)),
            pl.BlockSpec((1, ps, nh, dh),
                         lambda b, p, pt, kl: (pt[b, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nh, dh), lambda b, p, pt, kl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, 1), jnp.float32),   # running max
            pltpu.VMEM((nh, 1), jnp.float32),   # running denominator
            pltpu.VMEM((nh, dh), jnp.float32),  # running numerator
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        cost_estimate=pl.CostEstimate(
            flops=B * nh * 2 * 2 * P * ps * dh,
            bytes_accessed=(2 * B * P * ps * nh * dh * k_pool.dtype.itemsize
                            + 2 * B * nh * dh * q.dtype.itemsize),
            transcendentals=B * nh * P * ps),
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(page_table, kv_lens, q, k_pool, v_pool)


def _workbench_register():
    from . import workbench

    def _reference(q, k_pool, v_pool, page_table, kv_lens, sm_scale=1.0):
        from ..attention_ops import _paged_attention_reference

        return _paged_attention_reference(q, k_pool, v_pool, page_table,
                                          kv_lens, sm_scale)

    return workbench.register_kernel(
        "attention_paged_decode",
        reference=_reference,
        supported=paged_supported,
        decision_op="attention",
        equivalence_test="test_paged_attention_pallas_matches_reference",
        note="ragged paged decode attention (sq=1) over the KV page pool; "
             "scalar-prefetch page-table DMA, forward-only")


@_workbench_register()
def paged_decode_attention(q, k_pool, v_pool, page_table, kv_lens,
                           sm_scale=1.0):
    """One decode step of ragged paged attention.

    q: [B, nh, dh] (this step's query per request row);
    k_pool/v_pool: [num_pages, page_size, nh, dh] (the preallocated pool);
    page_table: [B, P] int32 (row b's context lives in pages
    page_table[b, 0..ceil(kv_lens[b]/page_size))); kv_lens: [B] int32 valid
    slot counts. Returns [B, nh, dh] in q's dtype. Callers gate on
    `paged_supported`.
    """
    return _call(q, k_pool, v_pool, page_table, kv_lens,
                 float(sm_scale), bool(INTERPRET))
