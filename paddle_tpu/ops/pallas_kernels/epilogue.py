"""Fused normalize+affine+activation(+residual) epilogue: Pallas TPU kernels.

Why this exists (PERF.md r6): ResNet-50's entire recoverable gap vs the
0.45-MFU target sits in the BN/elementwise tail — the conv's epilogue chain
normalize -> scale/bias -> (residual add) -> relu re-crosses HBM once per
fusion boundary XLA declines. PR 5 moved the BN *statistics* into the conv
epilogue (conv2d_bn); these kernels attack the remaining *apply* chain:

  * `bn_apply_act` — given per-channel statistics (the conv2d_bn epilogue
    already produced them, or jnp reductions XLA fuses into the producer),
    one kernel visit computes act((x - mean) * inv * scale + bias
    [+ residual]) — ONE read of x (+ residual), one write of y, fp32 math
    between, in both layouts (NHWC channels-last, NCHW channels-row).
    The unfused chain costs up to three extra HBM round trips when XLA
    splits the elementwise consumers from the producer.
  * `layer_norm_act` — per-row layer norm with the affine+activation in
    the same VMEM visit: row statistics are recomputed on-chip in fp32
    (one-pass, no stat residuals), so the whole LN->act chain is one read
    + one write. The backward recomputes statistics the same way and fuses
    the five per-row gradient terms.

Both kernels carry a custom VJP whose backward is itself one Pallas kernel
emitting dx plus per-tile partial sums for the parameter gradients (the
[n_tiles, C] partials reduce outside — a tiny jnp sum XLA folds away),
so training steps keep the one-read-one-write property end to end.

Dispatch contract (the r5 rule): ships OFF by default. ops/nn_ops.py routes
batch_norm/conv2d_bn/layer_norm epilogues here only when a swept tuning-DB
verdict keeps the kernel for the exact shape (or FLAGS_pallas_epilogue=on
forces it for A/B arms), and only where `epilogue_supported` accepts the
shape on a platform that can run it — everywhere else the XLA reference
below defines the numbers.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import workbench

# tests flip this to run the kernels through the Pallas interpreter on CPU
INTERPRET = False

_ACTS = {
    "identity": lambda z: z,
    "": lambda z: z,
    "relu": lambda z: jnp.maximum(z, 0.0),
}

# act'(z) — the backward kernels recompute z on-chip, so the derivative
# needs no saved residuals
_ACT_GRADS = {
    "identity": lambda z: 1.0,
    "": lambda z: 1.0,
    "relu": lambda z: (z > 0.0).astype(jnp.float32),
}

ACTS = tuple(a for a in _ACTS if a)


def epilogue_supported(shape, dtype, channel_last=True, act="identity") -> bool:
    """Shapes the apply kernels handle: >=2-D floating tensors whose
    canonical 2-D row (channels for NHWC, spatial extent for NCHW) fits a
    VMEM slab at tile-rows >= 1, with a registered activation."""
    if act not in _ACTS:
        return False
    if len(shape) < 2 or not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        return False
    row = shape[-1] if channel_last else _prod(shape[2:])
    rows = _prod(shape) // max(1, row)
    # fwd holds ~4 fp32 row-copies (x, z, out, residual), bwd ~6
    return (1 <= row and row * 4 * 6 <= workbench.VMEM_BUDGET
            and rows >= 1)


def _prod(xs):
    out = 1
    for v in xs:
        out *= int(v)
    return out


# ---------------------------------------------------------------------------
# bn_apply_act — normalize+affine+act(+residual) given per-channel stats
# ---------------------------------------------------------------------------


def _apply_fwd_kernel(x_ref, s_ref, b_ref, m_ref, v_ref, *rest,
                      act, has_res):
    (r_ref, o_ref) = rest if has_res else (None, rest[0])
    xf = x_ref[...].astype(jnp.float32)
    z = (xf - m_ref[...]) * (v_ref[...] * s_ref[...]) + b_ref[...]
    if has_res:
        z = z + r_ref[...].astype(jnp.float32)
    o_ref[...] = _ACTS[act](z).astype(o_ref.dtype)


def _apply_bwd_kernel(x_ref, s_ref, b_ref, m_ref, v_ref, *rest,
                      act, has_res, red_axis):
    if has_res:
        r_ref, dy_ref, dx_ref, dr_ref, p1_ref, p2_ref = rest
    else:
        dy_ref, dx_ref, p1_ref, p2_ref = rest
        r_ref = dr_ref = None
    xf = x_ref[...].astype(jnp.float32)
    xc = xf - m_ref[...]
    g = v_ref[...] * s_ref[...]
    z = xc * g + b_ref[...]
    if has_res:
        z = z + r_ref[...].astype(jnp.float32)
    dz = dy_ref[...].astype(jnp.float32) * _ACT_GRADS[act](z)
    dx_ref[...] = (dz * g).astype(dx_ref.dtype)
    if has_res:
        dr_ref[...] = dz.astype(dr_ref.dtype)
    # per-channel partials: P1 = sum dz, P2 = sum dz*(x-m); the caller
    # derives dbias/dmean from P1 and dscale/dinv from P2 (scalar algebra
    # per channel), so the kernel ships two reductions, not four
    p1_ref[...] = jnp.sum(dz, axis=red_axis, keepdims=True)
    p2_ref[...] = jnp.sum(dz * xc, axis=red_axis, keepdims=True)


def _apply_specs(mode, tr, row, nt):
    """(x/out spec, param spec, partial spec) for one canonical layout.

    mode "cl": x2 [R, C] channels-last — params broadcast as [1, C] rows,
    per-tile partials land in [NT, C]. mode "cr": x2 [R=N*C, HW] channels-
    row — params are per-row [TR, 1] columns (pre-tiled to [R, 1]), partials
    are complete per-row sums [R, 1]."""
    xspec = pl.BlockSpec((tr, row), lambda i: (i, 0))
    if mode == "cl":
        pspec = pl.BlockSpec((1, row), lambda i: (0, 0))
        partial = pl.BlockSpec((1, row), lambda i: (i, 0))
    else:
        pspec = pl.BlockSpec((tr, 1), lambda i: (i, 0))
        partial = pl.BlockSpec((tr, 1), lambda i: (i, 0))
    return xspec, pspec, partial


def _apply_call_fwd(x2, params, res2, act, mode, interpret):
    R, row = x2.shape
    tr = workbench.pick_block(R, row * 4 * (5 if res2 is not None else 4))
    nt = R // tr
    xspec, pspec, _ = _apply_specs(mode, tr, row, nt)
    in_specs = [xspec] + [pspec] * 4 + ([xspec] if res2 is not None else [])
    kernel = functools.partial(_apply_fwd_kernel, act=act,
                               has_res=res2 is not None)
    args = (x2, *params) + ((res2,) if res2 is not None else ())
    return pl.pallas_call(
        kernel,
        grid=(nt,),
        in_specs=in_specs,
        out_specs=xspec,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        cost_estimate=pl.CostEstimate(
            flops=6 * R * row, transcendentals=0,
            bytes_accessed=(2 + (1 if res2 is not None else 0))
            * R * row * x2.dtype.itemsize),
        compiler_params=workbench.compiler_params(("parallel",)),
        interpret=interpret,
    )(*args)


def _apply_call_bwd(x2, params, res2, dy2, act, mode, interpret):
    R, row = x2.shape
    has_res = res2 is not None
    tr = workbench.pick_block(R, row * 4 * (8 if has_res else 6))
    nt = R // tr
    xspec, pspec, partial = _apply_specs(mode, tr, row, nt)
    pshape = (nt, row) if mode == "cl" else (R, 1)
    in_specs = [xspec] + [pspec] * 4 + [xspec] * (2 if has_res else 1)
    out_specs = [xspec] + ([xspec] if has_res else []) + [partial] * 2
    out_shape = ([jax.ShapeDtypeStruct(x2.shape, x2.dtype)]
                 + ([jax.ShapeDtypeStruct(x2.shape, dy2.dtype)]
                    if has_res else [])
                 + [jax.ShapeDtypeStruct(pshape, jnp.float32)] * 2)
    kernel = functools.partial(_apply_bwd_kernel, act=act, has_res=has_res,
                               red_axis=0 if mode == "cl" else 1)
    args = (x2, *params) + ((res2, dy2) if has_res else (dy2,))
    return pl.pallas_call(
        kernel,
        grid=(nt,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        cost_estimate=pl.CostEstimate(
            flops=10 * R * row, transcendentals=0,
            bytes_accessed=(3 + (2 if has_res else 0))
            * R * row * x2.dtype.itemsize),
        compiler_params=workbench.compiler_params(("parallel",)),
        interpret=interpret,
    )(*args)


@functools.lru_cache(maxsize=None)
def _make_apply(act: str, mode: str, has_res: bool, interpret: bool):
    """Cached custom-VJP apply function over canonical 2-D operands.

    Differentiable args: (x2, scale, bias, mean, inv[, res2]) — params in
    the kernel's block orientation ([1, C] rows for "cl", [R, 1] per-row
    columns for "cr"), fp32. The backward emits dx (+dres) from one kernel
    plus the two per-channel partial-sum planes it derives all four
    parameter grads from."""

    def _bwd_shared(saved, dy2):
        x2, s, b, m, v, res2 = saved
        outs = _apply_call_bwd(x2, (s, b, m, v), res2, dy2, act, mode,
                               interpret)
        if has_res:
            dx2, dr2, p1, p2 = outs
        else:
            dx2, p1, p2 = outs
            dr2 = None
        if mode == "cl":
            P1 = jnp.sum(p1, axis=0, keepdims=True)      # [1, C]
            P2 = jnp.sum(p2, axis=0, keepdims=True)
        else:
            P1, P2 = p1, p2                              # [R, 1] complete
        ds = P2 * v
        db = P1
        dm = -P1 * v * s
        dv = P2 * s
        return dx2, ds, db, dm, dv, dr2

    def _fwd(x2, s, b, m, v, r2):
        return _apply_call_fwd(x2, (s, b, m, v), r2, act, mode, interpret)

    if has_res:
        @jax.custom_vjp
        def apply(x2, s, b, m, v, r2):
            return _fwd(x2, s, b, m, v, r2)

        def vjp_fwd(x2, s, b, m, v, r2):
            return _fwd(x2, s, b, m, v, r2), (x2, s, b, m, v, r2)

        def vjp_bwd(saved, dy2):
            dx2, ds, db, dm, dv, dr2 = _bwd_shared(saved, dy2)
            return dx2, ds, db, dm, dv, dr2
    else:
        @jax.custom_vjp
        def apply(x2, s, b, m, v):
            return _fwd(x2, s, b, m, v, None)

        def vjp_fwd(x2, s, b, m, v):
            return _fwd(x2, s, b, m, v, None), (x2, s, b, m, v, None)

        def vjp_bwd(saved, dy2):
            dx2, ds, db, dm, dv, _ = _bwd_shared(saved, dy2)
            return dx2, ds, db, dm, dv

    apply.defvjp(vjp_fwd, vjp_bwd)
    return apply


def bn_apply_act_reference(x, scale, bias, mean, inv, act="identity",
                           residual=None, channel_last=True):
    """The XLA composition defining the kernel's numerics: fp32 math,
    normalize -> affine -> (+residual) -> act, cast back to x.dtype."""
    cax = x.ndim - 1 if channel_last else 1
    bshape = [1] * x.ndim
    bshape[cax] = -1
    f32 = lambda a: a.astype(jnp.float32).reshape(bshape)  # noqa: E731
    z = ((x.astype(jnp.float32) - f32(mean)) * (f32(inv) * f32(scale))
         + f32(bias))
    if residual is not None:
        z = z + residual.astype(jnp.float32)
    return _ACTS[act](z).astype(x.dtype)


@workbench.register_kernel(
    "epilogue_bn_apply",
    reference=bn_apply_act_reference,
    supported=epilogue_supported,
    decision_op="epilogue",
    equivalence_test="test_bn_apply_act_matches_reference",
    note="normalize+affine+act(+residual) given per-channel stats; "
         "NHWC channels-last and NCHW channels-row layouts")
def bn_apply_act(x, scale, bias, mean, inv, act="identity", residual=None,
                 channel_last=True):
    """One-pass epilogue apply: act((x - mean) * inv * scale + bias
    [+ residual]) in fp32, returned in x.dtype. scale/bias/mean/inv are
    per-channel [C]; residual must match x's shape. Differentiable in
    x, scale, bias, mean, inv, residual. Callers gate on
    `epilogue_supported`."""
    act = act or "identity"
    shape = x.shape
    if channel_last:
        C = shape[-1]
        x2 = x.reshape(-1, C)
        params = tuple(p.astype(jnp.float32).reshape(1, C)
                       for p in (scale, bias, mean, inv))
        mode = "cl"
    else:
        N, C = shape[0], shape[1]
        hw = _prod(shape[2:])
        x2 = x.reshape(N * C, hw)
        params = tuple(jnp.tile(p.astype(jnp.float32), N).reshape(N * C, 1)
                       for p in (scale, bias, mean, inv))
        mode = "cr"
    res2 = residual.reshape(x2.shape) if residual is not None else None
    fn = _make_apply(act, mode, res2 is not None, bool(INTERPRET))
    args = (x2, *params) + ((res2,) if res2 is not None else ())
    return fn(*args).reshape(shape)


# ---------------------------------------------------------------------------
# layer_norm_act — per-row LN with affine+act in the same VMEM visit
# ---------------------------------------------------------------------------


def _ln_fwd_kernel(x_ref, s_ref, b_ref, o_ref, *, eps, act):
    xf = x_ref[...].astype(jnp.float32)
    m = jnp.mean(xf, axis=1, keepdims=True)
    xc = xf - m
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    r = jax.lax.rsqrt(var + eps)
    z = xc * r * s_ref[...] + b_ref[...]
    o_ref[...] = _ACTS[act](z).astype(o_ref.dtype)


def _ln_bwd_kernel(x_ref, s_ref, b_ref, dy_ref, dx_ref, ds_ref, db_ref,
                   *, eps, act):
    xf = x_ref[...].astype(jnp.float32)
    m = jnp.mean(xf, axis=1, keepdims=True)
    xc = xf - m
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    r = jax.lax.rsqrt(var + eps)
    xhat = xc * r
    z = xhat * s_ref[...] + b_ref[...]
    dz = dy_ref[...].astype(jnp.float32) * _ACT_GRADS[act](z)
    dxhat = dz * s_ref[...]
    a = jnp.mean(dxhat, axis=1, keepdims=True)
    c = jnp.mean(dxhat * xhat, axis=1, keepdims=True)
    dx_ref[...] = (r * (dxhat - a - xhat * c)).astype(dx_ref.dtype)
    ds_ref[...] = jnp.sum(dz * xhat, axis=0, keepdims=True)
    db_ref[...] = jnp.sum(dz, axis=0, keepdims=True)


@functools.lru_cache(maxsize=None)
def _make_ln(eps: float, act: str, interpret: bool):
    def call_fwd(x2, s, b):
        R, K = x2.shape
        tr = workbench.pick_block(R, K * 4 * 5)
        return pl.pallas_call(
            functools.partial(_ln_fwd_kernel, eps=eps, act=act),
            grid=(R // tr,),
            in_specs=[pl.BlockSpec((tr, K), lambda i: (i, 0)),
                      pl.BlockSpec((1, K), lambda i: (0, 0)),
                      pl.BlockSpec((1, K), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((tr, K), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
            cost_estimate=pl.CostEstimate(
                flops=9 * R * K, transcendentals=R,
                bytes_accessed=2 * R * K * x2.dtype.itemsize),
            compiler_params=workbench.compiler_params(("parallel",)),
            interpret=interpret,
        )(x2, s, b)

    @jax.custom_vjp
    def ln(x2, s, b):
        return call_fwd(x2, s, b)

    def vjp_fwd(x2, s, b):
        return call_fwd(x2, s, b), (x2, s, b)

    def vjp_bwd(saved, dy2):
        x2, s, b = saved
        R, K = x2.shape
        tr = workbench.pick_block(R, K * 4 * 7)
        nt = R // tr
        dx2, ds_p, db_p = pl.pallas_call(
            functools.partial(_ln_bwd_kernel, eps=eps, act=act),
            grid=(nt,),
            in_specs=[pl.BlockSpec((tr, K), lambda i: (i, 0)),
                      pl.BlockSpec((1, K), lambda i: (0, 0)),
                      pl.BlockSpec((1, K), lambda i: (0, 0)),
                      pl.BlockSpec((tr, K), lambda i: (i, 0))],
            out_specs=[pl.BlockSpec((tr, K), lambda i: (i, 0)),
                       pl.BlockSpec((1, K), lambda i: (i, 0)),
                       pl.BlockSpec((1, K), lambda i: (i, 0))],
            out_shape=[jax.ShapeDtypeStruct(x2.shape, x2.dtype),
                       jax.ShapeDtypeStruct((nt, K), jnp.float32),
                       jax.ShapeDtypeStruct((nt, K), jnp.float32)],
            cost_estimate=pl.CostEstimate(
                flops=16 * R * K, transcendentals=R,
                bytes_accessed=3 * R * K * x2.dtype.itemsize),
            compiler_params=workbench.compiler_params(("parallel",)),
            interpret=interpret,
        )(x2, s, b, dy2)
        return dx2, jnp.sum(ds_p, axis=0, keepdims=True), \
            jnp.sum(db_p, axis=0, keepdims=True)

    ln.defvjp(vjp_fwd, vjp_bwd)
    return ln


def layer_norm_act_reference(x2, scale, bias, eps=1e-5, act="identity"):
    """The XLA composition defining the kernel's numerics (rows of x2
    normalized over the last dim, fp32 statistics)."""
    xf = x2.astype(jnp.float32)
    m = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - m), axis=-1, keepdims=True)
    z = (xf - m) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        z = z * scale.astype(jnp.float32).reshape(1, -1)
    if bias is not None:
        z = z + bias.astype(jnp.float32).reshape(1, -1)
    return _ACTS[act or "identity"](z).astype(x2.dtype)


@workbench.register_kernel(
    "epilogue_layer_norm",
    reference=layer_norm_act_reference,
    supported=lambda shape, dtype, act="identity": epilogue_supported(
        shape, dtype, channel_last=True, act=act),
    decision_op="epilogue",
    equivalence_test="test_layer_norm_act_matches_reference",
    note="one-pass per-row LN (+affine+act) with in-kernel fp32 statistics")
def layer_norm_act(x2, scale=None, bias=None, eps=1e-5, act="identity"):
    """Fused LN epilogue over canonical rows: x2 [R, K] normalized over K
    with affine+act in the same VMEM visit. scale/bias default to 1/0.
    Differentiable in x2, scale, bias. Callers gate on
    `epilogue_supported((R, K), dtype)`."""
    act = act or "identity"
    K = x2.shape[-1]
    s = (jnp.ones((1, K), jnp.float32) if scale is None
         else scale.astype(jnp.float32).reshape(1, K))
    b = (jnp.zeros((1, K), jnp.float32) if bias is None
         else bias.astype(jnp.float32).reshape(1, K))
    return _make_ln(float(eps), act, bool(INTERPRET))(x2, s, b)
