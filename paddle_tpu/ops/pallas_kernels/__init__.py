"""Hand-written Pallas TPU kernels (the compute-path native layer).

XLA fuses most of the framework's ops well; these kernels exist for the
cases where measurement (PERF.md) showed XLA leaving throughput on the
table. The package is organized as a small kernel WORKBENCH (workbench.py):
shared version-tolerant CompilerParams, block-shape/VMEM helpers, and a
registry in which every kernel records its XLA reference, shape gate,
tuning-DB decision op, and equivalence test — `tools/gate.py
check_kernel_registry` fails the build on any kernel missing one, so no
unmeasured kernel can land silently. Each kernel module exposes a plain
jax-callable function with a custom VJP so the op registry's
derived-gradient machinery works through it, and dispatches through the
tuning layer (keep-or-retire per shape, degradation to the reference when
the platform cannot run the kernel).
"""
from . import workbench
from .attention import short_seq_attention, short_seq_supported
from .epilogue import (bn_apply_act, bn_apply_act_reference,
                       epilogue_supported, layer_norm_act,
                       layer_norm_act_reference)
from .short_attention import short128_attention, short128_supported
from .workbench import all_kernels, register_kernel

__all__ = [
    "workbench", "all_kernels", "register_kernel",
    "short_seq_attention", "short_seq_supported",
    "short128_attention", "short128_supported",
    "bn_apply_act", "bn_apply_act_reference", "epilogue_supported",
    "layer_norm_act", "layer_norm_act_reference",
]
