"""Hand-written Pallas TPU kernels (the compute-path native layer).

XLA fuses most of the framework's ops well; these kernels exist for the
cases where measurement (PERF.md) showed XLA leaving throughput on the
table. Each kernel module exposes a plain jax-callable function with a
custom VJP so the op registry's derived-gradient machinery works through it.
"""
from .attention import short_seq_attention, short_seq_supported

__all__ = ["short_seq_attention", "short_seq_supported"]
