"""Kernel-workbench substrate: the conventions every Pallas kernel shares.

ROADMAP item 5 ("Tensor Processing Primitives", arXiv:2104.05755) calls for
a small reusable custom-kernel layer rather than a pile of one-off files.
This module is that layer's spine — the pieces attention.py / xent.py /
paged_attention.py each re-invented privately, factored once:

  * `compiler_params` — version-tolerant CompilerParams construction. jax
    renamed pltpu.TPUCompilerParams -> CompilerParams (and back) across
    0.4.x/0.5.x; paged_attention.py carried the shim, attention.py did not
    and broke on 0.4.37 (the pre-existing test_pallas_attention failures).
    One spelling here, used by every kernel.
  * block-shape helpers — `pick_block` (largest divisor under a VMEM
    budget, sublane-friendly), `fit_heads` (the attention head-block rule),
    and the lane/sublane constants, so kernels size their slabs against the
    same ~16 MB VMEM model instead of private magic numbers.
  * the kernel REGISTRY — `register_kernel` records, for every kernel the
    workbench ships, its jax-callable entry point, the XLA reference that
    defines its numerics, the `supported` shape gate the dispatcher must
    consult, the tuning-DB op kind its decisions key under, and the name of
    its equivalence test. `tools/gate.py check_kernel_registry` (and the
    tier-1 lint test) fail the build when any kernel is missing one of
    those — an unmeasured or unreferenced kernel cannot land silently,
    which is the TVM-flavored keep-or-retire contract (arXiv:1802.04799)
    made structural.

Every kernel module keeps its own `INTERPRET` flag (tests flip it to run
the kernel through the Pallas interpreter on CPU); `runnable` centralizes
the "TPU or interpreter" dispatch gate.
"""
from __future__ import annotations

import dataclasses
from typing import Callable

import jax

LANES = 128
# per-step VMEM slab budget (bytes): leaves room for double buffering and
# fp32 score/stat scratch inside the ~16 MB of VMEM per core
VMEM_BUDGET = 3 * 1024 * 1024


def compiler_params(dimension_semantics: tuple):
    """Version-tolerant pltpu CompilerParams: jax moved CompilerParams ->
    TPUCompilerParams and back across releases; every kernel builds its
    params through this one shim so a rename breaks one line, not N files."""
    from jax.experimental.pallas import tpu as pltpu

    cp = (getattr(pltpu, "CompilerParams", None)
          or getattr(pltpu, "TPUCompilerParams"))
    return cp(dimension_semantics=tuple(dimension_semantics))


def sublanes(dtype) -> int:
    """Min sublane tile for a dtype (fp32 8, bf16 16, int8/fp8 32)."""
    import jax.numpy as jnp

    size = jnp.dtype(dtype).itemsize
    return {4: 8, 2: 16, 1: 32}.get(size, 8)


def round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def pick_block(extent: int, row_bytes: int,
               budget: int = VMEM_BUDGET, prefer_multiple: int = 8) -> int:
    """Largest divisor of `extent` whose slab (divisor * row_bytes) fits
    `budget`, preferring sublane multiples. Divisor-only so grids never
    overrun the array edge — kernels with per-block reductions must not see
    padding garbage rows. Degrades to 1 (always a divisor)."""
    cap = max(1, budget // max(1, row_bytes))
    divisors = [c for c in range(1, min(extent, cap) + 1) if extent % c == 0]
    preferred = [c for c in divisors if c % prefer_multiple == 0]
    return (preferred or divisors)[-1]


def fit_heads(nh: int, per_head_bytes: int,
              budget: int = VMEM_BUDGET) -> int:
    """Largest divisor of nh whose per-step slab fits the budget — the
    attention head-block rule (attention.py) shared with any kernel that
    batches a head-like dim through the MXU."""
    gh = nh
    while gh > 1 and gh * per_head_bytes > budget:
        gh -= 1
        while nh % gh:
            gh -= 1
    return max(1, gh)


def on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


def runnable(module) -> bool:
    """The dispatch gate every kernel shares: a Pallas kernel runs on a TPU
    backend or under the module's interpreter flag, nowhere else."""
    return on_tpu() or bool(getattr(module, "INTERPRET", False))


# ---------------------------------------------------------------------------
# Kernel registry — the lint surface tools/gate.py checks
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class KernelSpec:
    """One workbench kernel's accountability record.

    name            — registry key (stable; PERF.md verdicts cite it)
    fn              — the jax-callable kernel entry point
    reference       — the XLA composition defining the kernel's numerics
                      (equivalence tests pin fn against it)
    supported       — shape gate callable; dispatchers must consult it and
                      fall back to `reference` when it rejects
    decision_op     — tuning-DB op kind the kernel's keep/retire verdicts
                      key under ("attention", "epilogue", ...); every
                      kernel MUST resolve through tuning.decide so a swept
                      verdict can keep or retire it per shape
    equivalence_test— name of the tier-1 test function pinning fn ==
                      reference (gate.py greps tests/ for its definition)
    default_on      — False (the r5 rule): a kernel ships off until a
                      swept DB verdict keeps it. True only for kernels that
                      already earned an end-to-end keep (bundled dispatch
                      rules replay the measured PERF.md split).
    """

    name: str
    fn: Callable
    reference: Callable
    supported: Callable
    decision_op: str
    equivalence_test: str
    default_on: bool = False
    note: str = ""


_KERNELS: dict[str, KernelSpec] = {}


def register_kernel(name: str, *, reference, supported, decision_op,
                    equivalence_test, default_on=False, note=""):
    """Decorator registering a kernel entry point with its full
    accountability record (see KernelSpec). gate.py's registry lint fails
    on any kernel whose record is incomplete."""

    def deco(fn):
        _KERNELS[name] = KernelSpec(
            name=name, fn=fn, reference=reference, supported=supported,
            decision_op=decision_op, equivalence_test=equivalence_test,
            default_on=default_on, note=note)
        return fn

    return deco


def all_kernels() -> dict[str, KernelSpec]:
    """Every registered kernel (import side effect: pulls in the kernel
    modules so their registrations run)."""
    from . import attention, epilogue, paged_attention, short_attention  # noqa: F401
    from . import xent  # noqa: F401

    return dict(_KERNELS)
