"""Detection ops: SSD-style priors, box coding, IoU, NMS.

TPU-native re-design of the reference detection operator family
(/root/reference/paddle/fluid/operators/detection/): prior_box_op.h,
box_coder_op.h, iou_similarity_op.h, multiclass_nms_op.cc.

Everything is fixed-shape: NMS returns a [keep_top_k, 6] tensor padded with
-1 labels (the reference returns a LoD tensor of variable length; the padded
layout carries the same detections with an explicit validity convention),
and suppression runs as a lax.scan over the score-sorted candidates instead
of the reference's data-dependent while loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import ExecContext, register_op


@register_op("prior_box", grad="none")
def prior_box(ctx: ExecContext):
    """SSD prior boxes (reference prior_box_op.h): one box per
    (min_size, aspect_ratio) plus the sqrt(min*max) box, centered on each
    feature-map cell, normalized to the image."""
    feat = ctx.input("Input")    # [N, C, H, W]
    img = ctx.input("Image")     # [N, 3, IH, IW]
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    min_sizes = [float(s) for s in ctx.attr("min_sizes")]
    max_sizes = [float(s) for s in ctx.attr("max_sizes", []) or []]
    ars = [float(a) for a in ctx.attr("aspect_ratios", [1.0]) or [1.0]]
    flip = bool(ctx.attr("flip", False))
    clip = bool(ctx.attr("clip", False))
    variances = [float(v) for v in
                 ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    step_w = float(ctx.attr("step_w", 0.0)) or IW / W
    step_h = float(ctx.attr("step_h", 0.0)) or IH / H
    offset = float(ctx.attr("offset", 0.5))

    # ExpandAspectRatios: 1.0 first, then each ratio (+ flip), deduped
    ratios = [1.0]
    for ar in ars:
        if all(abs(ar - r) > 1e-6 for r in ratios):
            ratios.append(ar)
            if flip:
                ratios.append(1.0 / ar)

    whs = []  # (w, h) per prior, reference ordering
    for k, ms in enumerate(min_sizes):
        for ar in ratios:
            whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if abs(ar - 1.0) < 1e-6 and max_sizes:
                big = np.sqrt(ms * max_sizes[k])
                whs.append((big, big))
    P = len(whs)
    wh = jnp.asarray(np.array(whs, np.float32))          # [P, 2]

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)                      # [H, W]
    cxg = cxg[..., None]
    cyg = cyg[..., None]
    half_w = wh[None, None, :, 0] / 2
    half_h = wh[None, None, :, 1] / 2
    boxes = jnp.stack(
        [(cxg - half_w) / IW, (cyg - half_h) / IH,
         (cxg + half_w) / IW, (cyg + half_h) / IH], axis=-1)  # [H,W,P,4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), (H, W, P, 4))
    return {"Boxes": boxes, "Variances": var}


@register_op("box_coder", grad="none")
def box_coder(ctx: ExecContext):
    """Center-size box encode/decode (reference box_coder_op.h).
    PriorBox [M, 4], PriorBoxVar [M, 4]?, TargetBox encode:[N, 4] /
    decode:[N, M, 4]. code_type attr: encode_center_size|decode_center_size.
    """
    prior = ctx.input("PriorBox")
    pvar = ctx.input("PriorBoxVar")
    target = ctx.input("TargetBox")
    code_type = str(ctx.attr("code_type", "encode_center_size"))
    norm = bool(ctx.attr("box_normalized", True))

    eps = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + eps
    ph = prior[:, 3] - prior[:, 1] + eps
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if pvar is None:
        pvar = jnp.ones_like(prior)

    if code_type.startswith("encode"):
        tw = target[:, 2] - target[:, 0] + eps
        th = target[:, 3] - target[:, 1] + eps
        tcx = target[:, 0] + tw / 2
        tcy = target[:, 1] + th / 2
        # broadcast [N, 1] vs [1, M]
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :] / pvar[None, :, 0]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :] / pvar[None, :, 1]
        ow = jnp.log(tw[:, None] / pw[None, :]) / pvar[None, :, 2]
        oh = jnp.log(th[:, None] / ph[None, :]) / pvar[None, :, 3]
        return {"OutputBox": jnp.stack([ox, oy, ow, oh], axis=-1)}

    # decode: target [N, M, 4] offsets -> boxes
    ox = target[..., 0] * pvar[None, :, 0] * pw[None, :] + pcx[None, :]
    oy = target[..., 1] * pvar[None, :, 1] * ph[None, :] + pcy[None, :]
    ow = jnp.exp(target[..., 2] * pvar[None, :, 2]) * pw[None, :]
    oh = jnp.exp(target[..., 3] * pvar[None, :, 3]) * ph[None, :]
    return {"OutputBox": jnp.stack(
        [ox - ow / 2, oy - oh / 2, ox + ow / 2 - eps, oy + oh / 2 - eps],
        axis=-1)}


def _encode_center_size(prior, pvar, boxes, eps=0.0):
    """Shared center-size encode (the box_coder formula; ssd_loss target
    encoding must stay in lockstep with it). prior/pvar [M, 4],
    boxes [M, 4] matched per prior -> offsets [M, 4]."""
    pw = prior[:, 2] - prior[:, 0] + eps
    ph = prior[:, 3] - prior[:, 1] + eps
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    gw = boxes[:, 2] - boxes[:, 0] + eps
    gh = boxes[:, 3] - boxes[:, 1] + eps
    gcx = boxes[:, 0] + gw / 2
    gcy = boxes[:, 1] + gh / 2
    tx = (gcx - pcx) / pw / pvar[:, 0]
    ty = (gcy - pcy) / ph / pvar[:, 1]
    tw = jnp.log(jnp.maximum(gw / pw, 1e-8)) / pvar[:, 2]
    th = jnp.log(jnp.maximum(gh / ph, 1e-8)) / pvar[:, 3]
    return jnp.stack([tx, ty, tw, th], axis=1)


def _iou(a, b, eps=0.0):
    """Pairwise IoU: a [N, 4], b [M, 4] -> [N, M]. eps=1.0 applies the
    reference's +1 width/height convention for UNnormalized pixel boxes
    (bbox_util.h JaccardOverlap)."""
    x1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    y1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    x2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    y2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.clip(x2 - x1 + eps, 0) * jnp.clip(y2 - y1 + eps, 0)
    area_a = (jnp.clip(a[:, 2] - a[:, 0] + eps, 0)
              * jnp.clip(a[:, 3] - a[:, 1] + eps, 0))
    area_b = (jnp.clip(b[:, 2] - b[:, 0] + eps, 0)
              * jnp.clip(b[:, 3] - b[:, 1] + eps, 0))
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity", grad="none")
def iou_similarity(ctx: ExecContext):
    """reference iou_similarity_op.h: X [N, 4], Y [M, 4] -> [N, M]."""
    return {"Out": _iou(ctx.input("X"), ctx.input("Y"))}


def _nms_single(scores, base_iou, score_thr, nms_thr, top_k):
    """Greedy NMS over one class: scores [M], base_iou [M, M] (shared
    across classes — the boxes don't change per class) -> keep mask [M].

    Reference NMSFast semantics: the candidate POOL is the top nms_top_k by
    score (lower-ranked boxes are never considered), then greedy IoU
    suppression over that pool — which also bounds the sequential scan to
    top_k steps instead of M."""
    M = scores.shape[0]
    k = min(int(top_k), M) if top_k > 0 else M
    order = jnp.argsort(-scores)[:k]
    ss = scores[order]
    iou = base_iou[order][:, order]

    def step(kept, i):
        valid = ss[i] > score_thr
        sup = jnp.any(kept & (iou[i] > nms_thr))
        keep_i = valid & ~sup
        return kept.at[i].set(keep_i), None

    kept, _ = jax.lax.scan(step, jnp.zeros((k,), bool), jnp.arange(k))
    # scatter the pool's keep decisions back to original positions
    full = jnp.zeros((M,), bool)
    return full.at[order].set(kept)


@register_op("multiclass_nms", grad="none")
def multiclass_nms(ctx: ExecContext):
    """reference multiclass_nms_op.cc on fixed shapes.

    BBoxes [N, M, 4], Scores [N, C, M]. Per class: score threshold + greedy
    IoU NMS (nms_top_k); across classes: keep_top_k by score. Output
    [N, keep_top_k, 6] rows (label, score, x1, y1, x2, y2), label = -1 marks
    padding (the reference's empty-LoD convention)."""
    bboxes = ctx.input("BBoxes")
    scores = ctx.input("Scores")
    score_thr = float(ctx.attr("score_threshold", 0.0))
    nms_thr = float(ctx.attr("nms_threshold", 0.3))
    nms_top_k = int(ctx.attr("nms_top_k", 400))
    keep_top_k = int(ctx.attr("keep_top_k", 200))
    bg = int(ctx.attr("background_label", 0))
    normalized = bool(ctx.attr("normalized", True))
    N, C, M = scores.shape
    if keep_top_k < 0:
        keep_top_k = C * M

    def per_image(bx, sc):
        base_iou = _iou(bx, bx, eps=0.0 if normalized else 1.0)
        all_scores, all_labels, all_boxes = [], [], []
        for c in range(C):
            if c == bg:
                continue
            keep = _nms_single(sc[c], base_iou, score_thr, nms_thr,
                               nms_top_k)
            all_scores.append(jnp.where(keep, sc[c], -1.0))
            all_labels.append(jnp.full((M,), c, jnp.float32))
            all_boxes.append(bx)
        fs = jnp.concatenate(all_scores)
        fl = jnp.concatenate(all_labels)
        fb = jnp.concatenate(all_boxes)
        k = min(keep_top_k, fs.shape[0])
        top_s, top_i = jax.lax.top_k(fs, k)
        rows = jnp.concatenate(
            [jnp.where(top_s > score_thr, fl[top_i], -1.0)[:, None],
             top_s[:, None], fb[top_i]], axis=1)
        if k < keep_top_k:
            pad = jnp.full((keep_top_k - k, 6), -1.0, rows.dtype)
            rows = jnp.concatenate([rows, pad], axis=0)
        return rows

    return {"Out": jax.vmap(per_image)(bboxes, scores)}


@register_op("ssd_loss")
def ssd_loss(ctx: ExecContext):
    """SSD multibox loss (reference detection.py:1280 ssd_loss pipeline,
    collapsed into one fixed-shape op).

    Inputs: Loc [N, M, 4] predicted offsets, Conf [N, M, C] raw logits,
    GTBox [N, G, 4], GTLabel [N, G, 1] (0 padding rows marked by
    GTCount [N] valid counts). PriorBox [M, 4], PriorBoxVar [M, 4]?.

    Matching is per-prediction (each prior -> best gt when IoU >= threshold)
    plus the bipartite guarantee that every valid gt claims its best prior —
    the reference's two-phase match — followed by max-negative hard mining at
    neg_pos_ratio. Returns Loss [N, 1].
    """
    loc = ctx.input("Loc")
    conf = ctx.input("Conf")
    gt_box = ctx.input("GTBox")
    gt_label = ctx.input("GTLabel").reshape(gt_box.shape[0], -1)
    gt_count = ctx.input("GTCount")
    prior = ctx.input("PriorBox")
    pvar = ctx.input("PriorBoxVar")
    bg = int(ctx.attr("background_label", 0))
    overlap_thr = float(ctx.attr("overlap_threshold", 0.5))
    neg_overlap = float(ctx.attr("neg_overlap", 0.5))
    neg_ratio = float(ctx.attr("neg_pos_ratio", 3.0))
    loc_w = float(ctx.attr("loc_loss_weight", 1.0))
    conf_w = float(ctx.attr("conf_loss_weight", 1.0))
    normalize = bool(ctx.attr("normalize", True))

    N, M, C = conf.shape
    G = gt_box.shape[1]
    if gt_count is None:
        gt_count = jnp.full((N,), G, jnp.int32)
    else:
        gt_count = gt_count.reshape(-1).astype(jnp.int32)
    if pvar is None:
        pvar = jnp.ones_like(prior)

    def per_image(bx, lbl, cnt, lc, cf):
        valid_gt = jnp.arange(G) < cnt                      # [G]
        iou = _iou(bx, prior)                               # [G, M]
        iou = jnp.where(valid_gt[:, None], iou, -1.0)
        # phase 1 (bipartite seed): each valid gt claims its best prior
        best_prior_per_gt = jnp.argmax(iou, axis=1)         # [G]
        # phase 2 (per-prediction): each prior takes its best gt over thr
        best_gt_per_prior = jnp.argmax(iou, axis=0)         # [M]
        best_iou_per_prior = jnp.max(iou, axis=0)
        matched_gt = jnp.where(best_iou_per_prior >= overlap_thr,
                               best_gt_per_prior, -1)
        # force the bipartite seeds; invalid gt rows scatter out of range
        # (mode="drop") so they can't race a valid row on the same prior
        seed_idx = jnp.where(valid_gt, best_prior_per_gt, M)
        matched_gt = matched_gt.at[seed_idx].set(jnp.arange(G), mode="drop")
        is_pos = matched_gt >= 0                            # [M]

        safe_gt = jnp.clip(matched_gt, 0, G - 1)
        mb = bx[safe_gt]                                    # [M, 4]
        target_loc = _encode_center_size(prior, pvar, mb)

        # smooth-l1 localization loss over positives
        d = lc - target_loc
        ad = jnp.abs(d)
        sl1 = jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5).sum(axis=1)
        loc_loss = jnp.where(is_pos, sl1, 0.0).sum()

        # per-prior softmax xent with matched labels (bg where unmatched)
        tgt_cls = jnp.where(is_pos, lbl[safe_gt].astype(jnp.int32), bg)
        logp = jax.nn.log_softmax(cf, axis=-1)
        xent = -jnp.take_along_axis(logp, tgt_cls[:, None], axis=1)[:, 0]

        # max-negative hard mining: top (ratio * n_pos) negatives by loss,
        # drawn only from priors below neg_overlap (the reference's ignore
        # band: overlap in [neg_overlap, overlap_threshold) trains neither
        # way)
        n_pos = is_pos.sum()
        n_neg = jnp.minimum((neg_ratio * n_pos).astype(jnp.int32),
                            M - n_pos)
        neg_candidate = (~is_pos) & (best_iou_per_prior < neg_overlap)
        neg_loss = jnp.where(neg_candidate, xent, -jnp.inf)
        order = jnp.argsort(-neg_loss)
        rank = jnp.zeros((M,), jnp.int32).at[order].set(jnp.arange(M))
        is_neg = neg_candidate & (rank < n_neg)

        conf_loss = jnp.where(is_pos | is_neg, xent, 0.0).sum()
        total = conf_w * conf_loss + loc_w * loc_loss
        if not normalize:
            return total
        return total / jnp.maximum(n_pos.astype(cf.dtype), 1.0)

    losses = jax.vmap(per_image)(gt_box, gt_label, gt_count, loc, conf)
    return {"Loss": losses[:, None].astype(conf.dtype)}
