"""Detection ops: SSD-style priors, box coding, IoU, NMS.

TPU-native re-design of the reference detection operator family
(/root/reference/paddle/fluid/operators/detection/): prior_box_op.h,
box_coder_op.h, iou_similarity_op.h, multiclass_nms_op.cc.

Everything is fixed-shape: NMS returns a [keep_top_k, 6] tensor padded with
-1 labels (the reference returns a LoD tensor of variable length; the padded
layout carries the same detections with an explicit validity convention),
and suppression runs as a lax.scan over the score-sorted candidates instead
of the reference's data-dependent while loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import ExecContext, register_op


@register_op("prior_box", grad="none")
def prior_box(ctx: ExecContext):
    """SSD prior boxes (reference prior_box_op.h): one box per
    (min_size, aspect_ratio) plus the sqrt(min*max) box, centered on each
    feature-map cell, normalized to the image."""
    feat = ctx.input("Input")    # [N, C, H, W]
    img = ctx.input("Image")     # [N, 3, IH, IW]
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    min_sizes = [float(s) for s in ctx.attr("min_sizes")]
    max_sizes = [float(s) for s in ctx.attr("max_sizes", []) or []]
    ars = [float(a) for a in ctx.attr("aspect_ratios", [1.0]) or [1.0]]
    flip = bool(ctx.attr("flip", False))
    clip = bool(ctx.attr("clip", False))
    variances = [float(v) for v in
                 ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    step_w = float(ctx.attr("step_w", 0.0)) or IW / W
    step_h = float(ctx.attr("step_h", 0.0)) or IH / H
    offset = float(ctx.attr("offset", 0.5))

    # ExpandAspectRatios: 1.0 first, then each ratio (+ flip), deduped
    ratios = [1.0]
    for ar in ars:
        if all(abs(ar - r) > 1e-6 for r in ratios):
            ratios.append(ar)
            if flip:
                ratios.append(1.0 / ar)

    whs = []  # (w, h) per prior, reference ordering
    for k, ms in enumerate(min_sizes):
        for ar in ratios:
            whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if abs(ar - 1.0) < 1e-6 and max_sizes:
                big = np.sqrt(ms * max_sizes[k])
                whs.append((big, big))
    P = len(whs)
    wh = jnp.asarray(np.array(whs, np.float32))          # [P, 2]

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)                      # [H, W]
    cxg = cxg[..., None]
    cyg = cyg[..., None]
    half_w = wh[None, None, :, 0] / 2
    half_h = wh[None, None, :, 1] / 2
    boxes = jnp.stack(
        [(cxg - half_w) / IW, (cyg - half_h) / IH,
         (cxg + half_w) / IW, (cyg + half_h) / IH], axis=-1)  # [H,W,P,4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), (H, W, P, 4))
    return {"Boxes": boxes, "Variances": var}


@register_op("box_coder", grad="none")
def box_coder(ctx: ExecContext):
    """Center-size box encode/decode (reference box_coder_op.h).
    PriorBox [M, 4], PriorBoxVar [M, 4]?, TargetBox encode:[N, 4] /
    decode:[N, M, 4]. code_type attr: encode_center_size|decode_center_size.
    """
    prior = ctx.input("PriorBox")
    pvar = ctx.input("PriorBoxVar")
    target = ctx.input("TargetBox")
    code_type = str(ctx.attr("code_type", "encode_center_size"))
    norm = bool(ctx.attr("box_normalized", True))

    eps = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + eps
    ph = prior[:, 3] - prior[:, 1] + eps
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if pvar is None:
        pvar = jnp.ones_like(prior)

    if code_type.startswith("encode"):
        tw = target[:, 2] - target[:, 0] + eps
        th = target[:, 3] - target[:, 1] + eps
        tcx = target[:, 0] + tw / 2
        tcy = target[:, 1] + th / 2
        # broadcast [N, 1] vs [1, M]
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :] / pvar[None, :, 0]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :] / pvar[None, :, 1]
        ow = jnp.log(tw[:, None] / pw[None, :]) / pvar[None, :, 2]
        oh = jnp.log(th[:, None] / ph[None, :]) / pvar[None, :, 3]
        return {"OutputBox": jnp.stack([ox, oy, ow, oh], axis=-1)}

    # decode: target [N, M, 4] offsets -> boxes
    ox = target[..., 0] * pvar[None, :, 0] * pw[None, :] + pcx[None, :]
    oy = target[..., 1] * pvar[None, :, 1] * ph[None, :] + pcy[None, :]
    ow = jnp.exp(target[..., 2] * pvar[None, :, 2]) * pw[None, :]
    oh = jnp.exp(target[..., 3] * pvar[None, :, 3]) * ph[None, :]
    return {"OutputBox": jnp.stack(
        [ox - ow / 2, oy - oh / 2, ox + ow / 2 - eps, oy + oh / 2 - eps],
        axis=-1)}


def _encode_center_size(prior, pvar, boxes, eps=0.0):
    """Shared center-size encode (the box_coder formula; ssd_loss target
    encoding must stay in lockstep with it). prior/pvar [M, 4],
    boxes [M, 4] matched per prior -> offsets [M, 4]."""
    pw = prior[:, 2] - prior[:, 0] + eps
    ph = prior[:, 3] - prior[:, 1] + eps
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    gw = boxes[:, 2] - boxes[:, 0] + eps
    gh = boxes[:, 3] - boxes[:, 1] + eps
    gcx = boxes[:, 0] + gw / 2
    gcy = boxes[:, 1] + gh / 2
    tx = (gcx - pcx) / pw / pvar[:, 0]
    ty = (gcy - pcy) / ph / pvar[:, 1]
    tw = jnp.log(jnp.maximum(gw / pw, 1e-8)) / pvar[:, 2]
    th = jnp.log(jnp.maximum(gh / ph, 1e-8)) / pvar[:, 3]
    return jnp.stack([tx, ty, tw, th], axis=1)


def _iou(a, b, eps=0.0):
    """Pairwise IoU: a [N, 4], b [M, 4] -> [N, M]. eps=1.0 applies the
    reference's +1 width/height convention for UNnormalized pixel boxes
    (bbox_util.h JaccardOverlap)."""
    x1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    y1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    x2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    y2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.clip(x2 - x1 + eps, 0) * jnp.clip(y2 - y1 + eps, 0)
    area_a = (jnp.clip(a[:, 2] - a[:, 0] + eps, 0)
              * jnp.clip(a[:, 3] - a[:, 1] + eps, 0))
    area_b = (jnp.clip(b[:, 2] - b[:, 0] + eps, 0)
              * jnp.clip(b[:, 3] - b[:, 1] + eps, 0))
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity", grad="none")
def iou_similarity(ctx: ExecContext):
    """reference iou_similarity_op.h: X [N, 4], Y [M, 4] -> [N, M]."""
    return {"Out": _iou(ctx.input("X"), ctx.input("Y"))}


def _nms_single(scores, base_iou, score_thr, nms_thr, top_k):
    """Greedy NMS over one class: scores [M], base_iou [M, M] (shared
    across classes — the boxes don't change per class) -> keep mask [M].

    Reference NMSFast semantics: the candidate POOL is the top nms_top_k by
    score (lower-ranked boxes are never considered), then greedy IoU
    suppression over that pool — which also bounds the sequential scan to
    top_k steps instead of M."""
    M = scores.shape[0]
    k = min(int(top_k), M) if top_k > 0 else M
    order = jnp.argsort(-scores)[:k]
    ss = scores[order]
    iou = base_iou[order][:, order]

    def step(kept, i):
        valid = ss[i] > score_thr
        sup = jnp.any(kept & (iou[i] > nms_thr))
        keep_i = valid & ~sup
        return kept.at[i].set(keep_i), None

    kept, _ = jax.lax.scan(step, jnp.zeros((k,), bool), jnp.arange(k))
    # scatter the pool's keep decisions back to original positions
    full = jnp.zeros((M,), bool)
    return full.at[order].set(kept)


@register_op("multiclass_nms", grad="none")
def multiclass_nms(ctx: ExecContext):
    """reference multiclass_nms_op.cc on fixed shapes.

    BBoxes [N, M, 4], Scores [N, C, M]. Per class: score threshold + greedy
    IoU NMS (nms_top_k); across classes: keep_top_k by score. Output
    [N, keep_top_k, 6] rows (label, score, x1, y1, x2, y2), label = -1 marks
    padding (the reference's empty-LoD convention)."""
    bboxes = ctx.input("BBoxes")
    scores = ctx.input("Scores")
    score_thr = float(ctx.attr("score_threshold", 0.0))
    nms_thr = float(ctx.attr("nms_threshold", 0.3))
    nms_top_k = int(ctx.attr("nms_top_k", 400))
    keep_top_k = int(ctx.attr("keep_top_k", 200))
    bg = int(ctx.attr("background_label", 0))
    normalized = bool(ctx.attr("normalized", True))
    N, C, M = scores.shape
    if keep_top_k < 0:
        keep_top_k = C * M

    def per_image(bx, sc):
        base_iou = _iou(bx, bx, eps=0.0 if normalized else 1.0)
        all_scores, all_labels, all_boxes = [], [], []
        for c in range(C):
            if c == bg:
                continue
            keep = _nms_single(sc[c], base_iou, score_thr, nms_thr,
                               nms_top_k)
            all_scores.append(jnp.where(keep, sc[c], -1.0))
            all_labels.append(jnp.full((M,), c, jnp.float32))
            all_boxes.append(bx)
        fs = jnp.concatenate(all_scores)
        fl = jnp.concatenate(all_labels)
        fb = jnp.concatenate(all_boxes)
        k = min(keep_top_k, fs.shape[0])
        top_s, top_i = jax.lax.top_k(fs, k)
        rows = jnp.concatenate(
            [jnp.where(top_s > score_thr, fl[top_i], -1.0)[:, None],
             top_s[:, None], fb[top_i]], axis=1)
        if k < keep_top_k:
            pad = jnp.full((keep_top_k - k, 6), -1.0, rows.dtype)
            rows = jnp.concatenate([rows, pad], axis=0)
        return rows

    return {"Out": jax.vmap(per_image)(bboxes, scores)}


@register_op("ssd_loss")
def ssd_loss(ctx: ExecContext):
    """SSD multibox loss (reference detection.py:1280 ssd_loss pipeline,
    collapsed into one fixed-shape op).

    Inputs: Loc [N, M, 4] predicted offsets, Conf [N, M, C] raw logits,
    GTBox [N, G, 4], GTLabel [N, G, 1] (0 padding rows marked by
    GTCount [N] valid counts). PriorBox [M, 4], PriorBoxVar [M, 4]?.

    Matching is per-prediction (each prior -> best gt when IoU >= threshold)
    plus the bipartite guarantee that every valid gt claims its best prior —
    the reference's two-phase match — followed by max-negative hard mining at
    neg_pos_ratio. Returns Loss [N, 1].
    """
    loc = ctx.input("Loc")
    conf = ctx.input("Conf")
    gt_box = ctx.input("GTBox")
    gt_label = ctx.input("GTLabel").reshape(gt_box.shape[0], -1)
    gt_count = ctx.input("GTCount")
    prior = ctx.input("PriorBox")
    pvar = ctx.input("PriorBoxVar")
    bg = int(ctx.attr("background_label", 0))
    overlap_thr = float(ctx.attr("overlap_threshold", 0.5))
    neg_overlap = float(ctx.attr("neg_overlap", 0.5))
    neg_ratio = float(ctx.attr("neg_pos_ratio", 3.0))
    loc_w = float(ctx.attr("loc_loss_weight", 1.0))
    conf_w = float(ctx.attr("conf_loss_weight", 1.0))
    normalize = bool(ctx.attr("normalize", True))

    N, M, C = conf.shape
    G = gt_box.shape[1]
    if gt_count is None:
        gt_count = jnp.full((N,), G, jnp.int32)
    else:
        gt_count = gt_count.reshape(-1).astype(jnp.int32)
    if pvar is None:
        pvar = jnp.ones_like(prior)

    def per_image(bx, lbl, cnt, lc, cf):
        valid_gt = jnp.arange(G) < cnt                      # [G]
        iou = _iou(bx, prior)                               # [G, M]
        iou = jnp.where(valid_gt[:, None], iou, -1.0)
        # phase 1 (bipartite seed): each valid gt claims its best prior
        best_prior_per_gt = jnp.argmax(iou, axis=1)         # [G]
        # phase 2 (per-prediction): each prior takes its best gt over thr
        best_gt_per_prior = jnp.argmax(iou, axis=0)         # [M]
        best_iou_per_prior = jnp.max(iou, axis=0)
        matched_gt = jnp.where(best_iou_per_prior >= overlap_thr,
                               best_gt_per_prior, -1)
        # force the bipartite seeds; invalid gt rows scatter out of range
        # (mode="drop") so they can't race a valid row on the same prior
        seed_idx = jnp.where(valid_gt, best_prior_per_gt, M)
        matched_gt = matched_gt.at[seed_idx].set(jnp.arange(G), mode="drop")
        is_pos = matched_gt >= 0                            # [M]

        safe_gt = jnp.clip(matched_gt, 0, G - 1)
        mb = bx[safe_gt]                                    # [M, 4]
        target_loc = _encode_center_size(prior, pvar, mb)

        # smooth-l1 localization loss over positives
        d = lc - target_loc
        ad = jnp.abs(d)
        sl1 = jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5).sum(axis=1)
        loc_loss = jnp.where(is_pos, sl1, 0.0).sum()

        # per-prior softmax xent with matched labels (bg where unmatched)
        tgt_cls = jnp.where(is_pos, lbl[safe_gt].astype(jnp.int32), bg)
        logp = jax.nn.log_softmax(cf, axis=-1)
        xent = -jnp.take_along_axis(logp, tgt_cls[:, None], axis=1)[:, 0]

        # max-negative hard mining: top (ratio * n_pos) negatives by loss,
        # drawn only from priors below neg_overlap (the reference's ignore
        # band: overlap in [neg_overlap, overlap_threshold) trains neither
        # way)
        n_pos = is_pos.sum()
        n_neg = jnp.minimum((neg_ratio * n_pos).astype(jnp.int32),
                            M - n_pos)
        neg_candidate = (~is_pos) & (best_iou_per_prior < neg_overlap)
        neg_loss = jnp.where(neg_candidate, xent, -jnp.inf)
        order = jnp.argsort(-neg_loss)
        rank = jnp.zeros((M,), jnp.int32).at[order].set(jnp.arange(M))
        is_neg = neg_candidate & (rank < n_neg)

        conf_loss = jnp.where(is_pos | is_neg, xent, 0.0).sum()
        total = conf_w * conf_loss + loc_w * loc_loss
        if not normalize:
            return total
        return total / jnp.maximum(n_pos.astype(cf.dtype), 1.0)

    losses = jax.vmap(per_image)(gt_box, gt_label, gt_count, loc, conf)
    return {"Loss": losses[:, None].astype(conf.dtype)}


@register_op("roi_align")
def roi_align(ctx: ExecContext):
    """RoI Align (reference detection/roi_align_op.*): average of
    `sampling_ratio^2` bilinear samples per output bin. Fixed-shape: ROIs
    [R, 4] in image coords plus RoisBatchId [R] int (the padded stand-in for
    the reference's LoD row mapping). Differentiable (pure gathers +
    weighted sums -> derived vjp)."""
    x = ctx.input("X")                    # [N, C, H, W]
    rois = ctx.input("ROIs")              # [R, 4] (x1, y1, x2, y2)
    batch_ids = (ctx.input("RoisBatchId").astype(jnp.int32)
                 if ctx.has_input("RoisBatchId")
                 else jnp.zeros((rois.shape[0],), jnp.int32))
    scale = float(ctx.attr("spatial_scale", 1.0))
    ph = int(ctx.attr("pooled_height", 1))
    pw = int(ctx.attr("pooled_width", 1))
    sr = int(ctx.attr("sampling_ratio", -1))
    # DEPARTURE from the reference's adaptive ceil(roi_h/ph) when
    # sampling_ratio <= 0: a data-dependent sample count cannot be a static
    # XLA shape, so the static default is 2 samples per bin axis. Pass an
    # explicit sampling_ratio for reference-exact pooling of large rois.
    sr = sr if sr > 0 else 2

    N, C, H, W = x.shape
    r = rois.astype(jnp.float32) * scale
    x1, y1, x2, y2 = r[:, 0], r[:, 1], r[:, 2], r[:, 3]
    roi_w = jnp.maximum(x2 - x1, 1.0)
    roi_h = jnp.maximum(y2 - y1, 1.0)
    bin_w = roi_w / pw
    bin_h = roi_h / ph

    # sample grid: [R, ph, sr] y coords, [R, pw, sr] x coords
    iy = (jnp.arange(sr, dtype=jnp.float32) + 0.5) / sr
    py = jnp.arange(ph, dtype=jnp.float32)
    ys = y1[:, None, None] + (py[None, :, None] + iy[None, None, :]) * bin_h[:, None, None]
    px = jnp.arange(pw, dtype=jnp.float32)
    xs = x1[:, None, None] + (px[None, :, None] + iy[None, None, :]) * bin_w[:, None, None]

    def bilinear(img, ys, xs):
        # img [C, H, W]; ys [ph, sr]; xs [pw, sr] -> [C, ph, sr, pw, sr].
        # Samples outside [-1, H]/[-1, W] contribute ZERO (reference
        # roi_align_op.h:197-202), not a clamped border value.
        val_y = (ys >= -1.0) & (ys <= H)
        val_x = (xs >= -1.0) & (xs <= W)
        ysc = jnp.clip(ys, 0.0, H - 1)
        xsc = jnp.clip(xs, 0.0, W - 1)
        y0 = jnp.floor(ysc)
        x0 = jnp.floor(xsc)
        y1i = jnp.clip(y0 + 1, 0, H - 1)
        x1i = jnp.clip(x0 + 1, 0, W - 1)
        wy = ysc - y0
        wx = xsc - x0
        yi0, yi1 = y0.astype(jnp.int32), y1i.astype(jnp.int32)
        xi0, xi1 = x0.astype(jnp.int32), x1i.astype(jnp.int32)
        g = lambda yy, xx: img[:, yy][:, :, :, xx]  # [C, ph, sr, pw, sr]
        v = (g(yi0, xi0) * ((1 - wy)[None, :, :, None, None] * (1 - wx)[None, None, None, :, :])
             + g(yi1, xi0) * (wy[None, :, :, None, None] * (1 - wx)[None, None, None, :, :])
             + g(yi0, xi1) * ((1 - wy)[None, :, :, None, None] * wx[None, None, None, :, :])
             + g(yi1, xi1) * (wy[None, :, :, None, None] * wx[None, None, None, :, :]))
        valid = (val_y[None, :, :, None, None] & val_x[None, None, None, :, :])
        v = jnp.where(valid, v, 0.0)
        return v.mean(axis=(2, 4))  # -> [C, ph, pw]

    imgs = x[batch_ids]  # [R, C, H, W]
    out = jax.vmap(bilinear)(imgs, ys, xs)
    return {"Out": out.astype(x.dtype)}


@register_op("roi_pool")
def roi_pool(ctx: ExecContext):
    """RoI max pooling (reference detection/roi_pool_op.*): adaptive integer
    bins, max within each. Implemented as a membership-mask max — static
    shapes for XLA (the reference's argmax bookkeeping becomes the derived
    vjp through jnp.max)."""
    x = ctx.input("X")                    # [N, C, H, W]
    rois = ctx.input("ROIs")
    batch_ids = (ctx.input("RoisBatchId").astype(jnp.int32)
                 if ctx.has_input("RoisBatchId")
                 else jnp.zeros((rois.shape[0],), jnp.int32))
    scale = float(ctx.attr("spatial_scale", 1.0))
    ph = int(ctx.attr("pooled_height", 1))
    pw = int(ctx.attr("pooled_width", 1))

    N, C, H, W = x.shape
    r = jnp.round(rois.astype(jnp.float32) * scale)
    x1, y1 = r[:, 0], r[:, 1]
    x2, y2 = r[:, 2], r[:, 3]
    roi_w = jnp.maximum(x2 - x1 + 1, 1.0)
    roi_h = jnp.maximum(y2 - y1 + 1, 1.0)

    hs = jnp.arange(H, dtype=jnp.float32)
    ws = jnp.arange(W, dtype=jnp.float32)
    pyi = jnp.arange(ph, dtype=jnp.float32)
    pxi = jnp.arange(pw, dtype=jnp.float32)
    # bin bounds per roi/bin: [R, ph] / [R, pw]
    hstart = jnp.floor(pyi[None, :] * roi_h[:, None] / ph) + y1[:, None]
    hend = jnp.ceil((pyi[None, :] + 1) * roi_h[:, None] / ph) + y1[:, None]
    wstart = jnp.floor(pxi[None, :] * roi_w[:, None] / pw) + x1[:, None]
    wend = jnp.ceil((pxi[None, :] + 1) * roi_w[:, None] / pw) + x1[:, None]
    in_h = ((hs[None, None, :] >= hstart[:, :, None])
            & (hs[None, None, :] < hend[:, :, None]))     # [R, ph, H]
    in_w = ((ws[None, None, :] >= wstart[:, :, None])
            & (ws[None, None, :] < wend[:, :, None]))     # [R, pw, W]
    imgs = x[batch_ids].astype(jnp.float32)               # [R, C, H, W]
    neg = jnp.float32(-1e30)
    # two-stage masked max keeps peak memory at O(R*C*pw*H*W') per stage
    # instead of a monolithic [R, C, ph, pw, H, W] broadcast (infeasible at
    # detection scale): reduce W under in_w, then H under in_h
    v_w = jnp.where(in_w[:, None, :, None, :],             # [R,1,pw,1,W]
                    imgs[:, :, None, :, :], neg)           # [R,C,pw,H,W]
    v_w = v_w.max(axis=4)                                  # [R,C,pw,H]
    v = jnp.where(in_h[:, None, None, :, :],               # [R,1,1,ph,H]
                  v_w[:, :, :, None, :], neg)              # [R,C,pw,ph,H]
    out = v.max(axis=4).transpose(0, 1, 3, 2)              # [R,C,ph,pw]
    out = jnp.where(out <= neg / 2, 0.0, out)  # empty bin -> 0 (reference)
    return {"Out": out.astype(x.dtype)}


@register_op("yolo_box", grad="none")
def yolo_box(ctx: ExecContext):
    """YOLOv3 box decoding (reference detection/yolo_box_op.*): X
    [N, an*(5+cls), H, W] + ImgSize [N, 2] -> Boxes [N, an*H*W, 4] in image
    coords, Scores [N, an*H*W, cls] = sigmoid(conf)*sigmoid(cls), zeroed
    below conf_thresh."""
    x = ctx.input("X")
    img_size = ctx.input("ImgSize").astype(jnp.float32)  # [N, 2] (h, w)
    anchors = [int(a) for a in ctx.attr("anchors")]
    class_num = int(ctx.attr("class_num"))
    conf_thresh = float(ctx.attr("conf_thresh", 0.01))
    downsample = int(ctx.attr("downsample_ratio", 32))
    an = len(anchors) // 2
    N, _, H, W = x.shape
    x = x.reshape(N, an, 5 + class_num, H, W).astype(jnp.float32)

    grid_x = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    in_h, in_w = H * downsample, W * downsample

    cx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / W
    cy = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / H
    bw = jnp.exp(x[:, :, 2]) * aw / in_w
    bh = jnp.exp(x[:, :, 3]) * ah / in_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    probs = jnp.where(conf[:, :, None] < conf_thresh,
                      jnp.zeros_like(probs), probs)

    ih = img_size[:, 0][:, None, None, None]
    iw = img_size[:, 1][:, None, None, None]
    x1 = (cx - bw / 2) * iw
    y1 = (cy - bh / 2) * ih
    x2 = (cx + bw / 2) * iw
    y2 = (cy + bh / 2) * ih
    # clip to image (reference clip_bbox)
    x1 = jnp.clip(x1, 0, iw - 1)
    y1 = jnp.clip(y1, 0, ih - 1)
    x2 = jnp.clip(x2, 0, iw - 1)
    y2 = jnp.clip(y2, 0, ih - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, an * H * W, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(N, an * H * W, class_num)
    return {"Boxes": boxes, "Scores": scores}


@register_op("anchor_generator", grad="none")
def anchor_generator(ctx: ExecContext):
    """RPN anchors (reference detection/anchor_generator_op.*): per feature
    cell, one anchor per (size, ratio): Anchors [H, W, A, 4] + Variances."""
    feat = ctx.input("Input")  # [N, C, H, W]
    sizes = [float(s) for s in ctx.attr("anchor_sizes")]
    ratios = [float(r) for r in ctx.attr("aspect_ratios", [1.0]) or [1.0]]
    variances = [float(v) for v in ctx.attr("variances",
                                            [0.1, 0.1, 0.2, 0.2])]
    stride = [float(s) for s in ctx.attr("stride")]
    offset = float(ctx.attr("offset", 0.5))
    H, W = feat.shape[2], feat.shape[3]

    # reference anchor_generator_op.h:55-81: rounded ratio-base sizes,
    # centers at idx*stride + offset*(stride-1), inclusive-pixel corners
    # spanning ±(w-1)/2 so that x2-x1+1 == anchor_width
    base = []
    area = stride[0] * stride[1]
    for r in ratios:
        base_w = round(np.sqrt(area / r))
        base_h = round(base_w * r)
        for s in sizes:
            w = s / stride[0] * base_w
            h = s / stride[1] * base_h
            base.append((-0.5 * (w - 1), -0.5 * (h - 1),
                         0.5 * (w - 1), 0.5 * (h - 1)))
    base = jnp.asarray(base, jnp.float32)               # [A, 4]
    cx = (jnp.arange(W, dtype=jnp.float32) * stride[0]
          + offset * (stride[0] - 1))
    cy = (jnp.arange(H, dtype=jnp.float32) * stride[1]
          + offset * (stride[1] - 1))
    centers = jnp.stack(
        [*jnp.meshgrid(cx, cy, indexing="xy")], axis=-1)  # [H, W, 2]
    ctr = jnp.concatenate([centers, centers], axis=-1)    # [H, W, 4]
    anchors = ctr[:, :, None, :] + base[None, None, :, :]
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           anchors.shape)
    return {"Anchors": anchors, "Variances": var}


@register_op("bipartite_match", grad="none")
def bipartite_match(ctx: ExecContext):
    """Greedy bipartite matching (reference detection/bipartite_match_op.cc,
    match_type='bipartite'): repeatedly take the globally largest entry of
    DistMat [B, R, C] (rows = gt, cols = priors), pair that row/col, mask
    both out. Outputs per column: matched row index (-1 = unmatched) and its
    distance. The reference's LoD batch becomes an explicit batch dim; the
    data-dependent loop becomes a fixed-length lax.scan over min(R, C)."""
    dist = ctx.input("DistMat").astype(jnp.float32)
    if dist.ndim == 2:
        dist = dist[None]
    B, R, C = dist.shape

    def one(mat):
        def step(carry, _):
            m, row_used, col_used, out_idx, out_d = carry
            masked = jnp.where(row_used[:, None] | col_used[None, :],
                               -jnp.inf, m)
            flat = jnp.argmax(masked)
            r, c = flat // C, flat % C
            # reference kEPS guard: a zero/near-zero distance is NOT a match
            # (bipartite_match_op.cc:115) — those columns stay unmatched
            valid = masked[r, c] > 1e-6
            out_idx = jnp.where(valid, out_idx.at[c].set(r), out_idx)
            out_d = jnp.where(valid, out_d.at[c].set(m[r, c]), out_d)
            row_used = jnp.where(valid, row_used.at[r].set(True), row_used)
            col_used = jnp.where(valid, col_used.at[c].set(True), col_used)
            return (m, row_used, col_used, out_idx, out_d), None

        init = (mat, jnp.zeros(R, bool), jnp.zeros(C, bool),
                jnp.full((C,), -1, jnp.int32), jnp.zeros((C,), jnp.float32))
        (_, _, _, idx, d), _ = jax.lax.scan(step, init, None,
                                            length=min(R, C))
        return idx, d

    idx, d = jax.vmap(one)(dist)
    return {"ColToRowMatchIndices": idx, "ColToRowMatchDist": d}


@register_op("density_prior_box", grad="none")
def density_prior_box(ctx: ExecContext):
    """Density prior boxes (reference detection/density_prior_box_op.*):
    for each fixed_size/density pair, a density x density grid of shifted
    boxes per cell at each fixed_ratio."""
    feat = ctx.input("Input")
    img = ctx.input("Image")
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    fixed_sizes = [float(s) for s in ctx.attr("fixed_sizes")]
    fixed_ratios = [float(r) for r in ctx.attr("fixed_ratios", [1.0]) or [1.0]]
    densities = [int(d) for d in ctx.attr("densities")]
    variances = [float(v) for v in ctx.attr("variances",
                                            [0.1, 0.1, 0.2, 0.2])]
    step_w = float(ctx.attr("step_w", 0.0)) or IW / W
    step_h = float(ctx.attr("step_h", 0.0)) or IH / H
    offset = float(ctx.attr("offset", 0.5))
    clip = bool(ctx.attr("clip", False))

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    boxes_per_cell = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            shift = size / density
            for di in range(density):
                for dj in range(density):
                    sx = -size / 2.0 + shift / 2.0 + dj * shift
                    sy = -size / 2.0 + shift / 2.0 + di * shift
                    boxes_per_cell.append((sx, sy, bw, bh))
    out = []
    for (sx, sy, bw, bh) in boxes_per_cell:
        bx = jnp.broadcast_to((cx + sx)[None, :], (H, W))
        by = jnp.broadcast_to((cy + sy)[:, None], (H, W))
        x1 = (bx - bw / 2) / IW
        y1 = (by - bh / 2) / IH
        x2 = (bx + bw / 2) / IW
        y2 = (by + bh / 2) / IH
        out.append(jnp.stack([x1, y1, x2, y2], axis=-1))
    boxes = jnp.stack(out, axis=2)  # [H, W, A, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), boxes.shape)
    return {"Boxes": boxes, "Variances": var}


@register_op("generate_proposals", grad="none")
def generate_proposals(ctx: ExecContext):
    """RPN proposal generation (reference detection/generate_proposals_op.cc):
    decode anchors with deltas, clip to image, filter tiny boxes, take
    pre_nms_topN by score, greedy-NMS, emit post_nms_topN [*, 4] proposals
    (fixed-shape: invalid slots carry zero boxes/scores)."""
    scores = ctx.input("Scores")     # [N, A, H, W]
    deltas = ctx.input("BboxDeltas")  # [N, A*4, H, W]
    im_info = ctx.input("ImInfo").astype(jnp.float32)  # [N, 3] (h, w, scale)
    anchors = ctx.input("Anchors").reshape(-1, 4).astype(jnp.float32)
    variances = ctx.input("Variances").reshape(-1, 4).astype(jnp.float32)
    pre_n = int(ctx.attr("pre_nms_topN", 6000))
    post_n = int(ctx.attr("post_nms_topN", 1000))
    nms_thresh = float(ctx.attr("nms_thresh", 0.5))
    # reference FilterBoxes floors min_size at 1 pixel
    min_size = max(float(ctx.attr("min_size", 0.1)), 1.0)
    bbox_clip = float(np.log(1000.0 / 16.0))  # reference kBBoxClipDefault

    N, A, H, W = scores.shape
    K = A * H * W
    sc = scores.transpose(0, 2, 3, 1).reshape(N, K).astype(jnp.float32)
    dl = deltas.reshape(N, A, 4, H, W).transpose(0, 3, 4, 1, 2).reshape(N, K, 4)

    # Anchors [H, W, A, 4] flattened row-major matches the [H, W, A] score
    # layout produced by the transpose above
    aw = anchors[:, 2] - anchors[:, 0] + 1
    ah = anchors[:, 3] - anchors[:, 1] + 1
    acx = anchors[:, 0] + aw / 2
    acy = anchors[:, 1] + ah / 2
    var = variances

    def one(sc_i, dl_i, info):
        cx = var[:, 0] * dl_i[:, 0] * aw + acx
        cy = var[:, 1] * dl_i[:, 1] * ah + acy
        w = jnp.exp(jnp.minimum(var[:, 2] * dl_i[:, 2], bbox_clip)) * aw
        h = jnp.exp(jnp.minimum(var[:, 3] * dl_i[:, 3], bbox_clip)) * ah
        x1 = jnp.clip(cx - w / 2, 0, info[1] - 1)
        y1 = jnp.clip(cy - h / 2, 0, info[0] - 1)
        x2 = jnp.clip(cx + w / 2, 0, info[1] - 1)
        y2 = jnp.clip(cy + h / 2, 0, info[0] - 1)
        ctr_x = x1 + (x2 - x1 + 1) / 2
        ctr_y = y1 + (y2 - y1 + 1) / 2
        keep = ((x2 - x1 + 1 >= min_size * info[2])
                & (y2 - y1 + 1 >= min_size * info[2])
                # reference FilterBoxes: box CENTER must lie in the image
                & (ctr_x < info[1]) & (ctr_y < info[0]))
        s = jnp.where(keep, sc_i, -jnp.inf)
        k = min(pre_n, K)
        top_s, top_i = jax.lax.top_k(s, k)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)[top_i]

        def iou(b, bs):
            xx1 = jnp.maximum(b[0], bs[:, 0])
            yy1 = jnp.maximum(b[1], bs[:, 1])
            xx2 = jnp.minimum(b[2], bs[:, 2])
            yy2 = jnp.minimum(b[3], bs[:, 3])
            inter = (jnp.maximum(xx2 - xx1 + 1, 0)
                     * jnp.maximum(yy2 - yy1 + 1, 0))
            a1 = (b[2] - b[0] + 1) * (b[3] - b[1] + 1)
            a2 = (bs[:, 2] - bs[:, 0] + 1) * (bs[:, 3] - bs[:, 1] + 1)
            return inter / jnp.maximum(a1 + a2 - inter, 1e-10)

        def nms_step(carry, i):
            alive, n_kept, out_b, out_s = carry
            ok = alive[i] & (top_s[i] > -jnp.inf) & (n_kept < post_n)
            out_b = jnp.where(ok, out_b.at[n_kept].set(boxes[i]), out_b)
            out_s = jnp.where(ok, out_s.at[n_kept].set(top_s[i]), out_s)
            sup = iou(boxes[i], boxes) > nms_thresh
            alive = jnp.where(ok, alive & ~sup, alive)
            n_kept = n_kept + ok.astype(jnp.int32)
            return (alive, n_kept, out_b, out_s), None

        init = (jnp.ones(k, bool), jnp.int32(0),
                jnp.zeros((post_n, 4), jnp.float32),
                jnp.zeros((post_n,), jnp.float32))
        (_, n_kept, out_b, out_s), _ = jax.lax.scan(
            nms_step, init, jnp.arange(k))
        return out_b, out_s, n_kept

    rois, probs, counts = jax.vmap(one)(sc, dl, im_info)
    return {"RpnRois": rois, "RpnRoiProbs": probs[..., None],
            "RpnRoisNum": counts}


def _sce(x, t):
    """SigmoidCrossEntropy exactly as yolov3_loss_op.h:129."""
    return jnp.maximum(x, 0.0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))


@register_op("yolov3_loss")
def yolov3_loss(ctx: ExecContext):
    """YOLOv3 training loss (reference detection/yolov3_loss_op.h, CPU
    kernel reproduced as one vectorized jnp computation; grads derive via
    vjp and match the kernel's analytic sce/l1 gradients).

    X [N, mask*(5+cls), H, W]; GTBox [N, B, 4] (cx, cy, w, h normalized to
    the input image); GTLabel [N, B] int; optional GTScore [N, B] (mixup).
    Outputs Loss [N], ObjectnessMask [N, mask, H, W], GTMatchMask [N, B]."""
    x = ctx.input("X").astype(jnp.float32)
    gt_box = ctx.input("GTBox").astype(jnp.float32)
    gt_label = ctx.input("GTLabel").astype(jnp.int32)
    if gt_label.ndim == 3:
        gt_label = gt_label.reshape(gt_label.shape[:2])
    anchors = [int(a) for a in ctx.attr("anchors")]
    mask = [int(m) for m in ctx.attr("anchor_mask")]
    class_num = int(ctx.attr("class_num"))
    ignore_thresh = float(ctx.attr("ignore_thresh", 0.7))
    downsample = int(ctx.attr("downsample_ratio", 32))
    use_smooth = bool(ctx.attr("use_label_smooth", True))
    N, _, H, W = x.shape
    an_num = len(anchors) // 2
    mask_num = len(mask)
    B = gt_box.shape[1]
    input_size = downsample * H
    if ctx.has_input("GTScore"):
        gt_score = ctx.input("GTScore").astype(jnp.float32)
        if gt_score.ndim == 3:
            gt_score = gt_score.reshape(gt_score.shape[:2])
    else:
        gt_score = jnp.ones((N, B), jnp.float32)

    label_pos, label_neg = 1.0, 0.0
    if use_smooth:
        delta = min(1.0 / class_num, 1.0 / 40)
        label_pos, label_neg = 1.0 - delta, delta

    xr = x.reshape(N, mask_num, 5 + class_num, H, W)
    valid = (gt_box[:, :, 2] > 1e-6) & (gt_box[:, :, 3] > 1e-6)  # [N, B]

    # --- ignore pass: best IoU of every prediction against every gt ------
    grid_x = jnp.arange(W, dtype=jnp.float32)[None, None, :]
    grid_y = jnp.arange(H, dtype=jnp.float32)[None, :, None]
    aw = jnp.asarray([anchors[2 * m] for m in mask],
                     jnp.float32)[:, None, None]
    ah = jnp.asarray([anchors[2 * m + 1] for m in mask],
                     jnp.float32)[:, None, None]
    px = (jax.nn.sigmoid(xr[:, :, 0]) + grid_x) / W       # [N, mask, H, W]
    py = (jax.nn.sigmoid(xr[:, :, 1]) + grid_y) / H
    pw = jnp.exp(xr[:, :, 2]) * aw[None] / input_size
    ph = jnp.exp(xr[:, :, 3]) * ah[None] / input_size

    def iou(cx1, cy1, w1, h1, cx2, cy2, w2, h2):
        ow = jnp.minimum(cx1 + w1 / 2, cx2 + w2 / 2) - \
            jnp.maximum(cx1 - w1 / 2, cx2 - w2 / 2)
        oh = jnp.minimum(cy1 + h1 / 2, cy2 + h2 / 2) - \
            jnp.maximum(cy1 - h1 / 2, cy2 - h2 / 2)
        inter = jnp.where((ow < 0) | (oh < 0), 0.0, ow * oh)
        return inter / (w1 * h1 + w2 * h2 - inter + 1e-10)

    g = gt_box[:, :, None, None, None, :]                 # [N, B, 1,1,1, 4]
    ious = iou(px[:, None], py[:, None], pw[:, None], ph[:, None],
               g[..., 0], g[..., 1], g[..., 2], g[..., 3])  # [N,B,mask,H,W]
    ious = jnp.where(valid[:, :, None, None, None], ious, 0.0)
    best_iou = jax.lax.stop_gradient(ious.max(axis=1))    # [N, mask, H, W]
    obj_mask = jnp.where(best_iou > ignore_thresh, -1.0, 0.0)

    # --- positive pass: per gt, best anchor over the FULL anchor list ----
    all_aw = jnp.asarray(anchors[0::2], jnp.float32) / input_size
    all_ah = jnp.asarray(anchors[1::2], jnp.float32) / input_size
    gw = gt_box[:, :, 2][:, :, None]
    gh = gt_box[:, :, 3][:, :, None]
    an_iou = iou(jnp.zeros_like(gw), jnp.zeros_like(gw), gw, gh,
                 0.0, 0.0, all_aw[None, None], all_ah[None, None])
    best_n = jnp.argmax(an_iou, axis=2).astype(jnp.int32)  # [N, B]
    mask_lookup = -jnp.ones((an_num,), jnp.int32)
    for mi, m in enumerate(mask):
        mask_lookup = mask_lookup.at[m].set(mi)
    mask_idx = mask_lookup[best_n]                         # [N, B]
    gt_match = jnp.where(valid, mask_idx, -1)

    gi = jnp.clip((gt_box[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gt_box[:, :, 1] * H).astype(jnp.int32), 0, H - 1)
    pos = valid & (mask_idx >= 0)
    m_safe = jnp.maximum(mask_idx, 0)
    bidx = jnp.arange(N)[:, None]
    # gather the responsible cell's raw predictions: [N, B, 5+cls]
    cell = xr[bidx, m_safe, :, gj, gi]
    tx = gt_box[:, :, 0] * W - gi.astype(jnp.float32)
    ty = gt_box[:, :, 1] * H - gj.astype(jnp.float32)
    aw_best = jnp.take(jnp.asarray(anchors[0::2], jnp.float32), best_n)
    ah_best = jnp.take(jnp.asarray(anchors[1::2], jnp.float32), best_n)
    tw = jnp.log(jnp.maximum(gt_box[:, :, 2] * input_size, 1e-9) / aw_best)
    th = jnp.log(jnp.maximum(gt_box[:, :, 3] * input_size, 1e-9) / ah_best)
    scale = (2.0 - gt_box[:, :, 2] * gt_box[:, :, 3]) * gt_score
    loc = (_sce(cell[:, :, 0], tx) + _sce(cell[:, :, 1], ty)
           + jnp.abs(cell[:, :, 2] - tw) + jnp.abs(cell[:, :, 3] - th))
    loc_loss = jnp.where(pos, loc * scale, 0.0).sum(axis=1)   # [N]

    cls_t = jnp.where(
        jax.nn.one_hot(gt_label, class_num) > 0.5, label_pos, label_neg)
    cls = _sce(cell[:, :, 5:], cls_t).sum(axis=2)
    cls_loss = jnp.where(pos, cls * gt_score, 0.0).sum(axis=1)

    # positive cells override the ignore mark with their (mixup) score;
    # later gts win on collision, like the reference's sequential writes
    def write_obj(om, t):
        val = jnp.where(pos[:, t], gt_score[:, t], om[bidx[:, 0], m_safe[:, t],
                                                      gj[:, t], gi[:, t]])
        return om.at[bidx[:, 0], m_safe[:, t], gj[:, t], gi[:, t]].set(val), None

    for t in range(B):
        obj_mask, _ = write_obj(obj_mask, t)

    obj_logit = xr[:, :, 4]
    obj_pos = jnp.where(obj_mask > 1e-5,
                        _sce(obj_logit, 1.0) * obj_mask, 0.0)
    obj_neg = jnp.where((obj_mask <= 1e-5) & (obj_mask > -0.5),
                        _sce(obj_logit, 0.0), 0.0)
    obj_loss = (obj_pos + obj_neg).sum(axis=(1, 2, 3))

    loss = loc_loss + cls_loss + obj_loss
    return {"Loss": loss.astype(ctx.input("X").dtype),
            "ObjectnessMask": jax.lax.stop_gradient(obj_mask),
            "GTMatchMask": gt_match}


@register_op("psroi_pool")
def psroi_pool(ctx: ExecContext):
    """Position-sensitive RoI pooling (reference psroi_pool_op.h): input
    channel c*ph*pw + i*pw + j feeds output channel c's bin (i, j); average
    over the bin's spatial extent. X [N, O*ph*pw, H, W], ROIs [R, 4]
    (x1, y1, x2, y2) + RoisBatch [R] -> Out [R, O, ph, pw]."""
    x = ctx.input("X").astype(jnp.float32)
    rois = ctx.input("ROIs").astype(jnp.float32)
    out_ch = int(ctx.attr("output_channels"))
    ph = int(ctx.attr("pooled_height"))
    pw = int(ctx.attr("pooled_width"))
    scale = float(ctx.attr("spatial_scale", 1.0))
    N, C, H, W = x.shape
    if ctx.has_input("RoisBatch"):
        roi_batch = ctx.input("RoisBatch").reshape(-1).astype(jnp.int32)
    else:
        roi_batch = jnp.zeros((rois.shape[0],), jnp.int32)

    def pool_one(roi, b):
        # reference: round then offset, bins at least 0.1 wide
        x1 = jnp.round(roi[0]) * scale
        y1 = jnp.round(roi[1]) * scale
        x2 = (jnp.round(roi[2]) + 1.0) * scale
        y2 = (jnp.round(roi[3]) + 1.0) * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw, bh = rw / pw, rh / ph
        img = x[b]                                       # [C, H, W]
        ys = jnp.arange(H, dtype=jnp.float32)[None, :]   # vs bin starts
        xs = jnp.arange(W, dtype=jnp.float32)[None, :]
        i = jnp.arange(ph, dtype=jnp.float32)[:, None]
        j = jnp.arange(pw, dtype=jnp.float32)[:, None]
        hstart = jnp.floor(y1 + i * bh)
        hend = jnp.ceil(y1 + (i + 1) * bh)
        wstart = jnp.floor(x1 + j * bw)
        wend = jnp.ceil(x1 + (j + 1) * bw)
        in_h = (ys >= jnp.clip(hstart, 0, H)) & \
            (ys < jnp.clip(hend, 0, H))                  # [ph, H]
        in_w = (xs >= jnp.clip(wstart, 0, W)) & \
            (xs < jnp.clip(wend, 0, W))                  # [pw, W]
        bin_mask = in_h[:, None, :, None] & in_w[None, :, None, :]
        # channels: out channel o's bin (i,j) reads input o*ph*pw + i*pw + j
        imgr = img.reshape(out_ch, ph, pw, H, W)
        sums = jnp.einsum("oijhw,ijhw->oij", imgr,
                          bin_mask.astype(jnp.float32))
        counts = bin_mask.sum(axis=(2, 3)).astype(jnp.float32)
        return jnp.where(counts[None] > 0, sums / jnp.maximum(counts, 1.0),
                         0.0)

    out = jax.vmap(pool_one)(rois, roi_batch)
    return {"Out": out.astype(ctx.input("X").dtype)}
