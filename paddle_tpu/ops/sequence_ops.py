"""Sequence ops under the LoD->padding design, plus beam search.

TPU-native re-design of the reference's sequence operator family
(/root/reference/paddle/fluid/operators/sequence_ops/, 47 files) and beam
search (operators/beam_search_op.cc, beam_search_decode_op.cc).

The reference represents ragged batches as LoD tensors: one flat value tensor
plus offset tables, and every sequence op walks the offsets. On TPU ragged
shapes defeat XLA, so the whole family is re-based on the framework-wide
padding contract (framework.py): a batch is [B, T, ...] plus an explicit
`length` int tensor [B]; masks replace offset walks. Each op below names the
reference op whose *semantics on the valid region* it reproduces.

Beam search keeps the reference's per-step op contract — `beam_search` inside
a While block selecting beam_size continuations, `beam_search_decode`
backtracking parent pointers — but on fixed [batch*beam, ...] arrays (a beam
is a static axis; finished beams are frozen on end_id rather than shrinking
the LoD, which is what makes the loop jittable as one lax.while_loop).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import ExecContext, register_op

from ..core.types import np_feed_dtype

# the runtime's index dtype: int32 under x64-off jax (an astype to
# int64 would warn-and-truncate on every trace), int64 when enabled
_INDEX_DTYPE = np_feed_dtype("int64")

_NEG_INF = -1e9


def _lengths(ctx, time_extent, batch):
    ln = ctx.input("Length")
    if ln is None:
        return jnp.full((batch,), time_extent, dtype=jnp.int32)
    return ln.reshape(-1).astype(jnp.int32)


def _time_mask(lengths, maxlen, dtype=jnp.float32):
    t = jnp.arange(maxlen, dtype=jnp.int32)
    return (t[None, :] < lengths[:, None]).astype(dtype)


@register_op("sequence_mask", grad="none")
def sequence_mask(ctx: ExecContext):
    """reference sequence_ops/sequence_mask_op.cc: lengths -> [B, maxlen]."""
    x = ctx.input("X").reshape(-1).astype(jnp.int32)
    maxlen = ctx.attr("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise ValueError(
            "sequence_mask requires a static maxlen attr under XLA "
            "(data-dependent output shapes cannot be jitted)")
    dt = np_feed_dtype(ctx.attr("out_dtype", "int64"))  # int64 -> runtime int
    t = jnp.arange(int(maxlen), dtype=jnp.int32)
    return {"Y": (t[None, :] < x[:, None]).astype(dt)}


@register_op("sequence_pad")
def sequence_pad(ctx: ExecContext):
    """reference sequence_pad_op.cc: keep the valid prefix, set the tail to
    pad_value. Input is already dense [B, T, ...] + Length; a static
    padded_length attr (reference's padded_length) truncates or extends the
    time extent."""
    x, pad = ctx.input("X"), ctx.input("PadValue")
    maxlen = ctx.attr("padded_length", -1)
    if maxlen is not None and maxlen > 0 and maxlen != x.shape[1]:
        if maxlen < x.shape[1]:
            x = x[:, :maxlen]
        else:
            widths = [(0, 0), (0, maxlen - x.shape[1])] + [(0, 0)] * (x.ndim - 2)
            x = jnp.pad(x, widths)
    ln = _lengths(ctx, x.shape[1], x.shape[0])
    ln = jnp.minimum(ln, x.shape[1])
    mask = _time_mask(ln, x.shape[1], jnp.bool_)
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    out = jnp.where(mask, x, jnp.asarray(pad, x.dtype))
    return {"Out": out, "Length": ln.astype(_INDEX_DTYPE)}


@register_op("sequence_unpad")
def sequence_unpad(ctx: ExecContext):
    """reference sequence_unpad_op.cc — under padding the dense layout stays;
    the tail is zeroed so downstream masked ops see a canonical form."""
    x = ctx.input("X")
    ln = _lengths(ctx, x.shape[1], x.shape[0])
    mask = _time_mask(ln, x.shape[1], x.dtype)
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    return {"Out": x * mask}


@register_op("sequence_pool")
def sequence_pool(ctx: ExecContext):
    """reference sequence_pool_op.cc: SUM/AVERAGE/SQRT/MAX/LAST/FIRST over
    the valid region of [B, T, D]."""
    x = ctx.input("X")
    pooltype = str(ctx.attr("pooltype", "SUM")).upper()
    B, T = x.shape[0], x.shape[1]
    ln = _lengths(ctx, T, B)
    mask = _time_mask(ln, T, x.dtype).reshape((B, T) + (1,) * (x.ndim - 2))
    if pooltype == "SUM":
        out = (x * mask).sum(axis=1)
    elif pooltype == "AVERAGE":
        out = (x * mask).sum(axis=1) / jnp.maximum(
            ln.astype(x.dtype), 1).reshape((B,) + (1,) * (x.ndim - 2))
    elif pooltype == "SQRT":
        out = (x * mask).sum(axis=1) / jnp.sqrt(jnp.maximum(
            ln.astype(x.dtype), 1)).reshape((B,) + (1,) * (x.ndim - 2))
    elif pooltype == "MAX":
        out = jnp.where(mask.astype(bool), x, _NEG_INF).max(axis=1)
    elif pooltype == "LAST":
        idx = jnp.maximum(ln - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((B, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
    elif pooltype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"sequence_pool: unknown pooltype '{pooltype}'")
    return {"Out": out}


@register_op("sequence_reverse")
def sequence_reverse(ctx: ExecContext):
    """reference sequence_reverse_op.h: reverse each valid prefix in place;
    padding stays at the tail."""
    x = ctx.input("X")
    B, T = x.shape[0], x.shape[1]
    ln = _lengths(ctx, T, B)
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    idx = jnp.where(t < ln[:, None], ln[:, None] - 1 - t, t)
    return {"Y": jnp.take_along_axis(
        x, idx.reshape((B, T) + (1,) * (x.ndim - 2)), axis=1)}


@register_op("sequence_expand")
def sequence_expand(ctx: ExecContext):
    """reference sequence_expand_op.cc with ref_level=-1 collapsed to the
    padding contract: repeat each row of X `Times` times along a new/beam
    axis. X [B, ...] + Times scalar attr -> [B*times, ...] (row-major repeat,
    the beam-search layout)."""
    x = ctx.input("X")
    times = int(ctx.attr("times", 1))
    return {"Out": jnp.repeat(x, times, axis=0)}


@register_op("sequence_softmax")
def sequence_softmax(ctx: ExecContext):
    """reference sequence_softmax_op.cc: softmax over each valid region of
    [B, T] (padding gets probability 0)."""
    x = ctx.input("X")
    B, T = x.shape[0], x.shape[1]
    ln = _lengths(ctx, T, B)
    mask = _time_mask(ln, T, jnp.bool_)
    z = jnp.where(mask, x, _NEG_INF)
    p = jax.nn.softmax(z, axis=1)
    return {"Out": jnp.where(mask, p, 0.0)}


@register_op("sequence_concat")
def sequence_concat(ctx: ExecContext):
    """reference sequence_concat_op.cc on padded operands: concat along
    time. Valid regions are assumed left-aligned (canonical padded form)."""
    xs = ctx.inputs("X")
    return {"Out": jnp.concatenate([x for x in xs if x is not None], axis=1)}


# ---------------------------------------------------------------------------
# beam search
# ---------------------------------------------------------------------------


@register_op("beam_search", grad="none")
def beam_search(ctx: ExecContext):
    """One decode step (reference beam_search_op.cc contract, fixed-shape).

    Inputs (flattened beam-major, BW = batch * beam_size):
      pre_ids    [BW, 1]  last selected token per live beam
      pre_scores [BW, 1]  cumulative log-prob per beam
      ids        [BW, K]  top-K candidate tokens from the decoder step
      scores     [BW, K]  candidate log-probs (already log-softmaxed)
    Outputs:
      selected_ids [BW, 1], selected_scores [BW, 1], parent_idx [BW] int32
      (index into the previous beam layout — gather decoder state with it).

    Finished beams (pre_id == end_id) are frozen: their only continuation is
    end_id with unchanged cumulative score, the fixed-shape analogue of the
    reference pruning finished hypotheses out of the LoD.
    """
    pre_ids = ctx.input("pre_ids").reshape(-1)
    pre_scores = ctx.input("pre_scores").reshape(-1)
    ids, scores = ctx.input("ids"), ctx.input("scores")
    beam = int(ctx.attr("beam_size"))
    end_id = int(ctx.attr("end_id"))
    first_step = bool(ctx.attr("is_first_step", False))
    BW = ids.shape[0]
    B = BW // beam

    finished = pre_ids == end_id
    # Append one guaranteed end_id candidate per beam: a finished hypothesis
    # must survive even when the decoder's top-K for that row doesn't happen
    # to contain end_id (the reference keeps finished hypotheses outside the
    # candidate set entirely; fixed shapes force them through the same top-k).
    ids = jnp.concatenate(
        [ids, jnp.full((BW, 1), end_id, ids.dtype)], axis=1)
    scores = jnp.concatenate(
        [scores, jnp.full((BW, 1), _NEG_INF, scores.dtype)], axis=1)
    K = ids.shape[1]
    # candidate cumulative scores; finished beams only propagate themselves
    cand = pre_scores[:, None] + jnp.where(finished[:, None], 0.0, scores)
    # frozen beams: kill every ORIGINAL column (the appended end_id column
    # carries the hypothesis forward at exactly pre_score, no duplicates)
    col = jnp.arange(K)
    cand = jnp.where(
        finished[:, None] & (col[None, :] < K - 1), _NEG_INF, cand)
    if first_step:
        # all beams of a batch start identical: keep only beam 0's candidates
        live0 = (jnp.arange(BW) % beam) == 0
        cand = jnp.where(live0[:, None], cand, _NEG_INF)

    flat = cand.reshape(B, beam * K)
    top_scores, top_pos = jax.lax.top_k(flat, beam)        # [B, beam]
    src_beam = top_pos // K                                 # within-batch beam
    batch_off = jnp.arange(B, dtype=jnp.int32)[:, None] * beam
    parent = (batch_off + src_beam).reshape(-1)             # [BW] flat index
    sel_ids = jnp.take_along_axis(
        ids.reshape(B, beam * K), top_pos, axis=1).reshape(-1, 1)
    return {
        "selected_ids": sel_ids.astype(_INDEX_DTYPE),
        "selected_scores": top_scores.reshape(-1, 1),
        "parent_idx": parent.astype(jnp.int32),
    }


@register_op("beam_search_decode", grad="none")
def beam_search_decode(ctx: ExecContext):
    """Backtrack parent pointers (reference beam_search_decode_op.cc).

    Inputs: Ids [T, BW] selected ids per step; ParentIdx [T, BW];
            Scores [T, BW] cumulative scores per step.
    Outputs: SentenceIds [BW, T] (each row a full hypothesis, end_id padded),
             SentenceScores [BW] final cumulative score.
    """
    ids, parents = ctx.input("Ids"), ctx.input("ParentIdx")
    scores = ctx.input("Scores")
    T = ids.shape[0]
    end_id = int(ctx.attr("end_id"))

    def step(carry, xs):
        ptr = carry
        step_ids, step_parent = xs
        tok = step_ids[ptr]
        nxt = step_parent[ptr]
        return nxt, tok

    init = jnp.arange(ids.shape[1], dtype=jnp.int32)
    _, toks = jax.lax.scan(
        step, init, (ids.astype(_INDEX_DTYPE), parents.astype(jnp.int32)),
        reverse=True)
    out = jnp.swapaxes(toks, 0, 1)  # [BW, T]
    final_scores = scores[-1].reshape(-1)
    return {"SentenceIds": out.astype(_INDEX_DTYPE),
            "SentenceScores": final_scores}


@register_op("sequence_slice")
def sequence_slice(ctx: ExecContext):
    """Per-instance sub-sequence (reference sequence_ops/sequence_slice_op.*):
    X [B, T, ...] + Offset [B] + Length [B] -> Out [B, T, ...] where row b
    holds X[b, off_b : off_b + len_b] left-aligned, zero-padded; OutLength
    carries len_b. LoD -> padded redesign: T stays static, the per-row gather
    uses a shifted iota."""
    x = ctx.input("X")
    off = ctx.input("Offset").reshape(-1).astype(jnp.int32)
    ln = ctx.input("Length").reshape(-1).astype(jnp.int32)
    B, T = x.shape[0], x.shape[1]
    t = jnp.arange(T, dtype=jnp.int32)[None, :]          # [1, T]
    src = jnp.clip(t + off[:, None], 0, T - 1)           # [B, T]
    gathered = x[jnp.arange(B)[:, None], src]            # any trailing dims
    mask = (t < ln[:, None])
    mshape = mask.shape + (1,) * (x.ndim - 2)
    out = jnp.where(mask.reshape(mshape), gathered, jnp.zeros_like(gathered))
    return {"Out": out, "OutLength": ln.astype(_INDEX_DTYPE)}


@register_op("sequence_erase", grad="none")
def sequence_erase(ctx: ExecContext):
    """Remove listed tokens, shift survivors left (reference
    sequence_ops/sequence_erase_op.*): X [B, T] int + Length [B] ->
    Out [B, T] zero-padded + OutLength. The data-dependent compaction is a
    cumsum-scatter (static shapes)."""
    x = ctx.input("X")
    B, T = x.shape
    if ctx.has_input("Length"):
        ln = ctx.input("Length").reshape(-1).astype(jnp.int32)
    else:
        ln = jnp.full((B,), T, jnp.int32)
    tokens = [int(t) for t in ctx.attr("tokens", [])]
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    valid = t < ln[:, None]
    keep = valid
    for tok in tokens:
        keep = keep & (x != tok)
    # destination position of each kept element = exclusive cumsum of keeps
    dst = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out_len = keep.sum(axis=1).astype(jnp.int32)
    out = jnp.zeros_like(x)
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    # scatter kept values; dropped ones write to a trash slot then zeroed
    dst_safe = jnp.where(keep, dst, T - 1)
    # each kept element has a UNIQUE destination (exclusive cumsum) and
    # trash writes land past out_len, so .set is exact — .at[].max against a
    # zero buffer would erase kept NEGATIVE values
    out = out.at[b_idx, dst_safe].set(jnp.where(keep, x, jnp.zeros_like(x)))
    # re-zero anything past the new length (trash writes land there)
    out = jnp.where(t < out_len[:, None], out, jnp.zeros_like(out))
    return {"Out": out, "OutLength": out_len.astype(_INDEX_DTYPE)}


@register_op("sequence_expand_as")
def sequence_expand_as(ctx: ExecContext):
    """reference sequence_ops/sequence_expand_as_op.*: tile each row of X to
    the matching row-count of Y. Padding redesign: Y's batch is a multiple
    of X's; each X row repeats (B_y / B_x) times."""
    x, y = ctx.input("X"), ctx.input("Y")
    bx, by = x.shape[0], y.shape[0]
    if by % bx:
        raise ValueError(
            f"sequence_expand_as: Y batch {by} not a multiple of X batch {bx}")
    return {"Out": jnp.repeat(x, by // bx, axis=0)}


@register_op("sequence_scatter")
def sequence_scatter(ctx: ExecContext):
    """reference sequence_ops/sequence_scatter_op.*: X [B, T] updated at
    per-row positions Ids [B, S] with Updates [B, S] (add-scatter, the
    reference's overwrite-within-sequence becomes accumulate — duplicates in
    Ids are the caller's contract); IdsLength masks trailing padding."""
    x = ctx.input("X")
    ids = ctx.input("Ids").astype(jnp.int32)
    upd = ctx.input("Updates")
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    if upd.ndim == 3 and upd.shape[-1] == 1 and x.ndim == 2:
        upd = upd.reshape(upd.shape[:-1])
    B, S = ids.shape
    if ctx.has_input("IdsLength"):
        ln = ctx.input("IdsLength").reshape(-1).astype(jnp.int32)
        m = jnp.arange(S, dtype=jnp.int32)[None, :] < ln[:, None]
        upd = jnp.where(m, upd, jnp.zeros_like(upd))
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, S))
    return {"Out": x.at[b_idx, ids].add(upd)}


@register_op("sequence_conv")
def sequence_conv(ctx: ExecContext):
    """reference sequence_ops/sequence_conv_op.*: context-window conv over
    time. X [B, T, D] + Filter [contextLength*D, F] -> Out [B, T, F]: at each
    step t the rows X[t+start : t+start+len] concatenate (zeros outside the
    valid region — the reference's up/down zero padding) and multiply the
    filter. Length [B] masks trailing padding rows."""
    x = ctx.input("X")
    filt = ctx.input("Filter")
    start = int(ctx.attr("contextStart", -1))
    length = int(ctx.attr("contextLength", 3))
    stride = int(ctx.attr("contextStride", 1))
    if stride != 1:
        raise NotImplementedError("sequence_conv: contextStride must be 1 "
                                  "(reference enforces the same)")
    B, T, D = x.shape
    t = jnp.arange(T, dtype=jnp.int32)
    cols = []
    for j in range(length):
        src = t + start + j                       # window tap j per step
        valid = (src >= 0) & (src < T)
        g = x[:, jnp.clip(src, 0, T - 1), :]
        cols.append(jnp.where(valid[None, :, None], g, 0.0))
    ctx_mat = jnp.concatenate(cols, axis=-1)      # [B, T, len*D]
    out = jnp.einsum("btk,kf->btf", ctx_mat, filt)
    if ctx.has_input("Length"):
        ln = ctx.input("Length").reshape(-1).astype(jnp.int32)
        out = jnp.where((t[None, :] < ln[:, None])[:, :, None], out, 0.0)
    return {"Out": out}


@register_op("sequence_enumerate", grad="none")
def sequence_enumerate(ctx: ExecContext):
    """reference sequence_ops/sequence_enumerate_op.*: sliding id windows.
    X [B, T] int -> Out [B, T, win_size]; window positions past the valid
    length (or past T) fill with pad_value."""
    x = ctx.input("X")
    if x.ndim == 3 and x.shape[-1] == 1:
        x = x.reshape(x.shape[:-1])
    win = int(ctx.attr("win_size", 2))
    pad = int(ctx.attr("pad_value", 0))
    B, T = x.shape
    t = jnp.arange(T, dtype=jnp.int32)
    if ctx.has_input("Length"):
        ln = ctx.input("Length").reshape(-1).astype(jnp.int32)
    else:
        ln = jnp.full((B,), T, jnp.int32)
    outs = []
    for j in range(win):
        src = t + j
        ok = src[None, :] < ln[:, None]
        g = x[:, jnp.clip(src, 0, T - 1)]
        outs.append(jnp.where(ok, g, jnp.asarray(pad, x.dtype)))
    return {"Out": jnp.stack(outs, axis=-1)}


@register_op("sequence_reshape")
def sequence_reshape(ctx: ExecContext):
    """reference sequence_ops/sequence_reshape_op.*: re-chunk the time x dim
    product to a new row width. [B, T, D] -> [B, T*D/new_dim, new_dim]."""
    x = ctx.input("X")
    new_dim = int(ctx.attr("new_dim", 1))
    B = x.shape[0]
    total = 1
    for d in x.shape[1:]:
        total *= d
    if total % new_dim:
        raise ValueError(
            f"sequence_reshape: {total} values per row not divisible by "
            f"new_dim {new_dim}")
    return {"Out": x.reshape(B, total // new_dim, new_dim)}


@register_op("sequence_topk_avg_pooling")
def sequence_topk_avg_pooling(ctx: ExecContext):
    """reference sequence_ops/sequence_topk_avg_pooling_op.h: per channel and
    per row of a [B, C, R, W] score tensor, average the top-k column scores
    for each k in `topks`. Out [B, R, C*len(topks)] matches the reference's
    row-major (r, channel, k) layout; ColLength [B] masks invalid columns
    (fewer valid than k -> average of all valid over k, like the reference's
    -1-position carry)."""
    x = ctx.input("X")
    topks = [int(k) for k in ctx.attr("topks", [1])]
    B, C, R, W = x.shape
    if ctx.has_input("ColLength"):
        cl = ctx.input("ColLength").reshape(-1).astype(jnp.int32)
    else:
        cl = jnp.full((B,), W, jnp.int32)
    col_ok = jnp.arange(W, dtype=jnp.int32)[None, :] < cl[:, None]  # [B, W]
    neg = jnp.finfo(x.dtype).min
    masked = jnp.where(col_ok[:, None, None, :], x, neg)
    s = jnp.sort(masked, axis=-1)[..., ::-1]                # desc [B,C,R,W]
    rank_ok = jnp.arange(W, dtype=jnp.int32)[None, None, None, :] < \
        cl[:, None, None, None]
    s = jnp.where(rank_ok, s, 0.0)                          # invalid -> 0
    csum = jnp.cumsum(s, axis=-1)
    pooled = []
    for k in topks:
        idx = min(k, W) - 1
        pooled.append(csum[..., idx] / float(k))            # [B, C, R]
    out = jnp.stack(pooled, axis=-1)                        # [B, C, R, K]
    out = out.transpose(0, 2, 1, 3).reshape(B, R, C * len(topks))
    if ctx.has_input("RowLength"):
        rl = ctx.input("RowLength").reshape(-1).astype(jnp.int32)
        row_ok = jnp.arange(R, dtype=jnp.int32)[None, :] < rl[:, None]
        out = jnp.where(row_ok[:, :, None], out, 0.0)
    return {"Out": out}


@register_op("match_matrix_tensor")
def match_matrix_tensor(ctx: ExecContext):
    """reference match_matrix_tensor_op.*: semantic match of two sequences.
    X [B, Tx, H], Y [B, Ty, H], W [H, C, H] -> Out [B, C, Tx, Ty] where
    Out[b,c,i,j] = x_i^T W_c y_j (the reference's per-pair [n, C, m] blocks,
    batched on the padding contract); XLength/YLength zero the padded tail."""
    x, y, w = ctx.input("X"), ctx.input("Y"), ctx.input("W")
    out = jnp.einsum("bih,hcg,bjg->bcij", x, w, y)
    Tx, Ty = x.shape[1], y.shape[1]
    if ctx.has_input("XLength"):
        xl = ctx.input("XLength").reshape(-1).astype(jnp.int32)
        m = jnp.arange(Tx, dtype=jnp.int32)[None, :] < xl[:, None]
        out = jnp.where(m[:, None, :, None], out, 0.0)
    if ctx.has_input("YLength"):
        yl = ctx.input("YLength").reshape(-1).astype(jnp.int32)
        m = jnp.arange(Ty, dtype=jnp.int32)[None, :] < yl[:, None]
        out = jnp.where(m[:, None, None, :], out, 0.0)
    return {"Out": out}
