"""CompiledProgram: attach a device mesh / build strategy to a Program.

TPU-native replacement for /root/reference/python/paddle/fluid/compiler.py
(CompiledProgram:65, with_data_parallel:143) + the whole ParallelExecutor
machinery (/root/reference/paddle/fluid/framework/parallel_executor.cc:361 and
ir/multi_devices_graph_pass/). Instead of replicating the graph per device and
inserting NCCL allreduce op-handles, `with_data_parallel` records a
`jax.sharding.Mesh` and batch-dim sharding intent; the Executor compiles ONE
SPMD XLA program with GSPMD shardings — gradient allreduce, bucketing/fusion
(fuse_all_reduce_op_pass) and deterministic ordering (all_reduce_deps_pass)
all become the XLA compiler's job.
"""
from __future__ import annotations

from .framework import Program

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy"]


class BuildStrategy:
    """Knob surface kept for API parity (reference details/build_strategy.h).
    Most knobs are no-ops on TPU (XLA subsumes them); the meaningful ones are
    the sharding-related fields."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.fuse_all_reduce_ops = True  # XLA always effectively fuses
        self.fuse_elewise_add_act_ops = True
        self.fuse_all_optimizer_ops = True
        self.memory_optimize = True
        self.enable_inplace = True
        self.num_trainers = 1
        self.trainer_id = 0
        self.use_hierarchical_allreduce = False
        # ZeRO-1 style: store optimizer accumulators sharded over the dp axis.
        # XLA computes the param update on each dp shard and all-gathers the
        # result into the replicated param — opt-state HBM drops by |dp|.
        self.sharded_optimizer_states = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 1
        self.use_experimental_executor = True


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy: BuildStrategy | None = None):
        if isinstance(program_or_graph, CompiledProgram):
            program_or_graph = program_or_graph._program
        self._program: Program = program_or_graph
        self._mesh = None
        self._spmd_mode = "gspmd"
        self._build_strategy = build_strategy or BuildStrategy()
        self._loss_name = None

    def with_data_parallel(
        self,
        loss_name: str | None = None,
        build_strategy: BuildStrategy | None = None,
        exec_strategy: ExecutionStrategy | None = None,
        share_vars_from=None,
        places=None,
        mesh=None,
    ) -> "CompiledProgram":
        """Mark the program for SPMD data parallelism over `places`/`mesh`.

        Reference contract: compiler.py:143. `places` defaults to all local
        devices; pass a `jax.sharding.Mesh` for explicit multi-axis layouts.
        """
        from .parallel.mesh import make_mesh

        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._mesh = mesh if mesh is not None else make_mesh(places=places)
        self._spmd_mode = "gspmd"
        if self._build_strategy.sharded_optimizer_states:
            self._annotate_opt_state_shardings()
        return self

    def _annotate_opt_state_shardings(self):
        """ZeRO-1: shard optimizer accumulators (tagged by
        Optimizer._add_accumulator) over the dp axis on their leading dim when
        it divides evenly. Reuses the ordinary GSPMD annotation machinery —
        the reference's ReduceSSAGraphBuilder 'balance optimizer compute'
        strategy (multi_devices_graph_pass.h:157) done the TPU way."""
        from .parallel.mesh import DATA_AXIS

        if DATA_AXIS not in self._mesh.axis_names:
            return
        dp = self._mesh.shape[DATA_AXIS]
        for v in self._program.global_block.vars.values():
            if (getattr(v, "is_opt_state", False) and v.sharding is None
                    and len(v.shape) >= 1 and v.shape[0] % dp == 0
                    and v.shape[0] >= dp):
                v.sharding = (DATA_AXIS,) + (None,) * (len(v.shape) - 1)

    def with_collective(self, mesh=None, places=None) -> "CompiledProgram":
        """Execute under shard_map with mesh axes bound, so transpiler-inserted
        `c_*` collective ops emit real psum/all_gather (the fleet regime,
        reference incubate/fleet/collective). Use after a
        parallel.collective.GradAllReduce-style transpile."""
        from .parallel.mesh import make_mesh

        self._mesh = mesh if mesh is not None else make_mesh(places=places)
        self._spmd_mode = "shard_map"
        return self

    # pass-throughs so CompiledProgram can stand in for Program
    @property
    def global_block(self):
        return self._program.global_block

    def all_parameters(self):
        return self._program.all_parameters()
