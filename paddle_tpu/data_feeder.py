"""DataFeeder: convert python sample lists into batched numpy feed dicts.

Reference: /root/reference/python/paddle/fluid/data_feeder.py (DataFeeder:48,
DataToLoDTensorConverter:27). The reference builds LoDTensors for ragged
sequences; XLA needs static shapes, so ragged fields are padded to the batch
max (plus an optional companion '<name>_len' length vector replacing LoD —
SURVEY.md §5 long-context notes)."""
from __future__ import annotations

import numpy as np

from .framework import Variable

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None, pad_ragged=True,
                 emit_lengths=False):
        self.feed_vars: list[Variable] = list(feed_list)
        self.place = place
        self.pad_ragged = pad_ragged
        self.emit_lengths = emit_lengths

    def feed(self, iterable) -> dict:
        """iterable: list of samples; each sample is a tuple/list with one
        entry per feed var. Returns {var_name: batched ndarray}."""
        samples = list(iterable)
        if not samples:
            raise ValueError("DataFeeder.feed got an empty batch")
        out = {}
        for i, var in enumerate(self.feed_vars):
            cols = [np.asarray(s[i]) for s in samples]
            dtype = var.np_dtype
            shapes = {c.shape for c in cols}
            if len(shapes) == 1:
                arr = np.stack(cols).astype(dtype, copy=False)
            elif self.pad_ragged:
                arr = _pad_stack(cols, dtype)
                if self.emit_lengths:
                    out[var.name + "_len"] = np.asarray(
                        [c.shape[0] for c in cols], np.int64)
            else:
                raise ValueError(
                    f"ragged samples for '{var.name}' and pad_ragged=False")
            # vars declared with trailing dim 1 (labels [1]) accept scalars
            want_rank = len(var.shape)
            if arr.ndim == want_rank - 1:
                arr = arr[..., None]
            out[var.name] = arr
        return out


def _pad_stack(cols, dtype):
    rank = cols[0].ndim
    maxes = [max(c.shape[d] for c in cols) for d in range(rank)]
    out = np.zeros([len(cols)] + maxes, dtype)
    for i, c in enumerate(cols):
        sl = tuple(slice(0, s) for s in c.shape)
        out[(i,) + sl] = c
    return out
