"""DataFeeder: convert python sample lists into batched numpy feed dicts.

Reference: /root/reference/python/paddle/fluid/data_feeder.py (DataFeeder:48,
DataToLoDTensorConverter:27). The reference builds LoDTensors for ragged
sequences; XLA needs static shapes, so ragged fields are padded to the batch
max (plus an optional companion '<name>_len' length vector replacing LoD —
SURVEY.md §5 long-context notes).

Shape bucketing (FLAGS_feed_bucketing or an explicit bucket_size): the
executor compiles one XLA executable per exact feed-shape signature, so the
ragged tail batch of every epoch — and every distinct padded sequence length —
is a fresh multi-second compile. Bucketing rounds those shapes to a small set:
  * the batch dim pads up to the bucket size (explicit bucket_size, else the
    largest batch seen so far) with zero rows;
  * ragged sample dims round up to the next power of two;
  * a float32 [bucket, 1] row mask lands in the feed under ROW_MASK_NAME
    (1.0 real row / 0.0 padding). Loss/metric ops must honor it for exact
    numerics: `sum(per_row * mask) / sum(mask)` reproduces the unpadded
    result bit-for-bit on the real rows (tests/test_async_pipeline.py).
"""
from __future__ import annotations

import numpy as np

from . import flags, profiler
from .framework import Variable

__all__ = ["DataFeeder", "ROW_MASK_NAME", "pad_feed_to_bucket"]

# the row-mask convention shared by DataFeeder and the Dataset runtime: any
# program that wants exact numerics under bucketing declares a data var with
# this name, shape [1], dtype float32, and weights its per-row losses by it
ROW_MASK_NAME = "batch_mask"


def _tuned_extent(var_name: str, dim: int, raw: int, default_extent: int) -> int:
    """Bucket-boundary resolution through the autotuner (tuning/): the
    pow2/HWM default is the analytic prior, a swept-DB entry (keyed by the
    raw extent it buckets) overrides it, and sweep mode records every
    boundary actually exercised so tools/tune.py can revisit the rounding
    rule with measured compile/step costs. An override below the raw extent
    is invalid (rows would be dropped) and falls through to the default."""
    from . import tuning

    if tuning.mode() == "off":
        return default_extent
    key = tuning.canonical_key(
        "feed_bucket", tuning.bucket_key(var_name, dim, raw), "-",
        tuning.device_kind())
    decision, _tier = tuning.decide(
        "feed_bucket", key,
        prior=lambda: {"pad_to": default_extent},
        default={"pad_to": default_extent},
        validate=lambda dd: isinstance(dd.get("pad_to"), int)
        and dd["pad_to"] >= raw)
    return int(decision.get("pad_to", default_extent))


def pad_feed_to_bucket(feed: dict, bucket: int,
                       mask_name: str = ROW_MASK_NAME) -> dict:
    """Pad every array's leading (batch) dim up to `bucket` rows with zeros
    and attach the [bucket, 1] float32 row mask. Always emits the mask — a
    feed whose key set changes between full and ragged batches would defeat
    the compile-cache hit bucketing exists for."""
    rows = next((np.asarray(v).shape[0] for v in feed.values()), bucket)
    bucket = _tuned_extent("<batch>", 0, rows, bucket)
    out = {}
    for name, v in feed.items():
        arr = np.asarray(v)
        if arr.shape[0] < bucket:
            pad = np.zeros((bucket - arr.shape[0],) + arr.shape[1:], arr.dtype)
            arr = np.concatenate([arr, pad])
        out[name] = arr
    mask = np.zeros((bucket, 1), np.float32)
    mask[:rows] = 1.0
    out[mask_name] = mask
    return out


def _round_up_pow2(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length())


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None, pad_ragged=True,
                 emit_lengths=False, bucket_size=None,
                 mask_name=ROW_MASK_NAME):
        self.feed_vars: list[Variable] = list(feed_list)
        self.place = place
        self.pad_ragged = pad_ragged
        self.emit_lengths = emit_lengths
        self.bucket_size = bucket_size
        self.mask_name = mask_name
        self._bucket_hwm = 0  # largest batch seen; the implicit bucket size

    def _bucketing(self) -> bool:
        return self.bucket_size is not None or flags.get_flag("feed_bucketing")

    def feed(self, iterable) -> dict:
        """iterable: list of samples; each sample is a tuple/list with one
        entry per feed var. Returns {var_name: batched ndarray}.

        Under FLAGS_feed_skip_corrupt a sample whose ndarray conversion or
        dtype cast raises (corrupt record) is dropped and counted on the
        profiler 'feed.skip_corrupt' counter instead of killing the epoch;
        a batch of ONLY corrupt samples still raises."""
        samples = list(iterable)
        if not samples:
            raise ValueError("DataFeeder.feed got an empty batch")
        if flags.get_flag("feed_skip_corrupt"):
            samples = self._drop_corrupt(samples)
        bucketing = self._bucketing()
        out = {}
        for i, var in enumerate(self.feed_vars):
            cols = [np.asarray(s[i]) for s in samples]
            # id/label vars declared int64 batch straight to int32 (the
            # runtime dtype under x64-off jax): explicit at the feed
            # boundary instead of an implicit device_put truncation
            dtype = var.np_feed_dtype
            shapes = {c.shape for c in cols}
            if len(shapes) == 1:
                arr = np.stack(cols).astype(dtype, copy=False)
            elif self.pad_ragged:
                arr = _pad_stack(cols, dtype, round_ragged=bucketing,
                                 var_name=var.name)
                if self.emit_lengths:
                    out[var.name + "_len"] = np.asarray(
                        [c.shape[0] for c in cols], np.int32)
            else:
                raise ValueError(
                    f"ragged samples for '{var.name}' and pad_ragged=False")
            # vars declared with trailing dim 1 (labels [1]) accept scalars
            want_rank = len(var.shape)
            if arr.ndim == want_rank - 1:
                arr = arr[..., None]
            out[var.name] = arr
        if bucketing:
            self._bucket_hwm = max(self._bucket_hwm, len(samples))
            bucket = max(self.bucket_size or 0, self._bucket_hwm)
            out = pad_feed_to_bucket(out, bucket, self.mask_name)
        return out

    def _drop_corrupt(self, samples):
        """Pre-validate each sample field-by-field against its feed var's
        dtype; the survivors carry already-converted ndarrays so the batch
        build below never re-hits the corruption."""
        good, bad = [], 0
        for s in samples:
            try:
                good.append(tuple(
                    np.asarray(s[i]).astype(v.np_feed_dtype, copy=False)
                    for i, v in enumerate(self.feed_vars)))
            except (ValueError, TypeError, IndexError, OverflowError):
                bad += 1
        if bad:
            profiler.bump("feed.skip_corrupt", bad)
        if not good:
            raise ValueError(
                f"DataFeeder.feed: every sample in the batch ({bad}) failed "
                f"ndarray conversion (FLAGS_feed_skip_corrupt)")
        return good


def _pad_stack(cols, dtype, round_ragged=False, var_name=""):
    rank = cols[0].ndim
    maxes = [max(c.shape[d] for c in cols) for d in range(rank)]
    if round_ragged:
        # bucket ragged dims to the next power of two so consecutive batches
        # with nearby max lengths share one compiled signature; uniform dims
        # keep their exact extent (they are part of the model's shape). The
        # pow2 boundary is the analytic prior of a tuned decision: a swept
        # DB entry can coarsen/refine it per (var, dim, raw extent), and
        # sweep mode records every boundary exercised (tuning/).
        maxes = [_tuned_extent(var_name, d + 1, m, _round_up_pow2(m))
                 if len({c.shape[d] for c in cols}) > 1 else m
                 for d, m in enumerate(maxes)]
    out = np.zeros([len(cols)] + maxes, dtype)
    for i, c in enumerate(cols):
        sl = tuple(slice(0, s) for s in c.shape)
        out[(i,) + sl] = c
    return out
