"""Program transpilers (reference python/paddle/fluid/transpiler/)."""
from .distribute_transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
    slice_variable,
)
from .ps_dispatcher import HashName, PSDispatcher, RoundRobin  # noqa: F401
