"""Parameter-block -> pserver endpoint dispatchers
(reference python/paddle/fluid/transpiler/ps_dispatcher.py)."""
from __future__ import annotations


class PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class RoundRobin(PSDispatcher):
    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


class HashName(PSDispatcher):
    def dispatch(self, varlist):
        # accepts Variables (.name) and VarBlocks (.varname)
        def _name(v):
            return getattr(v, "name", None) or v.varname

        return [
            self._eps[sum(ord(c) for c in _name(v)) % len(self._eps)]
            for v in varlist
        ]
