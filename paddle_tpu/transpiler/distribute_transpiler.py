"""DistributeTranspiler: rewrite a trained program into trainer + pserver
halves (reference python/paddle/fluid/transpiler/distribute_transpiler.py:
transpile:441, slice_variable:85, get_trainer_program:777,
get_pserver_program:911).

Contract kept:
  * user builds model + optimizer.minimize(loss), then transpiles;
  * the trainer program loses its optimizer ops and gains send / send_barrier
    / recv / fetch_barrier host ops after the backward ops;
  * each pserver program is one `listen_and_serv` op whose block_specs carry
    the per-parameter optimize sub-programs (the reference's per-grad
    optimize blocks), executed by the PServerRuntime event loop;
  * parameter placement balances by size (RoundRobin over size-sorted vars);
    large plain-SGD dense params are row-sliced across pservers
    (slice_variable); params with optimizer accumulators and sparse embedding
    tables are placed whole.

TPU-native departures: dense compute (fwd+bwd) lowers to XLA segments around
the host RPC ops (executor segmentation); pserver startup reuses the original
startup program — with equal random_seed, trainer-local init equals pserver
init, replacing the reference's moved init ops. Sync aggregation averages
trainer gradients (the fleet GradAllReduce `avg` convention), so N trainers
over batch shards reproduce single-process full-batch training.
"""
from __future__ import annotations

import copy
import math

import numpy as np

from ..framework import (
    Operator,
    Program,
    default_main_program,
    default_startup_program,
)
from .ps_dispatcher import RoundRobin

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig", "slice_variable"]


class DistributeTranspilerConfig:
    """Reference distribute_transpiler.py:131."""

    slice_var_up = True
    min_block_size = 8192
    split_method = RoundRobin
    # async parameter server (reference fleet DistributedStrategy sync_mode):
    # False = sends apply immediately server-side, Communicator merges +
    # recv-threads client-side, no barriers
    sync_mode = True
    # Delay-compensated async SGD (reference distribute_transpiler.py:1979
    # _append_dc_asgd_ops): in async mode the server compensates each
    # trainer's stale gradient with lambda * g * g * (param_now -
    # param_seen_by_that_trainer) before applying it, then snapshots the
    # fresh param for that trainer. Only meaningful with sync_mode=False.
    dc_asgd = False
    dc_asgd_lambda = 1.0
    # Geo-SGD (reference GeoSgdCommunicator): trainers optimize LOCALLY and
    # push accumulated parameter DELTAS every geo_sgd_need_push_nums steps;
    # the server adds deltas (no server-side optimizer).
    geo_sgd_mode = False
    geo_sgd_need_push_nums = 100


class VarBlock:
    def __init__(self, varname, block_id, begin, size):
        self.varname = varname
        self.block_id = block_id
        self.begin = begin  # row offset
        self.size = size    # rows

    def __str__(self):
        return f"{self.varname}:{self.block_id}:{self.size}"


def slice_variable(var_list, slice_count, min_block_size=8192):
    """Split vars into row-blocks, >= min_block_size elements each, at most
    slice_count blocks per var (reference slice_variable :85)."""
    blocks = []
    for var in var_list:
        rows = var.shape[0] if var.shape else 1
        row_width = int(np.prod(var.shape[1:])) if len(var.shape) > 1 else 1
        numel = rows * row_width
        split_count = min(slice_count, max(numel // min_block_size, 1))
        split_count = min(split_count, rows)
        per = int(math.ceil(rows / split_count))
        begin = 0
        bid = 0
        while begin < rows:
            size = min(per, rows - begin)
            blocks.append(VarBlock(var.name, bid, begin, size))
            begin += size
            bid += 1
    return blocks


# op types whose (Param, Grad) input slots mark them as optimize ops
def _is_optimize_op(op) -> bool:
    return "Param" in op.inputs and "Grad" in op.inputs


class DistributeTranspiler:
    def __init__(self, config: DistributeTranspilerConfig | None = None):
        self.config = config or DistributeTranspilerConfig()

    # -- main entry ----------------------------------------------------------
    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint=None):
        self.trainer_id = trainer_id
        self.n_trainers = trainers
        self.sync_mode = sync_mode
        self.origin_program = program or default_main_program()
        self.startup_program = startup_program or default_startup_program()
        self.eps = [e.strip() for e in pservers.split(",") if e.strip()]

        block = self.origin_program.global_block
        self._opt_ops = [op for op in block.ops if _is_optimize_op(op)]
        if not self._opt_ops:
            raise ValueError(
                "transpile() needs a program with optimizer ops — call "
                "optimizer.minimize(loss) first (reference flow)")
        sparse_params = {
            op.inputs["W"][0]
            for op in block.ops
            if op.type.startswith("lookup_table") and op.attr("is_sparse", False)
        }
        # distributed lookup tables (embedding(..., is_distributed=True)):
        # row-sharded across ALL pservers with trainer-side prefetch
        # (reference distribute_transpiler.py:1503-1656)
        self.dist_tables = {
            op.inputs["W"][0]
            for op in block.ops
            if op.type == "lookup_table" and op.attr("is_distributed", False)
        }

        # placement: size-desc round robin (reference same-size balancing)
        infos = []
        for op in self._opt_ops:
            pname, gname = op.inputs["Param"][0], op.inputs["Grad"][0]
            pvar = block.var(pname)
            infos.append({
                "op": op, "param": pname, "grad": gname, "var": pvar,
                "numel": int(np.prod(pvar.shape)) if pvar.shape else 1,
                "sparse": pname in sparse_params,
            })
        infos.sort(key=lambda d: -d["numel"])
        dispatcher = self.config.split_method(self.eps)
        self.param_blocks = []  # per param: {param, grad, eps, sections, sparse, specs}
        for info in infos:
            if info["param"] in self.dist_tables:
                if info["op"].type != "sgd":
                    raise NotImplementedError(
                        f"distributed lookup table '{info['param']}' needs "
                        "an accumulator-free optimizer (SGD) — sparse "
                        "accumulator sharding is not implemented")
                # even row split across every server, no size threshold:
                # the whole point is a table too big for one host
                n = len(self.eps)
                rows = info["var"].shape[0]
                per = int(math.ceil(rows / n))
                begins, sections, eps = [], [], []
                b = 0
                for j in range(n):
                    if b >= rows:
                        break
                    size = min(per, rows - b)
                    begins.append(b)
                    sections.append(size)
                    eps.append(self.eps[j])
                    b += size
                self.param_blocks.append({
                    **info, "sparse": True, "eps": eps, "sections": sections,
                    "begins": begins, "dist_table": True,
                })
                continue
            sliceable = (
                self.config.slice_var_up
                and not info["sparse"]
                and info["op"].type == "sgd"  # accumulator-free update
                and len(self.eps) > 1
                and info["var"].shape
                and info["var"].shape[0] >= len(self.eps)
                and info["numel"] >= self.config.min_block_size * 2
            )
            if sliceable:
                vblocks = slice_variable([info["var"]], len(self.eps),
                                         self.config.min_block_size)
                eps = dispatcher.dispatch(vblocks)
                sections = [b.size for b in vblocks]
                begins = [b.begin for b in vblocks]
            else:
                eps = dispatcher.dispatch([info["var"]])
                sections = []
                begins = [0]
            self.param_blocks.append({
                **info, "eps": eps, "sections": sections, "begins": begins,
            })

        self._build_pserver_specs()
        self._rewrite_trainer_program()
        return self

    # -- pserver side --------------------------------------------------------
    def _build_pserver_specs(self):
        self._ep_specs: dict[str, list] = {ep: [] for ep in self.eps}
        block = self.origin_program.global_block
        for pb in self.param_blocks:
            if pb["sections"]:
                rows = [(b, s) for b, s in zip(pb["begins"], pb["sections"])]
                for j, (ep, (begin, size)) in enumerate(zip(pb["eps"], rows)):
                    spec = self._make_optimize_program(
                        pb, block, begin=begin, rows=size, block_id=j)
                    self._ep_specs[ep].append(spec)
            else:
                spec = self._make_optimize_program(pb, block)
                self._ep_specs[pb["eps"][0]].append(spec)

    def _make_optimize_program(self, pb, block, begin=0, rows=None,
                               block_id=None) -> dict:
        """Replay the optimize op into a standalone program over (possibly
        row-sliced) vars; returns the serialized block spec."""
        op = pb["op"]
        sliced = block_id is not None
        prog = Program()
        dst = prog.global_block
        wire_param = f"{pb['param']}.block{block_id}" if sliced else pb["param"]
        wire_grad = f"{pb['grad']}.block{block_id}" if sliced else pb["grad"]

        def _slice_shape(shape):
            if not sliced or not shape:
                return list(shape)
            return [rows] + list(shape[1:])

        inputs = {}
        for slot, names in op.inputs.items():
            new = []
            for n in names:
                v = block.var(n)
                if slot == "Param":
                    dst.create_var(name=wire_param,
                                   shape=_slice_shape(v.shape),
                                   dtype=v.dtype, persistable=True)
                    new.append(wire_param)
                elif slot == "Grad":
                    dst.create_var(name=wire_grad,
                                   shape=_slice_shape(v.shape),
                                   dtype=v.dtype, is_data=True,
                                   stop_gradient=True)
                    new.append(wire_grad)
                else:  # LearningRate, moments, beta pows: persistable state
                    dst.create_var(name=n, shape=_slice_shape(v.shape)
                                   if slot.startswith("Moment") else list(v.shape),
                                   dtype=v.dtype, persistable=True)
                    new.append(n)
            inputs[slot] = new
        outputs = {}
        for slot, names in op.outputs.items():
            new = []
            for n in names:
                if n == pb["param"]:
                    new.append(wire_param)
                elif n in dst.vars:
                    new.append(n)
                else:
                    v = block.var(n)
                    dst.create_var(name=n, shape=list(v.shape), dtype=v.dtype,
                                   persistable=True)
                    new.append(n)
            outputs[slot] = new
        dst.append_op(op.type, inputs, outputs, copy.deepcopy(op.attrs))
        return {
            "grad": wire_grad,
            "param": wire_param,
            "origin_param": pb["param"],
            "begin": begin,
            "rows": rows,
            "sparse": pb["sparse"],
            "optimize_program": prog.to_dict(),
        }

    def get_pserver_program(self, endpoint: str) -> Program:
        if endpoint not in self._ep_specs:
            raise ValueError(f"unknown pserver endpoint {endpoint}; "
                             f"known: {self.eps}")
        prog = Program()
        prog.global_block.append_op(
            "listen_and_serv", {}, {},
            {
                "endpoint": endpoint,
                "Fanin": self.n_trainers,
                "sync_mode": self.sync_mode,
                "block_specs": self._ep_specs[endpoint],
                "dc_asgd": bool(getattr(self.config, "dc_asgd", False)),
                "dc_asgd_lambda": float(
                    getattr(self.config, "dc_asgd_lambda", 1.0)),
            },
        )
        return prog

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        """Pserver init: the ORIGINAL startup program — equal random_seed
        makes pserver param init identical to trainer init (replaces the
        reference's moved init ops). When distributed tables exist, the
        trainer's startup was stripped of their init ops, so the stashed
        pre-rewrite copy serves the pserver role."""
        if startup_program is not None:
            return startup_program
        return getattr(self, "_pserver_startup", None) or self.startup_program

    # -- trainer side --------------------------------------------------------
    def _rewrite_trainer_program(self):
        # the pserver role needs the ORIGINAL startup (it initializes +
        # slices the full tables); the trainer's startup is about to lose
        # the distributed tables' init ops, so stash a deep copy first
        self._pserver_startup = Program.from_dict(self.startup_program.to_dict())
        block = self.origin_program.global_block
        if getattr(self.config, "geo_sgd_mode", False):
            # geo-SGD: the trainer optimizes LOCALLY — the optimizer ops
            # STAY, no grads are sent, no recv ops exist. Parameter deltas
            # travel through the GeoCommunicator (get_geo_communicator)
            # every geo_sgd_need_push_nums steps instead.
            return
        opt_set = set(id(op) for op in self._opt_ops)
        block.ops = [op for op in block.ops if id(op) not in opt_set]
        if self.dist_tables:
            self._rewrite_dist_tables()
        common = {"endpoints": self.eps, "trainer_id": self.trainer_id}
        dist_begins = {pb["grad"]: pb["begins"] for pb in self.param_blocks
                       if pb.get("dist_table")}
        for pb in self.param_blocks:
            block.append_op(
                "send", {"X": [pb["grad"]]}, {},
                {"epmap": pb["eps"], "sections": pb["sections"],
                 "begins": dist_begins.get(pb["grad"], []),
                 "sparse": pb["sparse"], **common},
            )
        if self.sync_mode:
            block.append_op("send_barrier", {}, {}, dict(common))
            for pb in self.param_blocks:
                if pb.get("dist_table"):
                    continue  # never pulled whole — prefetch reads rows
                block.append_op(
                    "recv", {}, {"Out": [pb["param"]]},
                    {"epmap": pb["eps"], "sections": pb["sections"], **common},
                )
            block.append_op("fetch_barrier", {}, {}, dict(common))
        # async mode: NO recv/barrier ops — the Communicator's independent
        # recv thread refreshes parameters (reference async trainer program,
        # communicator.h:162; recv ops would re-introduce a sync round-trip
        # per step)

    def _rewrite_dist_tables(self):
        """Rewrite every distributed table's ops on the trainer (reference
        distribute_transpiler.py:1503 _replace_lookup_table_op_with_prefetch
        + :1656 grad rewrite):
          * forward lookup_table -> prefetch (only the batch's rows travel)
          * backward lookup_table_grad -> lookup_table_grad_rows (builds the
            SelectedRows grad WITHOUT the table value)
          * the table's startup init ops are dropped — a vocab too big to
            replicate must never materialize in the trainer scope.
        """
        block = self.origin_program.global_block
        by_param = {pb["param"]: pb for pb in self.param_blocks
                    if pb.get("dist_table")}
        new_ops = []
        for op in block.ops:
            if (op.type == "lookup_table"
                    and op.inputs["W"][0] in by_param):
                pb = by_param[op.inputs["W"][0]]
                nop = Operator(
                    block, "prefetch",
                    {"Ids": list(op.inputs["Ids"])},
                    {"Out": list(op.outputs["Out"])},
                    {
                        "table_name": pb["param"],
                        "epmap": pb["eps"], "begins": pb["begins"],
                        "sections": pb["sections"],
                        "endpoints": self.eps,
                        "trainer_id": self.trainer_id,
                        "padding_idx": op.attr("padding_idx", -1),
                    })
                new_ops.append(nop)
            elif (op.type == "lookup_table_grad"
                    and op.inputs.get("W", [""])[0] in by_param):
                pb = by_param[op.inputs["W"][0]]
                nop = Operator(
                    block, "lookup_table_grad_rows",
                    {"Ids": list(op.inputs["Ids"]),
                     "Out@GRAD": list(op.inputs["Out@GRAD"])},
                    {"W@GRAD": list(op.outputs["W@GRAD"])},
                    {"height": int(pb["var"].shape[0]),
                     "padding_idx": op.attr("padding_idx", -1)})
                new_ops.append(nop)
            else:
                new_ops.append(op)
        block.ops = new_ops
        # neutralize the big tables' init ops in the TRAINER startup: the
        # table must never materialize, but the op cannot simply be DELETED —
        # startup randomness is a sequential split stream, so removal would
        # shift every later init away from the pserver's (which runs the
        # original startup), desynchronizing step-1 gradients. Keep the op
        # (same RNG consumption), point it at a [1]-shaped throwaway.
        from ..ops.registry import get_op_def, has_op

        sblock = self.startup_program.global_block
        for op in sblock.ops:
            hit = set(op.output_names) & set(by_param)
            if not hit:
                continue
            if has_op(op.type) and get_op_def(op.type).needs_rng:
                dummy = sblock.create_var(
                    name=next(iter(hit)) + "@INIT_DROPPED", shape=[1],
                    dtype="float32")
                op.outputs = {s: [dummy.name if n in by_param else n
                                  for n in ns]
                              for s, ns in op.outputs.items()}
                if "shape" in op.attrs:
                    op.attrs = {**op.attrs, "shape": [1]}
            else:
                op.type = "fill_constant"
                dummy = sblock.create_var(
                    name=next(iter(hit)) + "@INIT_DROPPED", shape=[1],
                    dtype="float32")
                op.inputs = {}
                op.outputs = {"Out": [dummy.name]}
                op.attrs = {"shape": [1], "dtype": "float32", "value": 0.0}

    def get_trainer_program(self, wait_port=True) -> Program:
        return self.origin_program

    def get_geo_communicator(self, scope, client=None):
        """Geo-SGD mode: build the GeoCommunicator over every dense param
        (reference GeoSgdCommunicator). Call mark_step() once per local
        train step; pushes/rebases every config.geo_sgd_need_push_nums."""
        if not getattr(self.config, "geo_sgd_mode", False):
            raise RuntimeError("get_geo_communicator requires "
                               "config.geo_sgd_mode = True")
        from ..distributed.communicator import GeoCommunicator
        from ..distributed.ps_rpc import PSClient

        param_ctx = {}
        for pb in self.param_blocks:
            if pb.get("dist_table") or pb["sparse"]:
                continue  # geo ships dense param deltas only
            param_ctx[pb["param"]] = {"epmap": pb["eps"],
                                      "sections": pb["sections"]}
        client = client or PSClient.get(self.eps, self.trainer_id)
        return GeoCommunicator(
            param_ctx, client, scope,
            push_nums=int(getattr(self.config,
                                  "geo_sgd_need_push_nums", 100)))

    def get_communicator_context(self):
        """(send_ctx, recv_ctx) for the async Communicator: per-gradient and
        per-parameter endpoint/section maps (reference
        communicator.py Communicator(program, ...) extraction)."""
        send_ctx, recv_ctx = {}, {}
        for pb in self.param_blocks:
            send_ctx[pb["grad"]] = {"epmap": pb["eps"],
                                    "sections": pb["sections"],
                                    "begins": pb["begins"]}
            if pb.get("dist_table"):
                # never pulled whole: the prefetch op reads fresh rows per
                # batch, and materializing the table would defeat the
                # feature's memory contract
                continue
            recv_ctx[pb["param"]] = {"epmap": pb["eps"],
                                     "sections": pb["sections"]}
        return send_ctx, recv_ctx
