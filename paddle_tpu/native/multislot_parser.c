/* MultiSlot text parser — the native data-layer hot path.
 *
 * TPU-native counterpart of the reference's C++ data feed
 * (/root/reference/paddle/fluid/framework/data_feed.cc
 * MultiSlotDataFeed::ParseOneInstance): one text line per sample, and for
 * each slot in order `<n> v1 ... vn`. Values for a slot are padded (zero) or
 * truncated to the slot's fixed width — the LoD->padding design the Python
 * side documents (framework.py) applied at ingest time, so the device only
 * ever sees static shapes.
 *
 * The file is parsed in one pass with no per-token Python overhead; output is
 * a sample-major double buffer [n_samples, sum(widths)] the Python wrapper
 * slices per slot and casts to each var's dtype (ids fit doubles exactly up
 * to 2^53).
 *
 * Built on demand with `cc -O2 -shared -fPIC` and bound via ctypes
 * (paddle_tpu/native/__init__.py); a pure-Python fallback exists for
 * environments without a C compiler.
 */
#include <ctype.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

/* Count newline-terminated, non-empty lines (samples) in the file. */
long long multislot_count(const char *path) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  long long n = 0;
  int c, seen = 0;
  while ((c = fgetc(f)) != EOF) {
    if (c == '\n') {
      if (seen) n++;
      seen = 0;
    } else if (!isspace(c)) {
      seen = 1;
    }
  }
  if (seen) n++;
  fclose(f);
  return n;
}

/* Parse up to max_samples lines into out[max_samples][row_width] where
 * row_width = sum(widths). Returns samples parsed, or -1 on IO error,
 * -2 on malformed line (slot count missing). */
long long multislot_parse(const char *path, int n_slots,
                          const long long *widths, double *out,
                          long long max_samples) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;

  long long row_width = 0;
  for (int s = 0; s < n_slots; s++) row_width += widths[s];

  char *line = NULL;
  size_t cap = 0;
  long long sample = 0;
  while (sample < max_samples) {
    ssize_t len = getline(&line, &cap, f);
    if (len < 0) break;
    char *p = line;
    while (*p && isspace((unsigned char)*p)) p++;
    if (!*p) continue; /* blank line */

    double *row = out + sample * row_width;
    memset(row, 0, (size_t)row_width * sizeof(double));
    long long off = 0;
    for (int s = 0; s < n_slots; s++) {
      char *end;
      long long cnt = strtoll(p, &end, 10);
      if (end == p) { /* malformed: missing slot count */
        free(line);
        fclose(f);
        return -2;
      }
      p = end;
      long long w = widths[s];
      for (long long i = 0; i < cnt; i++) {
        double v = strtod(p, &end);
        if (end == p) { /* fewer values than declared */
          free(line);
          fclose(f);
          return -2;
        }
        p = end;
        if (i < w) row[off + i] = v; /* truncate beyond width */
      }
      off += w;
    }
    sample++;
  }
  free(line);
  fclose(f);
  return sample;
}
