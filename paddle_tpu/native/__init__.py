"""Native runtime components (C, built on demand, ctypes-bound).

The reference keeps its data layer in C++ (data_feed.cc, data_set.cc); here
the hot MultiSlot text parser is C compiled at first use with the system
compiler. Every binding has a pure-Python fallback so the framework still
works without a toolchain (slower ingest only).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "multislot_parser.c")
_SO = os.path.join(_DIR, "_multislot.so")

_lock = threading.Lock()
_lib = None
_build_failed = False


def _load():
    """Compile (if stale) and load the parser library; None if unavailable."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                cc = os.environ.get("CC", "cc")
                subprocess.run(
                    [cc, "-O2", "-shared", "-fPIC", "-o", _SO + ".tmp", _SRC],
                    check=True, capture_output=True)
                os.replace(_SO + ".tmp", _SO)
            lib = ctypes.CDLL(_SO)
            lib.multislot_count.restype = ctypes.c_longlong
            lib.multislot_count.argtypes = [ctypes.c_char_p]
            lib.multislot_parse.restype = ctypes.c_longlong
            lib.multislot_parse.argtypes = [
                ctypes.c_char_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.POINTER(ctypes.c_double), ctypes.c_longlong,
            ]
            _lib = lib
        except (OSError, subprocess.CalledProcessError):
            _build_failed = True
    return _lib


def parse_multislot_file(path: str, widths: list[int]) -> np.ndarray:
    """Parse one MultiSlot text file -> [n_samples, sum(widths)] float64."""
    lib = _load()
    if lib is not None:
        n = lib.multislot_count(path.encode())
        if n < 0:
            raise IOError(f"cannot read '{path}'")
        out = np.zeros((n, int(sum(widths))), dtype=np.float64)
        w = (ctypes.c_longlong * len(widths))(*widths)
        got = lib.multislot_parse(
            path.encode(), len(widths), w,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n)
        if got == -2:
            raise ValueError(f"malformed MultiSlot line in '{path}'")
        if got < 0:
            raise IOError(f"cannot read '{path}'")
        return out[:got]
    return _parse_multislot_py(path, widths)


def _parse_multislot_py(path: str, widths: list[int]) -> np.ndarray:
    """Pure-Python fallback with identical semantics."""
    rows = []
    row_width = int(sum(widths))
    with open(path) as f:
        for line in f:
            toks = line.split()
            if not toks:
                continue
            row = np.zeros(row_width, dtype=np.float64)
            i, off = 0, 0
            for w in widths:
                if i >= len(toks):
                    raise ValueError(f"malformed MultiSlot line in '{path}'")
                cnt = int(toks[i])
                i += 1
                vals = toks[i:i + cnt]
                if len(vals) != cnt:
                    raise ValueError(f"malformed MultiSlot line in '{path}'")
                i += cnt
                for j, v in enumerate(vals[:w]):
                    row[off + j] = float(v)
                off += w
            rows.append(row)
    if not rows:
        return np.zeros((0, row_width), dtype=np.float64)
    return np.stack(rows)


def native_available() -> bool:
    return _load() is not None
