/* Standalone native trainer.
 *
 * Role parity with the reference's Python-free train path
 * (/root/reference/paddle/fluid/train/demo/demo_trainer.cc: load a saved
 * ProgramDesc, run startup + main with the C++ Executor). On this
 * TPU-native stack the execution engine is XLA (native code reached through
 * the embedded runtime), so the standalone trainer is a C binary that hosts
 * the runtime in-process: no user Python, no scripts — argv in, trained
 * parameters out.
 *
 *   standalone_trainer MODEL_DIR DATA_FILE BATCH [EPOCHS] [SAVE_DIR]
 *
 * MODEL_DIR is io.save_train_model output (train_main/train_startup/
 * train_meta.json + persistables); DATA_FILE is MultiSlot text (the native
 * parser's format); trained persistables are written to SAVE_DIR (default:
 * MODEL_DIR).
 *
 * Build (tools/build_standalone_trainer.sh or the test):
 *   cc standalone_trainer.c $(python3-config --includes) \
 *      $(python3-config --ldflags --embed) -o standalone_trainer
 */
#include <Python.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

static const char *DRIVER =
    "import json, os, sys\n"
    "model_dir, data_file, batch, epochs, save_dir = (\n"
    "    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),\n"
    "    sys.argv[5])\n"
    "sys.path.insert(0, os.environ.get('PADDLE_TPU_HOME', os.getcwd()))\n"
    "import paddle_tpu as pt\n"
    "exe = pt.Executor()\n"
    "main, startup, meta = pt.io.load_train_model(model_dir, exe)\n"
    "ds = pt.DatasetFactory().create_dataset('QueueDataset')\n"
    "ds.set_batch_size(batch)\n"
    "use_vars = [main.global_block.var(n) for n in meta['feed_names']]\n"
    "ds.set_use_var(use_vars)\n"
    "ds.set_filelist([data_file])\n"
    "for _ in range(epochs):\n"
    "    exe.train_from_dataset(main, ds, fetch_list=[meta['loss_name']],\n"
    "                           fetch_info=['loss'], print_period=10)\n"
    "pt.io.save_persistables(exe, save_dir, main)\n"
    "print('standalone_trainer: saved to', save_dir, flush=True)\n";

int main(int argc, char **argv) {
  if (argc < 4) {
    fprintf(stderr,
            "usage: %s MODEL_DIR DATA_FILE BATCH [EPOCHS] [SAVE_DIR]\n",
            argv[0]);
    return 2;
  }
  const char *model_dir = argv[1];
  const char *data_file = argv[2];
  const char *batch = argv[3];
  const char *epochs = argc > 4 ? argv[4] : "1";
  const char *save_dir = argc > 5 ? argv[5] : argv[1];

  PyStatus status;
  PyConfig config;
  PyConfig_InitPythonConfig(&config);
  /* forward the trainer's argv into the embedded runtime */
  wchar_t *wargv[6];
  const char *cargv[6] = {"standalone_trainer", model_dir, data_file,
                          batch,               epochs,    save_dir};
  for (int i = 0; i < 6; i++) {
    wargv[i] = Py_DecodeLocale(cargv[i], NULL);
    if (!wargv[i]) {
      fprintf(stderr, "standalone_trainer: argv decode failed\n");
      return 1;
    }
  }
  status = PyConfig_SetArgv(&config, 6, wargv);
  if (PyStatus_Exception(status)) goto fail;
  config.parse_argv = 0; /* argv is data, not interpreter options */

  /* Resolve the runtime environment the way a shell would: the PATH's
   * python3 (or $PADDLE_TPU_PYTHON) — so a virtualenv's site-packages
   * (jaxlib, numpy: the native compute stack) is found. Without this the
   * embedded interpreter initializes against the bare system prefix. */
  {
    char pybuf[4096] = {0};
    const char *pyexe = getenv("PADDLE_TPU_PYTHON");
    if (!pyexe) {
      FILE *p = popen("command -v python3", "r");
      if (p) {
        if (fgets(pybuf, sizeof(pybuf) - 1, p)) {
          pybuf[strcspn(pybuf, "\n")] = 0;
          if (pybuf[0]) pyexe = pybuf;
        }
        pclose(p);
      }
    }
    if (pyexe) {
      /* the resolved interpreter must match the libpython this binary was
       * linked against — a PATH pointing at a different minor version would
       * otherwise die deep in Py_InitializeFromConfig with an opaque
       * encodings error */
      char cmd[4352];
      snprintf(cmd, sizeof(cmd),
               "'%s' -c 'import sys;print(\"%%d.%%d\"%%sys.version_info[:2])'",
               pyexe);
      FILE *v = popen(cmd, "r");
      char ver[32] = {0};
      if (v) {
        if (fgets(ver, sizeof(ver) - 1, v)) ver[strcspn(ver, "\n")] = 0;
        pclose(v);
      }
      char want[32];
      snprintf(want, sizeof(want), "%d.%d", PY_MAJOR_VERSION,
               PY_MINOR_VERSION);
      if (ver[0] && strcmp(ver, want) != 0) {
        fprintf(stderr,
                "standalone_trainer: python3 on PATH is %s but this binary "
                "embeds %s — set PADDLE_TPU_PYTHON to a %s interpreter\n",
                ver, want, want);
        return 1;
      }
      status = PyConfig_SetBytesString(&config, &config.executable, pyexe);
      if (PyStatus_Exception(status)) goto fail;
    }
  }
  status = Py_InitializeFromConfig(&config);
  if (PyStatus_Exception(status)) goto fail;
  PyConfig_Clear(&config);

  int rc = PyRun_SimpleString(DRIVER);
  if (rc != 0) {
    fprintf(stderr, "standalone_trainer: training failed\n");
    Py_Finalize();
    return 1;
  }
  if (Py_FinalizeEx() < 0) return 120;
  for (int i = 0; i < 6; i++) PyMem_RawFree(wargv[i]);
  return 0;

fail:
  PyConfig_Clear(&config);
  fprintf(stderr, "standalone_trainer: runtime init failed: %s\n",
          status.err_msg ? status.err_msg : "?");
  return 1;
}
