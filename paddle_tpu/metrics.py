"""Python-side streaming metrics (reference python/paddle/fluid/metrics.py:
MetricBase, CompositeMetric, Precision, Recall, Accuracy, Auc, EditDistance).

These accumulate NUMPY values fetched from executor runs — they are host-side
by design (same as the reference); in-graph metric ops live in ops/
(accuracy op, see also layers.accuracy)."""
from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Precision", "Recall", "Accuracy",
           "Auc", "EditDistance"]


def _to_np(x):
    return np.asarray(x)


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self):
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_")}


class CompositeMetric(MetricBase):
    """Evaluate several metrics over the same feed (metrics.py:214)."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics: list[MetricBase] = []

    def add_metric(self, metric: MetricBase):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision over thresholded predictions (metrics.py:262)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(_to_np(preds)).astype(np.int64).reshape(-1)
        labels = _to_np(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels != 1)))

    def eval(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(_to_np(preds)).astype(np.int64).reshape(-1)
        labels = _to_np(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds != 1) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Accuracy(MetricBase):
    """Weighted running mean of per-batch accuracy values (metrics.py:354)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        if weight < 0:
            raise ValueError("weight must be nonnegative")
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no batches accumulated — call update() first")
        return self.value / self.weight


class Auc(MetricBase):
    """Streaming ROC-AUC via threshold buckets (metrics.py:407)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        n = self._num_thresholds + 1
        self._stat_pos = np.zeros(n, np.int64)
        self._stat_neg = np.zeros(n, np.int64)

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels).reshape(-1)
        # preds: [N, 2] class probs (reference contract) or [N] positive prob
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        idx = np.clip((pos_prob * self._num_thresholds).astype(np.int64), 0,
                      self._num_thresholds)
        np.add.at(self._stat_pos, idx[labels == 1], 1)
        np.add.at(self._stat_neg, idx[labels != 1], 1)

    def eval(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (new_pos + tot_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc) / denom if denom else 0.0


class EditDistance(MetricBase):
    """Mean edit distance + instance error rate (metrics.py:310)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = _to_np(distances).reshape(-1)
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances != 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data accumulated — call update() first")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)
