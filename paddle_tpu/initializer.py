"""Initializers: append init ops to the startup program.

Reference: /root/reference/python/paddle/fluid/initializer.py (Constant:59,
Uniform:133, Normal:199, Xavier:327, MSRA:443, TruncatedNormal). Same design:
an Initializer is a callable that appends one op writing the parameter in the
*startup* program; the TPU executor runs that block once to materialize
params in the Scope.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = [
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "Xavier",
    "MSRA",
    "NumpyArrayInitializer",
]


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    def _dygraph_sample(self, key, shape, dtype, fan_in=None, fan_out=None):
        """Eager sampling for dygraph create_parameter (same distribution the
        static op path produces, drawn from the dygraph guard's PRNG)."""
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op(
            "fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype.value, "value": self.value},
        )

    def _dygraph_sample(self, key, shape, dtype, fan_in=None, fan_out=None):
        return np.full(shape, self.value, dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            "uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype.value,
                "min": self.low,
                "max": self.high,
                "seed": self.seed,
            },
        )

    def _dygraph_sample(self, key, shape, dtype, fan_in=None, fan_out=None):
        import jax

        return jax.random.uniform(key, shape, dtype, self.low, self.high)


class Normal(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            "gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype.value,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )

    def _dygraph_sample(self, key, shape, dtype, fan_in=None, fan_out=None):
        import jax

        return jax.random.normal(key, shape, dtype) * self.scale + self.loc


class TruncatedNormal(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            "truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype.value,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )


def _fan_in_out(var):
    shape = var.shape
    if len(shape) < 2:
        return (shape[0] if shape else 1), (shape[0] if shape else 1)
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    return shape[0] * receptive, shape[1] * receptive


class Xavier(Initializer):
    """Glorot init (reference initializer.py:327)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        f_in, f_out = _fan_in_out(var)
        f_in = self.fan_in if self.fan_in is not None else f_in
        f_out = self.fan_out if self.fan_out is not None else f_out
        if self.uniform:
            limit = math.sqrt(6.0 / (f_in + f_out))
            Uniform(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (f_in + f_out))
            Normal(0.0, std, self.seed)(var, block)

    def _dygraph_sample(self, key, shape, dtype, fan_in=None, fan_out=None):
        f_in = self.fan_in if self.fan_in is not None else fan_in
        f_out = self.fan_out if self.fan_out is not None else fan_out
        if self.uniform:
            limit = math.sqrt(6.0 / (f_in + f_out))
            return Uniform(-limit, limit)._dygraph_sample(key, shape, dtype)
        std = math.sqrt(2.0 / (f_in + f_out))
        return Normal(0.0, std)._dygraph_sample(key, shape, dtype)


class MSRA(Initializer):
    """Kaiming init (reference initializer.py:443)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        f_in, _ = _fan_in_out(var)
        f_in = self.fan_in if self.fan_in is not None else f_in
        if self.uniform:
            limit = math.sqrt(6.0 / f_in)
            Uniform(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / f_in)
            Normal(0.0, std, self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value: np.ndarray):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op(
            "assign_value",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(self.value.shape),
                "dtype": var.dtype.value,
                "values": self.value.reshape(-1).tolist(),
            },
        )


ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
XavierInitializer = Xavier
MSRAInitializer = MSRA
