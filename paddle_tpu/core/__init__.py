from .types import DType, VarKind, is_floating, np_dtype  # noqa: F401
