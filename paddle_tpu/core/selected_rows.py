"""SelectedRows: a sparse row-set {rows, values} with a dense height.

TPU-native redesign of the reference's SelectedRows
(/root/reference/paddle/fluid/framework/selected_rows.h:32): same contract —
`rows[i]` is the dense row index of `values[i]`, duplicates allowed (merged by
addition) — but with STATIC shapes: `rows` has fixed length K (the number of
lookups in the batch), so it traces through jit/XLA. Registered as a pytree,
it flows through the executor env, `send` ops, and sparse optimizer updates
without materializing the [height, width] dense gradient.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["SelectedRows", "is_selected_rows"]


class SelectedRows:
    def __init__(self, rows, values, height: int):
        self.rows = rows          # int32 [K]
        self.values = values      # [K, width...]
        self.height = int(height)  # dense dim-0 extent (static)

    def to_dense(self):
        """Scatter-add into the dense [height, ...] tensor (merges dups)."""
        dense = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                          self.values.dtype)
        return dense.at[self.rows].add(self.values)

    def merged(self):
        """Host-side merge of duplicate rows -> (unique_rows, summed_values).
        For pserver-side sparse updates (numpy)."""
        import numpy as np

        rows = np.asarray(self.rows)
        vals = np.asarray(self.values)
        uniq, inv = np.unique(rows, return_inverse=True)
        out = np.zeros((len(uniq),) + vals.shape[1:], vals.dtype)
        np.add.at(out, inv, vals)
        return uniq, out

    def __repr__(self):
        return (f"SelectedRows(rows={getattr(self.rows, 'shape', None)}, "
                f"values={getattr(self.values, 'shape', None)}, "
                f"height={self.height})")


def is_selected_rows(v) -> bool:
    return isinstance(v, SelectedRows)


jax.tree_util.register_pytree_node(
    SelectedRows,
    lambda sr: ((sr.rows, sr.values), sr.height),
    lambda height, children: SelectedRows(children[0], children[1], height),
)
