"""Core type system: dtypes and variable types.

TPU-native equivalent of the reference's VarType proto
(/root/reference/paddle/fluid/framework/framework.proto:105) — we keep the
same *contract* (named dtypes, tensor/reader/step-scope var kinds) but store
them as plain Python enums serializable to JSON instead of protobuf, and map
dtypes directly onto JAX/numpy dtypes (bfloat16 is first-class for TPU).
"""
from __future__ import annotations

import enum

import numpy as np

try:  # jax provides a real bfloat16 numpy scalar type
    import jax.numpy as jnp

    _bfloat16 = jnp.bfloat16
except Exception:  # pragma: no cover
    _bfloat16 = np.float32


class VarKind(enum.Enum):
    """What a Variable holds (reference: framework.proto VarType.Type)."""

    DENSE_TENSOR = "dense_tensor"  # reference LOD_TENSOR — TPU build uses padded dense
    SELECTED_ROWS = "selected_rows"  # sparse row-set (embedding grads)
    READER = "reader"
    STEP_SCOPES = "step_scopes"
    RAW = "raw"


class DType(enum.Enum):
    """Named dtypes; values are the canonical string spelling."""

    FP64 = "float64"
    FP32 = "float32"
    FP16 = "float16"
    BF16 = "bfloat16"
    INT64 = "int64"
    INT32 = "int32"
    INT16 = "int16"
    INT8 = "int8"
    UINT8 = "uint8"
    BOOL = "bool"

    @property
    def np(self):
        return _NP_MAP[self]

    @staticmethod
    def parse(x) -> "DType":
        if isinstance(x, DType):
            return x
        if isinstance(x, str):
            return DType(_STR_ALIASES.get(x, x))
        # numpy dtype / type object
        name = np.dtype(x).name if x is not _bfloat16 else "bfloat16"
        try:
            name = np.dtype(x).name
        except TypeError:
            name = str(x)
        if "bfloat16" in name:
            return DType.BF16
        return DType(name)


_NP_MAP = {
    DType.FP64: np.float64,
    DType.FP32: np.float32,
    DType.FP16: np.float16,
    DType.BF16: _bfloat16,
    DType.INT64: np.int64,
    DType.INT32: np.int32,
    DType.INT16: np.int16,
    DType.INT8: np.int8,
    DType.UINT8: np.uint8,
    DType.BOOL: np.bool_,
}

_STR_ALIASES = {
    "float": "float32",
    "double": "float64",
    "int": "int32",
    "long": "int64",
    "bf16": "bfloat16",
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
}


def np_dtype(dtype) -> np.dtype:
    """Resolve any dtype spelling to a numpy dtype (bfloat16 aware)."""
    d = DType.parse(dtype)
    return np.dtype(d.np)


def np_feed_dtype(dtype) -> np.dtype:
    """The dtype a FEED array should be cast to for this runtime.

    Declared int64/float64 vars (the reference API's defaults for ids and
    labels) run as int32/float32 on the device whenever jax's x64 mode is
    off — device_put would truncate them anyway, with jax emitting its
    "will be truncated to dtype int32" UserWarning on every astype it sees.
    Casting explicitly at the feed boundary keeps the truncation a stated
    contract (and halves the host->HBM bytes of every id/label feed)
    instead of an accident in the transfer path. With x64 enabled the
    declared dtype is honored unchanged."""
    dt = np_dtype(dtype)
    if dt not in (np.dtype(np.int64), np.dtype(np.uint64),
                  np.dtype(np.float64)):
        return dt
    try:
        import jax

        if jax.config.jax_enable_x64:
            return dt
    except Exception:  # pragma: no cover - jax not importable
        return dt
    return {np.dtype(np.int64): np.dtype(np.int32),
            np.dtype(np.uint64): np.dtype(np.uint32),
            np.dtype(np.float64): np.dtype(np.float32)}[dt]


def is_floating(dtype) -> bool:
    return DType.parse(dtype) in (DType.FP64, DType.FP32, DType.FP16, DType.BF16)
