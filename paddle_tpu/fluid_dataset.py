"""Dataset runtime: QueueDataset / InMemoryDataset + DatasetFactory.

TPU-native re-design of the reference's Dataset stack:
  * python API (/root/reference/python/paddle/fluid/dataset.py:21
    DatasetFactory, :63 DatasetBase, :269 InMemoryDataset, :613 QueueDataset)
  * C++ runtime (/root/reference/paddle/fluid/framework/data_set.h:41
    DatasetImpl, :212 MultiSlotDataset; data_feed.h MultiSlotDataFeed)

Same contract — slot-based text files, multi-threaded ingest, local/global
shuffle, consumed by `exe.train_from_dataset` — with the runtime re-shaped
for TPU:
  * parsing runs in the native C parser (paddle_tpu/native) on host threads;
    samples become padded fixed-width arrays at ingest (the LoD->padding
    design), so batches land on the device as static-shape buffers;
  * there are no per-thread device workers: one XLA stream consumes batches
    (device_worker.h's HogwildWorker parallelism only makes sense for CPU
    kernels); host threads overlap parse/shuffle with device steps instead;
  * global_shuffle partitions by sample hash across trainers — every trainer
    loads the shared filelist and keeps hash(i) % nranks == rank, which
    reproduces the reference's post-condition (each sample on exactly one
    trainer, seeded random order) without a fleet send path.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

__all__ = ["DatasetFactory", "QueueDataset", "InMemoryDataset", "MultiSlotDataset"]


class DatasetFactory:
    """reference dataset.py:21 — create_dataset("QueueDataset"|"InMemoryDataset")."""

    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        try:
            cls = {
                "QueueDataset": QueueDataset,
                "InMemoryDataset": InMemoryDataset,
                "MultiSlotDataset": QueueDataset,  # C++ name accepted too
            }[datafeed_class]
        except KeyError:
            raise ValueError(
                f"datafeed class {datafeed_class} does not exist")
        return cls()


class DatasetBase:
    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist: list[str] = []
        self.use_vars = []
        self.pipe_command = None  # accepted for API parity; not a hot path
        self.drop_last = False
        self._seed = 0

    # -- reference setters ---------------------------------------------------
    def set_batch_size(self, batch_size: int):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num: int):
        self.thread_num = max(1, int(thread_num))

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_use_var(self, var_list):
        """Declare the slot layout: one slot per var, width = prod(var.shape)."""
        self.use_vars = list(var_list)

    def set_pipe_command(self, pipe_command: str):
        self.pipe_command = pipe_command

    def set_drop_last(self, drop_last: bool):
        self.drop_last = bool(drop_last)

    # -- slot layout ---------------------------------------------------------
    def _widths(self):
        ws = []
        for v in self.use_vars:
            shape = [d for d in v.shape if d not in (-1, None)]
            w = 1
            for d in shape:
                w *= int(d)
            ws.append(max(1, w))
        return ws

    def _split_batch(self, flat: np.ndarray) -> dict:
        """[B, sum(widths)] float64 -> {var name: [B, *shape] typed array}.

        Under FLAGS_feed_bucketing the ragged tail batch of an epoch is
        padded up to batch_size with zero rows and the feed gains the
        float32 row mask (data_feeder.ROW_MASK_NAME) — every batch of the
        epoch then shares ONE compiled signature instead of the tail
        triggering a fresh XLA compile. Programs that must be exact under
        padding weight their per-row losses by the mask."""
        feed = {}
        off = 0
        for v, w in zip(self.use_vars, self._widths()):
            part = flat[:, off:off + w]
            off += w
            shape = [d for d in v.shape if d not in (-1, None)]
            arr = part.reshape([part.shape[0]] + [int(d) for d in shape])
            # id/label slots declared int64 cast straight to the int32 the
            # device runs (np_feed_dtype): explicit truncation at the feed
            # boundary, not an implicit one in device_put
            feed[v.name] = arr.astype(v.np_feed_dtype, copy=False)
        from . import flags

        if flags.get_flag("feed_bucketing"):
            from .data_feeder import pad_feed_to_bucket

            feed = pad_feed_to_bucket(feed, self.batch_size)
        return feed

    def _parse_file(self, path: str) -> np.ndarray:
        from .native import parse_multislot_file

        return parse_multislot_file(path, self._widths())

    # executor hooks (reference _prepare_to_run/_finish_to_run)
    def _prepare_to_run(self):
        if not self.use_vars:
            raise RuntimeError("Dataset.set_use_var must be called first")

    def _finish_to_run(self):
        pass


class QueueDataset(DatasetBase):
    """Streaming dataset (reference dataset.py:613): files are parsed by a
    thread pool during iteration; nothing is retained afterwards.

    Batch ASSEMBLY (the `_split_batch` slice/reshape/dtype-cast fan-out)
    runs on the parser workers too, not on the consuming thread: with the
    device step dispatching asynchronously, the r5 profile put the DeepFM
    end-to-end path at 0.6-0.7x its pure device throughput, and the
    assembly work serialized on the consumer was part of that residue
    (VERDICT r5 #3). Workers hand the executor feed-ready dicts, so the
    consumer thread's epoch loop is queue-pop -> dispatch."""

    def _iter_batches(self):
        from . import flags, profiler

        self._prepare_to_run()
        files = queue.Queue()
        for f in self.filelist:
            files.put(f)
        out: queue.Queue = queue.Queue(maxsize=max(4, 2 * self.thread_num))
        n_workers = min(self.thread_num, max(1, len(self.filelist)))
        errors: list[BaseException] = []
        stop = threading.Event()  # consumer abandoned the generator

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    out.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                while not stop.is_set():
                    try:
                        path = files.get_nowait()
                    except queue.Empty:
                        return
                    data = self._parse_file(path)
                    for i in range(0, len(data), self.batch_size):
                        chunk = data[i:i + self.batch_size]
                        if self.drop_last and len(chunk) < self.batch_size:
                            continue
                        try:
                            feed = self._split_batch(chunk)
                        except (ValueError, TypeError):
                            # corrupt record died in assembly, off-thread:
                            # same skip-and-count contract as the executor's
                            # own conversion site
                            if not flags.get_flag("feed_skip_corrupt"):
                                raise
                            profiler.bump("feed.skip_corrupt")
                            continue
                        if not _put(feed):
                            return
            except BaseException as e:  # propagate into the consumer
                errors.append(e)
            finally:
                _put(None) or out.put(None)  # sentinel must always land

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(n_workers)]
        for t in threads:
            t.start()
        finished = 0
        try:
            while finished < n_workers:
                item = out.get()
                if item is None:
                    finished += 1
                    continue
                yield item  # already assembled by the worker
        finally:
            # early exit (exe.run raised / caller broke out): unblock workers
            stop.set()
            while finished < n_workers:
                if out.get() is None:
                    finished += 1
        if errors:
            raise errors[0]


class InMemoryDataset(DatasetBase):
    """reference dataset.py:269 — load once, shuffle in memory, iterate many
    epochs; global_shuffle partitions samples across fleet trainers."""

    def __init__(self):
        super().__init__()
        self._data: np.ndarray | None = None

    def load_into_memory(self):
        self._prepare_to_run()
        parts = []
        files = queue.Queue()
        for f in self.filelist:
            files.put(f)
        lock = threading.Lock()
        errors: list[BaseException] = []

        def worker():
            try:
                while True:
                    try:
                        path = files.get_nowait()
                    except queue.Empty:
                        return
                    d = self._parse_file(path)
                    with lock:
                        parts.append(d)
            except BaseException as e:
                errors.append(e)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(min(self.thread_num,
                                      max(1, len(self.filelist))))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        self._data = (np.concatenate(parts) if parts
                      else np.zeros((0, int(sum(self._widths())))))

    def preload_into_memory(self):
        self._preload_error: BaseException | None = None

        def _load():
            try:
                self.load_into_memory()
            except BaseException as e:
                self._preload_error = e

        self._preload = threading.Thread(target=_load)
        self._preload.start()

    def wait_preload_done(self):
        self._preload.join()
        if self._preload_error is not None:
            raise self._preload_error

    def local_shuffle(self):
        if self._data is None:
            raise RuntimeError("call load_into_memory() before local_shuffle()")
        rng = np.random.default_rng(self._seed)
        self._seed += 1
        rng.shuffle(self._data)

    def global_shuffle(self, fleet=None, thread_num: int | None = None):
        """Keep this trainer's hash partition of the (shared) sample set,
        shuffled. Matches the reference post-condition when every trainer
        loaded the same filelist (data_set.cc GlobalShuffle's send-by-hash)."""
        if self._data is None:
            raise RuntimeError("call load_into_memory() before global_shuffle()")
        rank, nranks = 0, 1
        if fleet is not None:
            rank, nranks = fleet.worker_index(), fleet.worker_num()
        rng = np.random.default_rng(self._seed)
        self._seed += 1
        perm = rng.permutation(len(self._data))
        if nranks > 1:
            perm = perm[perm % nranks == rank]
        self._data = self._data[perm]

    def release_memory(self):
        self._data = None

    def get_memory_data_size(self, fleet=None) -> int:
        n = 0 if self._data is None else len(self._data)
        return n  # per-trainer count; fleet-wide sum needs a collective

    get_shuffle_data_size = get_memory_data_size

    def _iter_batches(self):
        """Assembly double-buffers ahead of the consumer (the pyreader.py
        pattern): one background thread slices/reshapes/casts the next
        batches while the device chews on the current one, bounded at
        depth 2 so a slow consumer doesn't balloon host memory."""
        self._prepare_to_run()
        if self._data is None:
            raise RuntimeError(
                "InMemoryDataset: call load_into_memory() before training")
        out: queue.Queue = queue.Queue(maxsize=2)
        stop = threading.Event()
        errors: list[BaseException] = []

        def assembler():
            try:
                for i in range(0, len(self._data), self.batch_size):
                    chunk = self._data[i:i + self.batch_size]
                    if self.drop_last and len(chunk) < self.batch_size:
                        continue
                    feed = self._split_batch(chunk)
                    while not stop.is_set():
                        try:
                            out.put(feed, timeout=0.2)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:
                errors.append(e)
            finally:
                while not stop.is_set():
                    try:
                        out.put(None, timeout=0.2)
                        return
                    except queue.Full:
                        continue

        t = threading.Thread(target=assembler, daemon=True)
        t.start()
        try:
            while True:
                item = out.get()
                if item is None:
                    break
                yield item
        finally:
            stop.set()
        if errors:
            raise errors[0]


MultiSlotDataset = QueueDataset
